"""Benchmark: TPU cluster chip utilization under the full control loop.

North-star metric (BASELINE.json): cluster-wide TPU chip utilization
achieved by dynamic slice partitioning, target ≥90%. The scenario runs the
ENTIRE suite in-process (scheduler, partitioner, tpuagents, operator, sim
kubelet — the same controllers a helm install deploys) over a 4-node v5e
cluster and drives two differently-shaped demand waves through it; the
second wave forces live re-carving of freed boards. Utilization is
chips-held-by-Running-pods / total-chips at each phase's convergence.

Prints ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": "%", "vs_baseline": N}
vs_baseline is value/90 (the reference publishes no controller metrics —
BASELINE.md; 90% is the stated north-star target). Detail metrics (p50
schedule latency, reconfigs, model step time on the default JAX backend)
go to stderr.
"""
from __future__ import annotations

import json
import statistics
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def run_control_plane_bench():
    from nos_tpu.api.config import GpuPartitionerConfig, SchedulerConfig
    from nos_tpu.api.v1alpha1 import constants
    from nos_tpu.cmd import build_cluster
    from nos_tpu.kube.objects import (
        Container,
        ObjectMeta,
        Pod,
        PodPhase,
        PodSpec,
    )
    from nos_tpu.kube.objects import Node, NodeStatus
    from nos_tpu.api.v1alpha1 import labels
    from nos_tpu.util import resources as res

    N_NODES = 4
    CHIPS_PER_NODE = 8
    TOTAL = N_NODES * CHIPS_PER_NODE

    cluster = build_cluster(
        partitioner_config=GpuPartitionerConfig(
            batch_window_timeout_seconds=0.5, batch_window_idle_seconds=0.05
        ),
        scheduler_config=SchedulerConfig(retry_seconds=0.1),
    )
    for i in range(N_NODES):
        alloc = {constants.RESOURCE_TPU: CHIPS_PER_NODE, "cpu": 64, "memory": 256}
        node = Node(
            metadata=ObjectMeta(
                name=f"tpu-{i}",
                labels={
                    labels.GKE_TPU_ACCELERATOR_LABEL: "tpu-v5-lite-podslice",
                    labels.GKE_TPU_TOPOLOGY_LABEL: "2x4",
                    labels.PARTITIONING_LABEL: "tpu",
                },
            ),
            status=NodeStatus(capacity=dict(alloc), allocatable=dict(alloc)),
        )
        cluster.add_tpu_node(node)
    cluster.start()

    created_at: dict = {}
    bound_at: dict = {}

    def submit(name: str, chips: int) -> None:
        pod = Pod(
            metadata=ObjectMeta(name=name, namespace="bench"),
            spec=PodSpec(containers=[Container(requests={constants.RESOURCE_TPU: chips})]),
        )
        created_at[name] = time.monotonic()
        cluster.store.create(pod)

    def running_chips() -> int:
        total = 0
        for pod in cluster.store.list("Pod", namespace="bench"):
            if pod.status.phase == PodPhase.RUNNING and pod.spec.node_name:
                total += res.tpu_chips_in(res.compute_pod_request(pod))
                if pod.metadata.name not in bound_at:
                    bound_at[pod.metadata.name] = time.monotonic()
        return total

    def wait_converged(expected_chips: int, timeout: float = 30.0) -> int:
        deadline = time.monotonic() + timeout
        best = 0
        while time.monotonic() < deadline:
            chips = running_chips()
            best = max(best, chips)
            if chips >= expected_chips:
                return chips
            time.sleep(0.05)
        return best

    try:
        # Phase 1: 4-chip jobs fill every board (8 x 4 = 32 chips).
        for i in range(8):
            submit(f"wave1-{i}", 4)
        phase1 = wait_converged(TOTAL)
        u1 = 100.0 * phase1 / TOTAL
        log(f"phase1: {phase1}/{TOTAL} chips running (u={u1:.1f}%)")

        # Phase 2: all jobs on two of the nodes finish (whole boards free
        # up — running pods cannot be migrated, so board-grained freeing is
        # the re-carvable case); whole-board jobs arrive, forcing the freed
        # 2x2 geometry to be re-carved into 2x4.
        by_node: dict = {}
        for pod in cluster.store.list("Pod", namespace="bench"):
            if pod.status.phase == PodPhase.RUNNING:
                by_node.setdefault(pod.spec.node_name, []).append(pod.metadata.name)
        finished = 0
        for node_name in sorted(by_node)[:2]:
            for pod_name in by_node[node_name]:
                def finish(p):
                    p.status.phase = PodPhase.SUCCEEDED

                cluster.store.patch_merge("Pod", pod_name, "bench", finish)
                finished += 1
        for i in range(2):
            submit(f"wave2-big-{i}", 8)

        expected = (8 - finished) * 4 + 2 * 8
        phase2 = wait_converged(expected)
        u2 = 100.0 * phase2 / TOTAL
        log(f"phase2: {phase2}/{TOTAL} chips running (u={u2:.1f}%)")

        latencies = sorted(
            bound_at[k] - created_at[k] for k in bound_at if k in created_at
        )
        p50 = statistics.median(latencies) if latencies else float("nan")
        log(
            f"p50 schedule latency: {p50*1000:.0f} ms over {len(latencies)} pods; "
            f"plans applied: {cluster.partitioner.plans_applied}"
        )
        return (u1 + u2) / 2.0
    finally:
        cluster.stop()


def run_model_step_bench() -> None:
    """Exercise the real accelerator path: steady-state forward step time of
    the tiny flagship config on the default JAX backend."""
    try:
        import jax
        import jax.numpy as jnp

        from nos_tpu.models.llama import init_llama_params, llama_forward, tiny_config

        config = tiny_config()
        params = init_llama_params(jax.random.key(0), config)
        tokens = jnp.zeros((8, 128), jnp.int32)
        fwd = jax.jit(lambda p, t: llama_forward(p, t, config))
        jax.block_until_ready(fwd(params, tokens))  # compile
        start = time.monotonic()
        iters = 20
        for _ in range(iters):
            out = fwd(params, tokens)
        jax.block_until_ready(out)
        step_ms = (time.monotonic() - start) / iters * 1000
        log(
            f"model step ({jax.default_backend()}): {step_ms:.2f} ms "
            f"(tiny llama fwd, batch 8 x 128)"
        )

    except Exception as e:  # pragma: no cover - accelerator quirks
        log(f"model step bench skipped: {type(e).__name__}: {e}")
        return

    try:
        flash_config = tiny_config(attention="flash")
        fwd_flash = jax.jit(lambda p, t: llama_forward(p, t, flash_config))
        jax.block_until_ready(fwd_flash(params, tokens))
        start = time.monotonic()
        for _ in range(iters):
            out = fwd_flash(params, tokens)
        jax.block_until_ready(out)
        log(
            f"model step flash-attn pallas: {(time.monotonic() - start) / iters * 1000:.2f} ms"
        )
    except Exception as e:  # pragma: no cover - pallas needs tpu or interpret
        log(f"flash-attn step skipped: {type(e).__name__}: {e}")


def main() -> None:
    sys.path.insert(0, ".")
    utilization = run_control_plane_bench()
    run_model_step_bench()
    print(
        json.dumps(
            {
                "metric": "tpu_chip_utilization",
                "value": round(utilization, 2),
                "unit": "%",
                "vs_baseline": round(utilization / 90.0, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
