"""Benchmark: real-TPU model step + TPU cluster control-loop north stars.

Two halves, in this order:

1. **Model-step bench on the real accelerator** — runs FIRST, in a fresh
   subprocess, before any control-plane threads exist (round 1's in-process
   attempt poisoned backend init). Measures trained-step time, tokens/s and
   MFU for the largest Llama config that fits one chip, plus dense-vs-flash
   forward step time. Falls back gracefully (bounded timeout, honest error
   string) when no accelerator is reachable.

2. **Control-plane bench** — the ENTIRE suite in-process (scheduler,
   partitioner, tpuagents, operator, sim kubelet — the same controllers a
   helm install deploys) over a 4-node v5e cluster:
   - phase 1 fill, phase 2 live re-carve of freed boards,
   - phase 3 contention: demand > chips with elastic-quota borrowing and
     fair-share preemption (CapacityScheduling PostFilter),
   - phase 4 churn: alternating demand shapes to measure sustained
     slice-reconfigs/sec.
   Utilization is EVENT-INTEGRATED over the steady stream window (chips x
   [bind, finish) intervals, not cherry-picked at convergence points); all
   three BASELINE north stars (utilization, p50 schedule latency,
   reconfigs/sec) land in the JSON line.

Prints ONE JSON line on stdout:
  {"metric": "tpu_chip_utilization", "value": N, "unit": "%",
   "vs_baseline": N, ...north stars..., ...tpu_* hardware numbers...}
vs_baseline is value/90 (the reference publishes no controller metrics —
BASELINE.md; 90% is the stated north-star target). Detail goes to stderr.
"""
from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import time

TPU_CHILD_TIMEOUT_S = 1200.0  # the child snapshots after every section,
# so a timeout still salvages everything completed; the budget covers
# the full section list (train, sweeps, decode+quant, ctx4k, engine x2,
# prefix, long-context, rolling) with tunnel-compile headroom
# Staged bring-up: before committing to the TPU_CHILD_TIMEOUT_S full child, run a tiny
# probe child that only does `jax.devices()`. The tunneled-TPU claim leg
# can hang indefinitely when the relay is wedged (observed r03/r04: two
# rounds lost to a 900 s init hang); the probe bounds that failure mode to
# PROBE_ATTEMPTS x PROBE_TIMEOUT_S and gives an honest, specific error.
PROBE_TIMEOUT_S = float(os.environ.get("NOS_BENCH_PROBE_TIMEOUT_S", "240"))
PROBE_ATTEMPTS = 3
PROBE_BACKOFF_S = 20.0
# A probe child that dies in under this many seconds failed
# deterministically (import error, bad platform) — retrying is waste.
PROBE_FAST_FAIL_S = 10.0


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# =====================================================================
# Half 1: model-step bench (runs in a fresh child: `python bench.py
# --tpu-child`), parent parses the last stdout line as JSON.
# =====================================================================

# bf16 peak FLOP/s per chip by device kind substring (public spec sheets).
_PEAK_BF16 = (
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v5e", 197e12),
    ("v6 lite", 918e12),
    ("v6e", 918e12),
    ("v5p", 459e12),
    ("v4", 275e12),
    ("v7", 2307e12),
)


def _peak_flops(device_kind: str) -> float:
    kind = device_kind.lower()
    for needle, peak in _PEAK_BF16:
        if needle in kind:
            return peak
    return 197e12  # default to v5e (BASELINE north-star hardware)


def _count_params(params) -> int:
    import jax

    return sum(x.size for x in jax.tree.leaves(params))


def run_tpu_child() -> None:
    """Model bench on the default backend. Prints one JSON line.

    NOS_BENCH_PLATFORM=cpu forces the CPU backend (config update, not env:
    this image's sitecustomize re-points jax_platforms at the remote-TPU
    plugin after import, so only an in-process update wins)."""
    import jax

    forced = os.environ.get("NOS_BENCH_PLATFORM")
    if forced:
        jax.config.update("jax_platforms", forced)
    import jax.numpy as jnp

    from nos_tpu.models.llama import (
        LlamaConfig,
        init_llama_params,
        llama_forward,
        tiny_config,
    )
    from nos_tpu.parallel.train import make_train_step
    from nos_tpu.parallel.mesh import mesh_from_devices

    t0 = time.monotonic()
    backend = jax.default_backend()
    dev = jax.devices()[0]
    log(f"[tpu-child] backend={backend} device={dev.device_kind} "
        f"init {time.monotonic()-t0:.1f}s")

    on_tpu = backend not in ("cpu",)
    if on_tpu:
        # ~1B-param Llama: the largest power-of-two-ish config whose train
        # state (params+velocity in bf16, grads transient) fits 16 GB HBM.
        config = LlamaConfig(
            vocab_size=32000,
            d_model=2048,
            n_layers=16,
            n_heads=16,
            n_kv_heads=8,
            d_ff=7168,
        )
        # (batch, seq, attention, remat): flash attention (O(S) memory,
        # no [S,S] scores) + per-layer remat is what lets a 1B model
        # train at real token counts on a 16 GB chip; prefer no-remat
        # (fewer recompute FLOPs) when the batch fits without it.
        batch_candidates = [
            (8, 2048, "flash", False),   # best MFU if it fits (no recompute)
            (16, 2048, "flash", True),   # 2x tokens amortize the remat tax
            (8, 2048, "flash", True),
            (4, 2048, "flash", True),
            # If every flash attempt failed, suspect the compact banded
            # grid (untested Mosaic toolchains): flip it off and retry
            # before surrendering to dense.
            (0, 0, "compact_off", False),
            (8, 2048, "flash", False),
            (2, 1024, "dense", False),
        ]
        train_iters, fwd_iters = 10, 20
    else:
        config = tiny_config()
        batch_candidates = [(8, 128, "dense", False)]
        train_iters, fwd_iters = 5, 10

    mesh = mesh_from_devices((1, 1), ("dp", "tp"), jax.devices()[:1])
    params = init_llama_params(jax.random.key(0), config)
    n_params = _count_params(params)
    log(f"[tpu-child] params={n_params/1e9:.3f}B")

    result = {
        "backend": backend,
        "device_kind": dev.device_kind,
        "model_params_b": round(n_params / 1e9, 4),
    }

    def snapshot() -> None:
        # Emit the running result after every section: the parent takes the
        # LAST stdout line, so a timeout mid-bench still salvages every
        # completed number instead of losing the run.
        print(json.dumps(result), flush=True)

    # ---- train step (loss -> grad -> momentum SGD), largest batch that fits
    import dataclasses

    state = None
    for batch, seq, attn, remat in batch_candidates:
        if attn == "compact_off":
            import importlib

            # nos_tpu.ops re-exports the flash_attention FUNCTION, which
            # shadows the module on every `import ... as` form
            _fa = importlib.import_module("nos_tpu.ops.flash_attention")
            _fa.set_compact(False)
            jax.clear_caches()
            log("[tpu-child] disabling the compact flash grid and "
                "retrying (all flash attempts failed)")
            continue
        tokens = jnp.zeros((batch, seq), jnp.int32)
        try:
            t_cfg = dataclasses.replace(config, attention=attn, remat=remat)
            train_step, shard_state = make_train_step(mesh, t_cfg)
            # Fresh params per attempt: the state is donated (halves peak
            # HBM), so a failed attempt leaves its buffers deleted.
            params = init_llama_params(jax.random.key(0), config)
            state = shard_state(params, donate=True)
            del params
            t_c = time.monotonic()
            state, loss = train_step(state, tokens)
            jax.block_until_ready(loss)
            log(f"[tpu-child] train compile+1st step {time.monotonic()-t_c:.1f}s "
                f"(batch {batch}x{seq} attn={attn} remat={remat})")
            start = time.monotonic()
            for _ in range(train_iters):
                state, loss = train_step(state, tokens)
            jax.block_until_ready(loss)
            step_s = (time.monotonic() - start) / train_iters
            tokens_per_step = batch * seq
            flops = 6.0 * n_params * tokens_per_step
            peak = _peak_flops(dev.device_kind)
            result.update(
                train_batch=batch,
                train_seq=seq,
                train_attention=attn,
                train_remat=remat,
                train_step_ms=round(step_s * 1000, 2),
                train_tokens_per_s=round(tokens_per_step / step_s, 1),
                train_mfu_pct=round(100.0 * flops / step_s / peak, 2),
            )
            log(f"[tpu-child] train: {step_s*1000:.1f} ms/step, "
                f"{tokens_per_step/step_s:.0f} tok/s, "
                f"MFU {result['train_mfu_pct']:.1f}% (peak {peak/1e12:.0f} TF)")
            snapshot()
            break
        except Exception as e:  # OOM etc. -> try the next smaller batch
            log(f"[tpu-child] train batch {batch}x{seq} attn={attn} "
                f"remat={remat} failed: {type(e).__name__}: {str(e)[:200]}")
            state = None
    del state
    # train_step donated the state (which may alias params): rebuild for
    # the forward benches.
    params = init_llama_params(jax.random.key(0), config)

    # ---- forward step, dense vs flash (same batch as train where possible)
    batch, seq = result.get("train_batch", batch_candidates[-1][0]), result.get(
        "train_seq", batch_candidates[-1][1]
    )
    tokens = jnp.zeros((batch, seq), jnp.int32)

    def bench_fwd(cfg, label, toks=None, iters=None):
        toks = tokens if toks is None else toks
        iters = iters or fwd_iters
        fwd = jax.jit(lambda p, t: llama_forward(p, t, cfg))
        out = fwd(params, toks)
        jax.block_until_ready(out)
        start = time.monotonic()
        for _ in range(iters):
            out = fwd(params, toks)
        jax.block_until_ready(out)
        ms = (time.monotonic() - start) / iters * 1000
        log(f"[tpu-child] fwd {label}: {ms:.2f} ms/step "
            f"(batch {'x'.join(map(str, toks.shape))})")
        return ms

    try:
        result["fwd_step_ms"] = round(bench_fwd(config, "dense"), 2)
    except Exception as e:
        log(f"[tpu-child] fwd dense failed: {type(e).__name__}: {str(e)[:200]}")
    if on_tpu:
        try:
            flash_cfg = dataclasses.replace(config, attention="flash")
            result["fwd_flash_step_ms"] = round(bench_fwd(flash_cfg, "flash"), 2)
            if "fwd_step_ms" in result:
                result["flash_speedup"] = round(
                    result["fwd_step_ms"] / result["fwd_flash_step_ms"], 3
                )
        except Exception as e:
            log(f"[tpu-child] fwd flash failed: {type(e).__name__}: {str(e)[:200]}")
        snapshot()

        # ---- raw attention-op bench: kernel vs XLA dense on exactly the
        # model's attention shape, across block sizes. Isolates the kernel
        # from the rest of the model (r02 measured whole-model flash at
        # 0.90x dense at 2x1024 — this pinpoints whether the kernel or
        # the surrounding program is at fault, and which (blk_q, blk_k)
        # the default should be on this chip generation).
        try:
            from nos_tpu.ops.flash_attention import flash_attention

            ab, as_, ahq, ahkv, ahd = (
                result.get("train_batch", 8) or 8,
                result.get("train_seq", 2048) or 2048,
                config.n_heads,
                config.n_kv_heads,
                config.d_model // config.n_heads,
            )
            kq = jax.random.normal(
                jax.random.key(1), (ab, as_, ahq, ahd), jnp.bfloat16
            )
            kk = jax.random.normal(
                jax.random.key(2), (ab, as_, ahkv, ahd), jnp.bfloat16
            )
            kv = jax.random.normal(
                jax.random.key(3), (ab, as_, ahkv, ahd), jnp.bfloat16
            )

            def time_op(fn, iters=20):
                out = fn(kq, kk, kv)
                jax.block_until_ready(out)
                start = time.monotonic()
                for _ in range(iters):
                    out = fn(kq, kk, kv)
                jax.block_until_ready(out)
                return (time.monotonic() - start) / iters * 1000

            def dense_ref(q, k, v):
                # The model's dense path: repeat kv heads, causal softmax.
                g = ahq // ahkv
                kr = jnp.repeat(k, g, axis=2)
                vr = jnp.repeat(v, g, axis=2)
                logits = jnp.einsum(
                    "bqhd,bkhd->bhqk", q, kr, preferred_element_type=jnp.float32
                ) / (ahd ** 0.5)
                mask = jnp.tril(jnp.ones((as_, as_), bool))
                logits = jnp.where(mask[None, None], logits, -jnp.inf)
                probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
                return jnp.einsum("bhqk,bkhd->bqhd", probs, vr)

            d_ms = time_op(jax.jit(dense_ref))
            result["attn_dense_ms"] = round(d_ms, 2)
            log(f"[tpu-child] attn dense: {d_ms:.2f} ms @ {ab}x{as_}")
            best = None
            for bq, bk in ((128, 256), (256, 256), (256, 512), (512, 512), (512, 1024)):
                if bq > as_ or bk > as_:
                    continue
                try:
                    f_ms = time_op(
                        jax.jit(
                            lambda q, k, v, bq=bq, bk=bk: flash_attention(
                                q, k, v, blk_q=bq, blk_k=bk
                            )
                        )
                    )
                    result[f"attn_flash_{bq}x{bk}_ms"] = round(f_ms, 2)
                    log(f"[tpu-child] attn flash {bq}x{bk}: {f_ms:.2f} ms "
                        f"({d_ms / f_ms:.2f}x dense)")
                    if best is None or f_ms < best[1]:
                        best = ((bq, bk), f_ms)
                except Exception as e:
                    log(f"[tpu-child] attn flash {bq}x{bk} failed: "
                        f"{type(e).__name__}: {str(e)[:120]}")
            if best is not None:
                result["attn_flash_best_blocks"] = f"{best[0][0]}x{best[0][1]}"
                result["attn_flash_best_ms"] = round(best[1], 2)
                result["attn_flash_vs_dense"] = round(d_ms / best[1], 3)

            # backward too: training runs the custom_vjp, whose cost can
            # differ wildly from the forward (dq + dk/dv are two more
            # kernel passes). Same scalarization for both sides.
            def grad_time(attn_fn):
                f = jax.jit(
                    jax.grad(
                        lambda q, k, v: jnp.sum(
                            attn_fn(q, k, v).astype(jnp.float32)
                        ),
                        argnums=(0, 1, 2),
                    )
                )
                out = f(kq, kk, kv)
                jax.block_until_ready(out)
                start = time.monotonic()
                for _ in range(10):
                    out = f(kq, kk, kv)
                jax.block_until_ready(out)
                return (time.monotonic() - start) / 10 * 1000

            try:
                bwd_d = grad_time(dense_ref)
                result["attn_dense_bwd_ms"] = round(bwd_d, 2)
                if best is not None:
                    bq, bk = best[0]
                    bwd_f = grad_time(
                        lambda q, k, v: flash_attention(q, k, v, blk_q=bq, blk_k=bk)
                    )
                    result["attn_flash_bwd_ms"] = round(bwd_f, 2)
                    result["attn_flash_bwd_vs_dense"] = round(bwd_d / bwd_f, 3)
                    log(f"[tpu-child] attn bwd: dense {bwd_d:.2f} ms, "
                        f"flash {bwd_f:.2f} ms ({bwd_d / bwd_f:.2f}x)")
            except Exception as e:
                log(f"[tpu-child] attn bwd failed: {type(e).__name__}: {str(e)[:120]}")
            del kq, kk, kv
            snapshot()
        except Exception as e:
            log(f"[tpu-child] attn-op bench failed: {type(e).__name__}: {str(e)[:160]}")

        # ---- serving: KV-cache autoregressive decode throughput (the
        # per-token cost a slice tenant sees; memory-bandwidth-bound).
        # Runs BEFORE the long-context sweep: its compiled executables and
        # score buffers are the biggest HBM pressure in the child, and a
        # timeout/OOM there must not cost the serving numbers.
        jax.clear_caches()
        try:
            from nos_tpu.models.generate import generate as kv_generate

            new_tokens = 64
            gen = jax.jit(
                lambda p, t: kv_generate(p, t, config, max_new_tokens=new_tokens)
            )
            prompt = jnp.zeros((1, 128), jnp.int32)
            jax.block_until_ready(gen(params, prompt))
            start = time.monotonic()
            iters = 3
            for _ in range(iters):
                out = gen(params, prompt)
            jax.block_until_ready(out)
            tok_s = new_tokens * iters / (time.monotonic() - start)
            result["decode_tokens_per_s"] = round(tok_s, 1)
            log(f"[tpu-child] decode: {tok_s:.1f} tok/s "
                f"(KV cache, prompt 128 + {new_tokens} new)")
            snapshot()

            # int8 weight-only serving: decode re-reads every weight per
            # token, so halved weight bytes should read straight through
            # to tokens/s (HBM-bandwidth-bound).
            from nos_tpu.models.quantize import quantize_params, weight_bytes

            qparams = jax.jit(quantize_params)(params)
            ratio = weight_bytes(qparams) / max(1, weight_bytes(params))
            jax.block_until_ready(gen(qparams, prompt))
            start = time.monotonic()
            for _ in range(iters):
                out = gen(qparams, prompt)
            jax.block_until_ready(out)
            tok_s_q = new_tokens * iters / (time.monotonic() - start)
            result["decode_int8_tokens_per_s"] = round(tok_s_q, 1)
            result["int8_weight_bytes_ratio"] = round(ratio, 3)
            result["int8_decode_speedup"] = round(tok_s_q / tok_s, 3)
            log(f"[tpu-child] decode int8: {tok_s_q:.1f} tok/s "
                f"({result['int8_decode_speedup']}x, weights {ratio:.2f}x bytes)")
            del qparams
            snapshot()

            # int8 KV cache: at batch 8 x 4k context the per-step cache
            # stream (~2 GB bf16) rivals the weight bytes, so halving it
            # should show in tokens/s — the short-prompt decode above
            # cannot (its KV is noise next to 2 GB of weights).
            try:
                from nos_tpu.models.generate import decode_step, prefill

                def _ctx_decode(quant):
                    b, ctx, steps = 8, 4096, 32
                    toks = jnp.zeros((b, ctx), jnp.int32)
                    fcfg = dataclasses.replace(config, attention="flash")

                    def run(params, toks):
                        logits, cache = prefill(
                            params, toks, fcfg, ctx + steps, quant=quant
                        )
                        first = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)

                        def tick(carry, i):
                            cache, tok = carry
                            lg, cache = decode_step(
                                params, cache, ctx + i, tok, fcfg
                            )
                            return (cache, jnp.argmax(lg, -1).astype(jnp.int32)), ()

                        (cache, last), _ = jax.lax.scan(
                            tick, (cache, first), jnp.arange(steps)
                        )
                        return last

                    fn = jax.jit(run)
                    jax.block_until_ready(fn(params, toks))
                    start = time.monotonic()
                    out = fn(params, toks)
                    jax.block_until_ready(out)
                    return b * steps / (time.monotonic() - start)

                t_full = _ctx_decode(False)
                t_q = _ctx_decode(True)
                result["decode_ctx4k_tokens_per_s"] = round(t_full, 1)
                result["decode_ctx4k_kvq_tokens_per_s"] = round(t_q, 1)
                result["kv_quant_decode_speedup"] = round(t_q / t_full, 3)
                log(f"[tpu-child] decode @8x4k ctx: {t_full:.1f} tok/s bf16 "
                    f"KV, {t_q:.1f} tok/s int8 KV "
                    f"({result['kv_quant_decode_speedup']}x)")
            except Exception as e:
                log(f"[tpu-child] kv-quant decode failed: "
                    f"{type(e).__name__}: {str(e)[:160]}")
            snapshot()

            # int4 group-wise: a QUARTER of bf16's weight bytes — decode
            # bandwidth should read through again if the nibble unpack
            # fuses ahead of the MXU dot. Own try/except: an int4-only
            # failure must not cost the engine/prefix numbers downstream.
            try:
                from nos_tpu.models.quantize import quantize_params_int4

                q4params = jax.jit(quantize_params_int4)(params)
                ratio4 = weight_bytes(q4params) / max(1, weight_bytes(params))
                jax.block_until_ready(gen(q4params, prompt))
                start = time.monotonic()
                for _ in range(iters):
                    out = gen(q4params, prompt)
                jax.block_until_ready(out)
                tok_s_q4 = new_tokens * iters / (time.monotonic() - start)
                result["decode_int4_tokens_per_s"] = round(tok_s_q4, 1)
                result["int4_weight_bytes_ratio"] = round(ratio4, 3)
                result["int4_decode_speedup"] = round(tok_s_q4 / tok_s, 3)
                log(f"[tpu-child] decode int4: {tok_s_q4:.1f} tok/s "
                    f"({result['int4_decode_speedup']}x, "
                    f"weights {ratio4:.2f}x bytes)")
                del q4params
            except Exception as e:
                log(f"[tpu-child] int4 decode failed: "
                    f"{type(e).__name__}: {str(e)[:160]}")
            snapshot()

            # continuous batching: decode is weight-bandwidth-bound, so
            # batched slots share each weight read — aggregate tok/s should
            # approach slots x single-stream.
            from nos_tpu.serve import Engine, GenRequest

            gen_len = 64

            def bench_engine(slots, n_req, key_prefix):
                """Cold-start one engine (warm-up request compiles the
                prefill bucket, decode scan, splice — serving replicas
                compile once per process but serve for hours, so the
                steady-state tokens/s is the capacity number), then time
                n_req same-shape requests; records under key_prefix."""
                # 16 ticks/sync: dispatch latency (a network RTT on
                # tunneled chips) amortizes over the chunk
                eng = Engine(params, config, max_slots=slots, max_len=256,
                             ticks_per_sync=16)
                t_cold = time.monotonic()
                eng.submit(GenRequest(prompt=[7] * 120, max_new_tokens=gen_len))
                eng.run()
                cold_s = round(time.monotonic() - t_cold, 1)
                for _ in range(n_req):
                    eng.submit(
                        GenRequest(prompt=[7] * 120, max_new_tokens=gen_len)
                    )
                start = time.monotonic()
                total = sum(len(t) for t in eng.run().values())
                wall = time.monotonic() - start
                result[f"{key_prefix}_slots"] = slots
                result[f"{key_prefix}_cold_start_s"] = cold_s
                result[f"{key_prefix}_tokens_per_s"] = round(total / wall, 1)
                result[f"{key_prefix}_vs_single_stream"] = round(
                    (total / wall) / tok_s, 3
                )
                log(f"[tpu-child] engine x{slots} slots: {total} tokens / "
                    f"{wall:.1f}s = {total/wall:.1f} tok/s "
                    f"({result[f'{key_prefix}_vs_single_stream']}x "
                    f"single-stream, cold start {cold_s}s)")
                snapshot()

            slots, n_req = 4, 8
            bench_engine(slots, n_req, "serve")
            # Slot scaling: decode shares each weight read across rows,
            # so doubling slots should nearly double aggregate tokens/s
            # until KV-cache bandwidth catches up.
            bench_engine(8, 16, "serve8")

            # prefix caching: same aggregate workload but a long shared
            # system prompt and the chunked path + LRU cache — measures
            # end-to-end request throughput when admissions skip the
            # shared prefill (requests/s is the visible win; decode
            # dominates tokens/s).
            shared = [7] * 384 + [11] * 16  # 384 aligns to prefill_chunk=128
            eng = Engine(params, config, max_slots=slots, max_len=512,
                         ticks_per_sync=16, prefill_chunk=128,
                         prefix_cache_entries=4)
            # Warm-up doubles as the cache-seeding request: the measured
            # window then sees the steady serving state (programs
            # compiled, shared prefix resident).
            eng.submit(GenRequest(prompt=shared, max_new_tokens=gen_len))
            eng.run()
            for _ in range(n_req):
                eng.submit(GenRequest(prompt=shared, max_new_tokens=gen_len))
            start = time.monotonic()
            results = eng.run()
            wall_warm = time.monotonic() - start
            total = sum(len(t) for t in results.values())
            from nos_tpu.util import metrics as _m

            result["serve_prefix_tokens_per_s"] = round(total / wall_warm, 1)
            result["serve_prefix_hits"] = int(_m.SERVE_PREFIX_HITS.value)
            log(f"[tpu-child] engine+prefix-cache: {total} tokens / "
                f"{wall_warm:.1f}s = {total/wall_warm:.1f} tok/s "
                f"({result['serve_prefix_hits']} prefix hits)")
            del eng
            snapshot()
        except Exception as e:
            log(f"[tpu-child] decode failed: {type(e).__name__}: {str(e)[:160]}")

        # ---- long context: where flash earns its keep. Dense materializes
        # fp32 [b,K,g,s,s] scores (s=8192: 4 GB per layer); flash streams
        # K/V blocks with O(blk) VMEM. Report per-seq dense/flash ms and
        # the speedup (dense OOM -> speedup reported as inf-proxy null,
        # flash time still recorded).
        jax.clear_caches()
        for long_seq in (4096, 8192):
            long_toks = jnp.zeros((1, long_seq), jnp.int32)
            d_ms = f_ms = None
            try:
                d_ms = bench_fwd(config, f"dense@{long_seq}", long_toks, iters=8)
            except Exception as e:
                log(f"[tpu-child] dense@{long_seq} failed: "
                    f"{type(e).__name__}: {str(e)[:160]}")
            try:
                f_ms = bench_fwd(
                    dataclasses.replace(config, attention="flash"),
                    f"flash@{long_seq}",
                    long_toks,
                    iters=8,
                )
            except Exception as e:
                log(f"[tpu-child] flash@{long_seq} failed: "
                    f"{type(e).__name__}: {str(e)[:160]}")
            tag = f"seq{long_seq // 1024}k"
            if d_ms is not None:
                result[f"fwd_dense_{tag}_ms"] = round(d_ms, 2)
            if f_ms is not None:
                result[f"fwd_flash_{tag}_ms"] = round(f_ms, 2)
            if d_ms is not None and f_ms is not None:
                result[f"flash_speedup_{tag}"] = round(d_ms / f_ms, 3)
            # Mistral-style banded attention: the kernel skips blocks past
            # the window, so compute is O(S·W) — the headline long-context
            # win over full-causal flash.
            try:
                w_ms = bench_fwd(
                    dataclasses.replace(
                        config, attention="flash", sliding_window=1024
                    ),
                    f"flash-w1024@{long_seq}",
                    long_toks,
                    iters=8,
                )
                result[f"fwd_flash_w1k_{tag}_ms"] = round(w_ms, 2)
                if f_ms is not None:
                    result[f"window_vs_full_{tag}"] = round(f_ms / w_ms, 3)
            except Exception as e:
                log(f"[tpu-child] flash-w1024@{long_seq} failed: "
                    f"{type(e).__name__}: {str(e)[:160]}")
            snapshot()

        # ---- rolling sliding-window serving: a windowed stream decodes
        # from an O(window) cache (physical slot = logical mod C). The
        # physical-layout engine needs prompt+budget cache slots; rolling
        # reads a fraction of the K/V per attention step, so long-stream
        # tokens/s should rise with the smaller working set.
        try:
            from nos_tpu.serve import Engine, GenRequest

            wcfg = dataclasses.replace(config, sliding_window=1024)
            # The stream must run WELL past the window for O(window) to
            # engage: physical needs prompt+budget slots (2312) while
            # rolling stays at its fixed 1281 — a ~1.8x smaller per-step
            # K/V working set (rolling also pays a few extra host syncs
            # from its 16-chunk horizon cap; that asymmetry is the
            # shipped behavior on both sides).
            prompt, new = [7] * 256, 2048
            times = {}
            for name, kw in (
                ("physical", dict(max_len=len(prompt) + new + 8)),
                # smallest C that still leaves the full 256-token ingest
                # piece (engine clamps pieces to C - window)
                ("rolling", dict(max_len=1024 + 257, rolling=True)),
            ):
                eng = Engine(params, wcfg, max_slots=1, ticks_per_sync=16,
                             prefill_chunk=256, **kw)
                eng.submit(GenRequest(prompt=prompt, max_new_tokens=new))
                eng.run()  # warm compile
                eng.submit(GenRequest(prompt=prompt, max_new_tokens=new))
                start = time.monotonic()
                eng.run()
                times[name] = time.monotonic() - start
                del eng
            result["serve_window_tokens_per_s"] = round(
                new / times["physical"], 1
            )
            result["serve_rolling_tokens_per_s"] = round(
                new / times["rolling"], 1
            )
            result["rolling_vs_physical"] = round(
                times["physical"] / times["rolling"], 3
            )
            log(f"[tpu-child] rolling serve: {new/times['rolling']:.1f} "
                f"tok/s from a {1024 + 257}-slot cache vs "
                f"{new/times['physical']:.1f} tok/s physical "
                f"({result['rolling_vs_physical']}x)")
        except Exception as e:
            log(f"[tpu-child] rolling serve failed: "
                f"{type(e).__name__}: {str(e)[:160]}")
        snapshot()

    print(json.dumps(result), flush=True)


def run_probe_child() -> None:
    """Minimal backend probe: import jax, list devices, print one JSON line.

    Runs in its own interpreter so a hung `jax.devices()` (wedged tunnel
    relay) is killable without poisoning the parent."""
    import jax

    forced = os.environ.get("NOS_BENCH_PLATFORM")
    if forced:
        # In-process update, not env: this image's sitecustomize re-points
        # jax_platforms at the remote-TPU plugin after import.
        jax.config.update("jax_platforms", forced)
    t0 = time.monotonic()
    devs = jax.devices()
    print(
        json.dumps(
            {
                "ok": True,
                "init_s": round(time.monotonic() - t0, 1),
                "backend": jax.default_backend(),
                "device_kind": devs[0].device_kind,
                "n_devices": len(devs),
            }
        ),
        flush=True,
    )


def probe_backend() -> dict:
    """Run the probe child up to PROBE_ATTEMPTS times with backoff.

    Returns the probe's JSON dict on success, else {"error": ...}. A wedged
    claim fails here in minutes instead of consuming the full child's TPU_CHILD_TIMEOUT_S
    budget (and tells the operator it was INIT that failed, not the bench)."""
    cmd = [sys.executable, os.path.abspath(__file__), "--probe-child"]
    last_err = "unknown"
    for attempt in range(1, PROBE_ATTEMPTS + 1):
        log(f"[bench] backend probe attempt {attempt}/{PROBE_ATTEMPTS} "
            f"(timeout {PROBE_TIMEOUT_S:.0f}s)")
        t0 = time.monotonic()
        try:
            proc = subprocess.run(
                cmd,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                timeout=PROBE_TIMEOUT_S,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            if proc.returncode == 0:
                out = json.loads(proc.stdout.decode().strip().splitlines()[-1])
                log(f"[bench] probe ok in {time.monotonic()-t0:.1f}s: "
                    f"{out.get('backend')}/{out.get('device_kind')}")
                return out
            tail = proc.stderr.decode(errors="replace").strip().splitlines()
            last_err = (f"probe exited rc={proc.returncode}: "
                        f"{' | '.join(tail[-3:]) if tail else 'no stderr'}")
            if time.monotonic() - t0 < PROBE_FAST_FAIL_S:
                # Sub-second/seconds death = deterministic failure
                # (ImportError, bad platform) — identical on retry.
                return {"error": f"backend probe failed fast: {last_err}"}
        except subprocess.TimeoutExpired:
            # Do NOT retry a timed-out probe: the kill landed mid-claim, and
            # a killed claim is exactly what wedges the tunneled chip for
            # hours — more attempts only deepen the wedge.
            return {"error": f"backend probe timed out after "
                             f"{PROBE_TIMEOUT_S:.0f}s (jax.devices() hung: "
                             "tunnel/claim wedged?)"}
        except Exception as e:  # torn output etc.
            last_err = f"probe parse failed: {e}"
        log(f"[bench] probe attempt {attempt} failed: {last_err}")
        if attempt < PROBE_ATTEMPTS:
            time.sleep(PROBE_BACKOFF_S)
    return {"error": f"backend probe failed {PROBE_ATTEMPTS}x: {last_err}"}


def run_tpu_bench_subprocess() -> dict:
    """Staged accelerator bench: cheap probe first, then the full child.

    The probe (jax.devices() only, short timeout, retried with backoff)
    keeps a wedged tunnel from eating the whole child budget; only a
    healthy backend earns the full model-step child."""
    probe = probe_backend()
    if "error" in probe:
        return {"error": probe["error"]}
    cmd = [sys.executable, os.path.abspath(__file__), "--tpu-child"]
    log(f"[bench] launching model-step child (timeout {TPU_CHILD_TIMEOUT_S:.0f}s)")
    try:
        proc = subprocess.run(
            cmd,
            stdout=subprocess.PIPE,
            stderr=sys.stderr,
            timeout=TPU_CHILD_TIMEOUT_S,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired as e:
        # Salvage the child's newest parseable JSON snapshot: the kill can
        # land mid-print, so scan backwards past any torn last line.
        for line in reversed((e.stdout or b"").decode().strip().splitlines()):
            try:
                out = json.loads(line)
            except ValueError:
                continue
            out["truncated"] = True
            return out
        return {"error": f"accelerator bench timed out after {TPU_CHILD_TIMEOUT_S:.0f}s "
                         "(backend init unreachable?)"}
    if proc.returncode != 0:
        return {"error": f"accelerator bench exited rc={proc.returncode}"}
    try:
        last = proc.stdout.decode().strip().splitlines()[-1]
        return json.loads(last)
    except Exception as e:
        return {"error": f"could not parse child output: {e}"}


# =====================================================================
# Half 2: control-plane bench.
# =====================================================================


def run_control_plane_bench() -> dict:
    from nos_tpu.api.config import (
        GpuPartitionerConfig,
        SchedulerConfig,
        TpuAgentConfig,
    )
    from nos_tpu.api.v1alpha1 import constants, labels
    from nos_tpu.api.v1alpha1.elasticquota import ElasticQuota, ElasticQuotaSpec
    from nos_tpu.cmd import build_cluster
    from nos_tpu.kube.objects import (
        Container,
        Node,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodPhase,
        PodSpec,
    )
    from nos_tpu.util import metrics as m
    from nos_tpu.util import resources as res

    N_NODES = 4
    CHIPS_PER_NODE = 8
    TOTAL = N_NODES * CHIPS_PER_NODE
    CHIPS = constants.RESOURCE_TPU_CHIPS

    cluster = build_cluster(
        partitioner_config=GpuPartitionerConfig(
            batch_window_timeout_seconds=0.25, batch_window_idle_seconds=0.05
        ),
        scheduler_config=SchedulerConfig(retry_seconds=0.1),
    )
    for i in range(N_NODES):
        alloc = {constants.RESOURCE_TPU: CHIPS_PER_NODE, "cpu": 64, "memory": 256}
        node = Node(
            metadata=ObjectMeta(
                name=f"tpu-{i}",
                labels={
                    labels.GKE_TPU_ACCELERATOR_LABEL: "tpu-v5-lite-podslice",
                    labels.GKE_TPU_TOPOLOGY_LABEL: "2x4",
                    labels.PARTITIONING_LABEL: "tpu",
                },
            ),
            status=NodeStatus(capacity=dict(alloc), allocatable=dict(alloc)),
        )
        cluster.add_tpu_node(
            node, agent_config=TpuAgentConfig(report_config_interval_seconds=0.15)
        )
    # Elastic quotas for the contention phase: each team guaranteed half
    # the cluster, allowed to borrow up to all of it.
    for ns in ("team-a", "team-b"):
        cluster.store.create(
            ElasticQuota(
                metadata=ObjectMeta(name=f"eq-{ns}", namespace=ns),
                spec=ElasticQuotaSpec(min={CHIPS: TOTAL // 2}, max={CHIPS: TOTAL}),
            )
        )
    cluster.start()

    created_at: dict = {}
    bound_at: dict = {}
    counter = {"n": 0}

    def submit(chips: int, ns: str = "bench", priority: int = 0) -> str:
        counter["n"] += 1
        name = f"job-{counter['n']}"
        pod = Pod(
            metadata=ObjectMeta(name=name, namespace=ns),
            spec=PodSpec(
                containers=[Container(requests={constants.RESOURCE_TPU: chips})],
                priority=priority,
                scheduler_name=constants.SCHEDULER_NAME,
            ),
        )
        created_at[(ns, name)] = time.monotonic()
        cluster.store.create(pod)
        return name

    def all_pods():
        pods = []
        for ns in ("bench", "team-a", "team-b"):
            pods.extend(cluster.store.list("Pod", namespace=ns))
        return pods

    def running_chips() -> int:
        total = 0
        for pod in all_pods():
            if pod.status.phase == PodPhase.RUNNING and pod.spec.node_name:
                total += res.tpu_chips_in(res.compute_pod_request(pod))
                key = (pod.metadata.namespace, pod.metadata.name)
                if key not in bound_at:
                    bound_at[key] = time.monotonic()
        return total

    def running_chips_by_ns() -> dict:
        by = {}
        for pod in all_pods():
            if pod.status.phase == PodPhase.RUNNING and pod.spec.node_name:
                by[pod.metadata.namespace] = by.get(
                    pod.metadata.namespace, 0
                ) + res.tpu_chips_in(res.compute_pod_request(pod))
        return by

    def wait_until(pred, timeout: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return True
            time.sleep(0.05)
        return False

    def finish_all_running() -> None:
        for pod in all_pods():
            if pod.status.phase == PodPhase.RUNNING:
                def fin(p):
                    p.status.phase = PodPhase.SUCCEEDED

                cluster.store.patch_merge(
                    "Pod", pod.metadata.name, pod.metadata.namespace, fin
                )

    def delete_all_pods() -> None:
        """Hard phase boundary: no leftover backlog leaks into the next
        phase's convergence predicate."""
        for pod in all_pods():
            try:
                cluster.store.delete(
                    "Pod", pod.metadata.name, pod.metadata.namespace
                )
            except Exception:
                pass

    preempt_before = m.PREEMPTIONS.value
    out: dict = {}
    try:

        # ---- Phase 1: fill an empty cluster (clean schedule-latency
        # sample: capacity exists, pods only wait on carve+schedule).
        for _ in range(8):
            submit(4)
        wait_until(lambda: running_chips() >= TOTAL)
        fill_lat = sorted(
            bound_at[k] - created_at[k] for k in list(bound_at) if k in created_at
        )
        p50 = statistics.median(fill_lat) if fill_lat else float("nan")
        log(f"phase1 fill: {running_chips()}/{TOTAL} chips running, "
            f"p50 carve+schedule latency {p50*1000:.0f} ms over "
            f"{len(fill_lat)} pods")

        # ---- Phase 2 (headline): steady-state stream. Jobs of mixed slice
        # sizes arrive continuously and auto-finish after 2-5 s (the fill
        # generation after 0.3-1.5 s); the submitter keeps a small pending
        # backlog so demand never starves. Utilization is time-integrated
        # over the steady window (ramp excluded). This is what "dynamic
        # partitioning keeps chips busy" means over hours, compressed to a
        # 20 s toy timeline.
        import random

        rng = random.Random(0)
        STREAM_S = 20.0
        RAMP_S = 2.5
        finish_at: dict = {}
        finished_at: dict = {}  # (ns, name) -> actual finish time
        job_chips: dict = {}
        stream_done = {"n": 0}
        t_stream = time.monotonic()
        # fill-phase jobs become the stream's first generation
        for pod in all_pods():
            if pod.status.phase == PodPhase.RUNNING:
                key = (pod.metadata.namespace, pod.metadata.name)
                finish_at[key] = t_stream + rng.uniform(0.3, 1.5)
                job_chips[key] = res.tpu_chips_in(res.compute_pod_request(pod))
        idle_samples = []  # (t0, t1, idle chips, pending chips)
        t_prev = t_stream
        while time.monotonic() - t_stream < STREAM_S:
            now = time.monotonic()
            running_now = pending_now = 0
            # One store scan per tick: the submitter competes with the
            # control plane for the same (possibly single) core.
            for pod in all_pods():
                key = (pod.metadata.namespace, pod.metadata.name)
                chips_ = job_chips.get(key)
                if chips_ is None:
                    chips_ = res.tpu_chips_in(res.compute_pod_request(pod))
                if pod.status.phase == PodPhase.RUNNING and pod.spec.node_name:
                    bound_at.setdefault(key, now)
                    if now >= finish_at.get(key, now + 1e9):
                        def fin(p):
                            p.status.phase = PodPhase.SUCCEEDED

                        cluster.store.patch_merge(
                            "Pod", pod.metadata.name, pod.metadata.namespace, fin
                        )
                        finished_at[key] = now
                        stream_done["n"] += 1
                    else:
                        running_now += chips_
                elif pod.status.phase == PodPhase.PENDING:
                    pending_now += chips_
            idle_samples.append((t_prev, now, TOTAL - running_now, pending_now))
            t_prev = now
            backlog = pending_now
            # Half a cluster of queued demand: enough that a full-board job
            # draining its reserved node never single-handedly starves the
            # submitter (a loaded cluster's queue is deeper than one job).
            while backlog < 16:
                chips = rng.choice([1, 2, 2, 4, 4, 4, 8])
                name = submit(chips)
                finish_at[("bench", name)] = now + rng.uniform(2.0, 5.0)
                job_chips[("bench", name)] = chips
                backlog += chips
            time.sleep(0.03)
        t_stream_end = time.monotonic()
        running_chips()  # final bound_at refresh for just-bound pods
        # Exact event-based utilization: each job occupies its chips from
        # bind to finish (clipped to the steady window) — no sampling noise.
        w0, w1 = t_stream + RAMP_S, t_stream_end
        busy = 0.0
        for key, chips in job_chips.items():
            b = bound_at.get(key)
            if b is None:
                continue
            f = finished_at.get(key, w1)
            busy += chips * max(0.0, min(f, w1) - max(b, w0))
        util = 100.0 * busy / ((w1 - w0) * TOTAL)
        # Per-second series (diagnosability: where did idle time go?)
        series = []
        for s0 in range(int(w1 - w0)):
            a0, a1 = w0 + s0, min(w0 + s0 + 1, w1)
            sb = sum(
                chips * max(0.0, min(finished_at.get(k, w1), a1) - max(bound_at[k], a0))
                for k, chips in job_chips.items()
                if k in bound_at
            )
            series.append(round(100.0 * sb / ((a1 - a0) * TOTAL)))
        log(f"phase2 stream: {util:.1f}% event-integrated utilization over "
            f"{w1 - w0:.1f}s steady window, {stream_done['n']} jobs "
            f"completed; per-second %: {series}")
        # Per-size bind-wait distribution: how long did jobs of each size
        # pend before binding (the submitter creates them pre-bound only
        # in the fill phase)?
        waits_by_size: dict = {}
        for key, chips in job_chips.items():
            if key in bound_at and key in created_at:
                waits_by_size.setdefault(chips, []).append(
                    bound_at[key] - created_at[key]
                )
        for chips in sorted(waits_by_size):
            ws = sorted(waits_by_size[chips])
            log(
                f"phase2 waits {chips}-chip jobs: n={len(ws)} "
                f"p50={statistics.median(ws):.2f}s max={ws[-1]:.2f}s "
                f"sum={sum(ws):.1f}s "
                f"all={[round(w, 2) for w in ws]}"
            )
        # Idle attribution: idle chip-seconds while pending demand existed
        # (scheduling/carve inefficiency) vs while the submitter's backlog
        # was empty of schedulable demand (workload starvation).
        ineff = starv = 0.0
        for t0, t1, idle, pend in idle_samples:
            dt = max(0.0, min(t1, w1) - max(t0, w0))
            if dt <= 0:
                continue
            covered = min(idle, pend)
            ineff += covered * dt
            starv += (idle - covered) * dt
        denom = (w1 - w0) * TOTAL
        log(
            f"phase2 idle attribution: {100.0 * ineff / denom:.1f}% "
            f"idle-with-pending-demand (scheduling inefficiency), "
            f"{100.0 * starv / denom:.1f}% idle-no-pending-demand "
            f"(submitter starvation)"
        )
        log(
            f"phase2 control events: {m.BOARD_RESERVATIONS.value} board "
            f"reservations, {m.DIVERGENCE_REPLANS.value} divergence "
            f"replans, {m.PLANS_APPLIED.value} plans applied"
        )
        delete_all_pods()

        # ---- Phase 3: contention + quota borrowing + preemption.
        # team-a floods the cluster (borrowing past its min); team-b then
        # claims its guaranteed min, which requires preempting team-a's
        # over-quota pods.
        for _ in range(10):  # 40 chips of demand for 32 chips
            submit(4, ns="team-a")
        borrowed = wait_until(
            lambda: running_chips_by_ns().get("team-a", 0) >= TOTAL
        )
        log(f"phase3a: team-a borrow {'ok' if borrowed else 'TIMED OUT'}: "
            f"{running_chips_by_ns()}")
        for _ in range(4):  # team-b takes back its guaranteed 16
            submit(4, ns="team-b")
        ok = wait_until(
            lambda: running_chips_by_ns().get("team-b", 0) >= TOTAL // 2
        )
        by_ns = running_chips_by_ns()
        preemptions = int(m.PREEMPTIONS.value - preempt_before)
        log(f"phase3b: fair-share rebalance {'ok' if ok else 'TIMED OUT'}: "
            f"{by_ns}, preemptions={preemptions}")
        delete_all_pods()

        # ---- Phase 4: churn — alternate demand shapes, sustained
        # slice-reconfigs/sec (per-node board re-carves). The next wave is
        # submitted before the old one finishes so every freed board is
        # immediately re-carvable.
        plans_before = cluster.partitioner.plans_applied
        nodes_before = cluster.partitioner.nodes_repartitioned
        t_churn = time.monotonic()
        shapes = [(4, 8), (8, 4), (4, 8), (8, 4), (4, 8), (8, 4)]
        churn_ok = True

        def failed_chips() -> int:
            # An OutOfTpu admission rejection is terminal; its job never
            # runs, so the wave's reachable ceiling drops accordingly.
            return sum(
                res.tpu_chips_in(res.compute_pod_request(p))
                for p in all_pods()
                if p.status.phase == PodPhase.FAILED
            )

        for n_pods, chips in shapes:
            for _ in range(n_pods):
                submit(chips)
            finish_all_running()
            churn_ok &= wait_until(
                lambda: running_chips() >= TOTAL - failed_chips(), timeout=15
            )
        churn_s = time.monotonic() - t_churn
        delete_all_pods()
        plans = cluster.partitioner.plans_applied - plans_before
        reconfigs = cluster.partitioner.nodes_repartitioned - nodes_before
        reconfig_rate = reconfigs / churn_s if churn_s > 0 else 0.0
        log(f"phase4 churn: {plans} plans / {reconfigs} board re-carves in "
            f"{churn_s:.1f}s ({reconfig_rate:.2f} reconfigs/sec, "
            f"converged={churn_ok})")

        # ---- Phase 5: multi-host slice. ONE pod asks for the whole
        # cluster (32 chips = a 4x8 ICI slice over all 4 hosts); the
        # expander builds the gang, the planner carves every host, Permit
        # binds atomically. Measured: submission -> whole gang Running.
        from nos_tpu.scheduler.plugins.gang import GANG_NAME_LABEL

        t_mh = time.monotonic()
        big_name = submit(TOTAL, ns="bench")

        def gang_running():
            members = [
                p
                for p in cluster.store.list("Pod", namespace="bench")
                if p.metadata.labels.get(GANG_NAME_LABEL) == big_name
            ]
            return len(members) == N_NODES and all(
                p.status.phase == PodPhase.RUNNING and p.spec.node_name
                for p in members
            )

        multihost_ok = wait_until(gang_running, timeout=30.0)
        multihost_s = time.monotonic() - t_mh
        log(f"phase5 multihost: {TOTAL}-chip request -> {N_NODES}-host gang "
            f"{'RUNNING' if multihost_ok else 'TIMED OUT'} in {multihost_s:.1f}s")

        out = {
            "utilization_pct": round(util, 2),
            "p50_schedule_latency_ms": round(p50 * 1000, 1),
            "stream_jobs_completed": stream_done["n"],
            "pods_created": counter["n"],
            "slice_reconfigs_per_sec": round(reconfig_rate, 2),
            "plans_applied": cluster.partitioner.plans_applied,
            "preemptions": preemptions,
            "borrow_converged": bool(borrowed),
            "fair_share_restored": bool(ok and borrowed),
            "admission_rejects": getattr(cluster.kubelet, "admission_rejects", 0),
            "multihost_gang_formed": bool(multihost_ok),
            "multihost_time_to_running_s": round(multihost_s, 2),
        }
        return out
    finally:
        cluster.stop()


def main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    if "--tpu-child" in sys.argv:
        run_tpu_child()
        return
    if "--probe-child" in sys.argv:
        run_probe_child()
        return
    tpu = {} if "--control-plane-only" in sys.argv else run_tpu_bench_subprocess()
    cp = run_control_plane_bench()
    util = cp.get("utilization_pct", 0.0)
    line = {
        "metric": "tpu_chip_utilization",
        "value": util,
        "unit": "%",
        "vs_baseline": round(util / 90.0, 4),
    }
    for k, v in cp.items():
        if k != "utilization_pct":
            line[k] = v
    for k, v in tpu.items():
        line[f"tpu_{k}"] = v
    print(json.dumps(line))


if __name__ == "__main__":
    main()
