"""Planner microbenchmark: plan() latency + fork throughput, CPU-only.

Synthetic clusters (no real TPU, no kube apiserver): N v5e nodes in mixed
fill states × P pending pods drawn from a realistic request mix. Each
iteration builds a fresh snapshot (plan() mutates it) and times one
plan() call. Two snapshot engines:

  cow       — the journaled copy-on-write ClusterSnapshot (default engine)
  deepcopy  — DeepcopyClusterSnapshot, the pre-CoW semantics (full node-map
              deepcopy per fork, cluster-walk free pool), kept in-tree as
              the measurable baseline

The cow engine additionally runs in two verdict-cache modes (on/off, the
planner's ``verdict_cache_enabled`` knob), so the equivalence-class filter
cache's contribution is measured separately from the CoW fork win; cached
rows carry the hit/miss/bypass tallies. The deepcopy engine always runs
cache-off (it exists to show the pre-optimization cost) and is skipped
entirely at >= 1024 nodes, where a single plan() takes minutes.

Output: one JSON line per (engine, cache mode, nodes, pods) config with
p50/p95 plan latency (ms) and forks/sec, e.g.

  make bench-planner
  python bench_planner.py --quick
  python bench_planner.py --output BENCH_planner.json
"""
from __future__ import annotations

import argparse
import json
import statistics
import time

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants
from nos_tpu.partitioning.core import ClusterSnapshot, DeepcopyClusterSnapshot, Planner, SnapshotNode
from nos_tpu.scheduler.framework import Framework, NodeResourcesFit, NodeSelectorFit
from nos_tpu.api.v1alpha1 import labels
from nos_tpu.kube.objects import Container, Node, NodeStatus, ObjectMeta, Pod, PodSpec
from nos_tpu.tpu.node import TpuNode

V5E = "tpu-v5-lite-podslice"
ENGINES = {"cow": ClusterSnapshot, "deepcopy": DeepcopyClusterSnapshot}


def build_node(name: str, annotations=None) -> Node:
    alloc = {constants.RESOURCE_TPU: 8, "cpu": 8, "memory": 128}
    return Node(
        metadata=ObjectMeta(
            name=name,
            labels={
                labels.GKE_TPU_ACCELERATOR_LABEL: V5E,
                labels.GKE_TPU_TOPOLOGY_LABEL: "2x4",
                labels.PARTITIONING_LABEL: "tpu",
            },
            annotations=annotations or {},
        ),
        status=NodeStatus(capacity=dict(alloc), allocatable=dict(alloc)),
    )


def build_pod(name: str, requests: dict) -> Pod:
    return Pod(
        metadata=ObjectMeta(name=name, namespace="bench"),
        spec=PodSpec(
            containers=[Container(requests=dict(requests))],
            scheduler_name=constants.SCHEDULER_NAME,
        ),
    )


def make_cluster(n_nodes: int, snapshot_cls):
    """Deterministic mixed-fill cluster: 1/3 virgin boards, 1/3 with one
    free 2x2, 1/3 half-used — enough fragmentation that the planner forks
    real carve trials instead of shortcutting."""
    nodes = {}
    for i in range(n_nodes):
        style = i % 3
        if style == 0:
            ann = None
        elif style == 1:
            ann = annot.status_from_devices(free={0: {"2x2": 1}}, used={})
        else:
            ann = annot.status_from_devices(free={}, used={0: {"2x2": 1, "1x1": 2}})
        name = f"node-{i:04d}"
        nodes[name] = SnapshotNode(partitionable=TpuNode(build_node(name, ann)))
    return snapshot_cls(nodes)


def make_pending(n_pods: int):
    """Request mix: small slices, board slices, plain chips — and demand
    deliberately exceeding supply so the carve loop runs to exhaustion
    (the worst-case path the latency target is about)."""
    mixes = [
        {constants.tpu_slice_resource("1x1"): 1},
        {constants.tpu_slice_resource("2x2"): 1},
        {constants.tpu_slice_resource("2x4"): 1},
        {constants.RESOURCE_TPU: 4},
        {constants.RESOURCE_TPU: 1},
    ]
    return [build_pod(f"pend-{i:04d}", mixes[i % len(mixes)]) for i in range(n_pods)]


def bench_config(
    engine: str, n_nodes: int, n_pods: int, repeats: int, cache_on: bool = True
) -> dict:
    snapshot_cls = ENGINES[engine]
    latencies = []
    forks = 0
    hits = misses = bypasses = 0
    for rep in range(repeats + 1):  # rep 0 is untimed warm-up
        snapshot = make_cluster(n_nodes, snapshot_cls)
        # Count forks engine-independently (the deepcopy baseline skips the
        # CoW metrics counters by design).
        if rep > 0:
            inner_fork = snapshot.fork

            def counting_fork(inner_fork=inner_fork):
                nonlocal forks
                forks += 1
                inner_fork()

            snapshot.fork = counting_fork
        planner = Planner(
            Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()]),
            verdict_cache_enabled=cache_on,
        )
        pods = make_pending(n_pods)
        started = time.perf_counter()
        planner.plan(snapshot, pods)
        if rep > 0:
            latencies.append(time.perf_counter() - started)
            h, m, b = planner.verdict_cache_stats()
            hits, misses, bypasses = hits + h, misses + m, bypasses + b
    total = sum(latencies)
    quantiles = (
        statistics.quantiles(latencies, n=20) if len(latencies) > 1 else latencies * 2
    )
    row = {
        "bench": "bench_planner",
        "engine": engine,
        "verdict_cache": "on" if cache_on else "off",
        "nodes": n_nodes,
        "pending_pods": n_pods,
        "repeats": repeats,
        "p50_plan_ms": round(statistics.median(latencies) * 1e3, 2),
        "p95_plan_ms": round(quantiles[-1] * 1e3, 2),
        "forks_per_sec": round(forks / total, 1) if total else None,
        "forks_total": forks,
    }
    if cache_on:
        eligible = hits + misses
        row["cache_hits"] = hits
        row["cache_misses"] = misses
        row["cache_bypasses"] = bypasses
        row["cache_hit_rate"] = round(hits / eligible, 4) if eligible else None
    return row


def export_sample_trace(path: str) -> None:
    """One traced plan() over the 16x50 config, exported as Chrome
    trace-event JSON — the 'open this in Perfetto' artifact next to the
    latency numbers."""
    from nos_tpu.util.tracing import TRACER

    TRACER.reset()
    snapshot = make_cluster(16, ClusterSnapshot)
    planner = Planner(
        Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()])
    )
    planner.plan(snapshot, make_pending(50))
    traces = TRACER.store.list()
    if not traces:
        return
    with open(path, "w") as fh:
        json.dump(traces[0].to_chrome(), fh, indent=2)
    print(f"sample trace -> {path}", flush=True)


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--engines", default="cow,deepcopy")
    parser.add_argument(
        "--configs",
        default="16x50,64x200,256x400,1024x800",
        help="comma-separated nodesxpods pairs",
    )
    parser.add_argument("--repeats", type=int, default=5)
    parser.add_argument("--quick", action="store_true", help="16x50 only, 2 repeats")
    parser.add_argument("--output", default="", help="also append JSON lines to file")
    parser.add_argument(
        "--trace-output",
        default="",
        help="write a sample plan() trace (Chrome trace-event JSON) here; "
        "defaults to <output-stem>_trace.json when --output is set",
    )
    args = parser.parse_args()

    configs = [tuple(map(int, c.split("x"))) for c in args.configs.split(",")]
    repeats = args.repeats
    if args.quick:
        configs, repeats = [(16, 50)], 2

    results = []
    for engine in args.engines.split(","):
        # cow runs with the verdict cache on AND off (the off rows are the
        # like-for-like before/after for the cache); deepcopy is the
        # pre-everything baseline and only runs cache-off.
        cache_modes = (True, False) if engine == "cow" else (False,)
        for n_nodes, n_pods in configs:
            if engine == "deepcopy" and n_nodes >= 1024:
                # A single deepcopy plan() at 1024 nodes takes minutes —
                # the collapse is already documented by the 256-node row.
                continue
            # The deepcopy baseline at full scale is exactly the collapse
            # this bench exists to document; cap its largest run so the
            # suite still finishes.
            reps = repeats if not (engine == "deepcopy" and n_nodes >= 256) else max(
                1, repeats // 2
            )
            for cache_on in cache_modes:
                result = bench_config(engine, n_nodes, n_pods, reps, cache_on)
                results.append(result)
                print(json.dumps(result), flush=True)

    raw = list(results)
    for a in raw:
        if not (a["engine"] == "cow" and a["verdict_cache"] == "on" and a["p50_plan_ms"]):
            continue
        for b in raw:
            if (a["nodes"], a["pending_pods"]) != (b["nodes"], b["pending_pods"]):
                continue
            if b["engine"] == "deepcopy":
                speedup = {
                    "bench": "bench_planner_speedup",
                    "nodes": a["nodes"],
                    "pending_pods": a["pending_pods"],
                    "p50_speedup": round(b["p50_plan_ms"] / a["p50_plan_ms"], 2),
                }
                results.append(speedup)
                print(json.dumps(speedup), flush=True)
            elif b["engine"] == "cow" and b["verdict_cache"] == "off":
                speedup = {
                    "bench": "bench_planner_cache_speedup",
                    "nodes": a["nodes"],
                    "pending_pods": a["pending_pods"],
                    "p50_speedup": round(b["p50_plan_ms"] / a["p50_plan_ms"], 2),
                    "cache_hit_rate": a.get("cache_hit_rate"),
                }
                results.append(speedup)
                print(json.dumps(speedup), flush=True)

    if args.output:
        with open(args.output, "a") as fh:
            for result in results:
                fh.write(json.dumps(result) + "\n")
    trace_path = args.trace_output
    if not trace_path and args.output:
        stem = args.output[:-5] if args.output.endswith(".json") else args.output
        trace_path = f"{stem}_trace.json"
    if trace_path:
        export_sample_trace(trace_path)


if __name__ == "__main__":
    main()
