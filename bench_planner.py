"""Planner microbenchmark: plan() latency + fork throughput, CPU-only.

Synthetic clusters (no real TPU, no kube apiserver): N v5e nodes in mixed
fill states × P pending pods drawn from a realistic request mix. Each
iteration builds a fresh snapshot (plan() mutates it) and times one
plan() call. Two snapshot engines:

  cow       — the journaled copy-on-write ClusterSnapshot (default engine)
  deepcopy  — DeepcopyClusterSnapshot, the pre-CoW semantics (full node-map
              deepcopy per fork, cluster-walk free pool), kept in-tree as
              the measurable baseline

The cow engine additionally runs in two verdict-cache modes (on/off, the
planner's ``verdict_cache_enabled`` knob), so the equivalence-class filter
cache's contribution is measured separately from the CoW fork win; cached
rows carry the hit/miss/bypass tallies. The deepcopy engine always runs
cache-off (it exists to show the pre-optimization cost) and is skipped
entirely at >= 1024 nodes, where a single plan() takes minutes.

``--plan-mode incremental`` measures steady-state REPLANS instead of cold
plans: one persistent snapshot + planner across cycles, each cycle
dirtying ``--churn`` of the nodes through ``refresh_node`` and replanning
with the dirty set (the partitioner controller's incremental path), over
a fragmented cluster whose pending residue is mostly unservable — the
regime a production partitioner spends its life in.

``--plan-mode sharded`` measures the pool-sharded pipeline at ``--pools``
pools (nodes labeled, pods selector-pinned round-robin): per-pool
steady-state replans + the cross-pool merge, under ``--parallel``
serial/thread/process execution (``both`` = serial+thread, ``all`` adds
process). Every mode is timed — on a single core under the GIL threads
buy nothing for this pure-Python workload, and spawned workers ADD frame
codec + pipe overhead; the rows carry a ``cpus`` field so the numbers
read honestly on the box that produced them instead of assuming a
many-core deployment. Process rows run the real ``PoolWorkerPool``
delta protocol (bootstrap from a full wire image, dirty-node deltas per
cycle, touched-boards replies overlaid on the parent mirror). The mode
also emits the sharded-vs-unsharded byte-identity oracle row and the
warm-boot restart bench (persisted memo adoption vs a from-scratch cold
plan).

Output: one JSON line per (engine, cache mode, nodes, pods) config with
p50/p95 plan latency (ms) and forks/sec, e.g.

  make bench-planner
  python bench_planner.py --quick
  python bench_planner.py --output BENCH_planner.json
  python bench_planner.py --plan-mode incremental --churn 0.05
"""
from __future__ import annotations

import argparse
import gc
import json
import os
import statistics
import time

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants
from nos_tpu.partitioning.core import ClusterSnapshot, DeepcopyClusterSnapshot, Planner, SnapshotNode
from nos_tpu.scheduler.framework import Framework, NodeResourcesFit, NodeSelectorFit
from nos_tpu.api.v1alpha1 import labels
from nos_tpu.api.v1alpha1.labels import GKE_NODEPOOL_LABEL
from nos_tpu.kube.objects import Container, Node, NodeStatus, ObjectMeta, Pod, PodSpec
from nos_tpu.tpu.node import TpuNode

V5E = "tpu-v5-lite-podslice"
ENGINES = {"cow": ClusterSnapshot, "deepcopy": DeepcopyClusterSnapshot}


def build_node(name: str, annotations=None, pool: str = "") -> Node:
    alloc = {constants.RESOURCE_TPU: 8, "cpu": 8, "memory": 128}
    node_labels = {
        labels.GKE_TPU_ACCELERATOR_LABEL: V5E,
        labels.GKE_TPU_TOPOLOGY_LABEL: "2x4",
        labels.PARTITIONING_LABEL: "tpu",
    }
    if pool:
        node_labels[GKE_NODEPOOL_LABEL] = pool
    return Node(
        metadata=ObjectMeta(
            name=name,
            labels=node_labels,
            annotations=annotations or {},
        ),
        status=NodeStatus(capacity=dict(alloc), allocatable=dict(alloc)),
    )


def build_pod(name: str, requests: dict, pool: str = "") -> Pod:
    spec = PodSpec(
        containers=[Container(requests=dict(requests))],
        scheduler_name=constants.SCHEDULER_NAME,
    )
    if pool:
        spec.node_selector[GKE_NODEPOOL_LABEL] = pool
    return Pod(metadata=ObjectMeta(name=name, namespace="bench"), spec=spec)


def node_name(i: int) -> str:
    return f"node-{i:05d}"


def pool_of(i: int, pools: int) -> str:
    return f"pool-{i % pools}" if pools else ""


def build_cluster(n_nodes: int, ann_of, snapshot_cls=ClusterSnapshot, pools: int = 0):
    """The one cluster builder every bench mode seeds from: ``ann_of(i)``
    supplies node i's slice-state annotations, ``pools`` > 0 labels nodes
    pool-{i %% pools} round-robin (the sharded bench's partition seeds)."""
    nodes = {}
    for i in range(n_nodes):
        name = node_name(i)
        nodes[name] = SnapshotNode(
            partitionable=TpuNode(build_node(name, ann_of(i), pool=pool_of(i, pools)))
        )
    return snapshot_cls(nodes)


def mixed_fill_annotations(i: int):
    """1/3 virgin boards, 1/3 with one free 2x2, 1/3 half-used — enough
    fragmentation that the planner forks real carve trials instead of
    shortcutting."""
    style = i % 3
    if style == 0:
        return None
    if style == 1:
        return annot.status_from_devices(free={0: {"2x2": 1}}, used={})
    return annot.status_from_devices(free={}, used={0: {"2x2": 1, "1x1": 2}})


def make_cluster(n_nodes: int, snapshot_cls):
    return build_cluster(n_nodes, mixed_fill_annotations, snapshot_cls)


def make_pending(n_pods: int):
    """Request mix: small slices, board slices, plain chips — and demand
    deliberately exceeding supply so the carve loop runs to exhaustion
    (the worst-case path the latency target is about)."""
    mixes = [
        {constants.tpu_slice_resource("1x1"): 1},
        {constants.tpu_slice_resource("2x2"): 1},
        {constants.tpu_slice_resource("2x4"): 1},
        {constants.RESOURCE_TPU: 4},
        {constants.RESOURCE_TPU: 1},
    ]
    return [build_pod(f"pend-{i:04d}", mixes[i % len(mixes)]) for i in range(n_pods)]


def steady_annotations(variant: bool):
    """One fragmented node's slice state for the steady-state bench: a
    used 2x2 pins the board (no full-board carve can ever succeed) while
    free 1x1 slices keep the node in the candidate set. The two variants
    differ in their free/used 1x1 split so a churn refresh is a real
    geometry change."""
    if variant:
        return annot.status_from_devices(
            free={0: {"1x1": 1}}, used={0: {"2x2": 1, "1x1": 1}}
        )
    return annot.status_from_devices(free={0: {"1x1": 2}}, used={0: {"2x2": 1}})


def build_steady_node(name: str, variant: bool, pool: str = "") -> SnapshotNode:
    return SnapshotNode(
        partitionable=TpuNode(build_node(name, steady_annotations(variant), pool=pool))
    )


def make_steady_cluster(n_nodes: int, pools: int = 0) -> ClusterSnapshot:
    return build_cluster(n_nodes, lambda i: steady_annotations(False), pools=pools)


def make_steady_pending(n_pods: int, pools: int = 0):
    """Steady-state residue: mostly board-sized requests no fragmented
    node can ever serve (every carve provably futile — the futility memo
    carries the replan) plus ~10%% small slices the free pool claims each
    cycle (exercising the claim pre-pass and cross-cycle verdict reuse).
    With ``pools`` > 0 each pod is selector-pinned round-robin so the
    partition stays pool-independent (no multi-pool selector edges)."""
    mixes = [{constants.tpu_slice_resource("2x4"): 1}] * 9 + [
        {constants.tpu_slice_resource("1x1"): 1}
    ]
    return [
        build_pod(f"pend-{i:04d}", mixes[i % len(mixes)], pool=pool_of(i, pools))
        for i in range(n_pods)
    ]


def capacity_row(snapshot, n_nodes: int, n_pods: int, churn: float) -> dict:
    """Steady-state capacity shape of the churned cluster, measured with
    the capacity ledger's fragmentation helpers over each node's final
    slice-state annotations: the cluster fragmentation index (1 - largest
    free slice / largest satisfiable ask) and the utilization the churn
    regime settles into — the same numbers `/debug/capacity` reports for
    a live cluster. The old free-chip-weighted mean of per-node indices
    read 0.0 exactly when every node was down to slivers — the most
    fragmented state a cluster can reach."""
    from nos_tpu.capacity import (
        cluster_fragmentation_index,
        fragmentation_from_annotations,
        largest_profile_chips,
    )

    capacity = free_total = largest_any = 0
    for snap_node in snapshot.get_nodes().values():
        node = snap_node.partitionable.node
        capacity += int(node.status.capacity.get(constants.RESOURCE_TPU, 0))
        _, largest, free = fragmentation_from_annotations(
            node.metadata.annotations, V5E
        )
        free_total += free
        largest_any = max(largest_any, largest)
    index = cluster_fragmentation_index(
        free_total, largest_any, largest_profile_chips(V5E)
    )
    return {
        "bench": "bench_capacity",
        "nodes": n_nodes,
        "pending_pods": n_pods,
        "churn": churn,
        "capacity_chips": capacity,
        "free_chips": free_total,
        "steady_state_utilization": round(1 - free_total / capacity, 4)
        if capacity
        else None,
        "fragmentation_index": round(index, 4),
        "largest_free_slice_chips": largest_any,
    }


def bench_incremental(
    n_nodes: int, n_pods: int, repeats: int, churn: float = 0.05
) -> list:
    """Steady-state replans over ONE persistent snapshot + planner: an
    untimed cold plan (fallback mode — builds the caches at base
    versions), then `repeats` timed cycles, each dirtying `churn` of the
    nodes via refresh_node before replanning with the dirty set. Every
    timed cycle must take the incremental path."""
    snapshot = make_steady_cluster(n_nodes)
    planner = Planner(
        Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()])
    )
    pods = make_steady_pending(n_pods)
    all_names = set(snapshot.get_nodes())
    started = time.perf_counter()
    planner.plan(snapshot, pods, dirty=all_names)
    cold_ms = (time.perf_counter() - started) * 1e3
    if planner.last_plan_mode != "fallback":
        raise RuntimeError(f"cold plan mode {planner.last_plan_mode!r}")
    k = max(1, int(n_nodes * churn)) if churn > 0 else 0
    variant: dict = {}
    latencies = []
    for cycle in range(repeats + 1):  # cycle 0 is untimed warm-up
        dirty = set()
        for j in range(k):
            name = node_name((cycle * k + j) % n_nodes)
            variant[name] = not variant.get(name, False)
            snapshot.refresh_node(name, build_steady_node(name, variant[name]))
            dirty.add(name)
        t0 = time.perf_counter()
        planner.plan(snapshot, pods, dirty=dirty)
        if cycle > 0:
            latencies.append(time.perf_counter() - t0)
        if planner.last_plan_mode != "incremental":
            raise RuntimeError(f"replan mode {planner.last_plan_mode!r}")
    quantiles = (
        statistics.quantiles(latencies, n=20) if len(latencies) > 1 else latencies * 2
    )
    hits, misses, bypasses = planner.verdict_cache_stats()
    eligible = hits + misses
    row = {
        "bench": "bench_planner_incremental",
        "engine": "cow",
        "plan_mode": "incremental",
        "nodes": n_nodes,
        "pending_pods": n_pods,
        "churn": churn,
        "dirty_per_cycle": k,
        "cycles": repeats,
        "cold_plan_ms": round(cold_ms, 2),
        "p50_replan_ms": round(statistics.median(latencies) * 1e3, 2),
        "p95_replan_ms": round(quantiles[-1] * 1e3, 2),
        "replan_speedup_vs_cold": round(
            cold_ms / (statistics.median(latencies) * 1e3), 1
        ),
        "futility_hits_last_cycle": planner._futility_hits,
        "cache_hit_rate_last_cycle": round(hits / eligible, 4) if eligible else None,
    }
    return [row, capacity_row(snapshot, n_nodes, n_pods, churn)]


def _framework():
    return Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()])


def _ages(pods):
    return {p.namespaced_name: 0.0 for p in pods}


def bench_sharded(
    n_nodes: int,
    n_pods: int,
    repeats: int,
    pools: int,
    churn: float = 0.05,
    parallelism: str = "serial",
) -> dict:
    """Steady-state replans through the pool-sharded pipeline: one
    persistent per-pool snapshot + planner per pool, each cycle dirtying
    ``churn`` of the nodes in their owning pool snapshots, replanning
    every pool (serial or ThreadPoolExecutor — both are measured so the
    GIL story is told honestly, not assumed), then the deterministic
    merge + cross-pool invariant check the controller runs before
    actuation. The timed cycle is the WHOLE sharded pipeline, merge
    included. ``process`` runs the same pipeline through real spawned
    pool workers (see bench_sharded_process)."""
    if parallelism == "process":
        return bench_sharded_process(n_nodes, n_pods, repeats, pools, churn)
    from nos_tpu.partitioning.core.pools import (
        check_merge_invariants,
        merge_pool_states,
        node_capacities,
        partition_pools,
        run_pool_plans,
        split_pending,
        split_snapshot,
    )

    snapshot = make_steady_cluster(n_nodes, pools=pools)
    pending = make_steady_pending(n_pods, pools=pools)
    ages = _ages(pending)
    partition = partition_pools(snapshot, pending)
    if len(partition.pools) != pools:
        raise RuntimeError(
            f"expected {pools} pools, partitioned into {partition.pools}"
        )
    pool_snaps = split_snapshot(snapshot, partition)
    pool_pending = split_pending(pending, partition)
    planners = {pool: Planner(_framework()) for pool in partition.pools}
    capacities = node_capacities(pool_snaps.values())

    def cold_task(pool):
        def task():
            planners[pool].plan(
                pool_snaps[pool],
                pool_pending[pool],
                dirty=set(pool_snaps[pool].get_nodes()),
                pending_ages=ages,
            )

        return task

    started = time.perf_counter()
    run_pool_plans({p: cold_task(p) for p in partition.pools}, parallelism)
    cold_ms = (time.perf_counter() - started) * 1e3
    k = max(1, int(n_nodes * churn)) if churn > 0 else 0
    variant: dict = {}
    latencies, merge_latencies, gc_pauses = [], [], []
    for cycle in range(repeats + 1):  # cycle 0 is untimed warm-up
        pool_dirty = {pool: set() for pool in partition.pools}
        for j in range(k):
            i = (cycle * k + j) % n_nodes
            name = node_name(i)
            variant[name] = not variant.get(name, False)
            pool = partition.node_pool[name]
            pool_snaps[pool].refresh_node(
                name, build_steady_node(name, variant[name], pool=pool_of(i, pools))
            )
            pool_dirty[pool].add(name)

        def make_task(pool):
            def task():
                # Pre-plan state first: plan() commits carves into its
                # base, and the merge check + actuation baseline need the
                # observed state.
                current = pool_snaps[pool].partitioning_state()
                desired = planners[pool].plan(
                    pool_snaps[pool],
                    pool_pending[pool],
                    dirty=pool_dirty[pool],
                    pending_ages=ages,
                )
                return current, desired

            return task

        t0 = time.perf_counter()
        outcomes = run_pool_plans(
            {p: make_task(p) for p in partition.pools}, parallelism
        )
        t1 = time.perf_counter()
        pool_current = {p: cur for p, (cur, _) in outcomes.items()}
        pool_desired = {p: des for p, (_, des) in outcomes.items()}
        violations = check_merge_invariants(
            partition, pool_current, pool_desired, capacities=capacities
        )
        merge_pool_states(pool_desired)
        t2 = time.perf_counter()
        if violations:
            raise RuntimeError(f"merge invariants failed: {violations[:3]}")
        for pool, planner in planners.items():
            if planner.last_plan_mode != "incremental":
                raise RuntimeError(
                    f"pool {pool} replan mode {planner.last_plan_mode!r}"
                )
        if cycle > 0:
            latencies.append(t2 - t0)
            merge_latencies.append(t2 - t1)
        # Gen-2 collection of a 16k-65k-node heap is a multi-hundred-ms
        # pause that auto-triggers in exactly ONE of these five cycles —
        # whichever mode it lands on "regresses" its p95 by GC roulette,
        # which is how the committed thread-617ms-vs-serial-409ms mystery
        # row happened. Collect between cycles instead, outside the timed
        # window, and report the pause as its own measured column so the
        # replan percentiles compare plan work across modes while the GC
        # bill stays on the books.
        t_gc = time.perf_counter()
        gc.collect()
        gc_pauses.append(time.perf_counter() - t_gc)
    quantiles = (
        statistics.quantiles(latencies, n=20) if len(latencies) > 1 else latencies * 2
    )
    return {
        "bench": "bench_planner_sharded",
        "plan_mode": "sharded",
        "nodes": n_nodes,
        "pending_pods": n_pods,
        "pools": pools,
        "parallelism": parallelism,
        "churn": churn,
        "dirty_per_cycle": k,
        "cycles": repeats,
        "cold_plan_ms": round(cold_ms, 2),
        "p50_replan_ms": round(statistics.median(latencies) * 1e3, 2),
        "p95_replan_ms": round(quantiles[-1] * 1e3, 2),
        "p50_merge_ms": round(statistics.median(merge_latencies) * 1e3, 3),
        "gc_p50_pause_ms": round(statistics.median(gc_pauses) * 1e3, 2),
        "gc_max_pause_ms": round(max(gc_pauses) * 1e3, 2),
        "cpus": os.cpu_count(),
    }


def bench_sharded_process(
    n_nodes: int,
    n_pods: int,
    repeats: int,
    pools: int,
    churn: float = 0.05,
) -> dict:
    """The sharded pipeline through the REAL multi-process backend: one
    spawned worker per pool (``partitioning/core/procpool.py``),
    bootstrapped from a full wire image, then delta-fed per cycle exactly
    as the controller feeds it — dirty-node wire entries + pending pods +
    parent-stamped ages out, touched-boards replies back, overlaid on the
    parent's desired mirror, then the same merge + invariant check. The
    timed cycle spans frame encode through merge, so the row prices the
    transport honestly; ``cold_plan_ms`` includes the bootstrap (shipping
    the wire image is part of what a cold start costs here), broken out
    as ``bootstrap_ms``."""
    from nos_tpu.kube.serde import pod_to_wire
    from nos_tpu.partitioning.core.partition_state import (
        partitioning_state_from_dict,
    )
    from nos_tpu.partitioning.core.pools import (
        check_merge_invariants,
        merge_pool_states,
        node_capacities,
        partition_pools,
        split_pending,
        split_snapshot,
    )
    from nos_tpu.partitioning.core.procpool import (
        PoolWorkerPool,
        framework_spec,
        planner_knobs,
        snapshot_node_to_wire,
    )

    snapshot = make_steady_cluster(n_nodes, pools=pools)
    pending = make_steady_pending(n_pods, pools=pools)
    partition = partition_pools(snapshot, pending)
    if len(partition.pools) != pools:
        raise RuntimeError(
            f"expected {pools} pools, partitioned into {partition.pools}"
        )
    pool_snaps = split_snapshot(snapshot, partition)
    pool_pending = split_pending(pending, partition)
    capacities = node_capacities(pool_snaps.values())
    spec = framework_spec(_framework())
    if spec is None:
        raise RuntimeError("bench framework is not distributable")
    worker_pool = PoolWorkerPool(
        kind="tpu",
        slice_codec_name=type(snapshot.codec).__name__,
        spec=spec,
        knobs=planner_knobs(Planner(_framework())),
        # Generous deadlines: the bench prices the protocol, it does not
        # assert liveness — a loaded CI box must not flake it.
        cycle_timeout_seconds=600.0,
        bootstrap_timeout_seconds=600.0,
    )
    try:
        started = time.perf_counter()
        worker_pool.sync_pools(partition.pools)
        for pool in sorted(partition.pools):
            entries = [
                snapshot_node_to_wire(snap_node)
                for _, snap_node in sorted(pool_snaps[pool].get_nodes().items())
            ]
            worker_pool.bootstrap(pool, entries, [])
        bootstrap_ms = (time.perf_counter() - started) * 1e3

        def requests_for(pool_deltas):
            return {
                pool: {
                    "deltas": pool_deltas.get(pool, []),
                    "pending": [pod_to_wire(p) for p in pool_pending[pool]],
                    "ages": {
                        p.namespaced_name: 0.0 for p in pool_pending[pool]
                    },
                    "external_usage": {},
                }
                for pool in partition.pools
            }

        # Cold cycle: workers plan their whole freshly-bootstrapped pools.
        started = time.perf_counter()
        replies = worker_pool.plan_cycle(requests_for({}))
        cold_ms = bootstrap_ms + (time.perf_counter() - started) * 1e3
        mirror = {}
        for pool in partition.pools:
            reply = replies[pool]
            if not isinstance(reply, dict):
                raise RuntimeError(f"pool {pool} cold cycle failed: {reply}")
            desired = dict(pool_snaps[pool].partitioning_state())
            desired.update(partitioning_state_from_dict(reply["touched"]))
            mirror[pool] = desired

        k = max(1, int(n_nodes * churn)) if churn > 0 else 0
        variant: dict = {}
        latencies, merge_latencies, gc_pauses = [], [], []
        for cycle in range(repeats + 1):  # cycle 0 is untimed warm-up
            pool_deltas = {pool: [] for pool in partition.pools}
            for j in range(k):
                i = (cycle * k + j) % n_nodes
                name = node_name(i)
                variant[name] = not variant.get(name, False)
                pool = partition.node_pool[name]
                refreshed = build_steady_node(
                    name, variant[name], pool=pool_of(i, pools)
                )
                pool_snaps[pool].refresh_node(name, refreshed)
                pool_deltas[pool].append(snapshot_node_to_wire(refreshed))
            t0 = time.perf_counter()
            replies = worker_pool.plan_cycle(requests_for(pool_deltas))
            t1 = time.perf_counter()
            pool_desired = {}
            for pool in partition.pools:
                reply = replies[pool]
                if not isinstance(reply, dict):
                    raise RuntimeError(f"pool {pool} cycle failed: {reply}")
                if cycle > 0 and reply["plan_mode"] != "incremental":
                    raise RuntimeError(
                        f"pool {pool} replan mode {reply['plan_mode']!r}"
                    )
                mirror[pool].update(
                    partitioning_state_from_dict(reply["touched"])
                )
                pool_desired[pool] = dict(mirror[pool])
            pool_current = {
                pool: pool_snaps[pool].partitioning_state()
                for pool in partition.pools
            }
            violations = check_merge_invariants(
                partition, pool_current, pool_desired, capacities=capacities
            )
            merge_pool_states(pool_desired)
            t2 = time.perf_counter()
            if violations:
                raise RuntimeError(
                    f"merge invariants failed: {violations[:3]}"
                )
            if cycle > 0:
                latencies.append(t2 - t0)
                merge_latencies.append(t2 - t1)
            # Same untimed between-cycle collect as the serial/thread
            # rows (see bench_sharded): this prices the PARENT's GC like
            # theirs; worker-heap pauses are inherently part of the reply
            # RTT and stay inside the timed cycle.
            t_gc = time.perf_counter()
            gc.collect()
            gc_pauses.append(time.perf_counter() - t_gc)
    finally:
        worker_pool.close()
    quantiles = (
        statistics.quantiles(latencies, n=20) if len(latencies) > 1 else latencies * 2
    )
    return {
        "bench": "bench_planner_sharded",
        "plan_mode": "sharded",
        "nodes": n_nodes,
        "pending_pods": n_pods,
        "pools": pools,
        "parallelism": "process",
        "churn": churn,
        "dirty_per_cycle": k,
        "cycles": repeats,
        "cold_plan_ms": round(cold_ms, 2),
        "bootstrap_ms": round(bootstrap_ms, 2),
        "p50_replan_ms": round(statistics.median(latencies) * 1e3, 2),
        "p95_replan_ms": round(quantiles[-1] * 1e3, 2),
        "p50_merge_ms": round(statistics.median(merge_latencies) * 1e3, 3),
        "gc_p50_pause_ms": round(statistics.median(gc_pauses) * 1e3, 2),
        "gc_max_pause_ms": round(max(gc_pauses) * 1e3, 2),
        "cpus": os.cpu_count(),
    }


def bench_sharded_equivalence(n_nodes: int, n_pods: int, pools: int) -> dict:
    """Byte-identity oracle row: on pool-independent inputs (every pod
    selector-pinned, draw_decomposes holds) the merged sharded plan must
    equal the unsharded planner's output byte for byte."""
    from nos_tpu.partitioning.core.partition_state import (
        partitioning_state_to_dict,
    )
    from nos_tpu.partitioning.core.pools import (
        draw_decomposes,
        merge_pool_states,
        partition_pools,
        split_pending,
        split_snapshot,
    )

    pending = make_steady_pending(n_pods, pools=pools)
    ages = _ages(pending)
    unsharded = Planner(_framework()).plan(
        make_steady_cluster(n_nodes, pools=pools), list(pending), pending_ages=ages
    )
    snapshot = make_steady_cluster(n_nodes, pools=pools)
    partition = partition_pools(snapshot, pending)
    decomposes = draw_decomposes(snapshot, partition, pending)
    pool_snaps = split_snapshot(snapshot, partition)
    pool_pending = split_pending(pending, partition)
    pool_desired = {
        pool: Planner(_framework()).plan(
            pool_snaps[pool], pool_pending[pool], pending_ages=ages
        )
        for pool in partition.pools
    }
    sharded = merge_pool_states(pool_desired)

    def state_bytes(state):
        return json.dumps(partitioning_state_to_dict(state), sort_keys=True)

    return {
        "bench": "bench_planner_sharded_equivalence",
        "nodes": n_nodes,
        "pending_pods": n_pods,
        "pools": len(partition.pools),
        "draw_decomposes": decomposes,
        "byte_identical": state_bytes(sharded) == state_bytes(unsharded),
    }


def bench_warm_boot(n_nodes: int, n_pods: int, repeats: int = 3) -> dict:
    """Restart economics, median of ``repeats`` fresh worlds: a
    from-scratch cold plan vs a restart that adopts persisted warm state
    (signature-matched futility/verdict memos) and replans only the
    unmatched residue. ``warm_plan_speedup_vs_cold`` is the headline —
    the restart's first plan, which would otherwise be the cold plan —
    and the one-time adoption cost (file load + per-node signature
    verification) is reported separately as part of the honest restart
    total. The warm plan's bytes must equal the from-scratch plan's."""
    import os
    import tempfile

    from nos_tpu.partitioning.core.partition_state import (
        partitioning_state_to_dict,
    )
    from nos_tpu.partitioning.core.snapcodec import WarmStateCodec

    def state_bytes(state):
        return json.dumps(partitioning_state_to_dict(state), sort_keys=True)

    cold_samples, adopt_samples, warm_samples = [], [], []
    identical = True
    matched = unmatched = 0
    for _ in range(repeats):
        pending = make_steady_pending(n_pods)
        ages = _ages(pending)
        snapshot = make_steady_cluster(n_nodes)
        planner = Planner(_framework())
        started = time.perf_counter()
        desired_cold = planner.plan(
            snapshot, pending, dirty=set(snapshot.get_nodes()), pending_ages=ages
        )
        cold_samples.append(time.perf_counter() - started)
        with tempfile.TemporaryDirectory() as tmp:
            path = os.path.join(tmp, "warm.json")
            WarmStateCodec(path).save(snapshot, planner, force=True)
            restarted = make_steady_cluster(n_nodes)
            warm_planner = Planner(_framework())
            t0 = time.perf_counter()
            report = WarmStateCodec(path).adopt(restarted, warm_planner)
            adopt_samples.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            desired_warm = warm_planner.plan(
                restarted, pending, dirty=set(report.unmatched), pending_ages=ages
            )
            warm_samples.append(time.perf_counter() - t0)
        identical = identical and (
            state_bytes(desired_warm) == state_bytes(desired_cold)
        )
        matched, unmatched = report.matched, len(report.unmatched)
    cold_ms = statistics.median(cold_samples) * 1e3
    adopt_ms = statistics.median(adopt_samples) * 1e3
    warm_ms = statistics.median(warm_samples) * 1e3
    return {
        "bench": "bench_planner_warm_boot",
        "nodes": n_nodes,
        "pending_pods": n_pods,
        "repeats": repeats,
        "cold_plan_ms": round(cold_ms, 2),
        "adopt_ms": round(adopt_ms, 2),
        "warm_plan_ms": round(warm_ms, 2),
        "warm_plan_speedup_vs_cold": round(cold_ms / warm_ms, 1),
        "restart_total_ms": round(adopt_ms + warm_ms, 2),
        "nodes_matched": matched,
        "nodes_unmatched": unmatched,
        "byte_identical": identical,
    }


def bench_config(
    engine: str, n_nodes: int, n_pods: int, repeats: int, cache_on: bool = True
) -> dict:
    snapshot_cls = ENGINES[engine]
    latencies = []
    forks = 0
    hits = misses = bypasses = 0
    for rep in range(repeats + 1):  # rep 0 is untimed warm-up
        snapshot = make_cluster(n_nodes, snapshot_cls)
        # Count forks engine-independently (the deepcopy baseline skips the
        # CoW metrics counters by design).
        if rep > 0:
            inner_fork = snapshot.fork

            def counting_fork(inner_fork=inner_fork):
                nonlocal forks
                forks += 1
                inner_fork()

            snapshot.fork = counting_fork
        planner = Planner(
            Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()]),
            verdict_cache_enabled=cache_on,
        )
        pods = make_pending(n_pods)
        started = time.perf_counter()
        planner.plan(snapshot, pods)
        if rep > 0:
            latencies.append(time.perf_counter() - started)
            h, m, b = planner.verdict_cache_stats()
            hits, misses, bypasses = hits + h, misses + m, bypasses + b
    total = sum(latencies)
    quantiles = (
        statistics.quantiles(latencies, n=20) if len(latencies) > 1 else latencies * 2
    )
    row = {
        "bench": "bench_planner",
        "engine": engine,
        "verdict_cache": "on" if cache_on else "off",
        "nodes": n_nodes,
        "pending_pods": n_pods,
        "repeats": repeats,
        "p50_plan_ms": round(statistics.median(latencies) * 1e3, 2),
        "p95_plan_ms": round(quantiles[-1] * 1e3, 2),
        "forks_per_sec": round(forks / total, 1) if total else None,
        "forks_total": forks,
    }
    if cache_on:
        eligible = hits + misses
        row["cache_hits"] = hits
        row["cache_misses"] = misses
        row["cache_bypasses"] = bypasses
        row["cache_hit_rate"] = round(hits / eligible, 4) if eligible else None
    return row


def export_sample_trace(path: str) -> None:
    """One traced plan() over the 16x50 config, exported as Chrome
    trace-event JSON — the 'open this in Perfetto' artifact next to the
    latency numbers."""
    from nos_tpu.util.tracing import TRACER

    TRACER.reset()
    snapshot = make_cluster(16, ClusterSnapshot)
    planner = Planner(
        Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()])
    )
    planner.plan(snapshot, make_pending(50))
    traces = TRACER.store.list()
    if not traces:
        return
    with open(path, "w") as fh:
        json.dump(traces[0].to_chrome(), fh, indent=2)
    print(f"sample trace -> {path}", flush=True)


def export_sample_profile(path: str) -> None:
    """Collapsed-stack sampling profile of back-to-back 64x200 plan()
    calls — the flamegraph companion to the Perfetto trace. The bench
    thread is registered with the profiler only while plan() runs, so
    snapshot construction between plans never dilutes the attribution;
    with tracing on, every sample lands in a named span phase
    (partitioner.plan / plan.trial / ...)."""
    from nos_tpu.util.profiling import PROFILER
    from nos_tpu.util.tracing import TRACER

    TRACER.reset()
    tracing_was = TRACER.enabled
    TRACER.enabled = True
    PROFILER.reset()
    planner = Planner(
        Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()])
    )
    pending = make_pending(200)
    target_samples = 500
    PROFILER.start(interval_seconds=0.002)
    try:
        for _ in range(80):
            snapshot = make_cluster(64, ClusterSnapshot)
            with PROFILER.registered("bench-planner"):
                planner.plan(snapshot, pending)
            if PROFILER.total_samples >= target_samples:
                break
    finally:
        PROFILER.stop()
        TRACER.enabled = tracing_was
    with open(path, "w") as fh:
        fh.write(PROFILER.collapsed())
    report = PROFILER.phase_report()
    print(
        json.dumps(
            {
                "bench": "bench_planner_profile",
                "output": path,
                "total_samples": report["total_samples"],
                "attributed_fraction": report["attributed_fraction"],
                "overhead_fraction": round(PROFILER.overhead_fraction(), 6),
                "phases": report["phases"],
            }
        ),
        flush=True,
    )


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--engines", default="cow,deepcopy")
    parser.add_argument(
        "--configs",
        default="16x50,64x200,256x400,1024x800",
        help="comma-separated nodesxpods pairs",
    )
    parser.add_argument(
        "--plan-mode",
        default="full",
        choices=("full", "incremental", "both", "sharded"),
        help="full = cold from-scratch plans (the original bench); "
        "incremental = steady-state replans over one persistent snapshot "
        "with a churn phase (see bench_incremental); sharded = the "
        "pool-sharded pipeline (per-pool replans + merge), plus the "
        "warm-boot restart bench and the sharded-vs-unsharded "
        "byte-identity oracle",
    )
    parser.add_argument(
        "--incremental-configs",
        default="1024x800,4096x800",
        help="nodesxpods pairs for the incremental mode",
    )
    parser.add_argument(
        "--sharded-configs",
        default="4096x800,16384x800,65536x800",
        help="nodesxpods pairs for the sharded mode",
    )
    parser.add_argument(
        "--pools",
        type=int,
        default=8,
        help="node-pool count for the sharded mode (nodes and pods are "
        "labeled/pinned round-robin)",
    )
    parser.add_argument(
        "--parallel",
        default="both",
        choices=("serial", "thread", "process", "both", "all"),
        help="per-pool execution for the sharded mode; 'both' emits one "
        "row per thread-ladder mode and 'all' adds the multi-process "
        "backend, so the GIL story is measured, not assumed",
    )
    parser.add_argument(
        "--churn",
        type=float,
        default=0.05,
        help="fraction of nodes dirtied per incremental cycle",
    )
    parser.add_argument("--repeats", type=int, default=5)
    parser.add_argument("--quick", action="store_true", help="16x50 only, 2 repeats")
    parser.add_argument("--output", default="", help="also append JSON lines to file")
    parser.add_argument(
        "--trace-output",
        default="",
        help="write a sample plan() trace (Chrome trace-event JSON) here; "
        "defaults to <output-stem>_trace.json when --output is set",
    )
    parser.add_argument(
        "--profile",
        action="store_true",
        help="also capture a sampling profile of back-to-back plan() calls "
        "(collapsed-stack text for flamegraph.pl/speedscope)",
    )
    parser.add_argument(
        "--profile-output",
        default="",
        help="collapsed-stack profile path; defaults to "
        "<output-stem>_profile.txt when --output is set",
    )
    args = parser.parse_args()

    configs = [tuple(map(int, c.split("x"))) for c in args.configs.split(",")]
    incremental_configs = [
        tuple(map(int, c.split("x"))) for c in args.incremental_configs.split(",")
    ]
    sharded_configs = [
        tuple(map(int, c.split("x"))) for c in args.sharded_configs.split(",")
    ]
    pools = args.pools
    repeats = args.repeats
    if args.quick:
        configs, repeats = [(16, 50)], 2
        incremental_configs = [(64, 100)]
        sharded_configs, pools = [(64, 100)], 2

    results = []
    if args.plan_mode == "sharded":
        modes = {
            "both": ("serial", "thread"),
            "all": ("serial", "thread", "process"),
        }.get(args.parallel, (args.parallel,))
        # Warm boot and the equivalence oracle run FIRST: the 16k-node
        # sharded benches leave enough long-lived garbage behind that a
        # later warm-boot measurement in the same process inflates ~2x
        # (GC pressure), which is not the number a real restart pays.
        wb_nodes, wb_pods = min(sharded_configs)
        result = bench_warm_boot(wb_nodes, wb_pods)
        results.append(result)
        print(json.dumps(result), flush=True)
        eq_nodes, eq_pods = min(sharded_configs)
        result = bench_sharded_equivalence(min(eq_nodes, 256), min(eq_pods, 400), pools)
        results.append(result)
        print(json.dumps(result), flush=True)
        for n_nodes, n_pods in sharded_configs:
            for parallelism in modes:
                result = bench_sharded(
                    n_nodes, n_pods, repeats, pools,
                    churn=args.churn, parallelism=parallelism,
                )
                results.append(result)
                print(json.dumps(result), flush=True)
        _finish(args, results)
        return
    if args.plan_mode in ("incremental", "both"):
        for n_nodes, n_pods in incremental_configs:
            for result in bench_incremental(n_nodes, n_pods, repeats, churn=args.churn):
                results.append(result)
                print(json.dumps(result), flush=True)
    if args.plan_mode == "incremental":
        _finish(args, results)
        return
    for engine in args.engines.split(","):
        # cow runs with the verdict cache on AND off (the off rows are the
        # like-for-like before/after for the cache); deepcopy is the
        # pre-everything baseline and only runs cache-off.
        cache_modes = (True, False) if engine == "cow" else (False,)
        for n_nodes, n_pods in configs:
            if engine == "deepcopy" and n_nodes >= 1024:
                # A single deepcopy plan() at 1024 nodes takes minutes —
                # the collapse is already documented by the 256-node row.
                continue
            # The deepcopy baseline at full scale is exactly the collapse
            # this bench exists to document; cap its largest run so the
            # suite still finishes.
            reps = repeats if not (engine == "deepcopy" and n_nodes >= 256) else max(
                1, repeats // 2
            )
            for cache_on in cache_modes:
                result = bench_config(engine, n_nodes, n_pods, reps, cache_on)
                results.append(result)
                print(json.dumps(result), flush=True)

    raw = list(results)
    for a in raw:
        # Incremental rows carry no verdict_cache field — .get() skips them.
        if not (
            a.get("engine") == "cow"
            and a.get("verdict_cache") == "on"
            and a.get("p50_plan_ms")
        ):
            continue
        for b in raw:
            if (a["nodes"], a["pending_pods"]) != (b["nodes"], b["pending_pods"]):
                continue
            if b["engine"] == "deepcopy":
                speedup = {
                    "bench": "bench_planner_speedup",
                    "nodes": a["nodes"],
                    "pending_pods": a["pending_pods"],
                    "p50_speedup": round(b["p50_plan_ms"] / a["p50_plan_ms"], 2),
                }
                results.append(speedup)
                print(json.dumps(speedup), flush=True)
            elif b["engine"] == "cow" and b["verdict_cache"] == "off":
                speedup = {
                    "bench": "bench_planner_cache_speedup",
                    "nodes": a["nodes"],
                    "pending_pods": a["pending_pods"],
                    "p50_speedup": round(b["p50_plan_ms"] / a["p50_plan_ms"], 2),
                    "cache_hit_rate": a.get("cache_hit_rate"),
                }
                results.append(speedup)
                print(json.dumps(speedup), flush=True)

    _finish(args, results)


def _finish(args, results) -> None:
    if args.output:
        with open(args.output, "a") as fh:
            for result in results:
                fh.write(json.dumps(result) + "\n")
    trace_path = args.trace_output
    if not trace_path and args.output:
        stem = args.output[:-5] if args.output.endswith(".json") else args.output
        trace_path = f"{stem}_trace.json"
    if trace_path:
        export_sample_trace(trace_path)
    if args.profile or args.profile_output:
        profile_path = args.profile_output
        if not profile_path and args.output:
            stem = args.output[:-5] if args.output.endswith(".json") else args.output
            profile_path = f"{stem}_profile.txt"
        export_sample_profile(profile_path or "bench_planner_profile.txt")


if __name__ == "__main__":
    main()
