"""Weight-only int8 quantization for serving.

Decode is HBM-bandwidth-bound: every generated token re-reads the full
weight set, so weight bytes — not FLOPs — set the tokens/s ceiling. Storing
weights as int8 with per-output-channel float scales quarters the bytes
(vs bf16: halves) while the MXU still sees a normal matmul: XLA fuses the
int8→bf16 convert into the dot's operand load, so the dequant never
materializes in HBM.

The reference has no model stack (SURVEY.md §5: "It is NOT a training
framework"); this serves the TPU build's own serving north star — more
tokens/s per carved slice tenant.

Serving-only: quantized weights are not differentiable (there is no STE
here); keep the bf16 originals for training.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp

Params = Dict[str, Any]

# Weight leaves quantized as [in, out] matmul operands.
_LINEAR_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head")


@jax.tree_util.register_pytree_node_class
@dataclass
class QuantizedLinear:
    """int8 weight [in, out] + per-output-channel scale [out] (f32)."""

    q: jax.Array
    scale: jax.Array

    def matmul(self, x: jax.Array) -> jax.Array:
        # Convert-then-dot fuses on TPU: int8 rows stream from HBM, the
        # widening happens in registers feeding the MXU tiles.
        return (x @ self.q.astype(x.dtype)) * self.scale.astype(x.dtype)

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


@jax.tree_util.register_pytree_node_class
@dataclass
class QuantizedEmbedding:
    """int8 table [vocab, d] + per-row scale [vocab] (f32); dequant after
    the gather so only the looked-up rows widen."""

    q: jax.Array
    scale: jax.Array

    def lookup(self, tokens: jax.Array, dtype) -> jax.Array:
        return self.q[tokens].astype(dtype) * self.scale[tokens][..., None].astype(dtype)

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


@jax.tree_util.register_pytree_node_class
@dataclass
class QuantizedExpertStack:
    """Stacked MoE expert weights [E, in, out] in int8 with per-(expert,
    output-channel) scales [E, out]; the batched expert einsum dequants
    per tile like the 2-D path."""

    q: jax.Array
    scale: jax.Array

    def expert_matmul(self, x: jax.Array) -> jax.Array:
        # x [E, C, in] -> [E, C, out]
        return jnp.einsum("eci,eio->eco", x, self.q.astype(x.dtype)) * self.scale[
            :, None, :
        ].astype(x.dtype)

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def _absmax_quantize(w: jax.Array, axis: int):
    """Symmetric absmax int8 along ``axis`` (the contraction axis): returns
    (q int8, scale f32 with ``axis`` dropped)."""
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=axis)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(
        jnp.round(w32 / jnp.expand_dims(scale, axis)), -127, 127
    ).astype(jnp.int8)
    return q, scale


def quantize_linear(w: jax.Array) -> QuantizedLinear:
    """[in, out] weight → int8 with one scale per output column."""
    q, scale = _absmax_quantize(w, axis=0)
    return QuantizedLinear(q=q, scale=scale)


def quantize_embedding(w: jax.Array) -> QuantizedEmbedding:
    """[vocab, d] table → int8 with one scale per vocab row."""
    q, scale = _absmax_quantize(w, axis=1)
    return QuantizedEmbedding(q=q, scale=scale)


def quantize_expert_stack(w: jax.Array) -> QuantizedExpertStack:
    """[E, in, out] stacked experts → int8 along the contraction axis."""
    q, scale = _absmax_quantize(w, axis=1)
    return QuantizedExpertStack(q=q, scale=scale)


def quantize_params(params: Params) -> Params:
    """Llama param tree → serving tree with every dense matmul weight, the
    embedding table, and MoE expert stacks int8-quantized. Norm vectors
    stay in the model dtype (tiny, and RMSNorm is scale-sensitive); the
    MoE router stays float32 (routing is precision-sensitive).
    """
    out: Params = {
        "embed": quantize_embedding(params["embed"]),
        "final_norm": params["final_norm"],
        "layers": [],
    }
    if "lm_head" in params:  # absent for tied-unembedding models
        out["lm_head"] = quantize_linear(params["lm_head"])
    for layer in params["layers"]:
        q_layer: Params = {}
        for key, value in layer.items():
            if key in _LINEAR_KEYS:
                q_layer[key] = quantize_linear(value)
            elif key == "moe":
                q_layer[key] = {
                    "router": value["router"],
                    "w_gate": quantize_expert_stack(value["w_gate"]),
                    "w_up": quantize_expert_stack(value["w_up"]),
                    "w_down": quantize_expert_stack(value["w_down"]),
                }
            else:
                q_layer[key] = value
        out["layers"].append(q_layer)
    return out


def dequantize_params(params: Params, dtype=jnp.bfloat16) -> Params:
    """Inverse of quantize_params (up to rounding): expands every quantized
    leaf back to a dense weight — the fake-quant oracle tests compare the
    int8 forward against, and the escape hatch back to training dtype."""

    def expand(leaf):
        if isinstance(leaf, QuantizedLinear):
            return (leaf.q.astype(jnp.float32) * leaf.scale[None, :]).astype(dtype)
        if isinstance(leaf, QuantizedEmbedding):
            return (leaf.q.astype(jnp.float32) * leaf.scale[:, None]).astype(dtype)
        if isinstance(leaf, QuantizedExpertStack):
            return (leaf.q.astype(jnp.float32) * leaf.scale[:, None, :]).astype(dtype)
        return leaf

    return jax.tree_util.tree_map(
        expand,
        params,
        is_leaf=lambda x: isinstance(
            x, (QuantizedLinear, QuantizedEmbedding, QuantizedExpertStack)
        ),
    )


def weight_bytes(params: Params) -> int:
    """Total bytes of all array leaves (the HBM working set decode streams)."""
    return sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(params)
        if hasattr(leaf, "dtype")
    )
