"""Weight-only int8 quantization for serving.

Decode is HBM-bandwidth-bound: every generated token re-reads the full
weight set, so weight bytes — not FLOPs — set the tokens/s ceiling. Storing
weights as int8 with per-output-channel float scales quarters the bytes
(vs bf16: halves) while the MXU still sees a normal matmul: XLA fuses the
int8→bf16 convert into the dot's operand load, so the dequant never
materializes in HBM.

The reference has no model stack (SURVEY.md §5: "It is NOT a training
framework"); this serves the TPU build's own serving north star — more
tokens/s per carved slice tenant.

Serving-only: quantized weights are not differentiable (there is no STE
here); keep the bf16 originals for training.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp

Params = Dict[str, Any]

# Weight leaves quantized as [in, out] matmul operands.
_LINEAR_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head")


@jax.tree_util.register_pytree_node_class
@dataclass
class QuantizedLinear:
    """int8 weight [in, out] + per-output-channel scale [out] (f32)."""

    q: jax.Array
    scale: jax.Array

    def matmul(self, x: jax.Array) -> jax.Array:
        # Convert-then-dot fuses on TPU: int8 rows stream from HBM, the
        # widening happens in registers feeding the MXU tiles.
        return (x @ self.q.astype(x.dtype)) * self.scale.astype(x.dtype)

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


@jax.tree_util.register_pytree_node_class
@dataclass
class QuantizedLinear4:
    """int4 weight packed two-per-byte as GROUP-SPLIT halves with
    group-wise scales — per-channel alone is too coarse at 4 bits.

    Layout: q [G, group/2, out] uint8, where within group g the LOW
    nibble of row r holds w[g*group + r] and the HIGH nibble holds
    w[g*group + group/2 + r]; scale [G, out] f32. The split-half layout
    (instead of even/odd interleave) is what keeps the unpack a pure
    elementwise shift/mask on the packed bytes: matmul runs two grouped
    dots whose weight operands are elementwise functions of q, so XLA
    fuses the unpack into the tile load and the full-width bf16 weight
    never materializes in HBM (an interleave needs a stack+reshape
    shuffle, which r05 on-chip measurement showed forces a full f32
    dequant round-trip: 0.157x bf16 decode)."""

    q: jax.Array       # [G, group//2, out] uint8, two nibbles per byte
    scale: jax.Array   # [G, out] f32
    group: int

    def _unpack(self, dtype):
        """(lo, hi) nibble planes [G, half, out] in ``dtype`` — the ONE
        place the packing convention is decoded."""
        lo = ((self.q & 0xF).astype(jnp.int8) - 8).astype(dtype)
        hi = ((self.q >> 4).astype(jnp.int8) - 8).astype(dtype)
        return lo, hi

    def _dequant(self, dtype) -> jax.Array:
        lo, hi = self._unpack(jnp.float32)
        g, half, out = self.q.shape
        w = jnp.concatenate([lo, hi], axis=1) * self.scale[:, None, :]
        return w.reshape(g * 2 * half, out).astype(dtype)

    def matmul(self, x: jax.Array) -> jax.Array:
        g, half, out = self.q.shape
        *lead, d_in = x.shape
        xg = x.reshape(-1, g, 2, half)
        lo, hi = self._unpack(x.dtype)
        # Grouped dots in x's dtype (TPU MXU accumulates f32 internally;
        # CPU's DotThunk rejects mixed bf16->f32 output), then the group
        # scale and the cross-group sum in f32 — one rounding per
        # <=group-sized partial, which preserves the fake-quant oracle
        # parity the tests pin.
        acc = jnp.einsum("bgi,gio->bgo", xg[:, :, 0], lo) + jnp.einsum(
            "bgi,gio->bgo", xg[:, :, 1], hi
        )
        y = (acc.astype(jnp.float32) * self.scale[None]).sum(axis=1)
        return y.astype(x.dtype).reshape(*lead, out)

    def tree_flatten(self):
        return (self.q, self.scale), (self.group,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, group=aux[0])


@jax.tree_util.register_pytree_node_class
@dataclass
class QuantizedEmbedding:
    """int8 table [vocab, d] + per-row scale [vocab] (f32); dequant after
    the gather so only the looked-up rows widen."""

    q: jax.Array
    scale: jax.Array

    def lookup(self, tokens: jax.Array, dtype) -> jax.Array:
        return self.q[tokens].astype(dtype) * self.scale[tokens][..., None].astype(dtype)

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


@jax.tree_util.register_pytree_node_class
@dataclass
class QuantizedExpertStack:
    """Stacked MoE expert weights [E, in, out] in int8 with per-(expert,
    output-channel) scales [E, out]; the batched expert einsum dequants
    per tile like the 2-D path."""

    q: jax.Array
    scale: jax.Array

    def expert_matmul(self, x: jax.Array) -> jax.Array:
        # x [E, C, in] -> [E, C, out]
        return jnp.einsum("eci,eio->eco", x, self.q.astype(x.dtype)) * self.scale[
            :, None, :
        ].astype(x.dtype)

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def _absmax_quantize(w: jax.Array, axis: int):
    """Symmetric absmax int8 along ``axis`` (the contraction axis): returns
    (q int8, scale f32 with ``axis`` dropped)."""
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=axis)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(
        jnp.round(w32 / jnp.expand_dims(scale, axis)), -127, 127
    ).astype(jnp.int8)
    return q, scale


def quantize_linear(w: jax.Array) -> QuantizedLinear:
    """[in, out] weight → int8 with one scale per output column."""
    q, scale = _absmax_quantize(w, axis=0)
    return QuantizedLinear(q=q, scale=scale)


def quantize_linear4(w: jax.Array, group: int = 128) -> QuantizedLinear4:
    """[in, out] weight → packed int4 with one scale per (group, output
    column). ``group`` clamps to a divisor of the (even) contraction dim."""
    d_in, d_out = w.shape
    if d_in % 2:
        raise ValueError(f"int4 packing needs an even contraction dim, got {d_in}")
    # Largest EVEN divisor of d_in that is <= the requested group (pairs
    # must not straddle groups — lo/hi nibbles share a byte; 2 always
    # works since d_in is even).
    group = min(group, d_in)
    group -= group % 2
    while d_in % group:
        group -= 2
    w32 = w.astype(jnp.float32).reshape(d_in // group, group, d_out)
    absmax = jnp.max(jnp.abs(w32), axis=1)               # [groups, out]
    scale = jnp.where(absmax > 0, absmax / 7.0, 1.0)
    q = jnp.clip(
        jnp.round(w32 / scale[:, None, :]), -7, 7
    ).astype(jnp.int8)
    u = (q + 8).astype(jnp.uint8)                        # [G, group, out] in [0,15]
    half = group // 2
    lo = u[:, :half]                                     # first half of each group
    hi = u[:, half:]                                     # second half
    packed = (lo | (hi << 4)).astype(jnp.uint8)          # [G, group/2, out]
    return QuantizedLinear4(q=packed, scale=scale, group=group)


def quantize_embedding(w: jax.Array) -> QuantizedEmbedding:
    """[vocab, d] table → int8 with one scale per vocab row."""
    q, scale = _absmax_quantize(w, axis=1)
    return QuantizedEmbedding(q=q, scale=scale)


def quantize_expert_stack(w: jax.Array) -> QuantizedExpertStack:
    """[E, in, out] stacked experts → int8 along the contraction axis."""
    q, scale = _absmax_quantize(w, axis=1)
    return QuantizedExpertStack(q=q, scale=scale)


def _quantize_tree(params: Params, linear_fn) -> Params:
    """THE param-tree walk for weight-only quantization, parameterized by
    the dense-linear quantizer (int8 or int4) — embed stays row-gatherable
    int8, norms keep the model dtype (tiny, and RMSNorm is
    scale-sensitive), the MoE router stays float32 (routing is
    precision-sensitive) and expert stacks stay int8."""
    out: Params = {
        "embed": quantize_embedding(params["embed"]),
        "final_norm": params["final_norm"],
        "layers": [],
    }
    if "lm_head" in params:  # absent for tied-unembedding models
        out["lm_head"] = linear_fn(params["lm_head"])
    for layer in params["layers"]:
        q_layer: Params = {}
        for key, value in layer.items():
            if key in _LINEAR_KEYS:
                q_layer[key] = linear_fn(value)
            elif key == "moe":
                q_layer[key] = {
                    "router": value["router"],
                    "w_gate": quantize_expert_stack(value["w_gate"]),
                    "w_up": quantize_expert_stack(value["w_up"]),
                    "w_down": quantize_expert_stack(value["w_down"]),
                }
            else:
                q_layer[key] = value
        out["layers"].append(q_layer)
    return out


def quantize_params(params: Params) -> Params:
    """Llama param tree → int8 serving tree (see _quantize_tree)."""
    return _quantize_tree(params, quantize_linear)


def quantize_params_int4(params: Params, group: int = 128) -> Params:
    """Llama param tree → int4 serving tree: dense matmul weights as
    packed group-quantized nibbles (QUARTER of bf16's bytes); the
    embedding stays int8 (gather rows can't read packed pairs cheaply)
    and MoE expert stacks stay int8 — int4's group bookkeeping per
    expert isn't worth it at their size (see _quantize_tree)."""
    return _quantize_tree(
        params, lambda w: quantize_linear4(w, group)
    )


def dequantize_params(params: Params, dtype=jnp.bfloat16) -> Params:
    """Inverse of quantize_params (up to rounding): expands every quantized
    leaf back to a dense weight — the fake-quant oracle tests compare the
    int8 forward against, and the escape hatch back to training dtype."""

    def expand(leaf):
        if isinstance(leaf, QuantizedLinear4):
            return leaf._dequant(dtype)
        if isinstance(leaf, QuantizedLinear):
            return (leaf.q.astype(jnp.float32) * leaf.scale[None, :]).astype(dtype)
        if isinstance(leaf, QuantizedEmbedding):
            return (leaf.q.astype(jnp.float32) * leaf.scale[:, None]).astype(dtype)
        if isinstance(leaf, QuantizedExpertStack):
            return (leaf.q.astype(jnp.float32) * leaf.scale[:, None, :]).astype(dtype)
        return leaf

    return jax.tree_util.tree_map(
        expand,
        params,
        is_leaf=lambda x: isinstance(
            x,
            (QuantizedLinear, QuantizedLinear4, QuantizedEmbedding,
             QuantizedExpertStack),
        ),
    )


def weight_bytes(params: Params) -> int:
    """Total bytes of all array leaves (the HBM working set decode streams)."""
    return sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(params)
        if hasattr(leaf, "dtype")
    )
