"""Speculative decoding: draft-model lookahead with exact target outputs.

Decode is HBM-bandwidth-bound — each sequential token re-reads the target's
full weight set. A small draft model proposes ``k`` tokens autoregressively
(cheap weight reads), and the target verifies all k in ONE multi-token
``decode_chunk`` (one full-weight read for up to k+1 committed tokens).
Greedy acceptance commits only tokens that match the target's own argmax
(the first mismatch is replaced by the target's token — the "bonus"), so
the output equals the target's greedy sequence up to one numeric caveat:
the chunked verify accumulates in a different order than stepwise decode,
and an argmax whose top-2 gap is below that float drift can flip. The
tests pin token-identity on the shipped configs; a good draft only adds
speed, a bad one only costs it.

Per round, all inside one jitted dispatch with donated caches:
  1. draft scans k steps from the last committed token,
  2. target verifies [last, d_1..d_k] in one chunk,
  3. acceptance = longest matching prefix; positions advance per row,
  4. one extra draft step ingests d_k's K/V so the draft cache invariant
     (holds every committed token but the last) survives full acceptance.
Stale K/V beyond a row's frontier is never attended (the frontier only
unmasks written history, and rewinds overwrite before they re-expose), so
rejection "rollback" is just a position decrement — no cache copies.

Throughput gain ≈ (mean accepted + 1) / (1 + (k+1)·draft/target cost
ratio) — k scan steps plus the d_k ingest; with a well-matched draft,
several target tokens per full-weight read.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nos_tpu.models.generate import decode_chunk, decode_step, prefill
from nos_tpu.models.llama import LlamaConfig

Params = Dict[str, object]


def _spec_round(
    t_params, d_params, t_config: LlamaConfig, d_config: LlamaConfig, k: int
):
    """Builds the jitted one-round function (closure over static configs)."""

    def round_fn(t_cache, d_cache, pos, last, row_valid=None):
        b = last.shape[0]

        # 1. draft k tokens (writes K/V for [last, d_1..d_{k-1}])
        def draft_tick(carry, _):
            cache, p, tok = carry
            logits, cache = decode_step(
                d_params, cache, p, tok, d_config, row_valid=row_valid
            )
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (cache, p + 1, nxt), nxt

        (d_cache, _, _), drafts = jax.lax.scan(
            draft_tick, (d_cache, pos, last), None, length=k
        )
        drafts = jnp.moveaxis(drafts, 0, 1)  # [B, k]

        # 2. target verifies the whole chain in one chunk
        chunk = jnp.concatenate([last[:, None], drafts], axis=1)  # [B, k+1]
        logits, t_cache = decode_chunk(
            t_params, t_cache, pos, chunk, t_config, row_valid=row_valid
        )
        targets = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, k+1]

        # 3. longest matching prefix: accept while d_{i+1} == t_i
        match = drafts == targets[:, :k]  # [B, k]
        accepted = jnp.argmin(
            jnp.concatenate([match, jnp.zeros((b, 1), bool)], axis=1), axis=1
        ).astype(jnp.int32)  # [B]: k if all matched
        # committed tokens this round: d_1..d_a then the target's bonus
        idx = jnp.arange(k + 1)[None, :]
        bonus = jnp.take_along_axis(targets, accepted[:, None], axis=1)[:, 0]
        drafts_pad = jnp.pad(drafts, ((0, 0), (0, 1)))  # [B, k+1]
        out = jnp.where(
            idx < accepted[:, None],
            drafts_pad,
            jnp.where(idx == accepted[:, None], bonus[:, None], 0),
        )  # [B, k+1]; rows valid through accepted+1 tokens
        count = accepted + 1

        # 4. ingest d_k's K/V so full acceptance leaves no draft-cache hole
        _, d_cache = decode_step(
            d_params, d_cache, pos + k, drafts[:, -1], d_config,
            row_valid=row_valid,
        )

        return t_cache, d_cache, pos + count, bonus, drafts, out, count

    return round_fn


def speculative_generate(
    target_params: Params,
    draft_params: Params,
    prompt: jax.Array,
    target_config: LlamaConfig,
    draft_config: LlamaConfig,
    max_new_tokens: int,
    k: int = 4,
    eos_id: Optional[int] = None,
) -> Tuple[jax.Array, dict]:
    """prompt [B, S] → (tokens [B, max_new_tokens], stats).

    Greedy speculative decoding; output matches
    ``generate(target_params, ...)`` up to the chunk-vs-step float drift
    described in the module docstring (token-identical on the pinned test
    configs). ``stats`` reports rounds and mean accepted drafts per
    active row-round — rows that finished (eos/max) are excluded from
    both numerator and denominator. Finished rows keep riding the batch;
    their surplus is trimmed host-side, and with ``eos_id`` rows are
    padded with it after their first EOS.
    """
    b, s = prompt.shape
    max_len = s + max_new_tokens + k + 2  # chunk overshoot + draft ingest margin
    t_logits, t_cache = prefill(target_params, prompt, target_config, max_len)
    _, d_cache = prefill(draft_params, prompt, draft_config, max_len)
    first = jnp.argmax(t_logits[:, -1], axis=-1).astype(jnp.int32)

    round_fn = jax.jit(
        _spec_round(target_params, draft_params, target_config, draft_config, k),
        donate_argnums=(0, 1),
    )

    pos = jnp.full((b,), s, jnp.int32)
    last = first
    rows: List[List[int]] = [[int(first[i])] for i in range(b)]
    done = [
        eos_id is not None and rows[i][0] == eos_id for i in range(b)
    ]
    rounds = 0
    accepted_total = 0
    active_row_rounds = 0
    while not all(
        len(r) >= max_new_tokens or d for r, d in zip(rows, done)
    ):
        active = [
            not d and len(r) < max_new_tokens for r, d in zip(rows, done)
        ]
        # Finished rows keep riding the batch while pos advances up to k+1
        # per round; clamp so their k+1 chunk writes stay inside max_len
        # (active rows never reach the clamp by the max_len sizing above),
        # and keep them out of the MoE expert-capacity race (row_valid) —
        # a ridden row's garbage tokens must never displace a live one.
        pos = jnp.minimum(pos, max_len - k - 1)
        t_cache, d_cache, pos, last, _, out, count = round_fn(
            t_cache, d_cache, pos, last, jnp.asarray(active)
        )
        rounds += 1
        out_np = np.asarray(out)
        count_np = np.asarray(count)
        for i in range(b):
            if not active[i]:
                # finished rows ride the batch but their garbage
                # acceptance must not pollute the stats
                continue
            active_row_rounds += 1
            accepted_total += int(count_np[i]) - 1  # drafts only, minus bonus
            for j in range(int(count_np[i])):
                if len(rows[i]) >= max_new_tokens:
                    break
                tok = int(out_np[i, j])
                rows[i].append(tok)
                if eos_id is not None and tok == eos_id:
                    done[i] = True
                    break
    for i in range(b):
        fill = eos_id if (eos_id is not None and done[i]) else 0
        rows[i] = (rows[i] + [fill] * max_new_tokens)[:max_new_tokens]
    stats = {
        "rounds": rounds,
        "mean_accepted": accepted_total / max(1, active_row_rounds),
    }
    return jnp.asarray(rows, jnp.int32), stats
