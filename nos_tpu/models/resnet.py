"""ResNet-50 in pure JAX (BASELINE.json config #3: single-host slice
inference/training workload; the role YOLOS-small plays in the reference's
demo). bfloat16, NHWC, folded batch-norm parameters (scale/bias) so the
whole network is convs + elementwise — ideal XLA fusion fodder.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


@dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 1000
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)  # ResNet-50
    width: int = 64
    dtype: Any = jnp.bfloat16


def tiny_resnet_config() -> ResNetConfig:
    return ResNetConfig(num_classes=10, stage_sizes=(1, 1), width=8)


def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    return (jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) * math.sqrt(2.0 / fan_in)).astype(dtype)


def _norm_params(cout, dtype):
    return {"scale": jnp.ones((cout,), dtype), "bias": jnp.zeros((cout,), dtype)}


def init_resnet_params(key: jax.Array, config: ResNetConfig) -> Params:
    c = config
    n_blocks = sum(c.stage_sizes)
    keys = iter(jax.random.split(key, 2 + n_blocks * 4))
    params: Params = {
        "stem": {
            "conv": _conv_init(next(keys), 7, 7, 3, c.width, c.dtype),
            "norm": _norm_params(c.width, c.dtype),
        },
        "stages": [],
    }
    cin = c.width
    for stage_index, blocks in enumerate(c.stage_sizes):
        stage: List[Params] = []
        width = c.width * (2**stage_index)
        cout = width * 4
        for block_index in range(blocks):
            block = {
                "conv1": _conv_init(next(keys), 1, 1, cin, width, c.dtype),
                "norm1": _norm_params(width, c.dtype),
                "conv2": _conv_init(next(keys), 3, 3, width, width, c.dtype),
                "norm2": _norm_params(width, c.dtype),
                "conv3": _conv_init(next(keys), 1, 1, width, cout, c.dtype),
                "norm3": _norm_params(cout, c.dtype),
            }
            if block_index == 0:
                block["proj"] = _conv_init(next(keys), 1, 1, cin, cout, c.dtype)
                block["proj_norm"] = _norm_params(cout, c.dtype)
            stage.append(block)
            cin = cout
        params["stages"].append(stage)
    head_key = next(keys)
    params["head"] = (
        jax.random.normal(head_key, (cin, c.num_classes), jnp.float32) / math.sqrt(cin)
    ).astype(c.dtype)
    return params


def _conv(x, kernel, stride=1):
    return jax.lax.conv_general_dilated(
        x,
        kernel,
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _norm(x, p):
    # Folded batch-norm: scale/bias only (inference-style; training benches
    # exercise the same compute shape).
    return x * p["scale"] + p["bias"]


def _bottleneck(x, block, stride):
    shortcut = x
    y = jax.nn.relu(_norm(_conv(x, block["conv1"]), block["norm1"]))
    y = jax.nn.relu(_norm(_conv(y, block["conv2"], stride=stride), block["norm2"]))
    y = _norm(_conv(y, block["conv3"]), block["norm3"])
    if "proj" in block:
        shortcut = _norm(_conv(x, block["proj"], stride=stride), block["proj_norm"])
    return jax.nn.relu(y + shortcut)


def resnet_forward(params: Params, images: jax.Array, config: ResNetConfig) -> jax.Array:
    """images [B, H, W, 3] → logits [B, num_classes] (float32)."""
    x = images.astype(config.dtype)
    x = jax.nn.relu(_norm(_conv(x, params["stem"]["conv"], stride=2), params["stem"]["norm"]))
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )
    for stage_index, stage in enumerate(params["stages"]):
        for block_index, block in enumerate(stage):
            stride = 2 if (stage_index > 0 and block_index == 0) else 1
            x = _bottleneck(x, block, stride)
    x = jnp.mean(x, axis=(1, 2))
    return (x @ params["head"]).astype(jnp.float32)
