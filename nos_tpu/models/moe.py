"""Mixture-of-experts MLP with expert parallelism.

TPU-first MoE: routing is top-k with a STATIC per-expert capacity (XLA
needs static shapes — overflow tokens are dropped, the standard
Switch/GShard discipline), dispatch/combine are scatter/gather einsums the
compiler lays out as all-to-alls when the expert dimension is sharded, and
the expert FFNs run as one batched einsum over stacked weights so the MXU
sees [E·C, d]×[d, f] tiles instead of E small matmuls.

Sharding: stacked expert weights and the [E, C, d] dispatch buffer shard
their leading dim over the ``ep`` mesh axis (each device owns E/ep
experts); the hidden dim can additionally shard over ``tp``. Constraints
are annotated — XLA inserts the token all-to-all across ep.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

Params = Dict[str, Any]


@dataclass(frozen=True)
class MoeConfig:
    d_model: int = 64
    d_ff: int = 128
    n_experts: int = 4
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16


def capacity_per_expert(n_tokens: int, config: MoeConfig) -> int:
    """Static buffer depth per expert: ceil(k·T/E · factor), min 1."""
    c = config
    return max(
        1, math.ceil(c.top_k * n_tokens / c.n_experts * c.capacity_factor)
    )


def init_moe_params(key: jax.Array, config: MoeConfig) -> Params:
    c = config
    k_router, k_gate, k_up, k_down = jax.random.split(key, 4)

    def dense(k, shape, scale_dim):
        return (
            jax.random.normal(k, shape, jnp.float32) / math.sqrt(scale_dim)
        ).astype(c.dtype)

    return {
        # Router stays float32: routing decisions are precision-sensitive.
        "router": jax.random.normal(k_router, (c.d_model, c.n_experts), jnp.float32)
        / math.sqrt(c.d_model),
        "w_gate": dense(k_gate, (c.n_experts, c.d_model, c.d_ff), c.d_model),
        "w_up": dense(k_up, (c.n_experts, c.d_model, c.d_ff), c.d_model),
        "w_down": dense(k_down, (c.n_experts, c.d_ff, c.d_model), c.d_ff),
    }


def moe_param_sharding(mesh, config: MoeConfig) -> Params:
    """NamedShardings: experts over ep, hidden over tp, the remaining
    d_model dimension FSDP-sharded over dp, router replicated. Axes
    missing from the mesh fall back to replication (partition_spec)."""
    from nos_tpu.parallel.mesh import partition_spec as ps

    return {
        "router": NamedSharding(mesh, P()),
        "w_gate": NamedSharding(mesh, ps(mesh, "ep", "dp", "tp")),
        "w_up": NamedSharding(mesh, ps(mesh, "ep", "dp", "tp")),
        "w_down": NamedSharding(mesh, ps(mesh, "ep", "tp", "dp")),
    }


def _emm(x: jax.Array, w) -> jax.Array:
    """Batched expert matmul [E, C, in] x [E, in, out] for dense stacks or
    int8 QuantizedExpertStack (serving path)."""
    from nos_tpu.models.quantize import QuantizedExpertStack

    if isinstance(w, QuantizedExpertStack):
        return w.expert_matmul(x)
    return jnp.einsum("eci,eio->eco", x, w)


def moe_mlp(
    params: Params,
    x: jax.Array,
    config: MoeConfig,
    mesh: Optional[Any] = None,
    return_aux: bool = False,
    token_mask: Optional[jax.Array] = None,
):
    """x [B, S, d] → [B, S, d] through top-k routed experts.

    With ``return_aux``, also returns the Switch-style load-balancing loss
    ``E · Σ_e f_e · P_e`` (dispatch fraction × mean router probability per
    expert) — add it to the training loss or the router collapses onto few
    experts and static capacity drops most tokens.

    ``token_mask`` [B, S] excludes padding columns entirely: masked
    tokens claim NO expert capacity (a pad must never displace a real
    token — the serving engine's batching-invisibility contract), output
    zero, and stay out of the aux-loss statistics.
    """
    c = config
    b, s, d = x.shape
    t = b * s
    cap = capacity_per_expert(t, c)
    flat = x.reshape(t, d)
    tmask = None if token_mask is None else token_mask.reshape(t)

    # ---- routing (float32)
    logits = flat.astype(jnp.float32) @ params["router"]  # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_e = jax.lax.top_k(probs, c.top_k)  # [T, k]
    top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)

    # ---- static-capacity positions: pair (token, k-slot) -> slot in expert
    pair_e = top_e.reshape(t * c.top_k)  # [P]
    pair_w = top_p.reshape(t * c.top_k)
    onehot = jax.nn.one_hot(pair_e, c.n_experts, dtype=jnp.int32)  # [P, E]
    pair_mask = None if tmask is None else jnp.repeat(tmask, c.top_k)
    if pair_mask is not None:
        # zeroed rows don't advance any expert's running count, so pads
        # are invisible to the capacity race; their own pos collapses to
        # 0 — the keep &= mask below discards them regardless
        onehot = onehot * pair_mask[:, None].astype(onehot.dtype)
    pos = jnp.sum((jnp.cumsum(onehot, axis=0) - 1) * onehot, axis=-1)  # [P]
    keep = pos < cap
    if pair_mask is not None:
        keep = keep & pair_mask
    pos = jnp.minimum(pos, cap - 1)

    # ---- dispatch [E, C, d]
    token_idx = jnp.repeat(jnp.arange(t), c.top_k)
    contrib = flat[token_idx] * keep[:, None].astype(flat.dtype)
    dispatch = jnp.zeros((c.n_experts, cap, d), flat.dtype).at[pair_e, pos].add(contrib)
    if mesh is not None and "ep" in mesh.axis_names:
        dispatch = jax.lax.with_sharding_constraint(
            dispatch, NamedSharding(mesh, P("ep", None, None))
        )

    # ---- expert FFN over stacked weights (one batched einsum per matmul)
    gate = _emm(dispatch, params["w_gate"])
    up = _emm(dispatch, params["w_up"])
    out_e = _emm(jax.nn.silu(gate) * up, params["w_down"])
    if mesh is not None and "ep" in mesh.axis_names:
        out_e = jax.lax.with_sharding_constraint(
            out_e, NamedSharding(mesh, P("ep", None, None))
        )

    # ---- combine: gather each pair's expert output, weight, sum over k
    gathered = out_e[pair_e, pos]  # [P, d]
    weighted = gathered * (pair_w * keep).astype(gathered.dtype)[:, None]
    out = jnp.sum(weighted.reshape(t, c.top_k, d), axis=1)
    out = out.reshape(b, s, d).astype(x.dtype)
    if not return_aux:
        return out
    # Load-balance loss (Switch): E · Σ_e f_e·P_e with f_e the fraction of
    # tokens whose TOP-1 choice is expert e and P_e the mean router
    # probability. Uniform routing scores 1.0; collapse scores ~E.
    # Masked (padding) tokens are excluded from both statistics.
    top1 = jax.nn.one_hot(top_e[:, 0], c.n_experts, dtype=jnp.float32)
    if tmask is None:
        top1_frac = jnp.mean(top1, axis=0)
        mean_prob = jnp.mean(probs, axis=0)
    else:
        w = tmask.astype(jnp.float32)[:, None]
        denom = jnp.maximum(jnp.sum(w), 1.0)
        top1_frac = jnp.sum(top1 * w, axis=0) / denom
        mean_prob = jnp.sum(probs * w, axis=0) / denom
    aux = c.n_experts * jnp.sum(top1_frac * mean_prob)
    return out, aux
