"""Demo workloads: the JAX jobs the cluster schedules onto carved slices.

The reference's benchmark workload is a YOLOS-small inference server
(demos/gpu-sharing-comparison); the TPU build's equivalents per
BASELINE.json configs are a ResNet-50 (single-host slice) and a
Llama-style transformer (multi-host gang), both TPU-first: bfloat16
matmuls sized for the MXU, static shapes, shardable over a
``jax.sharding.Mesh``.
"""

from nos_tpu.models.llama import LlamaConfig, llama_forward, init_llama_params
from nos_tpu.models.resnet import ResNetConfig, init_resnet_params, resnet_forward

__all__ = [
    "LlamaConfig",
    "ResNetConfig",
    "init_llama_params",
    "init_resnet_params",
    "llama_forward",
    "resnet_forward",
]
