"""HuggingFace Llama checkpoints → nos-tpu parameter trees.

Real weights for the workload stack: `transformers` Llama-family
checkpoints — plain RoPE (Llama 2/3.0, TinyLlama, …) and the llama3
scaled RoPE of Llama 3.1+ — convert into the pytree
`nos_tpu.models.llama` trains and serves, so a slice tenant can
fine-tune or deploy a published model rather than random init.
Checkpoints needing features the forward does not implement (other
rope_scaling types, attention biases, leftover adapter weights) are
REJECTED at conversion rather than converted into silently different
models.

Layout notes (verified by the torch-vs-JAX logits parity test):

- HF Linear stores [out, in]; this tree stores [in, out] → transpose.
- Rotary embedding conventions match (the half-split "neox" rotation with
  per-half frequency tables), so Q/K convert untouched.
- GQA head ordering matches (kv-head-major query heads).
- ``lm_head`` may be tied to the embedding (``tie_word_embeddings`` /
  Gemma): the config records ``tie_embeddings`` and the params tree then
  carries NO lm_head — every forward path unembeds through
  ``params["embed"].T`` (llama._unembed_weight).
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax.numpy as jnp
import numpy as np

from nos_tpu.models.llama import LlamaConfig

Params = Dict[str, Any]


def config_from_hf(hf_config, dtype=jnp.bfloat16) -> LlamaConfig:
    # Silent-corruption guards: features this forward does not implement
    # must fail at conversion, not at serving time with wrong logits.
    scaling = getattr(hf_config, "rope_scaling", None)
    rope_scaling = None
    if scaling:
        rope_type = scaling.get("rope_type", scaling.get("type", ""))
        if rope_type != "llama3":
            raise ValueError(
                f"rope_scaling={scaling!r} is not implemented by "
                "nos_tpu.models.llama (plain or llama3 RoPE only); refusing "
                "to convert a model whose positions would silently differ"
            )
        required = (
            "factor", "low_freq_factor", "high_freq_factor",
            "original_max_position_embeddings",
        )
        missing = [k for k in required if k not in scaling]
        if missing:
            raise ValueError(
                f"rope_scaling={scaling!r} lacks {missing}; refusing to "
                "guess scaled-RoPE parameters"
            )
        rope_scaling = ("llama3",) + tuple(float(scaling[k]) for k in required)
    # Gemma is the same decoder skeleton with four dialect switches:
    # gelu gated MLP, (1 + w) RMSNorm, sqrt(d_model)-scaled embeddings,
    # always-tied unembedding — plus an explicit head_dim (Gemma-7B's 256
    # does not equal hidden/heads).
    model_type = getattr(hf_config, "model_type", "llama")
    is_gemma = model_type == "gemma"
    head_dim = getattr(hf_config, "head_dim", None)
    derived = hf_config.hidden_size // hf_config.num_attention_heads
    qk_head_dim = None
    if head_dim not in (None, derived):
        if is_gemma:
            qk_head_dim = int(head_dim)
        else:
            raise ValueError(
                f"head_dim={head_dim} != hidden_size/num_heads={derived}: "
                "unsupported layout"
            )
    hidden_act = getattr(hf_config, "hidden_act", None) or getattr(
        hf_config, "hidden_activation", None
    ) or "silu"
    if hidden_act in ("gelu_pytorch_tanh", "gelu_new") or (
        hidden_act == "gelu" and is_gemma
    ):
        # tanh-approximate GELU (plain "gelu" is a legacy alias only in
        # Gemma configs — elsewhere it means exact erf GELU, which this
        # stack does not implement; refuse rather than silently differ).
        hidden_act = "gelu"
    elif hidden_act != "silu":
        raise ValueError(f"unsupported hidden_act={hidden_act!r}")
    # Mistral-family sliding window (the arch is otherwise Llama-shaped;
    # the same converter serves both). transformers uses None for "full".
    sliding = getattr(hf_config, "sliding_window", None)
    # Mixtral: Mistral attention + a routed MoE MLP per block. Routing
    # parity note: HF transformers (the checkpoints this converter reads)
    # softmaxes ALL router logits then renormalizes the top-k — the same
    # order this stack uses; it is the mistral-inference reference that
    # takes top-k over the logits first and softmaxes only the survivors.
    # Identical math either way (softmax is monotonic and the
    # renormalization cancels the common denominator).
    n_experts = 0
    moe_top_k = 2
    if model_type == "mixtral":
        n_experts = int(getattr(hf_config, "num_local_experts"))
        moe_top_k = int(getattr(hf_config, "num_experts_per_tok", 2))
    return LlamaConfig(
        n_experts=n_experts,
        moe_top_k=moe_top_k,
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(
            hf_config, "num_key_value_heads", hf_config.num_attention_heads
        ),
        d_ff=hf_config.intermediate_size,
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        rope_scaling=rope_scaling,
        norm_eps=float(hf_config.rms_norm_eps),
        sliding_window=int(sliding) if sliding else None,
        hidden_act=hidden_act,
        norm_offset=is_gemma,
        scale_embeddings=is_gemma,
        tie_embeddings=is_gemma
        or bool(getattr(hf_config, "tie_word_embeddings", False)),
        qk_head_dim=qk_head_dim,
        dtype=dtype,
    )


def _t(tensor, dtype) -> jnp.ndarray:
    """torch [out, in] weight → jnp [in, out]."""
    return jnp.asarray(np.asarray(tensor.detach().cpu().float().numpy().T), dtype)


def _v(tensor, dtype) -> jnp.ndarray:
    return jnp.asarray(np.asarray(tensor.detach().cpu().float().numpy()), dtype)


def params_from_hf_state_dict(state_dict, config: LlamaConfig) -> Params:
    """``model.state_dict()`` of a transformers LlamaForCausalLM → the
    nos-tpu parameter tree (in ``config.dtype``)."""
    c = config
    dt = c.dtype
    sd = dict(state_dict)
    consumed = set()

    def take(key, fn):
        consumed.add(key)
        return fn(sd[key], dt)

    embed = take("model.embed_tokens.weight", _v)
    params: Params = {
        "embed": embed,
        "final_norm": take("model.norm.weight", _v),
        "layers": [],
    }
    if c.tie_embeddings:
        # Tied unembedding: no separate matrix — the forward's _unembed
        # reuses params["embed"].T. Consume the checkpoint's lm_head copy
        # if one exists (some exports materialize it anyway).
        if "lm_head.weight" in sd:
            consumed.add("lm_head.weight")
    else:
        params["lm_head"] = (
            take("lm_head.weight", _t)
            if "lm_head.weight" in sd
            else embed.T  # tied checkpoint but untied config: materialize
        )
    for i in range(c.n_layers):
        prefix = f"model.layers.{i}."
        layer = {
            "attn_norm": take(prefix + "input_layernorm.weight", _v),
            "wq": take(prefix + "self_attn.q_proj.weight", _t),
            "wk": take(prefix + "self_attn.k_proj.weight", _t),
            "wv": take(prefix + "self_attn.v_proj.weight", _t),
            "wo": take(prefix + "self_attn.o_proj.weight", _t),
            "mlp_norm": take(prefix + "post_attention_layernorm.weight", _v),
        }
        if c.n_experts > 0:
            # Mixtral block-sparse MoE: gate.weight [E, d] is the router
            # (kept float32 — routing is precision-sensitive); per-expert
            # w1/w3/w2 are the gated-SiLU projections, stacked [E, ...]
            # for the batched expert einsum.
            moe_prefix = prefix + "block_sparse_moe."

            def stack_experts(name):
                # stack on HOST, one device transfer: per-expert
                # device_put + jnp.stack would hold two full copies of
                # every stacked tensor at peak
                consumed.update(
                    f"{moe_prefix}experts.{e}.{name}.weight"
                    for e in range(c.n_experts)
                )
                return jnp.asarray(
                    np.stack([
                        np.asarray(
                            sd[f"{moe_prefix}experts.{e}.{name}.weight"]
                            .detach().cpu().float().numpy().T
                        )
                        for e in range(c.n_experts)
                    ]),
                    dt,
                )

            layer["moe"] = {
                "router": take(
                    moe_prefix + "gate.weight",
                    lambda w, _dt: _t(w, jnp.float32),
                ),
                "w_gate": stack_experts("w1"),
                "w_up": stack_experts("w3"),
                "w_down": stack_experts("w2"),
            }
        else:
            layer.update(
                w_gate=take(prefix + "mlp.gate_proj.weight", _t),
                w_up=take(prefix + "mlp.up_proj.weight", _t),
                w_down=take(prefix + "mlp.down_proj.weight", _t),
            )
        params["layers"].append(layer)
    # Anything left over (attention/MLP biases, adapters, …) is a weight
    # this forward would NOT apply — dropping it silently would serve a
    # different model. Rotary frequency buffers are derived state, not
    # weights.
    leftover = [
        k for k in sd
        if k not in consumed and not k.endswith("rotary_emb.inv_freq")
    ]
    if leftover:
        raise ValueError(
            f"unconverted weights {leftover[:4]}{'...' if len(leftover) > 4 else ''}: "
            "this checkpoint uses features nos_tpu.models.llama does not "
            "implement (biases/adapters?)"
        )
    return params


def load_hf_llama(model_or_path, dtype=jnp.bfloat16) -> Tuple[Params, LlamaConfig]:
    """(params, config) from a transformers model instance or a local /
    hub checkpoint path."""
    if isinstance(model_or_path, str):
        # Auto resolves the family (Llama / Mistral / Mixtral / Gemma);
        # config_from_hf then accepts or rejects the architecture.
        from transformers import AutoModelForCausalLM

        model_or_path = AutoModelForCausalLM.from_pretrained(model_or_path)
    config = config_from_hf(model_or_path.config, dtype)
    return params_from_hf_state_dict(model_or_path.state_dict(), config), config
