"""LoRA fine-tuning: low-rank adapters on the attention/MLP projections.

Fine-tuning a checkpoint on a slice tenant's budget: instead of touching
the base weights (N params of optimizer state), train rank-r adapters
``delta W = (alpha/r) * A @ B`` on selected projections — the trainable
state is thousands of times smaller, the base stays frozen (and can stay
donated/shared between jobs), and the result either serves directly
(adapters applied on the fly) or merges back into a dense checkpoint that
composes with everything downstream (int8 quantization, TP sharding, the
serving engine).

TPU-first: adapters attach as ``LoraLinear`` pytree nodes the forward's
``_mm`` dispatch already understands (same mechanism as int8
QuantizedLinear), so NO model code forks — llama_forward, generate,
prefill, the engine all run adapted weights unchanged. The adapter matmul
``(x @ A) @ B`` keeps the low-rank structure (never materializes the
[in, out] delta) — rank-r tiles ride the MXU alongside the dense matmul.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]

# Projections LoRA understands (2-D [in, out] leaves of a llama layer).
_TARGETABLE = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


@dataclass(frozen=True)
class LoraConfig:
    rank: int = 8
    alpha: float = 16.0
    # Which per-layer projections get adapters (Q and V, the classic pick).
    targets: Tuple[str, ...] = ("wq", "wv")

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


@jax.tree_util.register_pytree_node_class
@dataclass
class LoraLinear:
    """Frozen base weight [in, out] + trainable low-rank delta A[in,r] @
    B[r,out] — applied on the fly, never materialized."""

    w: jax.Array
    a: jax.Array
    b: jax.Array
    # static (aux) so jit treats it as a compile-time constant
    scale: float = 1.0

    def matmul(self, x: jax.Array) -> jax.Array:
        base = x @ self.w
        delta = (x @ self.a.astype(x.dtype)) @ self.b.astype(x.dtype)
        return base + self.scale * delta

    def tree_flatten(self):
        return (self.w, self.a, self.b), self.scale

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, scale=aux)


@jax.tree_util.register_pytree_node_class
@dataclass
class MultiLoraLinear:
    """Frozen base weight + N STACKED adapters with a per-ROW selector:
    row b of the batch applies adapter ``idx[b]`` — the multi-tenant
    serving form (S-LoRA style), where every slot of a continuous-
    batching engine can run a different fine-tune against one shared
    base. Adapter 0 is reserved as the identity (zero delta).

    The gathered [B, in, r] adapter operands ride the MXU as batched
    rank-r matmuls next to the shared dense base matmul; the full
    [in, out] delta never materializes."""

    w: jax.Array      # [in, out] shared base
    a: jax.Array      # [N, in, r]
    b: jax.Array      # [N, r, out]
    idx: jax.Array    # [B] int32: row -> adapter id
    scale: float = 1.0

    def matmul(self, x: jax.Array) -> jax.Array:
        if x.ndim != 3:
            raise ValueError(
                f"MultiLoraLinear needs [B, S, d] activations, got {x.shape}"
            )
        base = x @ self.w
        a_sel = self.a[self.idx].astype(x.dtype)   # [B, in, r]
        b_sel = self.b[self.idx].astype(x.dtype)   # [B, r, out]
        delta = jnp.einsum("bsi,bir->bsr", x, a_sel)
        delta = jnp.einsum("bsr,bro->bso", delta, b_sel)
        return base + self.scale * delta

    def tree_flatten(self):
        return (self.w, self.a, self.b, self.idx), self.scale

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, scale=aux)


def stack_lora_adapters(
    params: Params, adapter_trees, lora: LoraConfig, rows: int = 1
) -> Params:
    """Base params + a LIST of adapter trees → serving tree whose
    targeted projections are MultiLoraLinear nodes. Adapter ids are
    1-based (id 0 = identity, stacked as zeros); every adapter must
    share the LoraConfig (rank/targets/scale). ``rows`` sizes the
    per-row selector (the engine's slot count), initialized to 0."""
    if not adapter_trees:
        raise ValueError(
            "stack_lora_adapters needs at least one adapter tree "
            "(a base-only engine doesn't need the stacked form)"
        )
    for ad in adapter_trees:
        _check_layer_counts(params, ad)
    idx = jnp.zeros((rows,), jnp.int32)
    out = dict(params)
    out["layers"] = []
    for li, base_layer in enumerate(params["layers"]):
        layer = dict(base_layer)
        for t in lora.targets:
            if t not in layer:
                raise ValueError(
                    f"LoRA target {t!r} absent from layer (MoE layers have "
                    "no dense MLP projections)"
                )
            a_stack = jnp.stack(
                [jnp.zeros_like(adapter_trees[0]["layers"][li][t]["a"])]
                + [ad["layers"][li][t]["a"] for ad in adapter_trees]
            )
            b_stack = jnp.stack(
                [jnp.zeros_like(adapter_trees[0]["layers"][li][t]["b"])]
                + [ad["layers"][li][t]["b"] for ad in adapter_trees]
            )
            layer[t] = MultiLoraLinear(
                w=layer[t], a=a_stack, b=b_stack, idx=idx, scale=lora.scale
            )
        out["layers"].append(layer)
    return out


def with_adapter_rows(params: Params, idx) -> Params:
    """Same tree with every MultiLoraLinear's row selector replaced by
    ``idx`` (shape sets the batch rows) — the engine points decode at
    its slots' adapters and admission at a single row, without copying
    any weight."""
    idx = jnp.asarray(idx, jnp.int32)

    def swap(leaf):
        if isinstance(leaf, MultiLoraLinear):
            return MultiLoraLinear(
                w=leaf.w, a=leaf.a, b=leaf.b, idx=idx, scale=leaf.scale
            )
        return leaf

    return jax.tree_util.tree_map(
        swap, params, is_leaf=lambda x: isinstance(x, MultiLoraLinear)
    )


def n_adapters(params: Params) -> int:
    """Stacked adapter count (including the identity at id 0), or 0 for
    trees without MultiLoraLinear nodes."""
    for leaf in jax.tree_util.tree_leaves(
        params, is_leaf=lambda x: isinstance(x, MultiLoraLinear)
    ):
        if isinstance(leaf, MultiLoraLinear):
            return leaf.a.shape[0]
    return 0


def init_lora_params(key: jax.Array, config, lora: LoraConfig) -> Params:
    """Adapter tree mirroring params['layers']: per layer, per target,
    {'a': [in, r] (scaled normal), 'b': [r, out] (ZEROS — the delta starts
    at exactly zero, so step 0 reproduces the base model bit for bit)."""
    for t in lora.targets:
        if t not in _TARGETABLE:
            raise ValueError(f"unknown LoRA target {t!r}; choose from {_TARGETABLE}")
    c = config
    hd = c.head_dim
    dims = {
        "wq": (c.d_model, c.n_heads * hd),
        "wk": (c.d_model, c.n_kv_heads * hd),
        "wv": (c.d_model, c.n_kv_heads * hd),
        "wo": (c.n_heads * hd, c.d_model),
        "w_gate": (c.d_model, c.d_ff),
        "w_up": (c.d_model, c.d_ff),
        "w_down": (c.d_ff, c.d_model),
    }
    layers = []
    keys = jax.random.split(key, c.n_layers)
    for lk in keys:
        t_keys = jax.random.split(lk, len(lora.targets))
        layer = {}
        for t, tk in zip(lora.targets, t_keys):
            d_in, d_out = dims[t]
            # Adapters stay float32: Adam's shrinking steps would round
            # to zero against bf16's 8-bit mantissa as the adapters grow
            # (the dense path accumulates f32 for the same reason), and at
            # rank<<d the extra bytes are noise. matmul casts per use.
            layer[t] = {
                "a": jax.random.normal(tk, (d_in, lora.rank), jnp.float32)
                / math.sqrt(d_in),
                "b": jnp.zeros((lora.rank, d_out), jnp.float32),
            }
        layers.append(layer)
    return {"layers": layers}


def _check_layer_counts(params: Params, lora_params: Params) -> None:
    n_base, n_ad = len(params["layers"]), len(lora_params["layers"])
    if n_base != n_ad:
        # zip would silently truncate the model to the shorter tree —
        # a 2-layer "merge" of a 32-layer checkpoint producing garbage.
        raise ValueError(
            f"adapter tree has {n_ad} layers but the model has {n_base}; "
            "the adapters were built for a different config"
        )


def attach_lora(params: Params, lora_params: Params, lora: LoraConfig) -> Params:
    """Base params + adapters → forward-ready tree with LoraLinear nodes at
    the targeted projections (everything else shared by reference)."""
    _check_layer_counts(params, lora_params)
    out = dict(params)
    out["layers"] = []
    for base_layer, ad_layer in zip(params["layers"], lora_params["layers"]):
        layer = dict(base_layer)
        for t, ab in ad_layer.items():
            if t not in layer:
                raise ValueError(
                    f"LoRA target {t!r} absent from layer (MoE layers have "
                    "no dense MLP projections)"
                )
            layer[t] = LoraLinear(
                w=layer[t], a=ab["a"], b=ab["b"], scale=lora.scale
            )
        out["layers"].append(layer)
    return out


def merge_lora(params: Params, lora_params: Params, lora: LoraConfig) -> Params:
    """Fold the adapters into dense weights: W + (alpha/r)·A@B — the
    serving artifact (quantizes, shards, and serves like any checkpoint)."""
    _check_layer_counts(params, lora_params)
    out = dict(params)
    out["layers"] = []
    for base_layer, ad_layer in zip(params["layers"], lora_params["layers"]):
        layer = dict(base_layer)
        for t, ab in ad_layer.items():
            if t not in layer:
                raise ValueError(
                    f"LoRA target {t!r} absent from layer (MoE layers have "
                    "no dense MLP projections)"
                )
            w = layer[t]
            delta = (
                ab["a"].astype(jnp.float32) @ ab["b"].astype(jnp.float32)
            ) * lora.scale
            layer[t] = (w.astype(jnp.float32) + delta).astype(w.dtype)
        out["layers"].append(layer)
    return out


def make_lora_train_step(
    mesh,
    config,
    lora: LoraConfig,
    learning_rate: float = 1e-3,
    optimizer=None,
):
    """Returns (train_step, shard_adapters) where
    train_step(adapter_state, base_params, tokens) -> (adapter_state, loss).

    Only the adapters carry gradients and optimizer state; the base params
    flow through as frozen constants (shard them once with
    llama_param_sharding and reuse across steps/jobs). Adapters are tiny —
    they replicate across the mesh (no FSDP needed at rank«d)."""
    import optax as _optax

    from nos_tpu.models.llama import llama_loss
    from nos_tpu.parallel.sharding import llama_data_sharding

    if optimizer is not None and learning_rate != 1e-3:
        # same contract as make_train_step: an optax optimizer OWNS its
        # hyperparameters — reject rather than silently ignore.
        raise ValueError(
            "learning_rate configures the built-in Adam; an optax optimizer "
            "carries its own — set it there instead"
        )
    opt = optimizer or _optax.adam(learning_rate)
    data_sharding = llama_data_sharding(mesh)

    def loss_fn(adapters, base_params, tokens):
        return llama_loss(attach_lora(base_params, adapters, lora), tokens, config, mesh)

    @jax.jit
    def train_step(adapter_state, base_params, tokens):
        adapters, opt_state = adapter_state
        tokens = jax.lax.with_sharding_constraint(tokens, data_sharding)
        loss, grads = jax.value_and_grad(loss_fn)(adapters, base_params, tokens)
        updates, opt_state = opt.update(grads, opt_state, adapters)
        adapters = _optax.apply_updates(adapters, updates)
        return (adapters, opt_state), loss

    def shard_adapters(adapters):
        from jax.sharding import NamedSharding, PartitionSpec as P

        replicated = NamedSharding(mesh, P())
        sharded = jax.device_put(
            adapters, jax.tree.map(lambda _: replicated, adapters)
        )
        return (sharded, opt.init(sharded))

    return train_step, shard_adapters
