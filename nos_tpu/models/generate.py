"""Autoregressive generation with a KV cache.

The serving half of the workload stack (the training half lives in
nos_tpu/parallel): prefill runs the full-sequence forward once and keeps
every layer's K/V; each decode step then attends one query position
against the cache — O(S) per token instead of O(S²) re-forwarding.

TPU-first choices: the cache is a static-shape [B, max_len, Hkv, hd]
ring-less buffer written with ``lax.dynamic_update_slice`` at a traced
position; the decode loop is a ``lax.scan`` over token steps (one compiled
program regardless of generation length); attention masks by position
against iota instead of slicing (no dynamic shapes anywhere, so XLA tiles
every matmul onto the MXU). GQA attends grouped queries against the
unexpanded cache exactly like the training path.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from nos_tpu.models.llama import (
    LlamaConfig,
    _apply_rope,
    _embed_rows,
    _mlp,
    _mm,
    _rms_norm,
    _rope,
    _rope_at,
    _unembed,
    _window_causal_mask,
    llama_forward,
)

Params = Dict[str, Any]
Cache = List[Dict[str, jax.Array]]


def init_kv_cache(
    config: LlamaConfig, batch: int, max_len: int, quant: bool = False
) -> Cache:
    """Per-layer K/V buffers [B, max_len, Hkv, hd] in the model dtype.

    ``quant``: int8 storage with per-(row, slot, head) f32 absmax scales
    — HALF the cache HBM (the long-context ceiling and the decode read
    bandwidth). Dequantization folds into attention (scores × k_scale;
    probs × v_scale before the value matmul), so the widened cache never
    materializes. Lossy: ~0.4% RMS per read, standard KV-quant
    discipline — prompt prefill still attends its own K/V exactly."""
    c = config
    shape = (batch, max_len, c.n_kv_heads, c.head_dim)
    if not quant:
        return [
            {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype)}
            for _ in range(c.n_layers)
        ]
    sshape = shape[:-1]
    return [
        {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(sshape, jnp.float32),
            "v_scale": jnp.zeros(sshape, jnp.float32),
        }
        for _ in range(c.n_layers)
    ]


def _kv_quantized(cache: Cache) -> bool:
    return bool(cache) and "k_scale" in cache[0]


def _quantize_kv(vec: jax.Array):
    """[..., hd] → (int8 [..., hd], f32 scale [...]): symmetric absmax
    over the head dim — one scale per written K/V vector."""
    v32 = vec.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(v32), axis=-1)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(v32 / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


def _ffn(
    h: jax.Array, layer: Params, config: LlamaConfig, token_mask=None
) -> jax.Array:
    """Dense MLP or routed MoE, matching llama_forward's block dispatch so
    MoE checkpoints serve through the same cache path. ``token_mask``
    keeps padding columns out of the MoE capacity race (a dense MLP is
    per-token, so pads can't affect neighbors there)."""
    if "moe" in layer:
        from nos_tpu.models.moe import moe_mlp

        return moe_mlp(
            layer["moe"], h, config.moe_config(), token_mask=token_mask
        )
    return _mlp(h, layer, config.hidden_act)


def _cache_attention(
    q, cache_k, cache_v, n_valid, config: LlamaConfig, key_valid=None,
    rolling: int = 0, k_scale=None, v_scale=None,
):
    """q [B, S, Hq, hd] against cache [B, T, Hkv, hd], masked to the first
    ``n_valid`` positions. ``n_valid`` may be a scalar (one shared
    frontier), [B] (per-row frontiers — continuous batching), or [B, S]
    (per-query frontiers — multi-token chunk decode, where query i sees
    keys [0, pos+i+1)). ``key_valid`` [B, T] additionally masks slots
    that hold padding (left-padded batches).

    ``rolling`` = C > 0 switches to the ROLLING sliding-window layout:
    physical slot s holds logical position l_s = (f-1) - ((f-1-s) mod C)
    for frontier f (the most recent logical ≡ s mod C), ``n_valid`` stays
    the LOGICAL frontier, and validity is l_s ≥ 0 within the window —
    an unbounded stream attends its last W keys from C cache slots.
    Slots ≥ C (the sacrificial pad-write slot) are never valid."""
    c = config
    b, s, hq, hd = q.shape
    t = cache_k.shape[1]
    group = c.n_heads // c.n_kv_heads
    qg = q.reshape(b, s, c.n_kv_heads, group, hd)
    if k_scale is not None:
        # int8 cache: the astype RELIES on XLA fusing the int8->bf16
        # convert into the dot's operand load (the int8-weight recipe) —
        # fused, the widened keys never round-trip HBM; per-vector
        # scales apply POST-score either way
        cache_k = cache_k.astype(qg.dtype)
    scores = jnp.einsum(
        "bsKgh,btKh->bKgst", qg, cache_k, preferred_element_type=jnp.float32
    )
    if k_scale is not None:
        scores = scores * k_scale.transpose(0, 2, 1)[:, :, None, None, :]
    scores = scores / math.sqrt(hd)
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, 1, t), 4)
    ndim = getattr(n_valid, "ndim", 0)
    if ndim == 2:
        frontier = n_valid[:, None, None, :, None]
    elif ndim == 1:
        frontier = n_valid[:, None, None, None, None]
    else:
        frontier = n_valid
    if rolling:
        if c.sliding_window is None:
            raise ValueError("rolling cache requires sliding_window")
        f1 = frontier - 1
        # logical position held by each physical slot (negative mod
        # stays well-defined: f1 - s may be negative only for slots the
        # l_s >= 0 check rejects anyway)
        ls = f1 - jnp.mod(f1 - iota, rolling)
        valid = (ls >= 0) & (ls > f1 - c.sliding_window) & (iota < rolling)
    else:
        valid = iota < frontier
        if c.sliding_window is not None:
            # the query at frontier f-1 sees keys (f-1-W, f-1]; cache
            # slots == logical positions on the unpadded serving path
            valid = valid & (iota >= frontier - c.sliding_window)
    if key_valid is not None:
        valid = valid & key_valid[:, None, None, None, :]
    scores = jnp.where(valid, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if v_scale is not None:
        # fold the value dequant into the probabilities: Σ_t p·v8·scale
        # = Σ_t (p·scale)·v8 — elementwise on probs, no widened values
        probs = probs * v_scale.transpose(0, 2, 1).astype(probs.dtype)[
            :, :, None, None, :
        ]
        cache_v = cache_v.astype(q.dtype)
    out = jnp.einsum("bKgst,btKh->bsKgh", probs, cache_v)
    return out.reshape(b, s, c.n_heads * hd)


def prefill(
    params: Params, tokens: jax.Array, config: LlamaConfig, max_len: int,
    pad_id: int = None, quant: bool = False,
) -> Tuple[jax.Array, Cache]:
    """Full forward over the prompt; returns (logits [B, S, vocab], cache
    holding the prompt's K/V in positions [0, S)).

    ``pad_id`` enables LEFT-padded variable-length batches: pad tokens are
    excluded from attention, and RoPE positions count only real tokens so
    every row's first real token sits at position 0. The last column is
    always a real token under left padding, so ``logits[:, -1]`` is the
    next-token distribution for every row."""
    c = config
    b, s = tokens.shape
    if s > max_len:
        raise ValueError(f"prompt length {s} exceeds cache capacity {max_len}")
    if c.sliding_window is not None and pad_id is not None:
        # left padding decouples physical cache slots from logical
        # positions; the window mask runs over physical slots, so the
        # combination would silently attend the wrong band
        raise ValueError(
            "sliding_window does not support left-padded prompts; batch "
            "via the engine's chunked admission instead"
        )
    x = _embed_rows(params["embed"], tokens, c.dtype, c.embed_scale)
    if pad_id is None:
        cos, sin = _rope(s, c.head_dim, c.rope_theta, c.dtype, c.rope_scaling)
        cos_b = sin_b = None
        token_valid = None
    else:
        token_valid = tokens != pad_id  # [B, S]
        positions = jnp.clip(jnp.cumsum(token_valid, axis=1) - 1, 0)  # [B, S]
        cos_b, sin_b = _rope_at(
            positions.reshape(-1), c.head_dim, c.rope_theta, c.dtype, c.rope_scaling
        )
        cos_b = cos_b.reshape(b, s, -1)[:, :, None, :]  # [B, S, 1, hd/2]
        sin_b = sin_b.reshape(b, s, -1)[:, :, None, :]
        cos = sin = None
    cache = init_kv_cache(c, b, max_len, quant=quant)
    def rope(arr):
        if pad_id is None:
            return _apply_rope(arr, cos, sin)
        return _apply_rope(arr, cos_b, sin_b)  # rank-4: per-row tables

    for i, layer in enumerate(params["layers"]):
        h = _rms_norm(x, layer["attn_norm"], c.norm_eps, c.norm_offset)
        hd = c.head_dim
        q = _mm(h, layer["wq"]).reshape(b, s, c.n_heads, hd)
        k = _mm(h, layer["wk"]).reshape(b, s, c.n_kv_heads, hd)
        v = _mm(h, layer["wv"]).reshape(b, s, c.n_kv_heads, hd)
        q = rope(q)
        k = rope(k)
        if quant:
            # store quantized for later decode reads; the prompt's OWN
            # attention below still runs on the exact fresh K/V
            k8, kvec_s = _quantize_kv(k)
            v8, vvec_s = _quantize_kv(v)
            cache[i]["k"] = jax.lax.dynamic_update_slice(
                cache[i]["k"], k8, (0, 0, 0, 0)
            )
            cache[i]["v"] = jax.lax.dynamic_update_slice(
                cache[i]["v"], v8, (0, 0, 0, 0)
            )
            cache[i]["k_scale"] = jax.lax.dynamic_update_slice(
                cache[i]["k_scale"], kvec_s, (0, 0, 0)
            )
            cache[i]["v_scale"] = jax.lax.dynamic_update_slice(
                cache[i]["v_scale"], vvec_s, (0, 0, 0)
            )
        else:
            cache[i]["k"] = jax.lax.dynamic_update_slice(
                cache[i]["k"], k.astype(c.dtype), (0, 0, 0, 0)
            )
            cache[i]["v"] = jax.lax.dynamic_update_slice(
                cache[i]["v"], v.astype(c.dtype), (0, 0, 0, 0)
            )
        # causal attention within the prompt; long prompts ride the flash
        # kernel (O(blk) VMEM) when the config asks for it, matching the
        # training path's dispatch. Padded batches need per-key masks the
        # kernel does not take, so they use the dense path.
        if c.attention == "flash" and pad_id is None:
            from nos_tpu.ops import flash_attention

            attn = flash_attention(
                q, k, v, causal=True, window=c.sliding_window,
                interpret=jax.default_backend() == "cpu",
            ).reshape(b, s, c.n_heads * hd)
        else:
            group = c.n_heads // c.n_kv_heads
            qg = q.reshape(b, s, c.n_kv_heads, group, hd)
            scores = jnp.einsum(
                "bsKgh,btKh->bKgst", qg, k, preferred_element_type=jnp.float32
            )
            scores = scores / math.sqrt(hd)
            mask = _window_causal_mask(s, c.sliding_window)[None, None, None]
            if token_valid is not None:
                mask = mask & token_valid[:, None, None, None, :]
            scores = jnp.where(mask, scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
            attn = jnp.einsum("bKgst,btKh->bsKgh", probs, v).reshape(
                b, s, c.n_heads * hd
            )
        x = x + _mm(attn, layer["wo"])
        x = x + _ffn(
            _rms_norm(x, layer["mlp_norm"], c.norm_eps, c.norm_offset),
            layer, c, token_mask=token_valid,
        )
    x = _rms_norm(x, params["final_norm"], c.norm_eps, c.norm_offset)
    return _unembed(params, x).astype(jnp.float32), cache


def decode_step(
    params: Params,
    cache: Cache,
    pos: jax.Array,
    token: jax.Array,
    config: LlamaConfig,
    rope_pos: jax.Array = None,
    key_valid: jax.Array = None,
    row_valid: jax.Array = None,
    rolling: bool = False,
) -> Tuple[jax.Array, Cache]:
    """One token at (traced) physical cache slot ``pos`` → (logits
    [B, vocab], cache with K/V written at pos).

    Left-padded batches decouple the two position notions: ``pos`` is the
    uniform physical slot (prompt length + step), while ``rope_pos`` [B]
    carries each row's LOGICAL position (real tokens seen so far);
    ``key_valid`` [B, T] masks the pad slots out of attention.

    ``pos`` may also be per-row [B] (continuous batching: every slot
    decodes at its own depth) — K/V writes become row scatters and the
    attention frontier is per-row; rope defaults to ``pos`` itself.

    ``row_valid`` [B] marks rows carrying a REAL token (continuous
    batching: idle/ridden slots are garbage); masked rows are kept out
    of the MoE expert-capacity race so a dead row can never displace a
    live one. Defaults to "has any valid key" when ``key_valid`` is
    given (the engine zeroes a retired row's key_valid).

    ``rolling`` (sliding-window configs, per-row ``pos``): physical
    slot = logical pos mod C with C = cache_len - 1, so a stream of any
    length serves from O(window) cache (see _cache_attention)."""
    c = config
    b = token.shape[0]
    hd = c.head_dim
    per_row = getattr(pos, "ndim", 0) == 1
    if row_valid is None and key_valid is not None:
        row_valid = jnp.any(key_valid, axis=1)
    ffn_mask = None if row_valid is None else row_valid[:, None]
    cap = cache[0]["k"].shape[1] - 1 if rolling else 0
    if rolling and not per_row:
        raise ValueError("rolling decode needs per-row positions")
    quant = _kv_quantized(cache)
    x = _embed_rows(params["embed"], token, c.dtype, c.embed_scale)[:, None, :]  # [B, 1, D]
    if rope_pos is None and per_row:
        rope_pos = pos
    if rope_pos is None:
        cos, sin = _rope_at(pos[None], hd, c.rope_theta, c.dtype, c.rope_scaling)
        cos = cos[None, :, None, :]  # [1, 1, 1, hd/2]: broadcast over rows
        sin = sin[None, :, None, :]
    else:
        cos, sin = _rope_at(rope_pos, hd, c.rope_theta, c.dtype, c.rope_scaling)
        cos = cos[:, None, None, :]  # [B, 1, 1, hd/2]: per-row tables
        sin = sin[:, None, None, :]

    def rope1(arr):  # arr [B, 1, H, hd]
        return _apply_rope(arr, cos, sin)

    rows = jnp.arange(b)
    new_cache: Cache = []
    for layer, kv in zip(params["layers"], cache):
        h = _rms_norm(x, layer["attn_norm"], c.norm_eps, c.norm_offset)
        q = _mm(h, layer["wq"]).reshape(b, 1, c.n_heads, hd)
        k = _mm(h, layer["wk"]).reshape(b, 1, c.n_kv_heads, hd)
        v = _mm(h, layer["wv"]).reshape(b, 1, c.n_kv_heads, hd)
        q = rope1(q)
        k = rope1(k)
        ks = vs = None
        if quant:
            k8, kvec_s = _quantize_kv(k[:, 0] if per_row else k)
            v8, vvec_s = _quantize_kv(v[:, 0] if per_row else v)
            if per_row:
                wslot = pos % cap if rolling else pos
                ck = kv["k"].at[rows, wslot].set(k8)
                cv = kv["v"].at[rows, wslot].set(v8)
                ks = kv["k_scale"].at[rows, wslot].set(kvec_s)
                vs = kv["v_scale"].at[rows, wslot].set(vvec_s)
            else:
                ck = jax.lax.dynamic_update_slice(kv["k"], k8, (0, pos, 0, 0))
                cv = jax.lax.dynamic_update_slice(kv["v"], v8, (0, pos, 0, 0))
                ks = jax.lax.dynamic_update_slice(kv["k_scale"], kvec_s, (0, pos, 0))
                vs = jax.lax.dynamic_update_slice(kv["v_scale"], vvec_s, (0, pos, 0))
            new_cache.append({"k": ck, "v": cv, "k_scale": ks, "v_scale": vs})
        elif per_row:
            wslot = pos % cap if rolling else pos
            ck = kv["k"].at[rows, wslot].set(k[:, 0].astype(c.dtype))
            cv = kv["v"].at[rows, wslot].set(v[:, 0].astype(c.dtype))
            new_cache.append({"k": ck, "v": cv})
        else:
            ck = jax.lax.dynamic_update_slice(kv["k"], k.astype(c.dtype), (0, pos, 0, 0))
            cv = jax.lax.dynamic_update_slice(kv["v"], v.astype(c.dtype), (0, pos, 0, 0))
            new_cache.append({"k": ck, "v": cv})
        attn = _cache_attention(
            q, ck, cv, pos + 1, c, key_valid=key_valid, rolling=cap,
            k_scale=ks, v_scale=vs,
        )
        x = x + _mm(attn, layer["wo"])
        x = x + _ffn(
            _rms_norm(x, layer["mlp_norm"], c.norm_eps, c.norm_offset),
            layer, c, token_mask=ffn_mask,
        )
    x = _rms_norm(x, params["final_norm"], c.norm_eps, c.norm_offset)
    return _unembed(params, x[:, 0]).astype(jnp.float32), new_cache


def decode_chunk(
    params: Params,
    cache: Cache,
    pos: jax.Array,
    tokens: jax.Array,
    config: LlamaConfig,
    write_mask: jax.Array = None,
    row_valid: jax.Array = None,
    rolling: bool = False,
) -> Tuple[jax.Array, Cache]:
    """``m`` tokens at per-row physical slots ``pos``..``pos+m-1`` →
    (logits [B, m, vocab], cache with the chunk's K/V written).

    The multi-token generalization of decode_step: query i attends the
    cache frontier [0, pos+i+1) — causal within the chunk, everything
    before it outside. One dispatch verifies a whole speculative draft or
    ingests a prompt chunk (chunked prefill) at O(m·T) instead of m
    sequential O(T) steps.

    ``pos`` is [B] (per-row, like the engine's decode). ``write_mask``
    [B, m] marks PADDING positions: their K/V writes redirect to the
    cache's LAST slot (callers must size the cache with a sacrificial
    trailing slot their frontier never reaches) AND, on MoE models,
    they claim no expert capacity and emit zero from the mixture — pads
    must be invisible to real tokens in every sense, not just the
    cache. ``row_valid`` [B] additionally masks WHOLE rows from the MoE
    capacity race (continuous batching: finished slots riding the
    chunk). ``rolling``: modular sliding-window layout over C =
    cache_len - 1 slots (slot C stays the pad target); requires
    C ≥ window + m so a chunk's writes never evict keys its own
    queries still need.
    """
    c = config
    b, m = tokens.shape
    hd = c.head_dim
    x = _embed_rows(params["embed"], tokens, c.dtype, c.embed_scale)  # [B, m, D]
    offsets = jnp.arange(m, dtype=pos.dtype)
    posmat = pos[:, None] + offsets[None, :]  # [B, m]
    cos, sin = _rope_at(
        posmat.reshape(-1), hd, c.rope_theta, c.dtype, c.rope_scaling
    )
    cos = cos.reshape(b, m, 1, -1)
    sin = sin.reshape(b, m, 1, -1)
    t_cache = cache[0]["k"].shape[1]
    cap = t_cache - 1 if rolling else 0
    real_pos = posmat % cap if rolling else posmat
    if write_mask is not None:
        write_pos = jnp.where(write_mask, real_pos, t_cache - 1)
    else:
        write_pos = real_pos
    ffn_mask = write_mask
    if row_valid is not None:
        row_col = row_valid[:, None] & jnp.ones((1, m), bool)
        ffn_mask = row_col if ffn_mask is None else (ffn_mask & row_col)
    rows = jnp.arange(b)[:, None]
    frontier = posmat + 1  # [B, m]: query i sees keys < pos+i+1
    quant = _kv_quantized(cache)

    new_cache: Cache = []
    for layer, kv in zip(params["layers"], cache):
        h = _rms_norm(x, layer["attn_norm"], c.norm_eps, c.norm_offset)
        q = _mm(h, layer["wq"]).reshape(b, m, c.n_heads, hd)
        k = _mm(h, layer["wk"]).reshape(b, m, c.n_kv_heads, hd)
        v = _mm(h, layer["wv"]).reshape(b, m, c.n_kv_heads, hd)
        q = _apply_rope(q, cos, sin)
        k = _apply_rope(k, cos, sin)
        ks = vs = None
        if quant:
            k8, kvec_s = _quantize_kv(k)
            v8, vvec_s = _quantize_kv(v)
            ck = kv["k"].at[rows, write_pos].set(k8)
            cv = kv["v"].at[rows, write_pos].set(v8)
            ks = kv["k_scale"].at[rows, write_pos].set(kvec_s)
            vs = kv["v_scale"].at[rows, write_pos].set(vvec_s)
            new_cache.append({"k": ck, "v": cv, "k_scale": ks, "v_scale": vs})
        else:
            ck = kv["k"].at[rows, write_pos].set(k.astype(c.dtype))
            cv = kv["v"].at[rows, write_pos].set(v.astype(c.dtype))
            new_cache.append({"k": ck, "v": cv})
        attn = _cache_attention(
            q, ck, cv, frontier, c, rolling=cap, k_scale=ks, v_scale=vs
        )
        x = x + _mm(attn, layer["wo"])
        x = x + _ffn(
            _rms_norm(x, layer["mlp_norm"], c.norm_eps, c.norm_offset),
            layer, c, token_mask=ffn_mask,
        )
    x = _rms_norm(x, params["final_norm"], c.norm_eps, c.norm_offset)
    return _unembed(params, x).astype(jnp.float32), new_cache


def _nucleus_cutoff(sorted_desc: jax.Array, top_p) -> jax.Array:
    """THE nucleus rule, shared by the static and per-row filters: given
    descending-sorted logits [..., V] and a broadcastable top_p, returns
    the per-row cutoff logit. Mass strictly ABOVE each rank: rank is kept
    while that mass < p, which keeps the first token whose inclusion
    crosses p. Rank 0 is kept unconditionally so top_p <= 0 degrades to
    greedy instead of masking the whole vocabulary (categorical over
    all--inf silently returns token 0)."""
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    mass_before = jnp.cumsum(probs, axis=-1) - probs
    keep = (mass_before < top_p).at[..., 0].set(True)
    return jnp.min(jnp.where(keep, sorted_desc, jnp.inf), axis=-1, keepdims=True)


def _filter_logits(logits: jax.Array, top_k: int, top_p: float) -> jax.Array:
    """Standard sampling filters with STATIC parameters (jit-stable for
    generate's scalar arguments): top-k keeps the k highest logits;
    nucleus (top-p) keeps the smallest prefix of the probability-sorted
    vocabulary whose mass reaches p. Masked entries go to -inf so
    ``jax.random.categorical`` never draws them."""
    if top_k and 0 < top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None and top_p < 1.0:
        sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
        cutoff = _nucleus_cutoff(sorted_desc, top_p)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return logits


def pick_tokens_per_row(
    logits: jax.Array,
    temp: jax.Array,
    top_k: jax.Array,
    top_p: jax.Array,
    keys: jax.Array,
) -> jax.Array:
    """Per-row next token for mixed batches (continuous batching): greedy
    where temp == 0, otherwise temperature sampling with per-row TRACED
    top-k / nucleus parameters and per-row PRNG keys [B] — each row's
    stream depends only on its own key sequence, never on its slot index
    or co-tenants. One descending sort serves both filters (masking below
    the k-th value preserves the order)."""
    v = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.where(temp > 0, temp, 1.0)[:, None]
    sorted_desc = jnp.sort(scaled, axis=-1)[..., ::-1]
    k_idx = jnp.clip(top_k - 1, 0, v - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=-1)
    kth = jnp.where((top_k > 0)[:, None], kth, -jnp.inf)
    filtered = jnp.where(scaled < kth, -jnp.inf, scaled)
    # top-k masking keeps descending order: reuse the sort for the nucleus
    sorted2 = jnp.where(sorted_desc < kth, -jnp.inf, sorted_desc)
    cutoff = _nucleus_cutoff(sorted2, top_p[:, None])
    filtered = jnp.where(filtered < cutoff, -jnp.inf, filtered)
    sampled = jax.vmap(jax.random.categorical)(keys, filtered).astype(jnp.int32)
    return jnp.where(temp > 0, sampled, greedy)


def generate(
    params: Params,
    prompt: jax.Array,
    config: LlamaConfig,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    # keyword-only: inserting these positionally would silently rebind
    # existing callers' positional rng/pad_id/eos_id arguments
    *,
    top_k: int = 0,
    top_p: float = 1.0,
    pad_id: Optional[int] = None,
    eos_id: Optional[int] = None,
    kv_quant: bool = False,
) -> jax.Array:
    """prompt [B, S] → generated tokens [B, max_new_tokens].

    Greedy when temperature == 0, otherwise temperature sampling with
    optional top-k / nucleus (top-p) filtering applied in that order. The
    decode loop is one ``lax.scan`` — compile once, reuse for any prompt
    of the same shape. Variable-length prompts batch via LEFT padding:
    pass ``pad_id`` and pad each row on the left; pads never attend and
    each row's RoPE counts only its real tokens, so the batched output
    equals row-by-row unpadded generation. With ``eos_id``, a row that
    emits it keeps emitting ``eos_id`` for the rest of the (static-length)
    scan — trim on the first occurrence. ``kv_quant`` stores the cache
    as int8 (half the HBM; lossy decode reads — see init_kv_cache)."""
    c = config
    b, s = prompt.shape
    max_len = s + max_new_tokens
    logits, cache = prefill(
        params, prompt, c, max_len, pad_id=pad_id, quant=kv_quant
    )
    if rng is None:
        rng = jax.random.key(0)

    if pad_id is not None:
        token_valid = prompt != pad_id
        rope_pos0 = jnp.sum(token_valid, axis=1)  # next logical position per row
        # Appended slots are physically bounded by pos+1 in decode, so
        # pre-marking them valid is safe; only prompt pads stay masked.
        key_valid = jnp.pad(
            token_valid, ((0, 0), (0, max_new_tokens)), constant_values=True
        )
    else:
        rope_pos0 = None
        key_valid = None

    def pick(logits, key):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(prompt.dtype)
        filtered = _filter_logits(logits / temperature, top_k, top_p)
        return jax.random.categorical(key, filtered, axis=-1).astype(prompt.dtype)

    # Single-use keys: every sample consumes a fresh split — the carried
    # key is only ever a split parent, never passed to categorical itself.
    # Left padding keeps the LAST column real, so logits[:, -1] is the
    # next-token distribution for every row either way.
    rng, first_key = jax.random.split(rng)
    first = pick(logits[:, -1], first_key)
    done0 = (
        jnp.zeros((b,), bool) if eos_id is None else first == eos_id
    )

    def body(carry, _):
        cache, pos, rope_pos, token, done, rng = carry
        rng, sub = jax.random.split(rng)
        logits, cache = decode_step(
            params, cache, pos, token, c, rope_pos=rope_pos, key_valid=key_valid
        )
        nxt = pick(logits, sub)
        if eos_id is not None:
            nxt = jnp.where(done, jnp.asarray(eos_id, nxt.dtype), nxt)
            done = done | (nxt == eos_id)
        next_rope = None if rope_pos is None else rope_pos + 1
        return (cache, pos + 1, next_rope, nxt, done, rng), token

    (_, _, _, _, _, _), tokens = jax.lax.scan(
        body, (cache, jnp.asarray(s), rope_pos0, first, done0, rng), None,
        length=max_new_tokens,
    )
    return jnp.moveaxis(tokens, 0, 1)  # [B, max_new_tokens]


def reference_generate(
    params: Params, prompt: jax.Array, config: LlamaConfig, max_new_tokens: int
) -> jax.Array:
    """Cache-free greedy generation (re-forwards the whole sequence every
    step) — the O(S²·N) oracle the cached path is tested against."""
    tokens = prompt
    for _ in range(max_new_tokens):
        logits = llama_forward(params, tokens, config)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(prompt.dtype)
        tokens = jnp.concatenate([tokens, nxt[:, None]], axis=1)
    return tokens[:, prompt.shape[1]:]
