"""Llama-style decoder-only transformer in pure JAX.

TPU-first choices: bfloat16 activations/params feeding the MXU, static
shapes throughout (no data-dependent control flow under jit), grouped-query
attention expressed as einsums XLA fuses and tiles, RoPE precomputed
per-call from static lengths. The flagship config mirrors Llama-3-8B
(BASELINE.json config #5: "Llama-3-8B JAX on auto-carved v5e 4x4 slice").
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    rope_theta: float = 500000.0
    # Llama-3.1-style frequency scaling, hashable form:
    # ("llama3", factor, low_freq_factor, high_freq_factor,
    #  original_max_position_embeddings); None = plain RoPE.
    rope_scaling: Any = None
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # "dense" (XLA einsum) or "flash" (Pallas kernel, nos_tpu/ops/ —
    # differentiable via its custom_vjp; O(S) memory for training and
    # serving at long context).
    attention: str = "dense"
    # Per-layer rematerialisation: save only each block's input and
    # recompute activations in the backward — trades ~1/3 more FLOPs for
    # activation memory that no longer scales with n_layers, which is what
    # lets a 16 GB chip train at real batch×sequence sizes.
    remat: bool = False
    # Sliding-window attention (Mistral-style): each query attends only
    # the last `sliding_window` positions. None = full causal attention.
    # Masking-only (the KV cache is not ring-buffered). Served by every
    # training/forward path: dense, the flash kernel, and BOTH
    # sequence-parallel strategies — banded blocks fully past the window
    # are skipped (kernel grid and ring hops alike), so long-context
    # compute is O(S·W), not O(S²).
    sliding_window: Any = None
    # Sequence-parallel strategy when the mesh has an sp axis: "ring"
    # (K/V rotation via ppermute, O(S/n) resident sequence) or "ulysses"
    # (two all_to_alls scatter heads / gather sequence — needs head
    # counts divisible by the sp degree; see parallel/ulysses.py for the
    # memory/comm trade).
    sp_strategy: str = "ring"
    # Gemma-style knobs (all default to the Llama behavior):
    # gated-MLP activation — "silu" (Llama/Mistral) or "gelu"
    # (Gemma's gelu_pytorch_tanh).
    hidden_act: str = "silu"
    # RMSNorm weight parameterization: True multiplies by (1 + w)
    # (Gemma stores norm weights near zero), False by w.
    norm_offset: bool = False
    # Multiply embeddings by sqrt(d_model) after lookup (Gemma).
    scale_embeddings: bool = False
    # Tie the unembedding to the input embedding (logits = x @ embed.T);
    # params carry no separate lm_head.
    tie_embeddings: bool = False
    # Explicit attention head dim when it differs from d_model/n_heads
    # (Gemma-7B: 256 heads dim at d_model 3072 / 16 heads). None derives.
    qk_head_dim: Any = None
    # n_experts > 0 swaps every MLP for a routed mixture-of-experts
    # (nos_tpu/models/moe.py) with experts sharded over the ep mesh axis.
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    # Weight of the Switch-style load-balancing loss in llama_loss.
    moe_aux_coef: float = 0.01

    def moe_config(self):
        from nos_tpu.models.moe import MoeConfig

        return MoeConfig(
            d_model=self.d_model,
            d_ff=self.d_ff,
            n_experts=self.n_experts,
            top_k=self.moe_top_k,
            capacity_factor=self.moe_capacity_factor,
            dtype=self.dtype,
        )

    @property
    def head_dim(self) -> int:
        if self.qk_head_dim is not None:
            return int(self.qk_head_dim)
        return self.d_model // self.n_heads

    @property
    def embed_scale(self):
        """Post-lookup embedding multiplier, or None (Gemma scales by
        sqrt(d_model) in the model's working dtype)."""
        if not self.scale_embeddings:
            return None
        import numpy as _np

        # bf16-rounded like the reference implementations (HF casts the
        # scalar to the embedding dtype before multiplying).
        return jnp.asarray(_np.sqrt(self.d_model), self.dtype)


def tiny_config(**overrides) -> LlamaConfig:
    """Small config for tests / dry runs; dims stay multiples of 8 so a
    virtual 8-device mesh can shard every axis."""
    defaults = dict(
        vocab_size=256,
        d_model=64,
        n_layers=2,
        n_heads=8,
        n_kv_heads=8,
        d_ff=128,
    )
    defaults.update(overrides)
    return LlamaConfig(**defaults)


def llama_3_8b_config() -> LlamaConfig:
    return LlamaConfig()


def gemma_2b_config() -> LlamaConfig:
    """Gemma-2B: same decoder skeleton, four dialect switches (gelu gated
    MLP, (1 + w) RMSNorm, sqrt(d_model)-scaled embeddings, tied
    unembedding) plus MQA (1 kv head) and an explicit 256 head dim."""
    return LlamaConfig(
        vocab_size=256000,
        d_model=2048,
        n_layers=18,
        n_heads=8,
        n_kv_heads=1,
        d_ff=16384,
        rope_theta=10000.0,
        norm_eps=1e-6,
        hidden_act="gelu",
        norm_offset=True,
        scale_embeddings=True,
        tie_embeddings=True,
        qk_head_dim=256,
    )


# ------------------------------------------------------------------- init


def _norm_init(c: LlamaConfig) -> jax.Array:
    # Identity norm at init: 1 for plain weights, 0 under the (1 + w)
    # offset parameterization.
    return jnp.zeros((c.d_model,), c.dtype) if c.norm_offset else jnp.ones(
        (c.d_model,), c.dtype
    )


def init_llama_params(key: jax.Array, config: LlamaConfig) -> Params:
    c = config
    keys = iter(jax.random.split(key, 4 + 7 * c.n_layers))

    def dense(k, shape, scale_dim):
        return (jax.random.normal(k, shape, jnp.float32) / math.sqrt(scale_dim)).astype(
            c.dtype
        )

    params: Params = {
        "embed": dense(next(keys), (c.vocab_size, c.d_model), c.d_model),
        "final_norm": _norm_init(c),
        "layers": [],
    }
    if not c.tie_embeddings:
        params["lm_head"] = dense(next(keys), (c.d_model, c.vocab_size), c.d_model)
    else:
        next(keys)  # keep downstream layer key streams stable
    hd = c.head_dim
    for _ in range(c.n_layers):
        layer = {
            "attn_norm": _norm_init(c),
            "wq": dense(next(keys), (c.d_model, c.n_heads * hd), c.d_model),
            "wk": dense(next(keys), (c.d_model, c.n_kv_heads * hd), c.d_model),
            "wv": dense(next(keys), (c.d_model, c.n_kv_heads * hd), c.d_model),
            "wo": dense(next(keys), (c.n_heads * hd, c.d_model), c.n_heads * hd),
            "mlp_norm": _norm_init(c),
        }
        if c.n_experts > 0:
            from nos_tpu.models.moe import init_moe_params

            layer["moe"] = init_moe_params(next(keys), c.moe_config())
            # consume the unused dense-mlp keys to keep layer streams stable
            next(keys), next(keys)
        else:
            layer["w_gate"] = dense(next(keys), (c.d_model, c.d_ff), c.d_model)
            layer["w_up"] = dense(next(keys), (c.d_model, c.d_ff), c.d_model)
            layer["w_down"] = dense(next(keys), (c.d_ff, c.d_model), c.d_ff)
        params["layers"].append(layer)
    return params


# ---------------------------------------------------------------- forward


def _mm(x: jax.Array, w) -> jax.Array:
    """x @ w, dispatching on the weight leaf: dense bf16, int8
    QuantizedLinear (serving), LoraLinear (adapter fine-tuning), or
    MultiLoraLinear (per-row multi-tenant adapter serving)."""
    from nos_tpu.models.lora import LoraLinear, MultiLoraLinear
    from nos_tpu.models.quantize import QuantizedLinear, QuantizedLinear4

    if isinstance(w, (QuantizedLinear, QuantizedLinear4)):
        return w.matmul(x)
    if isinstance(w, (LoraLinear, MultiLoraLinear)):
        return w.matmul(x)
    return x @ w


def _embed_rows(embed, tokens: jax.Array, dtype, scale=None) -> jax.Array:
    from nos_tpu.models.quantize import QuantizedEmbedding

    if isinstance(embed, QuantizedEmbedding):
        rows = embed.lookup(tokens, dtype)
    else:
        rows = embed[tokens]
    if scale is not None:
        rows = rows * scale
    return rows


def _rms_norm(
    x: jax.Array, weight: jax.Array, eps: float, offset: bool = False
) -> jax.Array:
    x32 = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    if offset:
        # Gemma's (1 + w) parameterization: weights sit near 0, so the
        # add must happen in float32 (HF computes `* (1 + w.float())`
        # before the downcast — doing it in bf16 would quantize away
        # small weights in steps of ~2^-7 around 1.0).
        return ((x32 * rms) * (weight.astype(jnp.float32) + 1.0)).astype(x.dtype)
    return (x32 * rms).astype(x.dtype) * weight


def _unembed_weight(params: Params):
    """The [d_model, vocab] unembedding operand for _mm; tied models reuse
    the embedding. A quantized tied embedding transposes into the exact
    QuantizedLinear layout — per-vocab-row scales become per-output-column
    scales — so int8 logits never materialize a dequantized table."""
    if "lm_head" in params:
        return params["lm_head"]
    embed = params["embed"]
    from nos_tpu.models.quantize import QuantizedEmbedding, QuantizedLinear

    if isinstance(embed, QuantizedEmbedding):
        return QuantizedLinear(q=embed.q.T, scale=embed.scale)
    return embed.T


def _unembed(params: Params, x: jax.Array) -> jax.Array:
    """Final projection to vocab logits; tied models reuse the embedding
    matrix (no lm_head in params)."""
    return _mm(x, _unembed_weight(params))


def _llama3_scaled_freqs(freqs: jax.Array, scaling) -> jax.Array:
    """The Llama-3.1 frequency transform: long wavelengths divide by
    ``factor``, short ones stay, the middle band interpolates smoothly
    (the public rope_type="llama3" recipe; parity-tested against the
    transformers implementation in tests/models/test_convert.py)."""
    _, factor, low_ff, high_ff, orig_max = scaling
    wavelen = 2.0 * math.pi / freqs
    low_wavelen = orig_max / low_ff
    high_wavelen = orig_max / high_ff
    smooth = (orig_max / wavelen - low_ff) / (high_ff - low_ff)
    mid = (1.0 - smooth) * freqs / factor + smooth * freqs
    out = jnp.where(wavelen > low_wavelen, freqs / factor, mid)
    return jnp.where(wavelen < high_wavelen, freqs, out)


def _rope_at(positions: jax.Array, head_dim: int, theta: float, dtype, scaling=None):
    """(cos, sin) tables for arbitrary (possibly traced) positions [P] →
    each [P, hd/2]. Shared by training/prefill (arange positions) and
    KV-cache decode (a single traced position)."""
    freqs = theta ** (-jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    if scaling is not None:
        freqs = _llama3_scaled_freqs(freqs, scaling)
    angles = positions.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.cos(angles).astype(dtype), jnp.sin(angles).astype(dtype)


def _rope(seq_len: int, head_dim: int, theta: float, dtype, scaling=None):
    return _rope_at(jnp.arange(seq_len), head_dim, theta, dtype, scaling)


def _apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x [B, S, H, hd] rotated by tables of rank 2 ([S, hd/2], shared
    across the batch) or rank 4 (already broadcast — per-row tables for
    left-padded serving). THE rotation formula: every path (training,
    prefill, decode) calls this one implementation."""
    x1, x2 = jnp.split(x, 2, axis=-1)
    if cos.ndim == 2:
        cos = cos[None, :, None, :]
        sin = sin[None, :, None, :]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)


def _window_causal_mask(s: int, sliding_window) -> jax.Array:
    """THE causal mask [s, s]: lower-triangular, banded to the last
    ``sliding_window`` positions when set (query i sees keys (i-W, i]).
    One source of truth for the training forward and serving prefill."""
    causal = jnp.tril(jnp.ones((s, s), bool))
    if sliding_window is not None:
        pos = jnp.arange(s)
        causal = causal & (pos[:, None] - pos[None, :] < sliding_window)
    return causal


def gqa_dense_attention(q, k, v, mask=None) -> jax.Array:
    """Grouped-query dense attention, q [B,S,Hq,hd], k/v [B,S,Hkv,hd] ->
    [B,S,Hq,hd]. ``mask`` is a [Sq,Skv] bool (True = attend); None = full.
    The ONE copy of the GQA einsum pattern — the dense model branch and
    the Ulysses SP path both call it, so masking/scaling fixes land once.
    """
    b, s, hq, hd = q.shape
    hkv = k.shape[2]
    group = hq // hkv
    qg = q.reshape(b, s, hkv, group, hd)
    scores = jnp.einsum(
        "bsKgh,btKh->bKgst", qg, k, preferred_element_type=jnp.float32
    ) / math.sqrt(hd)
    if mask is not None:
        # -1e30, not -inf: a fully masked row (never happens causally, but
        # callers may pass stricter masks) must soft-max to garbage-but-
        # finite instead of NaN.
        scores = jnp.where(mask[None, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bKgst,btKh->bsKgh", probs, v).reshape(b, s, hq, hd)


def _attention(
    x: jax.Array,
    layer: Params,
    config: LlamaConfig,
    cos: jax.Array,
    sin: jax.Array,
    mesh=None,
) -> jax.Array:
    c = config
    b, s, _ = x.shape
    hd = c.head_dim
    q = _mm(x, layer["wq"]).reshape(b, s, c.n_heads, hd)
    k = _mm(x, layer["wk"]).reshape(b, s, c.n_kv_heads, hd)
    v = _mm(x, layer["wv"]).reshape(b, s, c.n_kv_heads, hd)

    q = _apply_rope(q, cos, sin)
    k = _apply_rope(k, cos, sin)


    if mesh is not None and "sp" in mesh.axis_names and mesh.shape["sp"] > 1:
        if c.sp_strategy not in ("ring", "ulysses"):
            raise ValueError(
                f"unknown sp_strategy {c.sp_strategy!r}; expected 'ring' "
                "or 'ulysses'"
            )
        # Sequence-parallel path, strategy per config.sp_strategy:
        # "ring" — exact blockwise attention with K/V blocks rotating
        # over the sp ring (nos_tpu/parallel/ring_attention.py);
        # attention="flash" runs the Pallas kernel per ring block with
        # the hand-written ring backward, "dense" the portable jnp ring.
        # "ulysses" — all_to_all head-scatter/sequence-gather
        # (nos_tpu/parallel/ulysses.py), full-sequence attention per head
        # group (kernel or dense per config.attention).
        if c.sp_strategy == "ulysses":
            from nos_tpu.parallel.ulysses import ulysses_attention

            return _mm(
                ulysses_attention(
                    q, k, v, mesh, causal=True, attention=c.attention,
                    window=c.sliding_window,
                ),
                layer["wo"],
            )
        from nos_tpu.parallel.ring_attention import (
            ring_attention,
            ring_flash_attention,
        )

        if c.attention == "flash":
            return _mm(
                ring_flash_attention(
                    q, k, v, mesh, causal=True, window=c.sliding_window
                ),
                layer["wo"],
            )
        return _mm(
            ring_attention(q, k, v, mesh, causal=True, window=c.sliding_window),
            layer["wo"],
        )

    if c.attention == "flash":
        # Single-chip blockwise attention on the MXU (nos_tpu/ops/); the
        # kernel's custom_vjp makes this branch trainable.
        from nos_tpu.ops import flash_attention

        out = flash_attention(
            q, k, v, causal=True, window=c.sliding_window,
            interpret=jax.default_backend() == "cpu",
        )
        return _mm(out.reshape(b, s, c.n_heads * hd), layer["wo"])

    out = gqa_dense_attention(q, k, v, _window_causal_mask(s, c.sliding_window))
    return _mm(out.reshape(b, s, c.n_heads * hd), layer["wo"])


_ACTS = {"silu": jax.nn.silu, "gelu": lambda x: jax.nn.gelu(x, approximate=True)}


def _mlp(x: jax.Array, layer: Params, act: str = "silu") -> jax.Array:
    gate = _ACTS[act](_mm(x, layer["w_gate"]))
    return _mm(gate * _mm(x, layer["w_up"]), layer["w_down"])


def llama_forward(
    params: Params,
    tokens: jax.Array,
    config: LlamaConfig,
    mesh=None,
    with_aux: bool = False,
):
    """tokens [B, S] int32 → logits [B, S, vocab] (float32).

    With a mesh carrying an ``sp`` axis >1, attention runs sequence-parallel
    via ring attention; everything else is identical (XLA shards the
    elementwise/matmul ops along S from the data sharding). ``with_aux``
    additionally returns the summed MoE load-balancing loss (0 for dense).
    """
    c = config
    x = _embed_rows(params["embed"], tokens, c.dtype, c.embed_scale)
    # Position tables depend only on (seq_len, head_dim): one per forward.
    cos, sin = _rope(tokens.shape[1], c.head_dim, c.rope_theta, c.dtype, c.rope_scaling)
    def block(x, layer):
        x = x + _attention(
            _rms_norm(x, layer["attn_norm"], c.norm_eps, c.norm_offset),
            layer, c, cos, sin, mesh,
        )
        h = _rms_norm(x, layer["mlp_norm"], c.norm_eps, c.norm_offset)
        if "moe" in layer:
            from nos_tpu.models.moe import moe_mlp

            if with_aux:
                delta, aux = moe_mlp(
                    layer["moe"], h, c.moe_config(), mesh, return_aux=True
                )
            else:
                delta = moe_mlp(layer["moe"], h, c.moe_config(), mesh)
                aux = jnp.zeros((), jnp.float32)
        else:
            delta = _mlp(h, layer, c.hidden_act)
            aux = jnp.zeros((), jnp.float32)
        return x + delta, aux

    if c.remat:
        # Save only each block's input; recompute the rest in the backward.
        block = jax.checkpoint(block)

    aux_total = jnp.zeros((), jnp.float32)
    for layer in params["layers"]:
        x, aux = block(x, layer)
        aux_total = aux_total + aux
    x = _rms_norm(x, params["final_norm"], c.norm_eps, c.norm_offset)
    logits = _unembed(params, x).astype(jnp.float32)
    if with_aux:
        return logits, aux_total
    return logits


def next_token_nll(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Mean next-token NLL via nll = logsumexp(logits) - logits[target].

    Equivalent to -log_softmax[target] but never materializes the full
    [B, S, vocab] log-probability tensor for the backward — at real batch
    sizes that tensor is GBs of HBM (XLA recomputes the softmax from the
    saved logits instead)."""
    targets = tokens[:, 1:]
    logits_t = logits[:, :-1]
    lse = jax.scipy.special.logsumexp(logits_t, axis=-1)
    picked = jnp.take_along_axis(logits_t, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - picked)


def llama_loss(
    params: Params, tokens: jax.Array, config: LlamaConfig, mesh=None
) -> jax.Array:
    """Next-token cross entropy over shifted tokens.

    The forward runs on the FULL sequence (keeping S divisible by the sp
    axis) and the final position's logits are dropped from the loss.
    """
    logits, aux = llama_forward(params, tokens, config, mesh, with_aux=True)
    loss = next_token_nll(logits, tokens)
    if config.n_experts > 0:
        # Average the per-layer balance losses; keeps routing spread so the
        # static expert capacity stays effective.
        loss = loss + config.moe_aux_coef * aux / max(1, config.n_layers)
    return loss
