"""Component configuration kinds.

Reference pkg/api/nos.nebuly.com/config/v1alpha1/*: each binary takes one
``--config <file>`` decoded into a typed struct with Validate()
(cmd/gpupartitioner/gpupartitioner.go:90-101). Values mirror the helm
defaults (values.yaml:278-285: batch window 60s timeout / 10s idle).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from nos_tpu.api.v1alpha1 import constants


class ConfigError(ValueError):
    pass


@dataclass
class ManagerConfig:
    """Shared controller-manager knobs (the ControllerManagerConfigurationSpec
    embed: metrics/health endpoints, leader election)."""

    metrics_bind_address: str = ":8080"
    health_probe_bind_address: str = ":8081"
    leader_election: bool = False


@dataclass
class GpuPartitionerConfig:
    manager: ManagerConfig = field(default_factory=ManagerConfig)
    batch_window_timeout_seconds: float = 60.0
    batch_window_idle_seconds: float = 10.0
    # Known-geometries override file content: accelerator -> list of
    # geometries (KnownMigGeometriesFile analogue).
    known_tpu_geometries: Optional[Dict[str, List[Dict[str, int]]]] = None
    scheduler_config_file: str = ""
    device_plugin_config_map: str = "nos-device-plugin-config"
    device_plugin_delay_seconds: float = 0.0
    # Fairness aging for the planner's first-fit-descending sort: each
    # second a pod pends grows its effective size by this many chips, so
    # the smallest requests cannot be re-sorted last forever. 0 disables.
    aging_chips_per_second: float = 1.0
    # Plan only for pods this scheduler profile will bind (must match
    # SchedulerConfig.scheduler_name); empty = all pods.
    scheduler_name: str = constants.SCHEDULER_NAME
    # Fraction of plans the invariant auditor shadow-recomputes in live
    # mode (record/audit.py). 0 disables auditing entirely; replay always
    # audits exhaustively regardless of this rate.
    audit_sample_rate: float = 0.0
    # Incremental replanning (controllers/partitioner/incremental.py):
    # keep one base snapshot alive across plan cycles and warm-start the
    # planner from store deltas. Off = rebuild snapshot + caches per
    # cycle (pre-incremental behavior).
    incremental_planning: bool = True
    # Dirty fraction above which an incremental cycle falls back to a
    # from-scratch replan (still base-preserving).
    incremental_dirty_threshold: float = 0.25
    # Pool-sharded planning (partitioning/core/pools.py): partition the
    # cluster into pools no gang/affinity/quota edge crosses and plan
    # each with its own incremental base + planner, merged under
    # cross-pool invariants. Requires incremental_planning.
    pool_sharding: bool = False
    # How the per-pool plans execute: "serial" (sorted pool order,
    # reproducible timing) or "thread" (ThreadPoolExecutor — wins only
    # on multi-core GIL-released deployments; bench_planner --parallel
    # measures both honestly).
    pool_parallelism: str = "serial"
    # Thread-mode worker cap; 0 = one worker per pool.
    pool_max_workers: int = 0
    # Pool execution backend (partitioning/core/procpool.py): empty =
    # follow pool_parallelism; "process" runs one long-lived worker
    # process per pool, delta-fed across cycles — the only mode that
    # escapes the GIL on multi-core hosts. A dead/wedged worker escalates
    # that pool to in-parent serial planning and respawns from a fresh
    # wire image.
    pool_backend: str = ""
    # How long the parent waits for ALL process-backend plan replies in
    # one cycle before declaring the stragglers wedged.
    pool_cycle_timeout_seconds: float = 5.0
    # When set, persist the planners' warm state (carve-futility and
    # verdict memos keyed by node-state signature) to this file so a
    # restart or full-rebuild fallback warm-boots instead of replaying
    # the world (partitioning/core/snapcodec.py). Empty = no persistence.
    warm_state_path: str = ""
    warm_state_save_interval_seconds: float = 30.0
    # Placement forecasting (nos_tpu/forecast/): a background thread with
    # its own snapshot maintainer + planner publishes per-gang
    # earliest-feasible-start ETAs, backfill-safety verdicts, and the
    # read-only defrag advisor's plan every partitioner cycle. Read-only:
    # off the plan path, never actuates.
    forecast_enabled: bool = True
    # Background runs are throttled to at most one per this interval (a
    # notify storm under a burst must not become a forecast storm).
    forecast_min_interval_seconds: float = 0.25
    # Per-run work caps (sorted-order truncation, so deterministic).
    forecast_max_gangs: int = 32
    forecast_max_backfill_pairs: int = 64
    # Pods at or below this many chips count as backfill candidates.
    forecast_small_pod_chips: int = 2

    def validate(self) -> None:
        if self.aging_chips_per_second < 0:
            raise ConfigError("aging_chips_per_second must be >= 0")
        if not 0.0 <= self.audit_sample_rate <= 1.0:
            raise ConfigError("audit_sample_rate must be in [0, 1]")
        if not 0.0 < self.incremental_dirty_threshold <= 1.0:
            raise ConfigError(
                "incremental_dirty_threshold must be in (0, 1]"
            )
        if self.batch_window_timeout_seconds <= 0:
            raise ConfigError("batch_window_timeout_seconds must be > 0")
        if self.batch_window_idle_seconds < 0:
            raise ConfigError("batch_window_idle_seconds must be >= 0")
        if self.batch_window_idle_seconds > self.batch_window_timeout_seconds:
            raise ConfigError("idle window cannot exceed timeout window")
        if self.pool_sharding and not self.incremental_planning:
            raise ConfigError("pool_sharding requires incremental_planning")
        if self.pool_parallelism not in ("serial", "thread"):
            raise ConfigError(
                "pool_parallelism must be 'serial' or 'thread'"
            )
        if self.pool_max_workers < 0:
            raise ConfigError("pool_max_workers must be >= 0")
        if self.pool_backend not in ("", "serial", "thread", "process"):
            raise ConfigError(
                "pool_backend must be '', 'serial', 'thread', or 'process'"
            )
        if self.pool_backend == "process" and not self.pool_sharding:
            raise ConfigError("pool_backend 'process' requires pool_sharding")
        if self.pool_cycle_timeout_seconds <= 0:
            raise ConfigError("pool_cycle_timeout_seconds must be > 0")
        if self.warm_state_save_interval_seconds < 0:
            raise ConfigError(
                "warm_state_save_interval_seconds must be >= 0"
            )
        if self.forecast_min_interval_seconds < 0:
            raise ConfigError("forecast_min_interval_seconds must be >= 0")
        if self.forecast_max_gangs < 1:
            raise ConfigError("forecast_max_gangs must be >= 1")
        if self.forecast_max_backfill_pairs < 0:
            raise ConfigError("forecast_max_backfill_pairs must be >= 0")
        if self.forecast_small_pod_chips < 1:
            raise ConfigError("forecast_small_pod_chips must be >= 1")


@dataclass
class OperatorConfig:
    manager: ManagerConfig = field(default_factory=ManagerConfig)
    # Per-chip HBM GB used for the nos.nebuly.com/tpu-memory aggregate
    # (the reference's NvidiaGpuResourceMemoryGB, operator.go:50-126).
    tpu_chip_memory_gb: int = 16

    def validate(self) -> None:
        if self.tpu_chip_memory_gb < 1:
            raise ConfigError("tpu_chip_memory_gb must be >= 1")


@dataclass
class TpuAgentConfig:
    manager: ManagerConfig = field(default_factory=ManagerConfig)
    report_config_interval_seconds: float = 10.0

    def validate(self) -> None:
        if self.report_config_interval_seconds <= 0:
            raise ConfigError("report_config_interval_seconds must be > 0")


@dataclass
class AutoscalerConfig:
    manager: ManagerConfig = field(default_factory=ManagerConfig)
    # Burn-rate thresholds driving the policy: fast-window burn above
    # `scale_up_burn_threshold` adds a replica; scale-down requires fast
    # burn below `scale_down_burn_threshold` AND the spec's budget
    # surplus, sustained for `scale_down_stable_seconds`.
    scale_up_burn_threshold: float = 1.0
    scale_down_burn_threshold: float = 0.5
    scale_down_stable_seconds: float = 120.0
    # A cold model still counts as "recently active" (blocks
    # scale-to-zero) for this long after its last arrival.
    recent_activity_seconds: float = 30.0
    # Periodic resync so idle timers fire without a triggering event.
    resync_seconds: float = 5.0

    def validate(self) -> None:
        if self.scale_up_burn_threshold <= 0:
            raise ConfigError("scale_up_burn_threshold must be > 0")
        if not 0 <= self.scale_down_burn_threshold <= self.scale_up_burn_threshold:
            raise ConfigError(
                "scale_down_burn_threshold must be in [0, scale_up_burn_threshold]"
            )
        if self.scale_down_stable_seconds < 0:
            raise ConfigError("scale_down_stable_seconds must be >= 0")
        if self.recent_activity_seconds < 0:
            raise ConfigError("recent_activity_seconds must be >= 0")
        if self.resync_seconds <= 0:
            raise ConfigError("resync_seconds must be > 0")


@dataclass
class ObservabilityConfig:
    """Fleet-scale observability plane knobs (obsplane/): metric series
    budgets for the cardinality governor, tail-kept trace retention, and
    debug-endpoint pagination. Defaults leave every behavior off/unbounded
    so small worlds keep the pre-governor telemetry byte-for-byte."""

    # Per-family series budgets (family name -> max exact label sets);
    # the YAML shape is observability.seriesBudget.<family>: N. A family
    # over budget folds new label sets into one deterministic `_other`
    # child and counts the refusals in metric_series_dropped_total.
    series_budget: Dict[str, int] = field(default_factory=dict)
    # Budget applied to families without an explicit entry; None/0 = off.
    series_budget_default: Optional[int] = None
    # Tiered exposition: per-node capacity gauges keep only the K
    # worst-offender nodes (by idle chips then fragmentation) exact;
    # 0 = export every node (pre-tiering behavior). Exact per-pool
    # rollups are always exported alongside.
    node_top_k: int = 0
    # Tail-kept trace reservoir capacity (error/unschedulable/slow traces
    # that boring traffic cannot evict). 0 disables the pinned ring.
    trace_tail_capacity: int = 64
    # Keep 1 of every N boring traces in the main ring (head sampling);
    # 1 = keep all (pre-sampling behavior).
    trace_boring_sample_n: int = 1
    # Per-journey-kind latency thresholds (root span name -> seconds)
    # above which a trace is classified "slow" and pinned.
    trace_slow_thresholds: Dict[str, float] = field(default_factory=dict)
    # Default /debug page size when the request carries no ?limit=;
    # 0 = unpaginated (pre-streaming behavior).
    debug_page_limit: int = 500

    def validate(self) -> None:
        for family, budget in self.series_budget.items():
            if budget <= 0:
                raise ConfigError(
                    f"seriesBudget.{family} must be > 0 (got {budget})"
                )
        if self.series_budget_default is not None and self.series_budget_default <= 0:
            raise ConfigError("seriesBudget default must be > 0 when set")
        if self.node_top_k < 0:
            raise ConfigError("node_top_k must be >= 0")
        if self.trace_tail_capacity < 0:
            raise ConfigError("trace_tail_capacity must be >= 0")
        if self.trace_boring_sample_n < 1:
            raise ConfigError("trace_boring_sample_n must be >= 1")
        for kind, threshold in self.trace_slow_thresholds.items():
            if threshold <= 0:
                raise ConfigError(
                    f"trace_slow_thresholds.{kind} must be > 0 (got {threshold})"
                )
        if self.debug_page_limit < 0:
            raise ConfigError("debug_page_limit must be >= 0")


@dataclass
class SchedulerConfig:
    manager: ManagerConfig = field(default_factory=ManagerConfig)
    retry_seconds: float = 0.5
    gang_wait_timeout_seconds: float = 30.0
    # Pods opt in by setting spec.schedulerName to this value; everything
    # else is left to the cluster's default scheduler (reference
    # cmd/scheduler/scheduler.go:43-59 — the nos profile is one profile of
    # upstream kube-scheduler, selected per pod by schedulerName). Empty
    # string = handle every pod (single-scheduler sims only).
    scheduler_name: str = constants.SCHEDULER_NAME

    def validate(self) -> None:
        if self.retry_seconds <= 0:
            raise ConfigError("retry_seconds must be > 0")
