from nos_tpu.api.config.v1alpha1 import (
    AutoscalerConfig,
    GpuPartitionerConfig,
    ObservabilityConfig,
    OperatorConfig,
    SchedulerConfig,
    TpuAgentConfig,
)

__all__ = [
    "AutoscalerConfig",
    "GpuPartitionerConfig",
    "ObservabilityConfig",
    "OperatorConfig",
    "SchedulerConfig",
    "TpuAgentConfig",
]
