from nos_tpu.api.config.v1alpha1 import (
    GpuPartitionerConfig,
    OperatorConfig,
    SchedulerConfig,
    TpuAgentConfig,
)

__all__ = [
    "GpuPartitionerConfig",
    "OperatorConfig",
    "SchedulerConfig",
    "TpuAgentConfig",
]
