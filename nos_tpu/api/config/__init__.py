from nos_tpu.api.config.v1alpha1 import (
    AutoscalerConfig,
    GpuPartitionerConfig,
    OperatorConfig,
    SchedulerConfig,
    TpuAgentConfig,
)

__all__ = [
    "AutoscalerConfig",
    "GpuPartitionerConfig",
    "OperatorConfig",
    "SchedulerConfig",
    "TpuAgentConfig",
]
