from nos_tpu.api.v1alpha1 import annotations, constants, labels
from nos_tpu.api.v1alpha1.elasticquota import (
    CompositeElasticQuota,
    CompositeElasticQuotaSpec,
    ElasticQuota,
    ElasticQuotaSpec,
    ElasticQuotaStatus,
)
from nos_tpu.api.v1alpha1.modelserving import (
    ModelServing,
    ModelServingSpec,
    ModelServingStatus,
)

__all__ = [
    "annotations",
    "constants",
    "labels",
    "CompositeElasticQuota",
    "CompositeElasticQuotaSpec",
    "ElasticQuota",
    "ElasticQuotaSpec",
    "ElasticQuotaStatus",
    "ModelServing",
    "ModelServingSpec",
    "ModelServingStatus",
]
