"""Resource names and shared constants.

Parity with reference pkg/constant/constants.go:36-107 and
pkg/api/nos.nebuly.com/v1alpha1/constants.go:25-27, transposed to TPUs.
"""
import re

# The native TPU chip resource exposed by the TPU device plugin.
RESOURCE_TPU = "google.com/tpu"

# Sliced TPU resources carved by the partitioner, e.g.
# google.com/tpu-slice-2x2 (4 chips), google.com/tpu-slice-1x1 (1 chip).
# Analogue of nvidia.com/mig-1g.10gb (MIG) / nvidia.com/gpu-10gb (MPS).
RESOURCE_TPU_SLICE_PREFIX = "google.com/tpu-slice-"
RESOURCE_TPU_SLICE_REGEX = re.compile(r"^google\.com/tpu-slice-(\d+x\d+(?:x\d+)?)$")

# Aggregate custom resource used by ElasticQuota so quotas can be expressed
# in chips regardless of which sliced resource a pod requests. Analogue of
# nos.nebuly.com/gpu-memory (reference v1alpha1/constants.go:25-27).
RESOURCE_TPU_CHIPS = "nos.nebuly.com/tpu-chips"

# Reference-parity NVIDIA names (kept so MIG/MPS parity modes and the
# resource calculator can recognize them; reference pkg/constant/constants.go).
RESOURCE_NVIDIA_GPU = "nvidia.com/gpu"
RESOURCE_NVIDIA_MIG_PREFIX = "nvidia.com/mig-"
RESOURCE_NVIDIA_SLICE_REGEX = re.compile(r"^nvidia\.com/gpu-(\d+)gb$")
RESOURCE_GPU_MEMORY = "nos.nebuly.com/gpu-memory"
DEFAULT_NVIDIA_GPU_RESOURCE_MEMORY_GB = 16

# Scheduler / controller names.
SCHEDULER_NAME = "nos-scheduler"
DEFAULT_SCHEDULER_NAME = "default-scheduler"

# Indexer keys (reference cmd/gpupartitioner/gpupartitioner.go:270-292).
INDEX_POD_PHASE = "status.phase"
INDEX_POD_NODE = "spec.nodeName"
INDEX_EQ_NAMESPACE = "spec.namespaces"


def is_tpu_slice_resource(name: str) -> bool:
    return RESOURCE_TPU_SLICE_REGEX.match(name) is not None


def tpu_slice_topology(resource_name: str) -> str:
    """'google.com/tpu-slice-2x2' -> '2x2'; raises ValueError otherwise."""
    m = RESOURCE_TPU_SLICE_REGEX.match(resource_name)
    if m is None:
        raise ValueError(f"{resource_name!r} is not a TPU slice resource")
    return m.group(1)


def tpu_slice_resource(topology: str) -> str:
    return RESOURCE_TPU_SLICE_PREFIX + topology
