"""Resource names and shared constants.

Parity with reference pkg/constant/constants.go:36-107 and
pkg/api/nos.nebuly.com/v1alpha1/constants.go:25-27, transposed to TPUs.
"""
import re

# The native TPU chip resource exposed by the TPU device plugin.
RESOURCE_TPU = "google.com/tpu"

# Sliced TPU resources carved by the partitioner, e.g.
# google.com/tpu-slice-2x2 (4 chips), google.com/tpu-slice-1x1 (1 chip).
# Analogue of nvidia.com/mig-1g.10gb (MIG) / nvidia.com/gpu-10gb (MPS).
RESOURCE_TPU_SLICE_PREFIX = "google.com/tpu-slice-"
RESOURCE_TPU_SLICE_REGEX = re.compile(r"^google\.com/tpu-slice-(\d+x\d+(?:x\d+)?)$")

# Shared (time-multiplexed) TPU resources exposed by the sharing mode:
# HBM-denominated fractions of one chip, e.g. google.com/tpu-mem-8gb is a
# half of a 16GB v5e chip. Analogue of nvidia.com/gpu-<N>gb (MPS slicing,
# reference pkg/gpu/slicing/profile.go resourceRegexp).
RESOURCE_TPU_SHARED_PREFIX = "google.com/tpu-mem-"
RESOURCE_TPU_SHARED_REGEX = re.compile(r"^google\.com/tpu-mem-(\d+)gb$")

# Smallest shareable HBM slice (reference slicing constant MinSliceMemoryGB,
# pkg/gpu/slicing/constant.go:23).
MIN_SHARED_SLICE_GB = 1

# Aggregate custom resource used by ElasticQuota so quotas can be expressed
# in chips regardless of which sliced resource a pod requests. Analogue of
# nos.nebuly.com/gpu-memory (reference v1alpha1/constants.go:25-27).
RESOURCE_TPU_CHIPS = "nos.nebuly.com/tpu-chips"

# HBM-denominated aggregate (the direct nos.nebuly.com/gpu-memory analogue):
# shared fractions count their own GB; whole chips and topology slices count
# DEFAULT_TPU_CHIP_MEMORY_GB per chip (the reference defaults plain GPUs to
# NvidiaGpuResourceMemoryGB=16, pkg/constant/constants.go:91-96).
RESOURCE_TPU_MEMORY = "nos.nebuly.com/tpu-memory"
DEFAULT_TPU_CHIP_MEMORY_GB = 16

# Reference-parity NVIDIA names (kept so MIG/MPS parity modes and the
# resource calculator can recognize them; reference pkg/constant/constants.go).
RESOURCE_NVIDIA_GPU = "nvidia.com/gpu"
RESOURCE_NVIDIA_MIG_PREFIX = "nvidia.com/mig-"
RESOURCE_NVIDIA_SLICE_REGEX = re.compile(r"^nvidia\.com/gpu-(\d+)gb$")
RESOURCE_GPU_MEMORY = "nos.nebuly.com/gpu-memory"
DEFAULT_NVIDIA_GPU_RESOURCE_MEMORY_GB = 16

# Scheduler / controller names.
SCHEDULER_NAME = "nos-scheduler"
DEFAULT_SCHEDULER_NAME = "default-scheduler"

# Indexer keys (reference cmd/gpupartitioner/gpupartitioner.go:270-292).
INDEX_POD_PHASE = "status.phase"
INDEX_POD_NODE = "spec.nodeName"
INDEX_EQ_NAMESPACE = "spec.namespaces"

# Event reasons — the single source of truth. Every Event written through
# kube/events.py must use one of these (enforced by a lint test), so
# operators and e2e assertions can grep a closed vocabulary.
EVENT_REASON_FAILED_SCHEDULING = "FailedScheduling"
EVENT_REASON_SCHEDULED = "Scheduled"
EVENT_REASON_PREEMPTED = "Preempted"
EVENT_REASON_QUOTA_BORROWED = "QuotaBorrowed"
EVENT_REASON_QUOTA_RECLAIMED = "QuotaReclaimed"
EVENT_REASON_PARTITIONING_APPLIED = "PartitioningApplied"
EVENT_REASON_CARVE_FAILED = "CarveFailed"
EVENT_REASON_AUDIT_VIOLATION = "AuditViolation"
EVENT_REASON_SCALED_UP = "ScaledUp"
EVENT_REASON_SCALED_DOWN = "ScaledDown"
EVENT_REASON_SCALED_TO_ZERO = "ScaledToZero"
EVENT_REASON_COLD_START = "ColdStart"
EVENT_REASON_HEALTH_DEGRADED = "HealthDegraded"

EVENT_REASONS = (
    EVENT_REASON_FAILED_SCHEDULING,
    EVENT_REASON_SCHEDULED,
    EVENT_REASON_PREEMPTED,
    EVENT_REASON_QUOTA_BORROWED,
    EVENT_REASON_QUOTA_RECLAIMED,
    EVENT_REASON_PARTITIONING_APPLIED,
    EVENT_REASON_CARVE_FAILED,
    EVENT_REASON_AUDIT_VIOLATION,
    EVENT_REASON_SCALED_UP,
    EVENT_REASON_SCALED_DOWN,
    EVENT_REASON_SCALED_TO_ZERO,
    EVENT_REASON_COLD_START,
    EVENT_REASON_HEALTH_DEGRADED,
)


def is_tpu_slice_resource(name: str) -> bool:
    return RESOURCE_TPU_SLICE_REGEX.match(name) is not None


def tpu_slice_topology(resource_name: str) -> str:
    """'google.com/tpu-slice-2x2' -> '2x2'; raises ValueError otherwise."""
    m = RESOURCE_TPU_SLICE_REGEX.match(resource_name)
    if m is None:
        raise ValueError(f"{resource_name!r} is not a TPU slice resource")
    return m.group(1)


def tpu_slice_resource(topology: str) -> str:
    return RESOURCE_TPU_SLICE_PREFIX + topology


def is_tpu_shared_resource(name: str) -> bool:
    return RESOURCE_TPU_SHARED_REGEX.match(name) is not None


def tpu_shared_profile(resource_name: str) -> str:
    """'google.com/tpu-mem-8gb' -> '8gb'; raises ValueError otherwise."""
    m = RESOURCE_TPU_SHARED_REGEX.match(resource_name)
    if m is None:
        raise ValueError(f"{resource_name!r} is not a shared TPU resource")
    return m.group(1) + "gb"


def tpu_shared_resource(profile: str) -> str:
    """'8gb' (or 8) -> 'google.com/tpu-mem-8gb'."""
    if isinstance(profile, int):
        return f"{RESOURCE_TPU_SHARED_PREFIX}{profile}gb"
    return RESOURCE_TPU_SHARED_PREFIX + profile


def shared_profile_gb(profile: str) -> int:
    """'8gb' -> 8; raises ValueError otherwise."""
    if not profile.endswith("gb"):
        raise ValueError(f"{profile!r} is not a shared TPU profile")
    return int(profile[:-2])
