"""ElasticQuota / CompositeElasticQuota CRD-equivalent types.

Reference pkg/api/nos.nebuly.com/v1alpha1/elasticquota_types.go:30-71 and
compositeelasticquota_types.go:29-66. `min` is guaranteed quota, `max` is the
borrowing ceiling; namespaces may exceed `min` by borrowing unused quota from
others, and those over-quota pods are preemptible (SURVEY.md §1 item 2).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

from nos_tpu.kube.objects import ObjectMeta, ResourceList


@dataclass
class ElasticQuotaSpec:
    min: ResourceList = field(default_factory=dict)
    max: ResourceList = field(default_factory=dict)


@dataclass
class ElasticQuotaStatus:
    used: ResourceList = field(default_factory=dict)


@dataclass
class ElasticQuota:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ElasticQuotaSpec = field(default_factory=ElasticQuotaSpec)
    status: ElasticQuotaStatus = field(default_factory=ElasticQuotaStatus)
    kind: str = "ElasticQuota"


@dataclass
class CompositeElasticQuotaSpec:
    namespaces: List[str] = field(default_factory=list)
    min: ResourceList = field(default_factory=dict)
    max: ResourceList = field(default_factory=dict)


@dataclass
class CompositeElasticQuota:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CompositeElasticQuotaSpec = field(default_factory=CompositeElasticQuotaSpec)
    status: ElasticQuotaStatus = field(default_factory=ElasticQuotaStatus)
    kind: str = "CompositeElasticQuota"
