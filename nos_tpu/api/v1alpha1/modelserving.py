"""ModelServing CRD-equivalent type: a served model with SLO targets.

The declarative half of the autoscaling loop (ROADMAP item 3): spec names
the model, the mesh-sized slice profile each replica occupies (e.g. "2x4"
= 8 chips for a (batch, model) mesh), the replica bounds, and the SLO
targets in `slo/engine.py` spec syntax ("p95 ttft < 300ms",
"availability 99.9%"). The autoscaler controller reconciles
status.desired_replicas from measured burn rate + queue depth and acts
purely through Pods — the scheduler gang-places them and the partitioner
carves the slices, exactly as for hand-written workloads.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

from nos_tpu.api.v1alpha1 import constants
from nos_tpu.kube.objects import ObjectMeta


@dataclass
class ModelServingSpec:
    # Model identity routed by the serving shim (slo/routing.py); must
    # match a ModelProfile name in the workload driver.
    model: str = ""
    # Topology each replica's server pod occupies ("2x4" = 8 chips).
    slice_profile: str = "2x4"
    min_replicas: int = 0
    max_replicas: int = 1
    # SLO targets in slo/engine.py spec syntax; validated at admission.
    slos: List[str] = field(default_factory=list)
    # Scale-to-zero: tear down after this much idle time (no arrivals and
    # empty queue). Only meaningful when min_replicas == 0.
    scale_to_zero_idle_seconds: float = 300.0
    # After scaling to zero, hold the freed boards in an autoscaler-grace
    # reservation for this long so a cold start lands on a pre-carved
    # slice instead of waiting out a full re-carve.
    cold_start_grace_seconds: float = 60.0
    # Queue-depth target per replica; backlog above desired*target scales up.
    target_queue_depth: int = 4
    # Scale down one replica only while at least this fraction of error
    # budget remains across every declared SLO (sustained surplus).
    scale_down_budget_surplus: float = 0.5
    scheduler_name: str = constants.SCHEDULER_NAME

    def validate(self) -> None:
        from nos_tpu.slo.engine import SLOSpec
        from nos_tpu.tpu.topology import topology_chips

        if not self.model:
            raise ValueError("spec.model must be set")
        if topology_chips(self.slice_profile) < 1:
            raise ValueError(f"invalid slice_profile {self.slice_profile!r}")
        if self.min_replicas < 0:
            raise ValueError("min_replicas must be >= 0")
        if self.max_replicas < max(1, self.min_replicas):
            raise ValueError("max_replicas must be >= max(1, min_replicas)")
        for text in self.slos:
            SLOSpec.parse(text)  # raises ValueError on bad syntax
        if self.scale_to_zero_idle_seconds < 0:
            raise ValueError("scale_to_zero_idle_seconds must be >= 0")
        if self.cold_start_grace_seconds < 0:
            raise ValueError("cold_start_grace_seconds must be >= 0")
        if self.target_queue_depth < 1:
            raise ValueError("target_queue_depth must be >= 1")
        if not 0.0 <= self.scale_down_budget_surplus <= 1.0:
            raise ValueError("scale_down_budget_surplus must be in [0, 1]")

    @property
    def chips_per_replica(self) -> int:
        from nos_tpu.tpu.topology import topology_chips

        return topology_chips(self.slice_profile)


@dataclass
class ModelServingStatus:
    # Replica pods that currently exist / are bound to nodes.
    replicas: int = 0
    ready_replicas: int = 0
    # The controller's last reconciled target.
    desired_replicas: int = 0
    # Last policy verdict ("scale-up", "scale-down", "scale-to-zero",
    # "cold-start", "hold") and when desired_replicas last changed.
    last_verdict: str = ""
    last_transition_t: float = 0.0
    # Cold-start bookkeeping: set when scaling 0 -> N, cleared (and the
    # latency observed) when the first replica binds again.
    cold_start_since: float = 0.0
    cold_starts: int = 0


@dataclass
class ModelServing:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ModelServingSpec = field(default_factory=ModelServingSpec)
    status: ModelServingStatus = field(default_factory=ModelServingStatus)
    kind: str = "ModelServing"
