"""Well-known labels.

Parity with reference pkg/api/nos.nebuly.com/v1alpha1/labels.go:19-24, plus
the GKE TPU node labels that replace NVIDIA GPU-feature-discovery labels
(reference pkg/gpu/util.go:19-63 reads GFD labels; we read GKE TPU labels).
"""

# The opt-in switch: nodes labeled with this are managed by the partitioner.
# Values: "tpu" (this build's native mode), "mig", "mps" (reference parity).
PARTITIONING_LABEL = "nos.nebuly.com/gpu-partitioning"

# Pod capacity classification written by the ElasticQuota reconciler
# (reference internal/controllers/elasticquota/elasticquota.go:48-62).
CAPACITY_LABEL = "nos.nebuly.com/capacity"
CAPACITY_IN_QUOTA = "in-quota"
CAPACITY_OVER_QUOTA = "over-quota"

# GKE TPU node labels (the TPU analogue of NVIDIA GFD labels).
GKE_TPU_ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"
GKE_TPU_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"

# Device-plugin config selection label flipped by the MPS-style actuation
# path (reference internal/partitioning/mps/partitioner.go:102-110 flips
# nvidia.com/device-plugin.config; the TPU device plugin uses its own key).
TPU_DEVICE_PLUGIN_CONFIG_LABEL = "google.com/tpu-device-plugin.config"


class PartitioningKind:
    TPU = "tpu"
    # HBM-fraction chip sharing actuated through the device plugin
    # (the MPS analogue: reference internal/partitioning/mps/).
    SHARING = "sharing"
    MIG = "mig"
    MPS = "mps"

    ALL = (TPU, SHARING, MIG, MPS)


def partitioning_kind(node) -> str:
    """Partitioning kind from the node opt-in label, '' if unmanaged.

    Reference pkg/gpu/partitioning.go:87-135.
    """
    value = node.metadata.labels.get(PARTITIONING_LABEL, "")
    return value if value in PartitioningKind.ALL else ""
