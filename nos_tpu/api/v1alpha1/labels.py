"""Well-known labels.

Parity with reference pkg/api/nos.nebuly.com/v1alpha1/labels.go:19-24, plus
the GKE TPU node labels that replace NVIDIA GPU-feature-discovery labels
(reference pkg/gpu/util.go:19-63 reads GFD labels; we read GKE TPU labels).
"""

# The opt-in switch: nodes labeled with this are managed by the partitioner.
# Values: "tpu" (this build's native mode), "mig", "mps" (reference parity).
PARTITIONING_LABEL = "nos.nebuly.com/gpu-partitioning"

# Pod capacity classification written by the ElasticQuota reconciler
# (reference internal/controllers/elasticquota/elasticquota.go:48-62).
CAPACITY_LABEL = "nos.nebuly.com/capacity"
CAPACITY_IN_QUOTA = "in-quota"
CAPACITY_OVER_QUOTA = "over-quota"

# GKE TPU node labels (the TPU analogue of NVIDIA GFD labels).
GKE_TPU_ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"
GKE_TPU_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"

# GKE node-pool membership — the seed key for pool-sharded planning
# (partitioning/core/pools.py): nodes sharing this label value start in
# the same planning pool, then gang/affinity/quota edges merge pools.
# Unlabeled nodes fall into one implicit pool.
GKE_NODEPOOL_LABEL = "cloud.google.com/gke-nodepool"

# Replica pods created by the model autoscaler carry the owning
# ModelServing's "<namespace>.<name>" here so the controller can map pod
# events back to its object (kube-style ownership without a real GC).
MODEL_SERVING_LABEL = "nos.nebuly.com/model-serving"

# On hybrid nodes: how many of the node's chips (the highest-indexed ones)
# form the sharing pool; the rest are carved into slice boards. The TPU
# analogue of nos's per-GPU MIG-enabled flag, which decides whether a
# device belongs to the MIG or the MPS pass on a hybrid node.
SHARED_CHIPS_LABEL = "nos.nebuly.com/shared-chips"

# Device-plugin config selection label flipped by the MPS-style actuation
# path (reference internal/partitioning/mps/partitioner.go:102-110 flips
# nvidia.com/device-plugin.config; the TPU device plugin uses its own key).
TPU_DEVICE_PLUGIN_CONFIG_LABEL = "google.com/tpu-device-plugin.config"


class PartitioningKind:
    TPU = "tpu"
    # HBM-fraction chip sharing actuated through the device plugin
    # (the MPS analogue: reference internal/partitioning/mps/).
    SHARING = "sharing"
    # Both modes on one node: slice-carving boards and shared-fraction
    # chips coexisting (reference pkg/gpu/partitioning.go:91 declares
    # PartitioningKindHybrid; here hybrid nodes actually participate in
    # both the tpu and sharing planning passes).
    HYBRID = "hybrid"
    MIG = "mig"
    MPS = "mps"

    ALL = (TPU, SHARING, HYBRID, MIG, MPS)


def partitioning_kind(node) -> str:
    """Partitioning kind from the node opt-in label, '' if unmanaged.

    Reference pkg/gpu/partitioning.go:87-135.
    """
    value = node.metadata.labels.get(PARTITIONING_LABEL, "")
    return value if value in PartitioningKind.ALL else ""


def is_tpu_partitioning_enabled(node) -> bool:
    """Node participates in agent-actuated slice partitioning (tpu or
    hybrid) — analogue of reference gpu.IsMigPartitioningEnabled."""
    return partitioning_kind(node) in (PartitioningKind.TPU, PartitioningKind.HYBRID)


def is_sharing_partitioning_enabled(node) -> bool:
    """Node participates in device-plugin-actuated chip sharing (sharing
    or hybrid) — analogue of reference gpu.IsMpsPartitioningEnabled."""
    return partitioning_kind(node) in (
        PartitioningKind.SHARING,
        PartitioningKind.HYBRID,
    )


def shared_chip_count(node, total_chips: int) -> int:
    """How many of the node's chips belong to the sharing pass.

    Pure sharing nodes share everything; pure tpu nodes share nothing;
    hybrid nodes split per the shared-chips label (the highest-indexed N
    chips share, the rest carve into boards).
    """
    kind = partitioning_kind(node)
    if kind in (PartitioningKind.SHARING, PartitioningKind.MPS):
        return total_chips
    if kind != PartitioningKind.HYBRID:
        return 0
    try:
        n = int(node.metadata.labels.get(SHARED_CHIPS_LABEL, "0"))
    except ValueError:
        return 0
    return max(0, min(n, total_chips))


def kind_matches(node, kind: str) -> bool:
    """True when the node participates in planning pass ``kind`` —
    exact-kind nodes plus hybrid nodes for the tpu/sharing passes."""
    value = partitioning_kind(node)
    if value == kind:
        return True
    return value == PartitioningKind.HYBRID and kind in (
        PartitioningKind.TPU,
        PartitioningKind.SHARING,
    )
