"""The node-annotation wire protocol: desired vs reported slice state.

This is the heart of the architecture (SURVEY.md §7: "desired vs reported
state as node annotations + plan-id handshake"). The control plane writes
*spec* annotations describing the slice geometry each TPU board should have;
the node-local tpuagent writes *status* annotations describing what actually
exists, plus the id of the last plan it observed. Reference
pkg/api/nos.nebuly.com/v1alpha1/annotations.go:21-58 and
pkg/gpu/annotation.go:29-101.

Format (TPU mode):
  nos.nebuly.com/spec-tpu-<board>-<topology> = "<quantity>"
  nos.nebuly.com/status-tpu-<board>-<topology>-<free|used> = "<quantity>"
  nos.nebuly.com/spec-partitioning-plan   = "<plan-id>"
  nos.nebuly.com/status-partitioning-plan = "<plan-id>"
"""
from __future__ import annotations

import re
from collections import defaultdict
from dataclasses import dataclass
from typing import Dict, List, Tuple

PREFIX = "nos.nebuly.com/"
SPEC_PARTITIONING_PLAN = PREFIX + "spec-partitioning-plan"
STATUS_PARTITIONING_PLAN = PREFIX + "status-partitioning-plan"

# Cold-start grace reservation written by the model autoscaler on the
# nodes a scaled-to-zero model vacated: holder ("<ns>/<name>") and an
# absolute expiry timestamp. The capacity ledger attributes the idle
# chip-seconds under these keys to the "autoscaler-grace" bucket, and
# the autoscaler clears them at expiry (or on cold start).
AUTOSCALER_RESERVED = PREFIX + "autoscaler-reserved"
AUTOSCALER_RESERVED_UNTIL = PREFIX + "autoscaler-reserved-until"

# Profiles are either slice topologies ("2x2", "2x2x1" — tpu mode) or
# HBM fractions ("8gb" — sharing mode); both ride the same protocol the
# way MIG ("1g.10gb") and MPS ("10gb") profiles share the reference's.
_PROFILE = r"(\d+x\d+(?:x\d+)?|\d+gb)"
_SPEC_RE = re.compile(r"^nos\.nebuly\.com/spec-tpu-(\d+)-" + _PROFILE + r"$")
_STATUS_RE = re.compile(
    r"^nos\.nebuly\.com/status-tpu-(\d+)-" + _PROFILE + r"-(free|used)$"
)

STATUS_FREE = "free"
STATUS_USED = "used"


@dataclass(frozen=True)
class SpecAnnotation:
    board_index: int
    profile: str  # topology string, e.g. "2x2"
    quantity: int

    @property
    def key(self) -> str:
        return f"{PREFIX}spec-tpu-{self.board_index}-{self.profile}"


@dataclass(frozen=True)
class StatusAnnotation:
    board_index: int
    profile: str
    status: str  # free | used
    quantity: int

    @property
    def key(self) -> str:
        return f"{PREFIX}status-tpu-{self.board_index}-{self.profile}-{self.status}"


def parse_node_annotations(
    annotations: Dict[str, str],
) -> Tuple[List[SpecAnnotation], List[StatusAnnotation]]:
    """Parse the spec/status slice annotations off a node's annotation map.

    Malformed quantities are skipped (a real API server cannot enforce the
    value format), matching the tolerant parsing of reference
    pkg/gpu/annotation.go:29-101.
    """
    spec: List[SpecAnnotation] = []
    status: List[StatusAnnotation] = []
    for key, value in annotations.items():
        m = _SPEC_RE.match(key)
        if m:
            qty = _parse_quantity(value)
            if qty is not None:
                spec.append(SpecAnnotation(int(m.group(1)), m.group(2), qty))
            continue
        m = _STATUS_RE.match(key)
        if m:
            qty = _parse_quantity(value)
            if qty is not None:
                status.append(
                    StatusAnnotation(int(m.group(1)), m.group(2), m.group(3), qty)
                )
    return spec, status


def status_key_profile(key: str) -> "str | None":
    """Profile of a status annotation key ("2x2", "8gb"), None otherwise."""
    m = _STATUS_RE.match(key)
    return m.group(2) if m else None


def is_sharing_status_key(key: str) -> bool:
    """True when a status annotation carries a sharing profile ("<N>gb").

    On hybrid nodes the tpuagent owns topology entries and the sharingagent
    owns HBM-fraction entries; each reporter diffs only its own flavor so
    neither wipes the other's report.
    """
    profile = status_key_profile(key)
    return profile is not None and profile.endswith("gb")


def _parse_quantity(value: str) -> "int | None":
    """Slice counts must be positive integers; anything else is malformed."""
    try:
        qty = int(value)
    except ValueError:
        return None
    return qty if qty > 0 else None


def spec_from_geometries(geometries: Dict[int, Dict[str, int]]) -> Dict[str, str]:
    """Board-index → geometry map rendered as spec annotations."""
    out: Dict[str, str] = {}
    for board, geometry in geometries.items():
        for profile, qty in geometry.items():
            if qty > 0:
                out[SpecAnnotation(board, profile, qty).key] = str(qty)
    return out


def status_from_devices(
    free: Dict[int, Dict[str, int]], used: Dict[int, Dict[str, int]]
) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for source, label in ((free, STATUS_FREE), (used, STATUS_USED)):
        for board, geometry in source.items():
            for profile, qty in geometry.items():
                if qty > 0:
                    out[StatusAnnotation(board, profile, label, qty).key] = str(qty)
    return out


def _aggregate(entries) -> Dict[int, Dict[str, int]]:
    out: Dict[int, Dict[str, int]] = defaultdict(dict)
    for s in entries:
        out[s.board_index][s.profile] = out[s.board_index].get(s.profile, 0) + s.quantity
    return dict(out)


def spec_geometries(spec: List[SpecAnnotation]) -> Dict[int, Dict[str, int]]:
    return _aggregate(spec)


def status_geometries(status: List[StatusAnnotation]) -> Dict[int, Dict[str, int]]:
    """Total (free+used) geometry per board from status annotations."""
    return _aggregate(status)


def spec_matches_status(
    spec: List[SpecAnnotation], status: List[StatusAnnotation]
) -> bool:
    """True when reported total geometry equals desired geometry
    (reference internal/controllers/migagent/actuator.go:93-97)."""
    return spec_geometries(spec) == status_geometries(status)


def strip_spec_annotations(annotations: Dict[str, str]) -> Dict[str, None]:
    """Removal patch for all existing spec slice annotations."""
    return {k: None for k in annotations if _SPEC_RE.match(k)}


def strip_status_annotations(annotations: Dict[str, str]) -> Dict[str, None]:
    return {k: None for k in annotations if _STATUS_RE.match(k)}
