"""API surface: CRD-equivalent types, annotation protocol, labels, configs.

Mirror of the reference's pkg/api/nos.nebuly.com (SURVEY.md §2.6), extended
with the TPU partitioning mode.
"""
