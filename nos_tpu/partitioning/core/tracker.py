"""SliceTracker: requested + lacking slices per pending pod.

Reference internal/partitioning/core/tracker.go:26-88. Remove(pod)
decrements as pods get placed during planning, so the planner knows when
every lacking slice is served.
"""
from __future__ import annotations

from typing import Dict, Iterable, List

from nos_tpu.api.v1alpha1 import constants
from nos_tpu.kube.objects import Pod, ResourceList
from nos_tpu.tpu.known import profile_for_chips
from nos_tpu.util import resources as res


def _pod_key(pod: Pod) -> str:
    return pod.namespaced_name


class SliceTracker:
    def __init__(self, snapshot, pods: Iterable[Pod]) -> None:
        """Pods draw from ONE shared free pool, sequentially: if two pods
        each want the single free 2x2 slice, the second one is lacking.
        (Per-pod computation against the full pool — as in the reference —
        lets N pods hide behind one free slice and deadlocks the planner.)
        """
        self._lacking: Dict[str, ResourceList] = {}
        pool = snapshot.free_slice_resources()
        for pod in pods:
            lacking = snapshot.take_from_pool(pool, res.compute_pod_request(pod))
            if lacking:
                self._lacking[_pod_key(pod)] = lacking

    @property
    def empty(self) -> bool:
        return not self._lacking

    def __contains__(self, pod: Pod) -> bool:
        return _pod_key(pod) in self._lacking

    def pods_with_lacking_slices(self) -> List[str]:
        return sorted(self._lacking)

    @staticmethod
    def _convert_plain(lacking: ResourceList, accelerator: str) -> ResourceList:
        """Convert one pod's plain-chip lack to the accelerator's slice
        profile (per pod — two 4-chip pods are two 2x2 slices, not one
        2x4). A profile_for_chips miss means the request is bigger than any
        single-board profile — multi-host gang territory, nothing a board
        carve can serve — so the plain lack is dropped for that node."""
        entry = dict(lacking)
        plain = int(entry.pop(constants.RESOURCE_TPU, 0))
        if plain > 0 and accelerator:
            profile = profile_for_chips(plain, accelerator)
            if profile is not None:
                name = constants.tpu_slice_resource(profile)
                entry[name] = entry.get(name, 0) + 1
        elif plain > 0:
            entry[constants.RESOURCE_TPU] = plain
        return entry

    def lacking_totals(self, accelerator: str = "") -> ResourceList:
        """Aggregate lacking resources. With `accelerator`, each pod's
        plain-chip lack is converted to that generation's slice profile, so
        a candidate node of that generation knows what to carve."""
        total: ResourceList = {}
        for lacking in self._lacking.values():
            total = res.sum_resources(total, self._convert_plain(lacking, accelerator))
        return total

    def lacking_for(self, pod: Pod, accelerator: str = "") -> ResourceList:
        """One pod's lacking resources, plain chips converted to the
        accelerator's slice profile (same convention as lacking_totals) —
        what a dedicated carve for exactly this pod should aim at."""
        return self._convert_plain(self._lacking.get(_pod_key(pod), {}), accelerator)

    def remove(self, pod: Pod) -> None:
        self._lacking.pop(_pod_key(pod), None)
