"""SliceTracker: requested + lacking slices per pending pod.

Reference internal/partitioning/core/tracker.go:26-88. Remove(pod)
decrements as pods get placed during planning, so the planner knows when
every lacking slice is served.
"""
from __future__ import annotations

from typing import Dict, Iterable, List

from nos_tpu.api.v1alpha1 import constants
from nos_tpu.kube.objects import Pod, ResourceList
from nos_tpu.tpu.known import profile_for_chips
from nos_tpu.util import metrics, resources as res


def _pod_key(pod: Pod) -> str:
    return pod.namespaced_name


class SliceTracker:
    def __init__(self, snapshot, pods: Iterable[Pod]) -> None:
        """Pods draw from ONE shared free pool, sequentially: if two pods
        each want the single free 2x2 slice, the second one is lacking.
        (Per-pod computation against the full pool — as in the reference —
        lets N pods hide behind one free slice and deadlocks the planner.)
        """
        self._lacking: Dict[str, ResourceList] = {}
        # id(pod) -> (pod, key): namespaced_name is an f-string build per
        # read, and the carve loop probes membership per (pod, node); the
        # pinned pod ref keeps the id from being recycled.
        self._key_cache: Dict[int, tuple] = {}
        # Per-accelerator totals, maintained incrementally: computed once
        # on first request, then kept current by remove() subtracting the
        # departing pod's converted contribution (the carve loop used to
        # re-sum every pod's lack per candidate node — ROADMAP item).
        self._totals_cache: Dict[str, ResourceList] = {}
        self.totals_calls = 0
        self.totals_recomputes = 0
        pool = snapshot.free_slice_resources()
        for pod in pods:
            lacking = snapshot.take_from_pool(pool, res.compute_pod_request(pod))
            if lacking:
                self._lacking[_pod_key(pod)] = lacking

    @property
    def empty(self) -> bool:
        return not self._lacking

    def _key(self, pod: Pod) -> str:
        entry = self._key_cache.get(id(pod))
        if entry is None or entry[0] is not pod:
            entry = (pod, _pod_key(pod))
            self._key_cache[id(pod)] = entry
        return entry[1]

    def __contains__(self, pod: Pod) -> bool:
        return self._key(pod) in self._lacking

    def pods_with_lacking_slices(self) -> List[str]:
        return sorted(self._lacking)

    @staticmethod
    def _convert_plain(lacking: ResourceList, accelerator: str) -> ResourceList:
        """Convert one pod's plain-chip lack to the accelerator's slice
        profile (per pod — two 4-chip pods are two 2x2 slices, not one
        2x4). A profile_for_chips miss means the request is bigger than any
        single-board profile — multi-host gang territory, nothing a board
        carve can serve — so the plain lack is dropped for that node."""
        entry = dict(lacking)
        plain = int(entry.pop(constants.RESOURCE_TPU, 0))
        if plain > 0 and accelerator:
            profile = profile_for_chips(plain, accelerator)
            if profile is not None:
                name = constants.tpu_slice_resource(profile)
                entry[name] = entry.get(name, 0) + 1
        elif plain > 0:
            entry[constants.RESOURCE_TPU] = plain
        return entry

    def lacking_totals(self, accelerator: str = "") -> ResourceList:
        """Aggregate lacking resources. With `accelerator`, each pod's
        plain-chip lack is converted to that generation's slice profile, so
        a candidate node of that generation knows what to carve.

        Served from a per-accelerator cache that remove() keeps current, so
        repeated calls inside the carve loop are O(profiles) rather than
        O(pending pods)."""
        self.totals_calls += 1
        cached = self._totals_cache.get(accelerator)
        if cached is not None:
            metrics.TRACKER_TOTALS_INCREMENTAL.inc()
            return dict(cached)
        self.totals_recomputes += 1
        metrics.TRACKER_TOTALS_RECOMPUTES.inc()
        total: ResourceList = {}
        for lacking in self._lacking.values():
            total = res.sum_resources(total, self._convert_plain(lacking, accelerator))
        self._totals_cache[accelerator] = total
        return dict(total)

    def lacking_for(self, pod: Pod, accelerator: str = "") -> ResourceList:
        """One pod's lacking resources, plain chips converted to the
        accelerator's slice profile (same convention as lacking_totals) —
        what a dedicated carve for exactly this pod should aim at."""
        return self._convert_plain(self._lacking.get(self._key(pod), {}), accelerator)

    def remove(self, pod: Pod) -> None:
        lacking = self._lacking.pop(self._key(pod), None)
        if lacking is None:
            return
        # Keep every cached total current by subtracting this pod's
        # converted contribution (cheaper than invalidating: the carve loop
        # calls lacking_totals again right after each placement).
        for accelerator, total in self._totals_cache.items():
            for name, amount in self._convert_plain(lacking, accelerator).items():
                remaining = total.get(name, 0) - amount
                if remaining > 0:
                    total[name] = remaining
                else:
                    total.pop(name, None)
