"""Partitioning state types.

Reference internal/partitioning/state/partitioning.go:24-56:
GPUPartitioning{GPUIndex, Resources} → BoardPartitioning;
NodePartitioning{GPUs} → NodePartitioning{boards};
PartitioningState = map[nodeName]NodePartitioning with unordered equality.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from nos_tpu.kube.objects import ResourceList


@dataclass
class BoardPartitioning:
    board_index: int
    resources: ResourceList = field(default_factory=dict)  # slice resource → qty


@dataclass
class NodePartitioning:
    boards: List[BoardPartitioning] = field(default_factory=list)


PartitioningState = Dict[str, NodePartitioning]


@dataclass
class PartitioningPlan:
    desired_state: PartitioningState
    id: str


def partitioning_state_to_dict(state: PartitioningState) -> dict:
    """JSON projection for the flight recorder: node -> board index (as a
    string key) -> resources. Round-trips through
    partitioning_state_from_dict."""
    return {
        node: {
            str(b.board_index): dict(b.resources) for b in np.boards
        }
        for node, np in state.items()
    }


def partitioning_state_from_dict(data: dict) -> PartitioningState:
    return {
        node: NodePartitioning(
            boards=[
                BoardPartitioning(
                    board_index=int(index), resources=dict(resources)
                )
                for index, resources in sorted(boards.items(), key=lambda kv: int(kv[0]))
            ]
        )
        for node, boards in data.items()
    }


def _node_key(np: NodePartitioning) -> tuple:
    return tuple(
        sorted(
            (b.board_index, tuple(sorted(b.resources.items())))
            for b in np.boards
            if b.resources
        )
    )


def partitioning_state_equal(a: PartitioningState, b: PartitioningState) -> bool:
    """Unordered equality, ignoring empty board entries."""
    keys = set(a) | set(b)
    for k in keys:
        a_np = a.get(k, NodePartitioning())
        b_np = b.get(k, NodePartitioning())
        if _node_key(a_np) != _node_key(b_np):
            return False
    return True
