"""Actuator: applies a partitioning plan through a mode-specific Partitioner.

Reference internal/partitioning/core/actuator.go:39-66: diff desired vs
current PartitioningState, skip when equal or empty, otherwise call the
mode's Partitioner.ApplyPartitioning per changed node.
"""
from __future__ import annotations

import logging
from typing import Protocol

from nos_tpu.partitioning.core.partition_state import (
    NodePartitioning,
    PartitioningPlan,
    PartitioningState,
    _node_key,
    partitioning_state_equal,
)
from nos_tpu.util.tracing import TRACER

log = logging.getLogger("nos_tpu.partitioning")


class Partitioner(Protocol):
    """Mode-specific actuation seam: the reference binds it to MIG
    (annotations → migagent) and MPS (device-plugin ConfigMap + label flip);
    the TPU mode uses the annotation → tpuagent style."""

    def apply_partitioning(
        self, node_name: str, plan_id: str, partitioning: NodePartitioning
    ) -> None: ...


class Actuator:
    def __init__(self, partitioner: Partitioner) -> None:
        self.partitioner = partitioner

    def apply(
        self,
        current: PartitioningState,
        plan: PartitioningPlan,
    ) -> int:
        """Applies the per-node diff; returns the number of nodes actuated
        (0 = nothing to do, truthiness matches the reference's bool)."""
        desired = plan.desired_state
        if not desired:
            log.debug("actuator: empty desired state, skipping")
            return 0
        if partitioning_state_equal(current, desired):
            log.debug("actuator: desired == current, skipping")
            return 0
        applied = 0
        for node_name, node_partitioning in sorted(desired.items()):
            if _node_key(current.get(node_name, NodePartitioning())) == _node_key(
                node_partitioning
            ):
                continue  # this node already matches
            with TRACER.span("actuator.apply_node", node=node_name) as span:
                # The agent picks the plan up asynchronously from the node
                # annotation; the link carries the trace across that gap so
                # the tpuagent's reconfig span lands in the same trace.
                TRACER.link(("reconfig", node_name, plan.id), span)
                self.partitioner.apply_partitioning(
                    node_name, plan.id, node_partitioning
                )
            applied += 1
        return applied
