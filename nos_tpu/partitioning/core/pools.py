"""Pool-sharded planning: partition, per-pool execution, deterministic merge.

A single planner thread owning the whole cluster saturates a core around
16k nodes (ROADMAP item 1) — but most clusters decompose: gangs, affinity
edges and quota borrowing induce a partition of the node graph, and any
two components with no such edge between them can be planned independently
(the Omega insight, applied to our gang-aware global planner instead of
per-node partitioning). This module owns the decomposition:

- :func:`partition_pools` — a per-cycle pure function from (snapshot,
  pending pods, quotas) to a :class:`PoolPartition`. Pools are seeded by
  the GKE node-pool label and merged by a union-find over the edges that
  couple planning decisions: a pending pod whose node selector matches
  several pools, a gang with members across pools, and quota namespaces
  that can borrow (spec.max != spec.min). Anything whose footprint is
  inherently cluster-wide — topology spread, inter-pod (anti-)affinity,
  required node affinity — degrades the whole partition to one mega-pool
  rather than guessing locality.
- :func:`split_snapshot` — carve one ClusterSnapshot into per-pool
  snapshots with cloned nodes (versions reset: each pool snapshot runs
  its own mutation clock, and a foreign clock's ticks must never alias).
- :func:`merge_pool_states` / :func:`check_merge_invariants` — the
  deterministic recombination of per-pool ``PartitioningState``s and the
  cross-pool safety net behind it (no node claimed twice, every node
  accounted for, no board listed twice, and no node partitioned past its
  physical capacity — chips are never minted by the merge).
- :func:`run_pool_plans` — serial or ThreadPoolExecutor execution of the
  per-pool closures. Threads buy nothing on a single core under the GIL
  (the hot path is pure-Python dict work); both modes exist so the bench
  can measure that honestly, and the serial order is sorted-by-pool so
  results are reproducible.
- :func:`draw_decomposes` — the test/bench oracle for byte-identical
  sharded-vs-unsharded plans: the global planner draws every pod from ONE
  cluster-wide free-slice pool in first-fit-descending order, so identity
  holds exactly when that sequential draw decomposes per pool (each pod's
  lack unchanged when drawn only against its own pool). Deliberate
  deviation, documented in partitioner-performance.md: the sharded path
  carves toward pool-local lacking totals, so on inputs where the draw
  does NOT decompose the two paths may serve a contested profile to
  different pods; the per-pool shadow oracle still proves every sharded
  plan internally sound.

Pool ids: a merged pool takes the lexicographically smallest member seed,
so ids are stable across cycles whenever the edge set is — pool-keyed
planner memos survive steady state instead of flushing every cycle.
"""
from __future__ import annotations

import gc
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from nos_tpu.api.v1alpha1 import constants
from nos_tpu.api.v1alpha1.labels import GKE_NODEPOOL_LABEL
from nos_tpu.kube.objects import Pod
from nos_tpu.partitioning.core.partition_state import (
    NodePartitioning,
    PartitioningState,
)
from nos_tpu.partitioning.core.snapshot import ClusterSnapshot
from nos_tpu.partitioning.core.verdict_cache import needs_cluster_context
from nos_tpu.tpu.topology import topology_chips

# Seed pool for nodes without a node-pool label.
DEFAULT_POOL = "default"
# The single pool every node lands in when the graph is connected (or a
# cluster-wide constraint makes locality unknowable).
MEGA_POOL = "cluster"


def _gang_of(pod: Pod):
    # Lazy import, as in planner.py: the gang plugin pulls the KubeStore
    # stack this module's dependents don't otherwise need.
    from nos_tpu.scheduler.plugins.gang import gang_of

    return gang_of(pod)


@dataclass
class PoolPartition:
    """One cycle's decomposition of the cluster into independent pools."""

    # Sorted, deduplicated pool ids.
    pools: Tuple[str, ...]
    # node name -> pool id (every snapshot node appears exactly once).
    node_pool: Dict[str, str]
    # pending pod namespaced_name -> pool id the pod is planned in.
    pod_pool: Dict[str, str]
    # merged pool id -> the seed pools folded into it (only multi-seed
    # merges are recorded; singleton pools are absent).
    merged_from: Dict[str, Tuple[str, ...]] = field(default_factory=dict)
    # Non-empty when the partition degraded to one mega-pool, naming why
    # (observability: the /debug surface and tests read this).
    single_pool_reason: str = ""

    def nodes_of(self, pool: str) -> List[str]:
        return sorted(
            name for name, p in self.node_pool.items() if p == pool
        )


class SelectorPoolIndex:
    """pool -> multiset of (label key, value) pairs present on >= 1 node,
    maintained incrementally so selector routing never rescans the
    cluster. ``pools_for`` returns the pools whose nodes *may* match a
    node selector — an over-approximation (every term present somewhere
    in the pool, not necessarily on one node), which is safe: routing a
    pod to MORE pools only merges more, never splits what must stay
    together."""

    def __init__(self) -> None:
        # pool -> {(key, value): node count}
        self._pairs: Dict[str, Dict[tuple, int]] = {}
        # node name -> (seed pool, label pairs) as last indexed, so a
        # refresh needs only the node's NEW state.
        self._node_state: Dict[str, tuple] = {}
        # pool -> node count (a pool with zero nodes stops seeding).
        self._pool_nodes: Dict[str, int] = {}

    @staticmethod
    def _node_labels(snap_node) -> dict:
        node = getattr(snap_node.partitionable, "node", None)
        return dict(node.metadata.labels) if node is not None else {}

    @staticmethod
    def seed_of(snap_node) -> str:
        node = getattr(snap_node.partitionable, "node", None)
        if node is None:
            return DEFAULT_POOL
        return node.metadata.labels.get(GKE_NODEPOOL_LABEL, DEFAULT_POOL)

    def rebuild(self, snapshot: ClusterSnapshot) -> None:
        self._pairs = {}
        self._node_state = {}
        self._pool_nodes = {}
        for name, snap_node in snapshot.get_nodes().items():
            self.note(name, snap_node)

    def note(self, name: str, snap_node) -> None:
        """Index (or re-index) one node's current labels."""
        self.forget(name)
        pool = self.seed_of(snap_node)
        pairs = tuple(sorted(self._node_labels(snap_node).items()))
        self._node_state[name] = (pool, pairs)
        self._pool_nodes[pool] = self._pool_nodes.get(pool, 0) + 1
        counts = self._pairs.setdefault(pool, {})
        for pair in pairs:
            counts[pair] = counts.get(pair, 0) + 1

    def forget(self, name: str) -> None:
        state = self._node_state.pop(name, None)
        if state is None:
            return
        pool, pairs = state
        remaining_nodes = self._pool_nodes.get(pool, 0) - 1
        if remaining_nodes > 0:
            self._pool_nodes[pool] = remaining_nodes
        else:
            self._pool_nodes.pop(pool, None)
        counts = self._pairs.get(pool)
        if counts is None:
            return
        for pair in pairs:
            remaining = counts.get(pair, 0) - 1
            if remaining > 0:
                counts[pair] = remaining
            else:
                counts.pop(pair, None)
        if not counts:
            self._pairs.pop(pool, None)

    def seeds(self) -> Tuple[str, ...]:
        return tuple(sorted(self._pool_nodes))

    def pools_for(self, selector_items: Tuple[tuple, ...]) -> Tuple[str, ...]:
        """Pools that may satisfy a node selector (sorted). An empty
        selector matches every pool."""
        if not selector_items:
            return self.seeds()
        return tuple(
            sorted(
                pool
                for pool, counts in self._pairs.items()
                if all(pair in counts for pair in selector_items)
            )
        )


class _UnionFind:
    def __init__(self, keys: Iterable[str]) -> None:
        self._parent = {key: key for key in keys}

    def find(self, key: str) -> str:
        parent = self._parent
        root = key
        while parent[root] != root:
            root = parent[root]
        while parent[key] != root:
            parent[key], key = root, parent[key]
        return root

    def union(self, a: str, b: str) -> None:
        ra, rb = self.find(a), self.find(b)
        if ra == rb:
            return
        # Smaller id wins the root so merged pool ids are deterministic.
        if rb < ra:
            ra, rb = rb, ra
        self._parent[rb] = ra


def _mega(partition_nodes: Iterable[str], pending_pods: List[Pod], reason: str) -> PoolPartition:
    return PoolPartition(
        pools=(MEGA_POOL,),
        node_pool={name: MEGA_POOL for name in partition_nodes},
        pod_pool={p.namespaced_name: MEGA_POOL for p in pending_pods},
        merged_from={},
        single_pool_reason=reason,
    )


def partition_pools(
    snapshot: ClusterSnapshot,
    pending_pods: List[Pod],
    quotas: Iterable = (),
    selector_index: Optional[SelectorPoolIndex] = None,
) -> PoolPartition:
    """Decompose the snapshot into independently plannable pools.

    Pure function of its inputs: identical (snapshot shape, pending set,
    quota bounds) yield an identical partition, which is what keeps pool
    membership — and therefore per-pool planner memos — stable across
    no-op cycles."""
    nodes = snapshot.get_nodes()
    # Cluster-wide constraints first: any of these makes per-pool verdicts
    # unsound (they read nodes outside the candidate's pool), so locality
    # cannot be assumed for ANY pod this cycle.
    for pod in pending_pods:
        if needs_cluster_context(pod):
            return _mega(
                nodes, pending_pods,
                f"pending pod {pod.namespaced_name} needs cluster-wide context",
            )
        affinity = pod.spec.affinity
        if affinity is not None and affinity.required_terms:
            return _mega(
                nodes, pending_pods,
                f"pending pod {pod.namespaced_name} has required node affinity",
            )
    if snapshot.has_anti_affinity_pods():
        return _mega(
            nodes, pending_pods,
            "placed pods carry required anti-affinity (symmetric terms)",
        )

    index = selector_index
    if index is None:
        index = SelectorPoolIndex()
        index.rebuild(snapshot)
    seeds = index.seeds()
    if not seeds:
        return _mega(nodes, pending_pods, "no nodes")
    uf = _UnionFind(seeds)

    # Selector routing: a pod whose selector spans several pools couples
    # them (the planner must choose among all of them); a selector no pool
    # can satisfy routes to the first pool, where it will report unserved.
    routed: Dict[str, str] = {}
    gang_members: Dict[str, List[str]] = {}
    coupled_quota_pods: List[str] = []
    coupled_namespaces = {
        q.metadata.namespace
        for q in quotas
        if tuple(sorted(q.spec.min.items())) != tuple(sorted(q.spec.max.items()))
    }
    for pod in pending_pods:
        selector = tuple(sorted(pod.spec.node_selector.items()))
        matched = index.pools_for(selector)
        if not matched:
            routed[pod.namespaced_name] = seeds[0]
        else:
            first = matched[0]
            for other in matched[1:]:
                uf.union(first, other)
            routed[pod.namespaced_name] = first
        gang = _gang_of(pod)
        if gang:
            gang_members.setdefault(gang[0], []).append(pod.namespaced_name)
        if pod.metadata.namespace in coupled_namespaces:
            coupled_quota_pods.append(pod.namespaced_name)

    # Gang edges: every member of a gang — pending or already bound —
    # must be planned by one pool, or a pool could carve for a gang
    # another pool just proved half-formable.
    if gang_members:
        bound_pool: Dict[str, List[str]] = {}
        for name, snap_node in nodes.items():
            for placed in snap_node.pods:
                gang = _gang_of(placed)
                if gang and gang[0] in gang_members:
                    bound_pool.setdefault(gang[0], []).append(
                        index.seed_of(snap_node)
                    )
        for key, members in gang_members.items():
            anchor = routed[members[0]]
            for member in members[1:]:
                uf.union(anchor, routed[member])
            for pool in bound_pool.get(key, ()):
                uf.union(anchor, pool)

    # Quota borrowing (spec.max != spec.min) lets one namespace's usage
    # displace another's over-quota pods, so pending pods under borrowing
    # quotas plan together.
    if len(coupled_quota_pods) > 1:
        anchor = routed[coupled_quota_pods[0]]
        for name in coupled_quota_pods[1:]:
            uf.union(anchor, routed[name])

    node_pool = {
        name: uf.find(index.seed_of(snap_node))
        for name, snap_node in nodes.items()
    }
    pod_pool = {name: uf.find(pool) for name, pool in routed.items()}
    merged_from: Dict[str, Tuple[str, ...]] = {}
    for seed in seeds:
        root = uf.find(seed)
        if root != seed:
            merged_from.setdefault(root, (root,))
            merged_from[root] = tuple(sorted(set(merged_from[root]) | {seed}))
    pools = tuple(sorted({uf.find(seed) for seed in seeds}))
    return PoolPartition(
        pools=pools,
        node_pool=node_pool,
        pod_pool=pod_pool,
        merged_from=merged_from,
        single_pool_reason="",
    )


# --------------------------------------------------------------- split


def split_snapshot(
    snapshot: ClusterSnapshot, partition: PoolPartition
) -> Dict[str, ClusterSnapshot]:
    """Per-pool snapshots with cloned nodes. Versions are reset to zero:
    each pool snapshot runs its OWN mutation clock, and a tick inherited
    from the source clock could alias a future tick of the pool clock —
    version-keyed memos must never see two states share a key."""
    by_pool: Dict[str, Dict[str, object]] = {pool: {} for pool in partition.pools}
    for name, snap_node in snapshot.get_nodes().items():
        clone = snap_node.plan_clone()
        clone.version = 0
        by_pool[partition.node_pool[name]][name] = clone
    return {
        pool: ClusterSnapshot(nodes, codec=snapshot.codec)
        for pool, nodes in by_pool.items()
    }


def split_pending(
    pending_pods: List[Pod], partition: PoolPartition
) -> Dict[str, List[Pod]]:
    """Pending pods routed per pool, original order preserved."""
    out: Dict[str, List[Pod]] = {pool: [] for pool in partition.pools}
    for pod in pending_pods:
        out[partition.pod_pool[pod.namespaced_name]].append(pod)
    return out


# --------------------------------------------------------------- merge


def merge_pool_states(
    states: Dict[str, PartitioningState],
) -> PartitioningState:
    """Deterministic recombination: pools in sorted id order, nodes in
    sorted name order — byte-identical output regardless of the order the
    pool plans finished in."""
    merged: Dict[str, NodePartitioning] = {}
    for pool in sorted(states):
        for name in sorted(states[pool]):
            merged[name] = states[pool][name]
    return dict(sorted(merged.items()))


_CHIPS_PER_RESOURCE: Dict[str, float] = {}


def _resource_chips(resource: str) -> float:
    """Chips (or GB for sharing-mode resources) one unit of ``resource``
    amounts to; memoized — the invariant check calls this for every board
    resource of every touched node every cycle, and the underlying
    regex parses are the dominant cost at 16k nodes."""
    cached = _CHIPS_PER_RESOURCE.get(resource)
    if cached is not None:
        return cached
    if constants.is_tpu_slice_resource(resource):
        chips = float(topology_chips(constants.tpu_slice_topology(resource)))
    elif resource == constants.RESOURCE_TPU:
        chips = 1.0
    elif constants.is_tpu_shared_resource(resource):
        chips = float(
            constants.shared_profile_gb(constants.tpu_shared_profile(resource))
        )
    else:
        chips = 0.0
    _CHIPS_PER_RESOURCE[resource] = chips
    return chips


def _board_chips(board) -> float:
    """One board's partitioned capacity, in chips for slice/plain
    resources and GB for sharing-mode resources (a consistent measure is
    all conservation needs — carving never creates or destroys either)."""
    total = 0.0
    for resource, qty in board.resources.items():
        total += _resource_chips(resource) * qty
    return total


def node_capacity(snap_node) -> Optional[float]:
    """The node's total partitionable capacity in the same measure as
    :func:`_board_chips` (chips, or GB for sharing nodes); None when the
    node object carries neither resource kind."""
    node = getattr(snap_node.partitionable, "node", None)
    if node is None:
        return None
    qty = node.status.capacity.get(constants.RESOURCE_TPU)
    if qty:
        return float(qty)
    total = 0.0
    for resource, count in node.status.capacity.items():
        if constants.is_tpu_shared_resource(resource):
            total += constants.shared_profile_gb(
                constants.tpu_shared_profile(resource)
            ) * count
    return total or None


def node_capacities(snapshots: Iterable[ClusterSnapshot]) -> Dict[str, float]:
    """node -> capacity over a collection of (pool) snapshots, for
    :func:`check_merge_invariants`'s minting ceiling."""
    out: Dict[str, float] = {}
    for snap in snapshots:
        for name, snap_node in snap.get_nodes().items():
            cap = node_capacity(snap_node)
            if cap is not None:
                out[name] = cap
    return out


def check_merge_invariants(
    partition: PoolPartition,
    pool_current: Dict[str, PartitioningState],
    pool_desired: Dict[str, PartitioningState],
    capacities: Optional[Dict[str, float]] = None,
) -> List[str]:
    """Cross-pool safety net run on every sharded plan before actuation.
    Returns human-readable violations (empty = sound): a node claimed by
    two pools, a partition node no pool planned (or a planned node outside
    the partition), a node whose desired state lists the same board twice
    (merge corruption), or a node whose desired chip total exceeds its
    physical capacity (minting). Re-carving a board to a different chip
    total is deliberately legal — tearing down a degraded board and
    carving it back to full is exactly what a replan after chip-loss
    faults does — so the chip invariant is the capacity ceiling, not
    per-board equality."""
    violations: List[str] = []
    seen: Dict[str, str] = {}
    for pool, desired in pool_desired.items():
        for name in desired:
            prior = seen.get(name)
            if prior is not None:
                violations.append(
                    f"node {name} claimed by pools {prior} and {pool}"
                )
            seen[name] = pool
            if partition.node_pool.get(name) != pool:
                violations.append(
                    f"node {name} planned by pool {pool} but assigned to "
                    f"{partition.node_pool.get(name)!r}"
                )
    missing = set(partition.node_pool) - set(seen)
    for name in sorted(missing):
        violations.append(f"node {name} missing from every pool plan")
    for pool in sorted(pool_desired):
        current = pool_current.get(pool, {})
        for name in sorted(pool_desired[pool]):
            if pool_desired[pool][name] is current.get(name):
                # The memoized partitioning_state returns the SAME object
                # for a node the plan never touched — nothing to check,
                # and skipping it keeps this pass O(touched), not
                # O(cluster), at 16k nodes per cycle.
                continue
            desired_total = 0.0
            board_indices = set()
            for board in pool_desired[pool][name].boards:
                desired_total += _board_chips(board)
                if board.board_index in board_indices:
                    violations.append(
                        f"pool {pool}: node {name} lists board "
                        f"{board.board_index} twice"
                    )
                board_indices.add(board.board_index)
            cap = (capacities or {}).get(name)
            if cap is not None and desired_total > cap + 1e-9:
                violations.append(
                    f"pool {pool}: node {name} desired {desired_total} "
                    f"chips exceeds capacity {cap}"
                )
    return violations


# ----------------------------------------------------------- execution


def run_pool_plans(
    tasks: Dict[str, Callable[[], object]],
    parallelism: str = "serial",
    max_workers: int = 0,
) -> Dict[str, object]:
    """Run one closure per pool; serial mode executes in sorted pool
    order (reproducible), thread mode fans out on a ThreadPoolExecutor.
    On a single GIL-bound core the thread mode measures slightly WORSE
    than serial (bench_planner --parallel reports both); it exists for
    multi-core deployments and for honest measurement, not as a default."""
    if parallelism == "thread" and len(tasks) > 1:
        workers = max_workers if max_workers > 0 else len(tasks)
        # A generation-2 collection landing mid-fan-out stops every
        # worker thread at once (the collector runs under the GIL and
        # walks a heap that is O(cluster) at 16k nodes) — the thread
        # mode's p95 outlier: most cycles match serial, the one that
        # catches the full-heap pass pays it inside the timed window,
        # on top of the executor's own switch overhead. Deferring
        # collection to the join keeps the pause out of the per-pool
        # latencies; nothing is freed later than one cycle.
        was_enabled = gc.isenabled()
        if was_enabled:
            gc.disable()
        try:
            with ThreadPoolExecutor(max_workers=min(workers, len(tasks))) as pool:
                futures = {
                    name: pool.submit(task) for name, task in sorted(tasks.items())
                }
                return {name: future.result() for name, future in futures.items()}
        finally:
            if was_enabled:
                gc.enable()
    return {name: task() for name, task in sorted(tasks.items())}


# ------------------------------------------------------- equivalence


def draw_decomposes(
    snapshot: ClusterSnapshot,
    partition: PoolPartition,
    candidates: List[Pod],
) -> bool:
    """Whether the global planner's sequential free-pool draw (first-fit
    over `candidates`, which the caller passes already sorted) yields the
    same per-pod lack when each pod draws only from its own pool's free
    slices. When true — pool-independent inputs — the sharded and
    unsharded paths provably produce byte-identical PartitioningStates;
    when false, a contested profile may be served to different pods and
    the paths may diverge (soundly, but not identically). Test and bench
    oracle; never on the hot path."""
    from nos_tpu.util import resources as res

    codec = snapshot.codec
    global_pool = snapshot.free_slice_resources()
    accelerators = snapshot.accelerators()
    pool_free: Dict[str, dict] = {pool: {} for pool in partition.pools}
    pool_accels: Dict[str, set] = {pool: set() for pool in partition.pools}
    for name, snap_node in snapshot.get_nodes().items():
        pool = partition.node_pool[name]
        free = pool_free[pool]
        for profile, qty in snap_node.partitionable.free_slices().items():
            resource = codec.resource(profile)
            free[resource] = free.get(resource, 0) + qty
        accel = getattr(snap_node.partitionable, "accelerator", "")
        if accel:
            pool_accels[pool].add(accel)
    for pod in candidates:
        request = res.compute_pod_request(pod)
        global_lack = codec.take_from_pool(
            global_pool, request, accelerators
        )
        pool = partition.pod_pool[pod.namespaced_name]
        local_lack = codec.take_from_pool(
            pool_free[pool], request, sorted(pool_accels[pool])
        )
        if global_lack != local_lack:
            return False
    return True
