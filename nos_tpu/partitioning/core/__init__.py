from nos_tpu.partitioning.core.partition_state import (
    BoardPartitioning,
    NodePartitioning,
    PartitioningPlan,
    PartitioningState,
    partitioning_state_equal,
)
from nos_tpu.partitioning.core.state import ClusterState
from nos_tpu.partitioning.core.snapshot import (
    ClusterSnapshot,
    DeepcopyClusterSnapshot,
    SnapshotNode,
)
from nos_tpu.partitioning.core.tracker import SliceTracker
from nos_tpu.partitioning.core.verdict_cache import VerdictCache
from nos_tpu.partitioning.core.planner import Planner
from nos_tpu.partitioning.core.actuator import Actuator

__all__ = [
    "Actuator",
    "BoardPartitioning",
    "ClusterSnapshot",
    "ClusterState",
    "DeepcopyClusterSnapshot",
    "NodePartitioning",
    "PartitioningPlan",
    "PartitioningState",
    "Planner",
    "SliceTracker",
    "SnapshotNode",
    "VerdictCache",
    "partitioning_state_equal",
]
