"""Cluster snapshot with fork/commit/revert — the planner's working copy.

Reference internal/partitioning/core/snapshot.go:43-190: copy-on-write over
map[nodeName]PartitionableNode; GetLackingSlices(pod) = pod request minus
cluster-wide free resources; GetCandidateNodes = nodes with free capacity
sorted by name.

Fork is a real copy-on-write journal, matching the reference's semantics
instead of the deepcopy-the-world port it replaced: ``fork()`` pushes an
empty per-fork journal, the first touch of a node under a fork clones ONLY
that ``SnapshotNode`` into the journal (``plan_clone`` on the partitionable
— board/chip state is copied, the kube Node object is shared), ``revert()``
restores the journaled originals and ``commit()`` folds the journal into
the parent fork (or drops it at top level). Fork cost is therefore
proportional to nodes actually touched in a trial — typically one — not to
cluster size. Forks nest, which is what lets the planner run its gang
trial as a journaled fork around a whole ``_plan_pass`` instead of
deepcopying the entire snapshot.

Contract for mutations while forked: go through the snapshot-level
mutators (``update_geometry_for`` / ``add_pod``) or mutate a node obtained
from ``get_node()`` *after* the fork started (``get_node`` journals on
access). Mutating a node reference captured before ``fork()`` bypasses the
journal and cannot be reverted.

The cluster-wide free-slice pool is maintained incrementally: computed
once on first use, then adjusted by the delta each geometry carve or pod
placement produces on the touched node, and checkpointed/restored across
fork/revert — ``get_lacking_slices`` (called per pod × node trial) no
longer walks every node.

Mutation versions: every snapshot-level mutation stamps the touched node
(``SnapshotNode.version``) and the snapshot (``state_version``) with the
next tick of one shared monotonic clock. Two distinct states can never
share a version (the clock never repeats), and reverting a fork restores
the journaled nodes *with their old versions* plus the checkpointed
``state_version`` — so version-keyed caches (the planner's verdict cache)
see entries from before the fork become valid again instead of being
discarded. The versions are only maintained by the snapshot-level
mutators; mutating a node obtained from ``get_node()`` directly (the
legacy contract above) leaves them stale, which is safe for the planner
(it only mutates through the snapshot) but means out-of-band mutators
must not rely on them.
"""
from __future__ import annotations

import bisect
import copy
import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from nos_tpu.api.v1alpha1 import constants
from nos_tpu.kube.objects import Pod, ResourceList
from nos_tpu.partitioning.core.codec import SliceCodec, TpuSliceCodec
from nos_tpu.partitioning.core.partition_state import (
    BoardPartitioning,
    NodePartitioning,
    PartitioningState,
)
from nos_tpu.scheduler.framework import NodeInfo
from nos_tpu.tpu.topology import topology_chips
from nos_tpu.util import metrics
from nos_tpu.util import resources as res


@dataclass
class SnapshotNode:
    """A partitionable node + the pods scheduled onto it."""

    partitionable: object  # PartitionableNode protocol (e.g. tpu.TpuNode)
    pods: List[Pod] = field(default_factory=list)
    # True while the node's agent has not yet acknowledged its current
    # spec plan: its geometry is mid-change, so the planner must not carve
    # it again (per-node generalization of the reference's GLOBAL
    # "all nodes reported" gate, partitioner_controller.go:118-122 —
    # global gating stalls every other node's replan behind one
    # in-flight actuation).
    frozen: bool = False
    # Monotonic mutation version, stamped from the owning snapshot's
    # shared clock on every snapshot-level mutation (carve, placement).
    # (node name, version) pins the node's observable state exactly: the
    # clock never repeats, and a reverted fork restores the journaled
    # node together with its pre-fork version, re-validating any
    # version-keyed cache entries from before the fork.
    version: int = 0

    @property
    def name(self) -> str:
        return self.partitionable.name

    def sim_node_info(self) -> NodeInfo:
        """NodeInfo whose allocatable reflects the (possibly re-carved)
        geometry — what the embedded scheduler framework filters against."""
        return NodeInfo(node=self.partitionable.to_sim_node(), pods=list(self.pods))

    def add_pod(self, pod: Pod) -> bool:
        if not self.partitionable.add_pod(pod):
            return False
        self.pods.append(pod)
        return True

    def plan_clone(self) -> "SnapshotNode":
        """Journal backup: clone the mutable planning state (partitionable
        geometry + the pods list — Pod objects themselves are never mutated
        by planning, so they are shared)."""
        part = self.partitionable
        clone = part.plan_clone() if hasattr(part, "plan_clone") else copy.deepcopy(part)
        return SnapshotNode(
            partitionable=clone,
            pods=list(self.pods),
            frozen=self.frozen,
            version=self.version,
        )


class ClusterSnapshot:
    def __init__(
        self, nodes: Dict[str, SnapshotNode], codec: Optional[SliceCodec] = None
    ) -> None:
        self._nodes = nodes
        self.codec: SliceCodec = codec or TpuSliceCodec()
        # Fork journal stack: one dict per live fork, node name -> backup
        # SnapshotNode cloned at first touch under that fork.
        self._journals: List[Dict[str, SnapshotNode]] = []
        # Per-fork checkpoint of (free pool, state_version). A pool of
        # None means it was not yet computed when the fork started, so
        # revert just re-invalidates it.
        self._pool_backups: List[tuple] = []
        self._free_pool: Optional[ResourceList] = None
        # Shared monotonic mutation clock: every mutation stamps the
        # touched node's version and the snapshot-wide state_version with
        # the next tick. Never repeats — see the module docstring.
        self._mutation_clock = itertools.count(1)
        self.state_version = 0
        self._accel_cache: Optional[List[str]] = None
        self._sim_cache: Optional[List[NodeInfo]] = None
        # Count of placed pods carrying required anti-affinity, maintained
        # incrementally once computed (None = not yet computed): add_pod
        # increments it and fork/revert checkpoint it, so
        # has_anti_affinity_pods() never rescans the cluster per trial.
        self._anti_count: Optional[int] = None
        # node name -> (version, free chips, has_free_capacity,
        # has_free_slices): the best-fit candidate sort reads these per
        # node per call, and the version key keeps entries exact across
        # mutation and revert.
        self._free_chips_cache: Dict[str, tuple] = {}
        # Best-fit candidate order, maintained incrementally: (order list,
        # parallel sort-key list, state_version at build) plus the names
        # mutated since the build. A placement dirties ONE node, so the
        # next call repairs the prior order (bisect-remove each dirty name
        # at its RECORDED key, bisect-insert at its current key) instead
        # of re-sorting — or re-filtering — the whole cluster. The repair
        # reproduces the full sort exactly because untouched nodes keep
        # their keys and the (chips, name) key is a total order; keeping
        # the key list parallel to the order list is what makes removal a
        # binary search + C-level pop instead of an O(nodes) Python scan.
        self._cand_cache: Optional[tuple] = None
        self._cand_dirty: set = set()
        # name -> (chips, name) key the candidate order currently holds
        # for that member (absent = filtered out: frozen or no capacity).
        self._cand_keys: Dict[str, tuple] = {}
        # name -> (version, boards) for the partitioning_state projection:
        # building BoardPartitioning rows is an O(nodes) dict walk per
        # call, and the projection runs at least twice per plan cycle
        # (observed state + desired state) over mostly-untouched nodes.
        # Entries are shared with callers — the projection is read-only by
        # contract (actuators and recorders never mutate it).
        self._part_state_cache: Dict[str, tuple] = {}

    # ------------------------------------------------------ fork/commit

    @property
    def forked(self) -> bool:
        return bool(self._journals)

    def fork(self) -> None:
        """Start a (nestable) copy-on-write trial."""
        self._journals.append({})
        self._pool_backups.append(
            (
                dict(self._free_pool) if self._free_pool is not None else None,
                self.state_version,
                self._anti_count,
            )
        )
        self._sim_cache = None
        metrics.SNAPSHOT_FORKS.inc()

    def commit(self) -> int:
        """Keep the current fork's mutations. Inside a parent fork the
        journal folds upward (a backup the parent lacks is also the node's
        state at the parent's fork point — it would have been journaled in
        the parent had it been touched earlier), so an outer revert still
        undoes committed inner trials. Returns the number of nodes the
        ended fork had cloned — the trial's CoW cost, which the planner
        records on its trial spans."""
        if not self._journals:
            raise RuntimeError("snapshot not forked")
        journal = self._journals.pop()
        self._pool_backups.pop()
        if self._journals:
            parent = self._journals[-1]
            for name, backup in journal.items():
                parent.setdefault(name, backup)
        self._sim_cache = None
        metrics.SNAPSHOT_COMMITS.inc()
        metrics.FORK_NODES_COPIED.set(len(journal))
        return len(journal)

    def revert(self) -> int:
        """Discard the current fork's mutations by restoring the journaled
        node backups and the free-pool checkpoint. Returns the ended
        fork's cloned-node count, as commit() does."""
        if not self._journals:
            raise RuntimeError("snapshot not forked")
        journal = self._journals.pop()
        for name, backup in journal.items():
            self._nodes[name] = backup
        # Restored nodes differ from any candidate order built mid-fork.
        self._cand_dirty.update(journal)
        self._free_pool, self.state_version, self._anti_count = (
            self._pool_backups.pop()
        )
        self._sim_cache = None
        metrics.SNAPSHOT_REVERTS.inc()
        metrics.FORK_NODES_COPIED.set(len(journal))
        return len(journal)

    def _touch(self, name: str) -> None:
        """Journal `name` under the innermost fork before its first
        mutation (no-op outside forks or when already journaled)."""
        if not self._journals:
            return
        journal = self._journals[-1]
        if name in journal:
            return
        node = self._nodes.get(name)
        if node is None:
            return
        journal[name] = node.plan_clone()
        metrics.SNAPSHOT_NODES_COPIED.inc()

    # ------------------------------------------------ cross-cycle refresh

    def node_count(self) -> int:
        return len(self._nodes)

    def node_version(self, name: str) -> int:
        """O(1) mutation-clock read for one node (-1 = absent). The
        incremental planner revalidates version-keyed cache entries with
        this instead of walking ``get_nodes()``."""
        node = self._nodes.get(name)
        return node.version if node is not None else -1

    def refresh_node(self, name: str, replacement: SnapshotNode) -> None:
        """Replace one node's observed state between plan cycles, keeping
        every incremental aggregate exact: the free pool absorbs the
        old→new free-slice delta, the anti-affinity count is adjusted by
        the old and new pod sets, and the replacement is stamped with a
        fresh mutation tick so every version-keyed cache entry for the old
        state becomes unreachable (never wrong). This is the ONLY
        sanctioned out-of-band mutation for a snapshot used as a
        persistent planning base — node additions and removals change the
        snapshot's shape and require rebuilding it instead.

        Refusing to run under an active fork is load-bearing: a mid-trial
        replacement would bypass the journal and survive revert."""
        if self._journals:
            raise RuntimeError("refresh_node during an active fork")
        old = self._nodes.get(name)
        if old is None:
            raise KeyError(f"refresh_node: unknown node {name!r}")
        before = dict(old.partitionable.free_slices())
        if self._anti_count is not None:
            self._anti_count -= sum(
                1 for p in old.pods if p.spec.pod_anti_affinity
            )
            self._anti_count += sum(
                1 for p in replacement.pods if p.spec.pod_anti_affinity
            )
        if getattr(replacement.partitionable, "accelerator", "") != getattr(
            old.partitionable, "accelerator", ""
        ):
            self._accel_cache = None
        self._nodes[name] = replacement
        self._apply_free_delta(before, replacement)
        self._free_chips_cache.pop(name, None)
        self._sim_cache = None
        self._stamp(replacement)

    # --------------------------------------------------------- queries

    def get_node(self, name: str) -> Optional[SnapshotNode]:
        # Journal on access while forked: callers are allowed to mutate the
        # returned node directly (legacy contract), and a clone here is
        # cheap — board dicts plus a pods pointer-list.
        self._touch(name)
        return self._nodes.get(name)

    def get_nodes(self) -> Dict[str, SnapshotNode]:
        return self._nodes

    def accelerators(self) -> List[str]:
        """Accelerator generations present. Cached for the snapshot's
        lifetime — the node set is fixed after construction and geometry
        mutations never change a node's generation."""
        if self._accel_cache is None:
            self._accel_cache = sorted(
                {
                    n.partitionable.accelerator
                    for n in self._nodes.values()
                    if getattr(n.partitionable, "accelerator", "")
                }
            )
        return self._accel_cache

    def _node_free_state(self, name: str, node: SnapshotNode) -> tuple:
        """(free chips, has_free_capacity, has_free_slices) for one node,
        memoized on its mutation version — the candidate sort reads these
        for every node on every call, and most nodes are untouched between
        calls."""
        cached = self._free_chips_cache.get(name)
        if cached is not None and cached[0] == node.version:
            return cached[1], cached[2], cached[3]
        part = node.partitionable
        free = part.free_slices()
        chips = sum(topology_chips(profile) * qty for profile, qty in free.items())
        has_free = part.has_free_capacity()
        self._free_chips_cache[name] = (node.version, chips, has_free, bool(free))
        return chips, has_free, bool(free)

    def node_has_free_slices(self, name: str) -> bool:
        """Whether `name` currently exposes any free slice — the exact
        precondition for add_pod() to place a slice-consuming pod, read
        through the version-keyed memo so the claim pre-pass can skip
        exhausted nodes without probing them."""
        node = self._nodes.get(name)
        return bool(node) and self._node_free_state(name, node)[2]

    def _cand_sort_key(self, name: str) -> tuple:
        node = self._nodes[name]
        return self._node_free_state(name, node)[0], name

    def get_candidate_nodes(self) -> List[str]:
        """Nodes whose geometry could still change or serve slices.

        Best-fit order — fewest free chips first, name for determinism —
        instead of the reference's plain name order (snapshot.go:119-130):
        small lacking slices carve out of already-fragmented nodes, so
        whole free boards survive for board-sized requests.

        The order is cached and repaired incrementally: a plan placement
        dirties one node, so re-sorting the whole cluster per call (the
        dominant replan cost at 1k+ nodes) is replaced by bisect-removing
        the dirty names at their recorded keys and bisect-inserting them
        at their current keys — byte-identical output to the full sort at
        O(dirty · log nodes) comparisons. The lists are copied before
        repair (a C-level pointer memcpy) so iterations over previously
        returned orders never see mid-repair mutation."""
        cached = self._cand_cache
        if cached is not None and cached[2] == self.state_version:
            return cached[0]
        dirty = self._cand_dirty
        if cached is not None and len(dirty) * 8 <= len(self._nodes):
            order = list(cached[0])
            keys = list(cached[1])
            for name in sorted(dirty):
                old_key = self._cand_keys.pop(name, None)
                if old_key is not None:
                    index = bisect.bisect_left(keys, old_key)
                    if index < len(order) and order[index] == name:
                        order.pop(index)
                        keys.pop(index)
                node = self._nodes.get(name)
                if node is None or node.frozen:
                    continue
                chips, has_free, _ = self._node_free_state(name, node)
                if not has_free:
                    continue
                key = (chips, name)
                index = bisect.bisect_left(keys, key)
                order.insert(index, name)
                keys.insert(index, key)
                self._cand_keys[name] = key
        else:
            states = {
                name: self._node_free_state(name, node)
                for name, node in self._nodes.items()
            }
            order = [
                name
                for name, node in sorted(
                    self._nodes.items(),
                    key=lambda kv: (states[kv[0]][0], kv[0]),
                )
                if states[name][1] and not node.frozen
            ]
            keys = [(states[name][0], name) for name in order]
            self._cand_keys = dict(zip(order, keys))
        self._cand_cache = (order, keys, self.state_version)
        dirty.clear()
        return order

    def _compute_free_pool(self) -> ResourceList:
        total: ResourceList = {}
        for node in self._nodes.values():
            for profile, qty in node.partitionable.free_slices().items():
                name = self.codec.resource(profile)
                total[name] = total.get(name, 0) + qty
        return total

    def free_slice_resources(self) -> ResourceList:
        """Cluster-wide free slices as a ResourceList (a private copy —
        callers mutate it via take_from_pool). Maintained incrementally by
        the snapshot-level mutators; invalidate_free_pool() forces a
        recompute after out-of-band node mutations."""
        if self._free_pool is None:
            self._free_pool = self._compute_free_pool()
        return dict(self._free_pool)

    def invalidate_free_pool(self) -> None:
        self._free_pool = None
        # Out-of-band mutation signal: per-node versions were NOT bumped,
        # so version-keyed node entries must be dropped wholesale, and
        # anything keyed on the snapshot-wide state_version must miss.
        self._free_chips_cache = {}
        self._anti_count = None
        self._sim_cache = None
        self._cand_cache = None
        self._cand_dirty.clear()
        self._cand_keys = {}
        self._part_state_cache = {}
        self.state_version = next(self._mutation_clock)

    def _stamp(self, node: SnapshotNode) -> None:
        """Advance the mutation clock onto `node` and the snapshot."""
        tick = next(self._mutation_clock)
        node.version = tick
        self.state_version = tick
        self._cand_dirty.add(node.name)

    def _apply_free_delta(self, before: "Dict[str, int]", node: SnapshotNode) -> None:
        """Fold the change in one node's free slices into the cluster pool."""
        if self._free_pool is None:
            return
        after = node.partitionable.free_slices()
        for profile in set(before) | set(after):
            delta = after.get(profile, 0) - before.get(profile, 0)
            if not delta:
                continue
            name = self.codec.resource(profile)
            updated = self._free_pool.get(name, 0) + delta
            if updated:
                self._free_pool[name] = updated
            else:
                self._free_pool.pop(name, None)

    @staticmethod
    def is_tracked_resource(name: str) -> bool:
        """Resources the default (tpu) mode is responsible for serving.
        Instances answer per their own codec via `tracked`."""
        return constants.is_tpu_slice_resource(name) or name == constants.RESOURCE_TPU

    def tracked(self, name: str) -> bool:
        return self.codec.is_tracked(name)

    def normalize_request(
        self, request: ResourceList, accelerator: Optional[str] = None
    ) -> ResourceList:
        """Normalize a plain-chip request to a slice request.

        With `accelerator` (the per-candidate-node case) the node's own
        generation decides the profile. Without it, plain chips are kept
        plain — in a mixed-generation cluster there is no single right
        profile, and picking one deadlocks pods against nodes of the other
        generation."""
        return self.codec.normalize_request(request, accelerator or "")

    def take_from_pool(self, pool: ResourceList, request: ResourceList) -> ResourceList:
        """Serve `request`'s tracked resources from `pool` (mutating it);
        returns what remains lacking."""
        return self.codec.take_from_pool(pool, request, self.accelerators())

    def get_lacking_slices(self, pod: Pod) -> ResourceList:
        """Tracked resources the pod needs beyond cluster-wide free slices
        (snapshot.go:132-165). Only slice/chip resources count — everything
        else is the vanilla scheduler's problem. Plain-chip lack is reported
        as ``google.com/tpu`` since the serving profile depends on which
        node ends up carved."""
        request = res.compute_pod_request(pod)
        pool = self.free_slice_resources()
        return self.take_from_pool(pool, request)

    def sim_node_infos(self) -> List[NodeInfo]:
        """Every node's sim view, for predicates needing cluster-wide
        context (topology spread, inter-pod affinity). Cached until the
        next fork/commit/revert or node mutation — the planner's mutation
        points all invalidate it."""
        if self._sim_cache is None:
            self._sim_cache = [n.sim_node_info() for n in self._nodes.values()]
        return self._sim_cache

    def has_anti_affinity_pods(self) -> bool:
        """Whether any placed pod carries required anti-affinity — those
        terms are SYMMETRIC (they reject incoming pods), so the simulation
        must publish the cluster view even for term-less candidates. The
        planner calls this once per (pod, node) trial, so the count is
        computed once and then maintained incrementally by add_pod and the
        fork/revert checkpoints — never rescanned per trial."""
        if self._anti_count is None:
            self._anti_count = sum(
                1
                for node in self._nodes.values()
                for p in node.pods
                if p.spec.pod_anti_affinity
            )
        return self._anti_count > 0

    # -------------------------------------------------------- mutation

    def update_geometry_for(self, node_name: str, lacking: ResourceList) -> bool:
        """Re-carve one node toward `lacking`, journaled and with the free
        pool kept incremental. The planner's carve entry point."""
        node = self._nodes.get(node_name)
        if node is None:
            return False
        self._touch(node_name)
        before = dict(node.partitionable.free_slices())
        changed = node.partitionable.update_geometry_for(lacking)
        if changed:
            self._apply_free_delta(before, node)
            self._sim_cache = None
            self._stamp(node)
        return changed

    def add_pod(self, node_name: str, pod: Pod) -> bool:
        node = self._nodes.get(node_name)
        if node is None:
            return False
        self._touch(node_name)
        before = dict(node.partitionable.free_slices())
        added = node.add_pod(pod)
        if added:
            self._apply_free_delta(before, node)
            self._sim_cache = None
            if self._anti_count is not None and pod.spec.pod_anti_affinity:
                self._anti_count += 1
            self._stamp(node)
        return added

    # ------------------------------------------------------ projection

    def partitioning_state(self) -> PartitioningState:
        """Projection of every node's current geometry. Board rows are
        memoized per (node, mutation version) — the projection runs at
        least twice per plan cycle over mostly-untouched nodes, and the
        mutation clock makes the memo exact (a revert restores pre-fork
        versions together with pre-fork geometry). The returned structures
        are shared across calls and read-only by contract."""
        out: PartitioningState = {}
        cache = self._part_state_cache
        resource = self.codec.resource
        for name, node in self._nodes.items():
            entry = cache.get(name)
            if entry is None or entry[0] != node.version:
                boards = [
                    BoardPartitioning(
                        board_index=index,
                        resources={
                            resource(profile): qty
                            for profile, qty in geometry.items()
                        },
                    )
                    for index, geometry in sorted(
                        node.partitionable.geometry().items()
                    )
                ]
                # The NodePartitioning itself is memoized, not just its
                # boards: an untouched node projects as the SAME object in
                # consecutive calls, so current-vs-desired diffs (merge
                # invariants, actuation) can identity-skip it, and a 16k-
                # node cycle does not allocate 16k throwaway wrappers.
                entry = (node.version, NodePartitioning(boards=boards))
                cache[name] = entry
            out[name] = entry[1]
        return out


class DeepcopyClusterSnapshot(ClusterSnapshot):
    """The pre-CoW fork semantics: deepcopy the whole node map per fork and
    recompute every cluster-wide aggregate on demand.

    Kept as the oracle for the CoW property tests and as the measurable
    baseline for ``bench_planner`` — byte-for-byte the same observable
    behavior as ClusterSnapshot, at the old O(cluster) cost per trial.
    """

    def __init__(self, *args, **kwargs) -> None:
        super().__init__(*args, **kwargs)
        self._deep_stack: List[tuple] = []

    def fork(self) -> None:
        self._deep_stack.append((copy.deepcopy(self._nodes), self.state_version))
        self._sim_cache = None
        self._anti_count = None
        self._free_chips_cache = {}
        self._cand_cache = None
        self._cand_dirty.clear()
        self._cand_keys = {}
        self._part_state_cache = {}

    def commit(self) -> int:
        if not self._deep_stack:
            raise RuntimeError("snapshot not forked")
        self._deep_stack.pop()
        self._sim_cache = None
        self._anti_count = None
        self._free_chips_cache = {}
        self._cand_cache = None
        self._cand_dirty.clear()
        self._cand_keys = {}
        self._part_state_cache = {}
        return len(self._nodes)

    def revert(self) -> int:
        if not self._deep_stack:
            raise RuntimeError("snapshot not forked")
        # The deepcopied backup carries every node's pre-fork version, and
        # the checkpointed state_version comes back with it — same
        # re-validation semantics as the CoW journal.
        self._nodes, self.state_version = self._deep_stack.pop()
        self._sim_cache = None
        self._anti_count = None
        self._free_chips_cache = {}
        self._cand_cache = None
        self._cand_dirty.clear()
        self._cand_keys = {}
        self._part_state_cache = {}
        return len(self._nodes)

    @property
    def forked(self) -> bool:
        return bool(self._deep_stack)

    def _touch(self, name: str) -> None:  # deepcopy fork needs no journal
        return

    def accelerators(self) -> List[str]:
        self._accel_cache = None
        return super().accelerators()

    def free_slice_resources(self) -> ResourceList:
        return self._compute_free_pool()

    def _apply_free_delta(self, before, node) -> None:  # always recomputed
        return
