"""Cluster snapshot with fork/commit/revert — the planner's working copy.

Reference internal/partitioning/core/snapshot.go:43-190: copy-on-write over
map[nodeName]PartitionableNode; GetLackingSlices(pod) = pod request minus
cluster-wide free resources; GetCandidateNodes = nodes with free capacity
sorted by name.
"""
from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from nos_tpu.api.v1alpha1 import constants
from nos_tpu.kube.objects import Pod, ResourceList
from nos_tpu.partitioning.core.codec import SliceCodec, TpuSliceCodec
from nos_tpu.partitioning.core.partition_state import (
    BoardPartitioning,
    NodePartitioning,
    PartitioningState,
)
from nos_tpu.scheduler.framework import NodeInfo
from nos_tpu.util import resources as res


@dataclass
class SnapshotNode:
    """A partitionable node + the pods scheduled onto it."""

    partitionable: object  # PartitionableNode protocol (e.g. tpu.TpuNode)
    pods: List[Pod] = field(default_factory=list)
    # True while the node's agent has not yet acknowledged its current
    # spec plan: its geometry is mid-change, so the planner must not carve
    # it again (per-node generalization of the reference's GLOBAL
    # "all nodes reported" gate, partitioner_controller.go:118-122 —
    # global gating stalls every other node's replan behind one
    # in-flight actuation).
    frozen: bool = False

    @property
    def name(self) -> str:
        return self.partitionable.name

    def sim_node_info(self) -> NodeInfo:
        """NodeInfo whose allocatable reflects the (possibly re-carved)
        geometry — what the embedded scheduler framework filters against."""
        return NodeInfo(node=self.partitionable.to_sim_node(), pods=list(self.pods))

    def add_pod(self, pod: Pod) -> bool:
        if not self.partitionable.add_pod(pod):
            return False
        self.pods.append(pod)
        return True


class ClusterSnapshot:
    def __init__(
        self, nodes: Dict[str, SnapshotNode], codec: Optional[SliceCodec] = None
    ) -> None:
        self._nodes = nodes
        self.codec: SliceCodec = codec or TpuSliceCodec()
        self._backup: Optional[Dict[str, SnapshotNode]] = None
        self._sim_cache: Optional[List[NodeInfo]] = None
        self._anti_cache: Optional[bool] = None

    # ------------------------------------------------------ fork/commit

    def fork(self) -> None:
        if self._backup is not None:
            raise RuntimeError("snapshot already forked")
        self._backup = copy.deepcopy(self._nodes)
        self._sim_cache = None
        self._anti_cache = None

    def commit(self) -> None:
        self._backup = None
        self._sim_cache = None
        self._anti_cache = None

    def revert(self) -> None:
        if self._backup is None:
            raise RuntimeError("snapshot not forked")
        self._nodes = self._backup
        self._backup = None
        self._sim_cache = None
        self._anti_cache = None

    # --------------------------------------------------------- queries

    def get_node(self, name: str) -> Optional[SnapshotNode]:
        return self._nodes.get(name)

    def get_nodes(self) -> Dict[str, SnapshotNode]:
        return self._nodes

    def accelerators(self) -> List[str]:
        return sorted(
            {
                n.partitionable.accelerator
                for n in self._nodes.values()
                if getattr(n.partitionable, "accelerator", "")
            }
        )

    def get_candidate_nodes(self) -> List[str]:
        """Nodes whose geometry could still change or serve slices.

        Best-fit order — fewest free chips first, name for determinism —
        instead of the reference's plain name order (snapshot.go:119-130):
        small lacking slices carve out of already-fragmented nodes, so
        whole free boards survive for board-sized requests."""

        def free_chips(node) -> int:
            from nos_tpu.tpu.topology import Topology

            return sum(
                Topology(profile).chips * qty
                for profile, qty in node.partitionable.free_slices().items()
            )

        return [
            name
            for name, node in sorted(
                self._nodes.items(),
                key=lambda kv: (free_chips(kv[1]), kv[0]),
            )
            if node.partitionable.has_free_capacity() and not node.frozen
        ]

    def free_slice_resources(self) -> ResourceList:
        """Cluster-wide free slices as a ResourceList."""
        total: ResourceList = {}
        for node in self._nodes.values():
            for profile, qty in node.partitionable.free_slices().items():
                name = self.codec.resource(profile)
                total[name] = total.get(name, 0) + qty
        return total

    @staticmethod
    def is_tracked_resource(name: str) -> bool:
        """Resources the default (tpu) mode is responsible for serving.
        Instances answer per their own codec via `tracked`."""
        return constants.is_tpu_slice_resource(name) or name == constants.RESOURCE_TPU

    def tracked(self, name: str) -> bool:
        return self.codec.is_tracked(name)

    def normalize_request(
        self, request: ResourceList, accelerator: Optional[str] = None
    ) -> ResourceList:
        """Normalize a plain-chip request to a slice request.

        With `accelerator` (the per-candidate-node case) the node's own
        generation decides the profile. Without it, plain chips are kept
        plain — in a mixed-generation cluster there is no single right
        profile, and picking one deadlocks pods against nodes of the other
        generation."""
        return self.codec.normalize_request(request, accelerator or "")

    def take_from_pool(self, pool: ResourceList, request: ResourceList) -> ResourceList:
        """Serve `request`'s tracked resources from `pool` (mutating it);
        returns what remains lacking."""
        return self.codec.take_from_pool(pool, request, self.accelerators())

    def get_lacking_slices(self, pod: Pod) -> ResourceList:
        """Tracked resources the pod needs beyond cluster-wide free slices
        (snapshot.go:132-165). Only slice/chip resources count — everything
        else is the vanilla scheduler's problem. Plain-chip lack is reported
        as ``google.com/tpu`` since the serving profile depends on which
        node ends up carved."""
        request = res.compute_pod_request(pod)
        pool = self.free_slice_resources()
        return self.take_from_pool(pool, request)

    def sim_node_infos(self) -> List[NodeInfo]:
        """Every node's sim view, for predicates needing cluster-wide
        context (topology spread, inter-pod affinity). Cached until the
        next fork/commit/revert/add_pod — the planner's mutation points.
        The planner's geometry re-carve right after fork() is covered
        because fork invalidates and nothing reads between the two."""
        if self._sim_cache is None:
            self._sim_cache = [n.sim_node_info() for n in self._nodes.values()]
        return self._sim_cache

    def has_anti_affinity_pods(self) -> bool:
        """Whether any placed pod carries required anti-affinity — those
        terms are SYMMETRIC (they reject incoming pods), so the simulation
        must publish the cluster view even for term-less candidates.
        Cached with the same invalidation points as sim_node_infos — the
        planner calls this once per (pod, node) trial."""
        if self._anti_cache is None:
            self._anti_cache = any(
                p.spec.pod_anti_affinity
                for node in self._nodes.values()
                for p in node.pods
            )
        return self._anti_cache

    # -------------------------------------------------------- mutation

    def add_pod(self, node_name: str, pod: Pod) -> bool:
        node = self._nodes.get(node_name)
        if node is None:
            return False
        added = node.add_pod(pod)
        if added:
            self._sim_cache = None
            self._anti_cache = None
        return added

    # ------------------------------------------------------ projection

    def partitioning_state(self) -> PartitioningState:
        out: PartitioningState = {}
        for name, node in self._nodes.items():
            boards = [
                BoardPartitioning(
                    board_index=index,
                    resources={
                        self.codec.resource(profile): qty
                        for profile, qty in geometry.items()
                    },
                )
                for index, geometry in sorted(node.partitionable.geometry().items())
            ]
            out[name] = NodePartitioning(boards=boards)
        return out
