"""Per-mode slice resource codecs.

The engine core (snapshot/tracker/planner) is mode-agnostic in the
reference; what varies per mode is how profiles map to extended resource
names and how plain-chip requests normalize (the role SliceCalculator /
SliceFilter play in reference internal/partitioning/{mig,mps}/). A codec
bundles that mapping so ClusterSnapshot can serve both the tpu mode
(topology slices) and the sharing mode (HBM fractions).
"""
from __future__ import annotations

from typing import List, Protocol

from nos_tpu.api.v1alpha1 import constants
from nos_tpu.kube.objects import ResourceList
from nos_tpu.tpu.known import profile_for_chips
from nos_tpu.util import resources as res


class SliceCodec(Protocol):
    def is_tracked(self, name: str) -> bool: ...

    def resource(self, profile: str) -> str: ...

    def normalize_request(
        self, request: ResourceList, accelerator: str
    ) -> ResourceList: ...

    def take_from_pool(
        self, pool: ResourceList, request: ResourceList, accelerators: List[str]
    ) -> ResourceList: ...


class TpuSliceCodec:
    """Topology-slice resources (google.com/tpu-slice-<topo>); plain
    google.com/tpu chip requests normalize to each generation's smallest
    covering profile."""

    def is_tracked(self, name: str) -> bool:
        return constants.is_tpu_slice_resource(name) or name == constants.RESOURCE_TPU

    def resource(self, profile: str) -> str:
        return constants.tpu_slice_resource(profile)

    def normalize_request(self, request: ResourceList, accelerator: str) -> ResourceList:
        if accelerator:
            return res.normalize_tpu_request(request, accelerator)
        return dict(request)

    def take_from_pool(
        self, pool: ResourceList, request: ResourceList, accelerators: List[str]
    ) -> ResourceList:
        """Serve `request`'s tracked resources from `pool` (mutating it);
        returns what remains lacking. Plain-chip requests are served by any
        accelerator whose matching profile still has free slices."""
        lacking: ResourceList = {}
        for name, qty in request.items():
            if constants.is_tpu_slice_resource(name):
                take = min(qty, pool.get(name, 0))
                pool[name] = pool.get(name, 0) - take
                if qty - take > 0:
                    lacking[name] = qty - take
        plain = int(request.get(constants.RESOURCE_TPU, 0))
        if plain > 0:
            served = False
            for accelerator in accelerators:
                profile = profile_for_chips(plain, accelerator)
                if profile is None:
                    continue
                name = constants.tpu_slice_resource(profile)
                if pool.get(name, 0) >= 1:
                    pool[name] -= 1
                    served = True
                    break
            if not served:
                lacking[constants.RESOURCE_TPU] = plain
        return lacking


class SharedSliceCodec:
    """HBM-fraction resources (google.com/tpu-mem-<N>gb). Plain-chip
    requests are not the sharing mode's to serve (mirroring MPS, which
    only tracks nvidia.com/gpu-<N>gb), so they never normalize and never
    count as lacking here."""

    def is_tracked(self, name: str) -> bool:
        return constants.is_tpu_shared_resource(name)

    def resource(self, profile: str) -> str:
        return constants.tpu_shared_resource(profile)

    def normalize_request(self, request: ResourceList, accelerator: str) -> ResourceList:
        return dict(request)

    def take_from_pool(
        self, pool: ResourceList, request: ResourceList, accelerators: List[str]
    ) -> ResourceList:
        lacking: ResourceList = {}
        for name, qty in request.items():
            if constants.is_tpu_shared_resource(name):
                take = min(qty, pool.get(name, 0))
                pool[name] = pool.get(name, 0) - take
                if qty - take > 0:
                    lacking[name] = qty - take
        return lacking
