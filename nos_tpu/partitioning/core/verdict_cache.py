"""Equivalence-class filter-verdict cache for the planner's simulation.

The planner's ``_try_add_pod`` runs the scheduler framework (PreFilter +
Filter) once per (pod, candidate node) trial — thousands of times per
``plan()`` — with heavily repeated inputs: a verdict only depends on the
pod's normalized request/constraint signature and the node's current
state, so identical trials should hit a cache instead of the plugin
chain (the upstream kube-scheduler "equivalence cache" idea, scoped to
one ``plan()`` invocation where it can be made exact).

Key: ``(pod_signature, node name, node version)``.

- ``pod_signature`` hashes every pod field the cacheable predicate set
  reads: per-container normalized requests, namespace, labels,
  ``nodeName``, ``nodeSelector``, tolerations, and required node
  affinity. Two pods with identical signatures are the same trial.
- The node name pins all static node state (labels, taints,
  unschedulable) and ``SnapshotNode.version`` pins all mutable state
  (geometry, placed pods): versions come from a never-repeating clock,
  so a (name, version) pair can never alias two different states, and a
  reverted trial restores the pre-fork version — old entries become
  valid again rather than being discarded.

Bypass: verdicts that read *cross-node* context cannot be keyed by one
node's state. The planner bypasses the cache when the pod carries
topology-spread or inter-pod (anti-)affinity terms, or when any placed
pod has required anti-affinity (symmetric terms reject incoming pods).

A framework plugin participates only if it sets ``verdict_cacheable =
True`` (the in-tree predicate set does), promising its simulation
verdict is a pure function of the signed pod fields plus the candidate
node's own state, with no cross-plugin ``CycleState`` communication.
Unmarked plugins (e.g. store-backed quota/reservation filters) run fresh
on every trial, after the cached verdict for the marked ones.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

from nos_tpu.kube.objects import Pod


def pod_signature(pod: Pod) -> tuple:
    """Hashable equivalence class of every pod field the cacheable
    predicate set reads. Computed on the *simulation* pod (requests
    already normalized to the candidate node's generation), once per
    (pod, accelerator) and reused across all node trials."""
    spec = pod.spec
    meta = pod.metadata
    affinity = spec.affinity
    return (
        tuple(tuple(sorted(c.requests.items())) for c in spec.containers),
        tuple(tuple(sorted(c.requests.items())) for c in spec.init_containers),
        meta.namespace,
        tuple(sorted(meta.labels.items())),
        spec.node_name,
        tuple(sorted(spec.node_selector.items())),
        tuple((t.key, t.operator, t.value, t.effect) for t in spec.tolerations),
        None
        if affinity is None
        else tuple(
            tuple(
                (r.key, r.operator, tuple(r.values))
                for r in term.match_expressions
            )
            for term in affinity.required_terms
        ),
    )


def needs_cluster_context(pod: Pod) -> bool:
    """Whether this pod's own terms make its verdict depend on nodes other
    than the candidate — the per-pod half of the cache bypass (the
    snapshot-wide half is ``snapshot.has_anti_affinity_pods()``)."""
    spec = pod.spec
    return bool(
        spec.topology_spread_constraints
        or spec.pod_affinity
        or spec.pod_anti_affinity
    )


class VerdictCache:
    """One plan() invocation's verdict memo plus its hit/miss/bypass
    ledger. Entries never need mid-plan eviction: the version keys make
    stale entries unreachable rather than wrong. The planner rebuilds the
    cache per plan() on the full path; incremental plans instead prune
    entries whose version key no longer matches a live node and call
    ``reset_stats`` so the ledger stays per-plan."""

    __slots__ = ("entries", "hits", "misses", "bypasses")

    def __init__(self) -> None:
        self.entries: Dict[Tuple[tuple, str, int], bool] = {}
        self.hits = 0
        self.misses = 0
        self.bypasses = 0

    def get(self, key: Tuple[tuple, str, int]) -> Optional[bool]:
        """Cached verdict, counting the lookup as hit or miss. A miss must
        be followed by ``put(key, verdict)``."""
        verdict = self.entries.get(key)
        if verdict is None:
            self.misses += 1
        else:
            self.hits += 1
        return verdict

    def put(self, key: Tuple[tuple, str, int], verdict: bool) -> None:
        self.entries[key] = verdict

    def reset_stats(self) -> None:
        """Zero the ledger while keeping entries — incremental plan mode
        carries still-valid entries across plan() calls, but hit/miss
        accounting stays per-plan."""
        self.hits = 0
        self.misses = 0
        self.bypasses = 0

    def stats(self) -> Tuple[int, int, int]:
        return (self.hits, self.misses, self.bypasses)

    @property
    def lookups(self) -> int:
        return self.hits + self.misses + self.bypasses

    def hit_rate(self) -> float:
        """Hits over cache-eligible lookups (bypasses excluded)."""
        eligible = self.hits + self.misses
        return self.hits / eligible if eligible else 0.0
