"""Multi-process pool planning: one long-lived worker process per pool.

PR 13's pool-sharded planner decomposed the cluster into independent
pools, but CPython's GIL makes thread-parallel pool planning a wash on
wall-clock (bench_planner's serial-vs-thread rows say so). This module
escapes the GIL the only way CPython allows: each pool's planner runs in
its own PROCESS, holding that pool's warm incremental state — base
snapshot, version-keyed verdict/futility memos, candidate order —
resident across cycles, so the per-cycle boundary cost is the dirty-node
delta, never the world.

Protocol (snapcodec frames — header-versioned JSON over a pipe; live
snapshot objects are never pickled):

- ``bootstrap``: the pool's full wire image (one serde node + bound-pod
  projection per SnapshotNode, quota objects, planner knobs, framework
  spec) sent at spawn and after every pool rebuild. The worker rebuilds
  its replica store and base snapshot through the taker's
  ``take_snapshot_node`` — the exact constructor the parent used — and
  optionally warm-adopts persisted memos from the shared warm-state file.
- ``cycle``: rv-ordered dirty-node deltas (refreshed node + its bound
  pods), the pool's pending pods, parent-ledger fairness ages, and
  out-of-pool quota usage. The worker refreshes its base, replans, and
  replies with the TOUCHED nodes' board assignments plus the unserved
  ledger — the parent reconstructs the pool's desired PartitioningState
  from its own pre-plan state for untouched nodes, preserving the
  object-identity fast path ``check_merge_invariants`` relies on.
- ``export``: the planner's warm-state memo entries, for the parent's
  rate-limited save (signatures are taken parent-side from the pool
  bases the parent already owns).

Pool membership is static between rebuilds (PoolShardedMaintainer
rebuilds on ANY node_pool change, and node add/delete forces an inner
rebuild), so cycle frames never need add/remove — a shape change always
arrives as a fresh bootstrap.

Robustness: a timeout, EOF, or frame error marks the worker dead; the
parent escalates that pool to in-process serial planning for the cycle
and respawns the worker from a fresh wire image next cycle. The auditor's
shadow replans always run in-parent against the parent's own pool bases,
so a corrupted worker cannot self-certify its plans.
"""
from __future__ import annotations

import logging
import multiprocessing
import os
import time
from typing import Dict, List, Optional, Set

from nos_tpu.partitioning.core.snapcodec import (
    SNAPSHOT_CODEC_VERSION,
    FrameError,
    decode_frame,
    encode_frame,
)

log = logging.getLogger("nos_tpu.partitioner")

# ------------------------------------------------------------ wire docs


def snapshot_node_to_wire(snap_node) -> dict:
    """One SnapshotNode's wire projection: the raw kube Node plus its
    bound pods, via the sim apiserver's serde codec. Everything the
    taker's ``take_snapshot_node`` derives (usage, frozen flag, board
    geometry) is recomputed receiver-side from these inputs, so the two
    sides can never disagree about derivation."""
    from nos_tpu.kube.serde import node_to_wire, pod_to_wire

    return {
        "node": node_to_wire(snap_node.partitionable.node),
        "pods": [pod_to_wire(pod) for pod in snap_node.pods],
    }


def snapshot_node_from_wire(entry: dict, taker):
    from nos_tpu.kube.serde import node_from_wire, pod_from_wire

    node = node_from_wire(entry["node"])
    pods = [pod_from_wire(d) for d in entry["pods"]]
    return node, pods, taker.take_snapshot_node(node, pods)


def quotas_to_wire(quotas, composite_quotas) -> List[dict]:
    from nos_tpu.kube.serde import ceq_to_wire, eq_to_wire

    return [
        {"kind": "ElasticQuota", "doc": eq_to_wire(q)} for q in quotas
    ] + [
        {"kind": "CompositeElasticQuota", "doc": ceq_to_wire(q)}
        for q in composite_quotas
    ]


def quotas_from_wire(entries: List[dict]):
    from nos_tpu.kube.serde import ceq_from_wire, eq_from_wire

    out = []
    for entry in entries:
        if entry["kind"] == "ElasticQuota":
            out.append(eq_from_wire(entry["doc"]))
        else:
            out.append(ceq_from_wire(entry["doc"]))
    return out


# ------------------------------------------------------- framework spec
#
# The worker cannot receive a live Framework (its plugins may hold the
# parent's store), so the parent derives a SPEC — ordered plugin class
# names per chain — and the worker rebuilds the same plugin set against
# its own replica store. Only plugins in this registry are
# distributable; an unknown plugin makes framework_spec() return None
# and the controller falls back to thread/serial planning rather than
# silently planning with a different policy.

_PURE_PLUGINS = (
    "NodeResourcesFit",
    "NodeSelectorFit",
    "NodeAffinityFit",
    "TaintTolerationFit",
    "NodeUnschedulableFit",
    "PodTopologySpreadFit",
    "InterPodAffinityFit",
)
_STORE_PLUGINS = ("CapacityScheduling", "MultihostIciFilter", "BoardReservation")


def framework_spec(framework) -> Optional[dict]:
    """The distributable projection of a Framework, or None when any
    plugin (or a non-empty chain the planner would run) falls outside
    the registry."""
    if (
        framework.post_filter_plugins
        or framework.reserve_plugins
        or framework.permit_plugins
    ):
        return None
    spec: dict = {"pre_filter": [], "filter": []}
    for chain, plugins in (
        ("pre_filter", framework.pre_filter_plugins),
        ("filter", framework.filter_plugins),
    ):
        for plugin in plugins:
            name = type(plugin).__name__
            if name not in _PURE_PLUGINS and name not in _STORE_PLUGINS:
                return None
            spec[chain].append(name)
            if name == "CapacityScheduling":
                spec["chip_memory_gb"] = plugin.chip_memory_gb
    return spec


def build_framework_from_spec(spec: dict, store):
    from nos_tpu.scheduler import framework as fw
    from nos_tpu.scheduler.plugins.capacity import CapacityScheduling
    from nos_tpu.scheduler.plugins.reservation import BoardReservation
    from nos_tpu.scheduler.plugins.topology import MultihostIciFilter

    def build(name: str):
        if name == "CapacityScheduling":
            return CapacityScheduling(store, spec.get("chip_memory_gb"))
        if name == "MultihostIciFilter":
            return MultihostIciFilter(store)
        if name == "BoardReservation":
            return BoardReservation(store)
        return getattr(fw, name)()

    return fw.Framework(
        pre_filter_plugins=[build(n) for n in spec["pre_filter"]],
        filter_plugins=[build(n) for n in spec["filter"]],
    )


def planner_knobs(planner) -> dict:
    return {
        "aging_chips_per_second": planner.aging_chips_per_second,
        "verdict_cache_enabled": planner.verdict_cache_enabled,
        "reuse_gang_trial": planner.reuse_gang_trial,
        "futility_memo_enabled": planner.futility_memo_enabled,
        "incremental_dirty_threshold": planner.incremental_dirty_threshold,
    }


def _slice_codec(name: str):
    from nos_tpu.partitioning.core.codec import SharedSliceCodec, TpuSliceCodec

    return {"TpuSliceCodec": TpuSliceCodec, "SharedSliceCodec": SharedSliceCodec}[
        name
    ]()


def _taker(kind: str):
    if kind == "sharing":
        from nos_tpu.partitioning.sharing.snapshot_taker import (
            SharingSnapshotTaker,
        )

        return SharingSnapshotTaker()
    from nos_tpu.partitioning.tpu.snapshot_taker import TpuSnapshotTaker

    return TpuSnapshotTaker()


# --------------------------------------------------- pending-age ledger


class PendingSeenLedger:
    """Parent-side analogue of the planner's ``_pending_seen`` fairness
    ledger. With workers in separate processes, each worker's internal
    first-seen clock would drift from its siblings' (and reset on
    respawn, zeroing a starved pod's age) — so the PARENT owns one
    ledger and ships explicit ages every cycle, exactly the
    ``pending_ages`` override ``plan()`` already honors for replay."""

    TTL_S = 600.0

    def __init__(self) -> None:
        self._seen: Dict[str, tuple] = {}

    def ages(self, pods, now: Optional[float] = None) -> Dict[str, float]:
        now = time.monotonic() if now is None else now
        ages: Dict[str, float] = {}
        for pod in pods:
            key = pod.namespaced_name
            first, _ = self._seen.get(key, (now, now))
            self._seen[key] = (first, now)
            ages[key] = now - first
        stale = [
            key
            for key, (_, last) in self._seen.items()
            if now - last > self.TTL_S
        ]
        for key in stale:
            del self._seen[key]
        return ages


# ------------------------------------------------------- worker process


def pool_worker_main(conn, pool: str, kind: str) -> None:
    """Worker entry point (spawn target, importable at module level).
    Owns one pool's replica store, base snapshot, and planner; serves
    bootstrap/cycle/export/ping frames until ``stop`` or EOF. Any
    unexpected exception is reported as an ``error`` reply — the parent
    treats it like a crash (escalate + respawn), never as a plan."""
    state = _WorkerState(pool, kind)
    while True:
        try:
            request = decode_frame(conn.recv_bytes())
        except (EOFError, OSError):
            return
        except FrameError as exc:
            # A frame we cannot trust means we can no longer prove our
            # state matches the parent's: exit and let the parent
            # respawn us from a fresh wire image.
            try:
                conn.send_bytes(
                    encode_frame({"op": "error", "detail": str(exc)})
                )
            except (OSError, ValueError):
                pass
            return
        op = request.get("op")
        if op == "stop":
            return
        try:
            reply = state.dispatch(request)
        except Exception as exc:  # noqa: BLE001 — crash reporting seam
            reply = {
                "op": "error",
                "seq": request.get("seq"),
                "detail": f"{type(exc).__name__}: {exc}",
            }
        try:
            conn.send_bytes(encode_frame(reply))
        except (OSError, ValueError):
            return


class _WorkerState:
    """Everything a worker process owns for its pool."""

    def __init__(self, pool: str, kind: str) -> None:
        self.pool = pool
        self.kind = kind
        self.store = None
        self.base = None
        self.planner = None
        self.taker = _taker(kind)
        self.capacity_plugin = None
        self.bootstrap_dirty: Set[str] = set()
        # node name -> keys of its bound pods in the replica store, so a
        # refresh can retract pods that left the node.
        self._node_pods: Dict[str, Set[str]] = {}
        self._pending_keys: Set[str] = set()
        # Signature memoizer for export (WarmStateCodec caches per node
        # version); the path may be empty — this instance never saves.
        self._sig_codec = None

    def dispatch(self, request: dict) -> dict:
        op = request["op"]
        if op == "bootstrap":
            return self.bootstrap(request)
        if op == "cycle":
            return self.cycle(request)
        if op == "export":
            return self.export()
        if op == "ping":
            return {"op": "pong", "seq": request.get("seq")}
        return {"op": "error", "detail": f"unknown op {op!r}"}

    # -------------------------------------------------------- bootstrap

    def bootstrap(self, request: dict) -> dict:
        from nos_tpu.kube.store import KubeStore
        from nos_tpu.partitioning.core.planner import Planner
        from nos_tpu.partitioning.core.snapshot import ClusterSnapshot

        from nos_tpu.tpu.known import set_known_geometries

        if request.get("codec_version") != SNAPSHOT_CODEC_VERSION:
            # The parent speaks a different snapshot vocabulary than this
            # worker's tree. Refuse — adopting would be silent corruption
            # — and let the parent cold-boot a fresh worker.
            return {
                "op": "reject",
                "seq": request.get("seq"),
                "detail": (
                    f"codec version {request.get('codec_version')!r} != "
                    f"{SNAPSHOT_CODEC_VERSION}"
                ),
            }
        # Module-global geometry overrides do not survive the spawn:
        # replay the parent's so board derivation is bit-identical.
        set_known_geometries(request.get("geometry_overrides") or None)
        self.store = KubeStore()
        self._node_pods = {}
        self._pending_keys = set()
        for quota in quotas_from_wire(request.get("quotas", [])):
            self.store.apply_event("ADDED", quota)
        nodes = {}
        for entry in request["nodes"]:
            node, pods, snap_node = snapshot_node_from_wire(entry, self.taker)
            if snap_node is None:
                return {
                    "op": "error",
                    "seq": request.get("seq"),
                    "detail": f"node {node.metadata.name} out of taker scope",
                }
            self._apply_node(node, pods)
            nodes[node.metadata.name] = snap_node
        self.base = ClusterSnapshot(
            nodes, codec=_slice_codec(request["slice_codec"])
        )
        framework = build_framework_from_spec(request["framework"], self.store)
        self.capacity_plugin = next(
            (
                plugin
                for plugin in framework.pre_filter_plugins
                if type(plugin).__name__ == "CapacityScheduling"
            ),
            None,
        )
        self.planner = Planner(framework, **request["knobs"])
        self.bootstrap_dirty = set(nodes)
        from nos_tpu.partitioning.core.snapcodec import WarmStateCodec

        warm_path = request.get("warm_state_path") or ""
        self._sig_codec = WarmStateCodec(warm_path)
        adopted = 0
        if warm_path:
            report = self._sig_codec.adopt(self.base, self.planner)
            self.bootstrap_dirty = set(report.unmatched)
            adopted = report.adopted_entries
        return {
            "op": "ready",
            "seq": request.get("seq"),
            "pool": self.pool,
            "nodes": len(nodes),
            "adopted_entries": adopted,
            "pid": os.getpid(),
        }

    def _apply_node(self, node, pods) -> None:
        """Upsert one node and its bound-pod set into the replica store,
        retracting pods that were bound here last time but are gone."""
        self.store.apply_event("MODIFIED", node)
        keys = set()
        for pod in pods:
            self.store.apply_event("MODIFIED", pod)
            keys.add(pod.namespaced_name)
        for stale in self._node_pods.get(node.metadata.name, set()) - keys:
            namespace, _, name = stale.partition("/")
            try:
                self.store.delete("Pod", name, namespace)
            except KeyError:
                pass
        self._node_pods[node.metadata.name] = keys

    # ------------------------------------------------------------ cycle

    def cycle(self, request: dict) -> dict:
        from nos_tpu.kube.serde import pod_from_wire

        if self.base is None:
            return {
                "op": "error",
                "seq": request.get("seq"),
                "detail": "cycle before bootstrap",
            }
        dirty: Set[str] = set(self.bootstrap_dirty)
        self.bootstrap_dirty = set()
        for entry in request.get("deltas", []):
            node, pods, snap_node = snapshot_node_from_wire(entry, self.taker)
            if snap_node is None:
                return {
                    "op": "error",
                    "seq": request.get("seq"),
                    "detail": f"delta {node.metadata.name} out of taker scope",
                }
            self._apply_node(node, pods)
            self.base.refresh_node(node.metadata.name, snap_node)
            dirty.add(node.metadata.name)
        pending = [pod_from_wire(d) for d in request.get("pending", [])]
        pending_keys = set()
        for pod in pending:
            self.store.apply_event("MODIFIED", pod)
            pending_keys.add(pod.namespaced_name)
        for stale in self._pending_keys - pending_keys:
            namespace, _, name = stale.partition("/")
            try:
                self.store.delete("Pod", name, namespace)
            except KeyError:
                pass
        self._pending_keys = pending_keys
        if self.capacity_plugin is not None:
            self.capacity_plugin.set_external_usage(
                request.get("external_usage", {})
            )
        current = self.base.partitioning_state()
        t0 = time.perf_counter()
        desired = self.planner.plan(
            self.base,
            pending,
            dirty=dirty,
            pending_ages=dict(request.get("ages", {})),
        )
        duration = time.perf_counter() - t0
        # Only nodes the plan actually changed cross the boundary back:
        # partitioning_state() memoizes per node version, so an untouched
        # node's desired entry IS (identity) its pre-plan entry.
        touched = {
            name: {
                str(b.board_index): dict(b.resources) for b in np.boards
            }
            for name, np in desired.items()
            if np is not current.get(name)
        }
        return {
            "op": "plan",
            "seq": request.get("seq"),
            "pool": self.pool,
            "touched": touched,
            "unserved": dict(self.planner.last_unserved),
            "pending_ages": dict(self.planner.last_pending_ages),
            "plan_mode": self.planner.last_plan_mode,
            "duration": duration,
        }

    # ----------------------------------------------------------- export

    def export(self) -> dict:
        if self.planner is None or self.base is None:
            return {"op": "entries", "pool": self.pool, "entries": {}, "signatures": {}}
        entries = self.planner.export_warm_state(self.base)
        # Sign with THIS base's node states — the memos were derived from
        # its committed geometry, which only exists in this process.
        signatures = {
            name: self._sig_codec._signature(name, snap_node)
            for name, snap_node in self.base.get_nodes().items()
        }
        return {
            "op": "entries",
            "pool": self.pool,
            "entries": entries,
            "signatures": signatures,
        }


# -------------------------------------------------------- parent façade


class WorkerUnavailable(RuntimeError):
    """A worker that cannot serve this cycle: dead, wedged past the
    timeout, or speaking an untrusted frame. Carries the reason the
    escalation path records."""

    def __init__(self, pool: str, reason: str) -> None:
        super().__init__(f"pool {pool}: {reason}")
        self.pool = pool
        self.reason = reason


class _Worker:
    """One spawned worker process + its parent-side pipe end."""

    def __init__(self, ctx, pool: str, kind: str) -> None:
        self.pool = pool
        parent_conn, child_conn = ctx.Pipe(duplex=True)
        self.conn = parent_conn
        self.process = ctx.Process(
            target=pool_worker_main,
            args=(child_conn, pool, kind),
            name=f"poolworker-{pool}",
            daemon=True,
        )
        self.process.start()
        child_conn.close()
        self.bootstrapped = False
        self.replies = 0

    def send(self, doc: dict) -> None:
        self.conn.send_bytes(encode_frame(doc))

    def recv(self, timeout: float) -> dict:
        if not self.conn.poll(timeout):
            raise TimeoutError(f"no reply within {timeout:.1f}s")
        return decode_frame(self.conn.recv_bytes())

    def kill(self) -> None:
        try:
            self.conn.close()
        except OSError:
            pass
        if self.process.is_alive():
            self.process.terminate()
        self.process.join(timeout=2.0)


class PoolWorkerPool:
    """The parent-side façade the controller (and bench) drives: spawn
    once per pool, bootstrap on rebuild, one ``plan_cycle`` per plan
    cycle with all sends up front and a shared deadline on the collect
    side, escalation surfaced as ``WorkerUnavailable`` per pool rather
    than a failed cycle."""

    def __init__(
        self,
        kind: str,
        slice_codec_name: str,
        spec: dict,
        knobs: dict,
        cycle_timeout_seconds: float = 5.0,
        bootstrap_timeout_seconds: float = 60.0,
        warm_state_path: str = "",
    ) -> None:
        self.kind = kind
        self.slice_codec_name = slice_codec_name
        self.spec = spec
        self.knobs = knobs
        self.cycle_timeout = cycle_timeout_seconds
        self.bootstrap_timeout = bootstrap_timeout_seconds
        self.warm_state_path = warm_state_path
        self._ctx = multiprocessing.get_context("spawn")
        self._workers: Dict[str, _Worker] = {}
        self._seq = 0
        self.restarts = 0

    # ---------------------------------------------------------- helpers

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def _watchdog_register(self, pool: str) -> None:
        from nos_tpu.timeline.watchdog import WATCHDOG

        worker = self._workers.get(pool)
        WATCHDOG.register(
            f"poolworker.{pool}",
            periodic=False,
            counter_fn=(lambda w=worker: w.replies) if worker else None,
        )

    def _drop(self, pool: str, reason: str) -> None:
        from nos_tpu.timeline.watchdog import WATCHDOG
        from nos_tpu.util import metrics

        worker = self._workers.pop(pool, None)
        if worker is not None:
            worker.kill()
        WATCHDOG.unregister(f"poolworker.{pool}")
        metrics.PLAN_WORKER_RESTARTS.inc()
        self.restarts += 1
        log.warning(
            "procpool[%s]: dropping worker for pool %s: %s",
            self.kind,
            pool,
            reason,
        )

    # -------------------------------------------------------- lifecycle

    def pools(self) -> Set[str]:
        return set(self._workers)

    def sync_pools(self, pools) -> None:
        """Spawn workers for new pools, retire workers whose pool no
        longer exists. New workers are un-bootstrapped until the next
        ``bootstrap`` call covers them."""
        wanted = set(pools)
        for pool in sorted(set(self._workers) - wanted):
            self._drop(pool, "pool no longer exists")
        for pool in sorted(wanted - set(self._workers)):
            self._workers[pool] = _Worker(self._ctx, pool, self.kind)
            self._watchdog_register(pool)

    def needs_bootstrap(self, pool: str) -> bool:
        worker = self._workers.get(pool)
        return worker is None or not worker.bootstrapped

    def bootstrap(self, pool: str, entries: List[dict], quotas: List[dict]) -> None:
        """Ship one pool's full wire image; raises WorkerUnavailable on
        rejection or timeout (caller escalates and retries next cycle)."""
        if pool not in self._workers:
            self._workers[pool] = _Worker(self._ctx, pool, self.kind)
            self._watchdog_register(pool)
        from nos_tpu.tpu.known import known_geometry_overrides

        worker = self._workers[pool]
        seq = self._next_seq()
        doc = {
            "op": "bootstrap",
            "seq": seq,
            "codec_version": SNAPSHOT_CODEC_VERSION,
            "geometry_overrides": known_geometry_overrides(),
            "pool": pool,
            "slice_codec": self.slice_codec_name,
            "framework": self.spec,
            "knobs": self.knobs,
            "nodes": entries,
            "quotas": quotas,
            "warm_state_path": self.warm_state_path,
        }
        try:
            worker.send(doc)
            reply = worker.recv(self.bootstrap_timeout)
        except (OSError, EOFError, TimeoutError, FrameError, ValueError) as exc:
            self._drop(pool, f"bootstrap failed: {exc}")
            raise WorkerUnavailable(pool, f"bootstrap failed: {exc}") from exc
        if reply.get("op") != "ready" or reply.get("seq") != seq:
            # A reject (codec-version mismatch) or error: this worker can
            # never serve — cold-boot a fresh one next cycle.
            detail = reply.get("detail", f"unexpected reply {reply.get('op')!r}")
            self._drop(pool, f"bootstrap rejected: {detail}")
            raise WorkerUnavailable(pool, f"bootstrap rejected: {detail}")
        worker.bootstrapped = True
        worker.replies += 1

    # ------------------------------------------------------------ cycle

    def plan_cycle(self, requests: Dict[str, dict]) -> Dict[str, object]:
        """One plan cycle across pools: send every request first (the
        workers plan concurrently — this is the whole point), then
        collect under one shared deadline. Returns ``{pool: reply}``
        where a reply is either the worker's plan document or a
        WorkerUnavailable instance for pools the caller must escalate."""
        from nos_tpu.timeline.watchdog import WATCHDOG
        from nos_tpu.util import metrics

        results: Dict[str, object] = {}
        sent: Dict[str, tuple] = {}
        for pool in sorted(requests):
            worker = self._workers.get(pool)
            if worker is None or not worker.bootstrapped:
                results[pool] = WorkerUnavailable(pool, "not bootstrapped")
                continue
            doc = dict(requests[pool])
            doc["op"] = "cycle"
            doc["seq"] = self._next_seq()
            try:
                worker.send(doc)
            except (OSError, ValueError) as exc:
                self._drop(pool, f"send failed: {exc}")
                results[pool] = WorkerUnavailable(pool, f"send failed: {exc}")
                continue
            sent[pool] = (worker, doc["seq"], time.perf_counter())
        deadline = time.perf_counter() + self.cycle_timeout
        for pool, (worker, seq, t0) in sent.items():
            remaining = deadline - time.perf_counter()
            try:
                reply = worker.recv(max(0.0, remaining))
            except (OSError, EOFError, TimeoutError, FrameError) as exc:
                reason = f"{type(exc).__name__}: {exc}"
                self._drop(pool, reason)
                results[pool] = WorkerUnavailable(pool, reason)
                continue
            if reply.get("op") != "plan" or reply.get("seq") != seq:
                reason = reply.get(
                    "detail", f"unexpected reply {reply.get('op')!r}"
                )
                self._drop(pool, reason)
                results[pool] = WorkerUnavailable(pool, reason)
                continue
            rtt = time.perf_counter() - t0
            metrics.PLAN_WORKER_RTT.observe(rtt)
            worker.replies += 1
            WATCHDOG.beat(f"poolworker.{pool}")
            results[pool] = reply
        return results

    # ----------------------------------------------------------- export

    def export_warm(self, pool: str) -> Optional[tuple]:
        """The worker's warm-state ``(memo entries, node signatures)``,
        or None when the worker cannot serve (the caller just skips that
        pool's entries)."""
        worker = self._workers.get(pool)
        if worker is None or not worker.bootstrapped:
            return None
        seq = self._next_seq()
        try:
            worker.send({"op": "export", "seq": seq})
            reply = worker.recv(self.cycle_timeout)
        except (OSError, EOFError, TimeoutError, FrameError, ValueError) as exc:
            self._drop(pool, f"export failed: {exc}")
            return None
        if reply.get("op") != "entries":
            self._drop(pool, "export returned no entries")
            return None
        worker.replies += 1
        return reply.get("entries", {}), reply.get("signatures", {})

    # ------------------------------------------------------------ chaos

    def chaos_kill_one(self) -> Optional[str]:
        """Terminate one live worker process WITHOUT cleaning up parent
        state — the chaos driver's worker-kill fault. The parent
        discovers the death through the normal cycle path (timeout/EOF)
        and must escalate + respawn; returns the pool killed."""
        for pool in sorted(self._workers):
            worker = self._workers[pool]
            if worker.process.is_alive():
                worker.process.terminate()
                return pool
        return None

    def close(self) -> None:
        from nos_tpu.timeline.watchdog import WATCHDOG

        for pool, worker in sorted(self._workers.items()):
            try:
                worker.send({"op": "stop"})
            except (OSError, ValueError):
                pass
            worker.kill()
            WATCHDOG.unregister(f"poolworker.{pool}")
        self._workers = {}
