"""Versioned warm-state serialization for the planner's snapshot base.

The 4096-node cold plan costs ~358ms and most of it re-derives state the
previous process already proved: carve-futility entries (fork + carve
trials over thousands of geometry-no-op nodes) and scheduler verdicts.
This codec persists those memos next to a content signature of each
node's observed state, so a process restart — or a full-rebuild fallback
that reconstructs the base from the store — warm-starts instead of
replaying the world.

Safety model: **node state is never loaded from disk**. The store is the
only source of node truth; what is persisted per node is (a) a SHA-256
signature over every planner-relevant input (labels, taints, capacity,
geometry, placed-pod requests, frozen flag, accelerator) and (b) memo
entries derived from that exact state. At adoption the signature is
recomputed from the freshly store-built snapshot; only bit-identical
nodes have their entries re-keyed at the fresh mutation versions —
"never silently stale" holds by construction, per node. Unmatched nodes
are reported so the first plan treats them as dirty and the incremental
auditor's shadow oracle then proves the warm plan equals a cold plan
end-to-end.

Versioning: ``SNAPSHOT_CODEC_VERSION`` plus the slice-codec class name
gate the whole file — a mismatch (or any parse/shape error) makes
``load`` return ``None`` and the caller takes the ordinary cold path.
A version bump is therefore always a clean rebuild, never a crash.

The file is pool-agnostic: entries are keyed by node name, and the
sharded controller routes each adopted node to whichever pool owns it
this cycle.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import struct
import tempfile
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Set

from nos_tpu.partitioning.core.snapshot import ClusterSnapshot, SnapshotNode
from nos_tpu.util import resources as res

log = logging.getLogger("nos_tpu.partitioner")

SNAPSHOT_CODEC_VERSION = 1

# ---------------------------------------------------------- wire framing
#
# The multi-process pool backend (procpool.py) ships snapshot state and
# plan cycles between the parent and its worker processes as FRAMES over
# a pipe: a fixed header (magic + codec version + payload length) in
# front of one canonical JSON document. Live snapshot objects are never
# pickled across the boundary — the payloads are the same wire
# projections the sim apiserver's HTTP codec uses (kube/serde.py) plus
# this module's save_entries() document shape, so "what crosses the
# process boundary" and "what persists to disk" share one versioned
# vocabulary. A header mismatch is a protocol error the receiver can
# detect BEFORE parsing (a worker built from an older tree rejects the
# frame instead of mis-adopting state), and a short read surfaces as
# FrameError so the parent's reaction is a clean respawn, never a
# half-applied delta.

FRAME_MAGIC = b"NOSW"
_FRAME_HEADER = struct.Struct(">4sII")  # magic, codec version, payload len


class FrameError(ValueError):
    """A wire frame that cannot be trusted: bad magic, codec-version
    mismatch, truncated payload, or unparseable JSON. Receivers treat
    any FrameError as grounds to drop the peer (the parent respawns the
    worker from a fresh wire image; a worker exits and lets the parent's
    timeout path take over)."""


def encode_frame(doc: dict) -> bytes:
    """One framed message: header + canonical JSON payload."""
    payload = json.dumps(doc, separators=(",", ":"), sort_keys=True).encode(
        "utf-8"
    )
    return _FRAME_HEADER.pack(FRAME_MAGIC, SNAPSHOT_CODEC_VERSION, len(payload)) + payload


def decode_frame(data: bytes) -> dict:
    """Parse one framed message, validating header before payload."""
    if len(data) < _FRAME_HEADER.size:
        raise FrameError(f"short frame: {len(data)} bytes")
    magic, version, length = _FRAME_HEADER.unpack_from(data)
    if magic != FRAME_MAGIC:
        raise FrameError(f"bad frame magic {magic!r}")
    if version != SNAPSHOT_CODEC_VERSION:
        raise FrameError(
            f"frame codec version {version} != {SNAPSHOT_CODEC_VERSION}"
        )
    payload = data[_FRAME_HEADER.size:]
    if len(payload) != length:
        raise FrameError(
            f"truncated frame: header says {length} bytes, got {len(payload)}"
        )
    try:
        doc = json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as exc:
        raise FrameError(f"unparseable frame payload: {exc}") from exc
    if not isinstance(doc, dict):
        raise FrameError(f"frame payload is {type(doc).__name__}, not object")
    return doc


def _canon_quantities(mapping) -> list:
    """Sorted (key, value) pairs with numerically-equal values rendered
    identically: the serde wire codec parses every quantity to float, so
    a node observed in-parent (``memory: 128``) and the same node
    rebuilt from a wire frame (``memory: 128.0``) must not hash apart —
    that mismatch would silently cold-boot every process-backend worker
    whose warm file the serial path saved."""
    out = []
    for key, value in sorted(mapping.items()):
        if isinstance(value, float) and value.is_integer():
            value = int(value)
        out.append((key, value))
    return out


def node_state_signature(snap_node: SnapshotNode) -> str:
    """Canonical SHA-256 over every node-side input the persisted memos
    were derived from. Two nodes with equal signatures are planner-
    indistinguishable: same labels/taints/schedulability (static verdict
    inputs), same capacity and board geometry (carve + fit inputs), same
    placed-pod requests (allocatable consumption), same frozen flag and
    accelerator generation (candidate eligibility and normalization)."""
    part = snap_node.partitionable
    node = getattr(part, "node", None)
    doc = {
        "accelerator": getattr(part, "accelerator", ""),
        "frozen": snap_node.frozen,
        "labels": sorted(node.metadata.labels.items()) if node is not None else [],
        "taints": sorted(
            (t.key, t.value, t.effect) for t in node.spec.taints
        )
        if node is not None
        else [],
        "unschedulable": bool(node.spec.unschedulable) if node is not None else False,
        "capacity": _canon_quantities(node.status.capacity) if node is not None else [],
        "allocatable": _canon_quantities(node.status.allocatable)
        if node is not None
        else [],
        "geometry": [
            [index, _canon_quantities(geometry)]
            for index, geometry in sorted(part.geometry().items())
        ],
        "pods": sorted(
            [
                pod.metadata.namespace,
                pod.metadata.name,
                str(pod.metadata.uid),
                _canon_quantities(res.compute_pod_request(pod)),
            ]
            for pod in snap_node.pods
        ),
    }
    payload = json.dumps(doc, sort_keys=True, default=str).encode("utf-8")
    return hashlib.sha256(payload).hexdigest()


@dataclass
class AdoptReport:
    """What a warm-boot adoption actually covered — published to the
    warm-boot outcome metric and asserted by the restart smoke test."""

    matched: int = 0
    unmatched: Set[str] = field(default_factory=set)
    adopted_entries: int = 0


class WarmStateCodec:
    """Save/load/adopt for one partitioning mode's warm state. Signatures
    are memoized per (node name, mutation version) so steady-state saves
    only re-hash nodes that actually changed."""

    def __init__(self, path: str, save_interval_seconds: float = 30.0) -> None:
        self.path = path
        self.save_interval_seconds = save_interval_seconds
        self._last_save = 0.0
        self._sig_cache: Dict[str, tuple] = {}

    # ------------------------------------------------------- signatures

    def _signature(self, name: str, snap_node: SnapshotNode) -> str:
        cached = self._sig_cache.get(name)
        if cached is not None and cached[0] == snap_node.version:
            return cached[1]
        signature = node_state_signature(snap_node)
        self._sig_cache[name] = (snap_node.version, signature)
        return signature

    # ------------------------------------------------------------- save

    def due(self, now: Optional[float] = None) -> bool:
        """Whether the rate limit would admit a save right now — callers
        that must pay an export cost BEFORE saving (the sharded path
        exports per pool) check this first."""
        now = time.time() if now is None else now
        return now - self._last_save >= self.save_interval_seconds

    def save(
        self,
        snapshot: ClusterSnapshot,
        planner,
        now: Optional[float] = None,
        force: bool = False,
    ) -> bool:
        """Persist the planner's exportable memos keyed by node-state
        signature. Rate-limited (steady-state cycles are ~100ms; hashing
        and serializing 16k nodes per cycle would dominate them) and
        atomic (tmp + rename) so a crash mid-write leaves the previous
        file intact."""
        now = time.time() if now is None else now
        if not force and now - self._last_save < self.save_interval_seconds:
            return False
        entries = planner.export_warm_state(snapshot)
        return self.save_entries(snapshot, entries, now=now, force=True)

    def save_entries(
        self,
        snapshot: ClusterSnapshot,
        entries: Dict[str, dict],
        now: Optional[float] = None,
        force: bool = False,
        nodes: Optional[Dict[str, SnapshotNode]] = None,
        signatures: Optional[Dict[str, str]] = None,
    ) -> bool:
        """Persist pre-exported memo entries against node signatures.
        ``nodes`` overrides the signing set: the sharded controller signs
        with the POOL bases' nodes (the exact states its memos were
        derived from — the pool bases carry planned-but-not-yet-observed
        geometry the global base lacks), merged across pools (node keys
        are disjoint). ``signatures`` overrides signing entirely with
        precomputed per-node hashes: the process backend's workers hash
        their OWN base nodes (the states their memos came from live in
        another address space) and ship name→signature with the export."""
        now = time.time() if now is None else now
        if not force and now - self._last_save < self.save_interval_seconds:
            return False
        nodes_doc: Dict[str, dict] = {}
        if signatures is not None:
            for name, signature in signatures.items():
                memos = entries.get(name, {})
                nodes_doc[name] = {
                    "signature": signature,
                    "futility": memos.get("futility", []),
                    "verdicts": memos.get("verdicts", []),
                }
        else:
            if nodes is None:
                nodes = snapshot.get_nodes()
            for name, snap_node in nodes.items():
                memos = entries.get(name, {})
                nodes_doc[name] = {
                    "signature": self._signature(name, snap_node),
                    "futility": memos.get("futility", []),
                    "verdicts": memos.get("verdicts", []),
                }
        doc = {
            "codec_version": SNAPSHOT_CODEC_VERSION,
            "slice_codec": type(snapshot.codec).__name__,
            "saved_at": now,
            "nodes": nodes_doc,
        }
        directory = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(directory, exist_ok=True)
        fd, tmp_path = tempfile.mkstemp(dir=directory, prefix=".warm-state-")
        try:
            with os.fdopen(fd, "w") as handle:
                json.dump(doc, handle, separators=(",", ":"))
            os.replace(tmp_path, self.path)
        except OSError:
            try:
                os.unlink(tmp_path)
            except OSError:
                pass
            raise
        self._last_save = now
        return True

    # ------------------------------------------------------------- load

    def load(self, expected_codec: str) -> Optional[dict]:
        """The parsed warm-state document, or None for ANY reason the
        file cannot be trusted: absent, unparseable, wrong codec version,
        wrong slice codec, wrong shape. The caller's reaction to None is
        the ordinary cold path — loading can make a restart faster but
        never changes what it computes."""
        try:
            with open(self.path) as handle:
                doc = json.load(handle)
        except (OSError, ValueError):
            return None
        if not isinstance(doc, dict):
            return None
        if doc.get("codec_version") != SNAPSHOT_CODEC_VERSION:
            log.info(
                "warm-state %s: codec version %r != %d; cold rebuild",
                self.path,
                doc.get("codec_version"),
                SNAPSHOT_CODEC_VERSION,
            )
            return None
        if doc.get("slice_codec") != expected_codec:
            log.info(
                "warm-state %s: slice codec %r != %r; cold rebuild",
                self.path,
                doc.get("slice_codec"),
                expected_codec,
            )
            return None
        nodes = doc.get("nodes")
        if not isinstance(nodes, dict):
            return None
        return doc

    # ------------------------------------------------------------ adopt

    def adopt(
        self, snapshot: ClusterSnapshot, planner, doc: Optional[dict] = None
    ) -> AdoptReport:
        """Re-key persisted memos onto a freshly store-built snapshot.
        Every snapshot node whose recomputed signature matches the saved
        one gets its entries adopted at the live mutation version; every
        other node lands in ``unmatched`` (the caller plans it as dirty).
        With doc=None the file is loaded first; an untrusted file adopts
        nothing and reports every node unmatched — i.e. a cold boot."""
        if doc is None:
            doc = self.load(expected_codec=type(snapshot.codec).__name__)
        report = AdoptReport()
        live = snapshot.get_nodes()
        if doc is None:
            report.unmatched = set(live)
            return report
        saved_nodes = doc["nodes"]
        matched_entries: Dict[str, dict] = {}
        for name, snap_node in live.items():
            saved = saved_nodes.get(name)
            if (
                isinstance(saved, dict)
                and saved.get("signature") == self._signature(name, snap_node)
            ):
                matched_entries[name] = saved
                report.matched += 1
            else:
                report.unmatched.add(name)
        if matched_entries:
            report.adopted_entries = planner.adopt_warm_state(
                snapshot, matched_entries
            )
        return report
