"""Planner: the simulate-before-actuate optimization loop.

Reference internal/partitioning/core/planner.go:67-153. For each candidate
node: fork the snapshot, re-carve geometry toward the still-lacking slices,
simulation-schedule each pending pod against the forked node with the real
scheduler framework (PreFilter + Filter, planner.go:178-207), and commit the
fork only if at least one pod landed — otherwise revert. A cheap
lacking-slices shortcut (planner.go:155-175) avoids the framework run when
the cluster still cannot serve the pod at all.
"""
from __future__ import annotations

import logging
from typing import Iterable, List

from nos_tpu.kube.objects import Pod
from nos_tpu.partitioning.core.partition_state import PartitioningState
from nos_tpu.partitioning.core.snapshot import ClusterSnapshot
from nos_tpu.partitioning.core.tracker import SliceTracker
from nos_tpu.scheduler.framework import CycleState, Framework
from nos_tpu.util import resources as res
from nos_tpu.api.v1alpha1 import constants
from nos_tpu.tpu.topology import Topology

log = logging.getLogger("nos_tpu.partitioning")


def sort_candidate_pods(pods: Iterable[Pod]) -> List[Pod]:
    """Priority first, then smallest slice request, then namespace/name
    (reference core/util.go:34-71): high-priority pods get first pick and
    small slices pack tighter."""

    def smallest_slice_chips(pod: Pod) -> int:
        request = res.compute_pod_request(pod)
        chips = [
            Topology(constants.tpu_slice_topology(name)).chips
            for name in request
            if constants.is_tpu_slice_resource(name)
        ]
        plain = int(request.get(constants.RESOURCE_TPU, 0))
        if plain:
            chips.append(plain)
        return min(chips) if chips else 0

    return sorted(
        pods,
        key=lambda p: (
            -p.spec.priority,
            smallest_slice_chips(p),
            p.metadata.namespace,
            p.metadata.name,
        ),
    )


class Planner:
    def __init__(self, framework: Framework) -> None:
        self.framework = framework

    def plan(self, snapshot: ClusterSnapshot, pending_pods: List[Pod]) -> PartitioningState:
        tracker = SliceTracker(snapshot, pending_pods)
        if tracker.empty:
            # Nothing is lacking — current geometry already serves every
            # pending pod (planner.go:80-83).
            return snapshot.partitioning_state()

        candidates = sort_candidate_pods(pending_pods)
        for node_name in snapshot.get_candidate_nodes():
            if tracker.empty:
                break
            node = snapshot.get_node(node_name)
            accelerator = getattr(node.partitionable, "accelerator", "")
            snapshot.fork()
            changed = node.partitionable.update_geometry_for(
                tracker.lacking_totals(accelerator)
            )
            if not changed:
                snapshot.revert()
                continue
            added_any = False
            for pod in candidates:
                if pod not in tracker:
                    continue
                if self._try_add_pod(snapshot, node_name, pod):
                    tracker.remove(pod)
                    added_any = True
            if added_any:
                snapshot.commit()
                log.info("planner: node %s re-carved for pending pods", node_name)
            else:
                snapshot.revert()
        return snapshot.partitioning_state()

    # ------------------------------------------------------------------

    def _try_add_pod(self, snapshot: ClusterSnapshot, node_name: str, pod: Pod) -> bool:
        # Cheap shortcut: if the cluster still lacks slices for this pod,
        # no point running the scheduler simulation (planner.go:155-175).
        if snapshot.get_lacking_slices(pod):
            return False
        if not self._can_schedule(snapshot, node_name, pod):
            return False
        return snapshot.add_pod(node_name, pod)

    def _can_schedule(self, snapshot: ClusterSnapshot, node_name: str, pod: Pod) -> bool:
        """Run the real scheduler plugins against the forked node view
        (planner.go:178-207) so the plan only contains placements the real
        scheduler would accept."""
        node = snapshot.get_node(node_name)
        accelerator = getattr(node.partitionable, "accelerator", "")
        sim_pod = self._simulation_pod(snapshot, pod, accelerator)
        state = CycleState()
        status = self.framework.run_pre_filter_plugins(state, sim_pod)
        if not status.success:
            return False
        status = self.framework.run_filter_plugins(state, sim_pod, node.sim_node_info())
        return status.success

    @staticmethod
    def _simulation_pod(snapshot: ClusterSnapshot, pod: Pod, accelerator: str) -> Pod:
        """Pod with its TPU request normalized to the candidate node's own
        generation, matching the slice-denominated allocatable of the
        simulated node view."""
        sim = pod.deepcopy()
        for container in sim.spec.containers:
            container.requests = snapshot.normalize_request(container.requests, accelerator)
        for container in sim.spec.init_containers:
            container.requests = snapshot.normalize_request(container.requests, accelerator)
        return sim
