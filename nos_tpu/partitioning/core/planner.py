"""Planner: the simulate-before-actuate optimization loop.

Reference internal/partitioning/core/planner.go:67-153. For each candidate
node: fork the snapshot, re-carve geometry toward the still-lacking slices,
simulation-schedule each pending pod against the forked node with the real
scheduler framework (PreFilter + Filter, planner.go:178-207), and commit the
fork only if at least one pod landed — otherwise revert. A cheap
lacking-slices shortcut (planner.go:155-175) avoids the framework run when
the cluster still cannot serve the pod at all.

All forking rides the snapshot's copy-on-write journal (snapshot.py): a
candidate-node trial costs one touched-node clone, and the gang trial is a
nested fork around a whole ``_plan_pass`` instead of a full snapshot
deepcopy. Geometry carves go through ``snapshot.update_geometry_for`` so
the journal and the incremental free pool both see them.
"""
from __future__ import annotations

import logging
import time
from typing import Dict, Iterable, List, Tuple

from nos_tpu.kube.objects import Pod
from nos_tpu.partitioning.core.partition_state import PartitioningState
from nos_tpu.partitioning.core.snapshot import ClusterSnapshot
from nos_tpu.partitioning.core.tracker import SliceTracker
from nos_tpu.scheduler.framework import (
    CycleState,
    Framework,
    TOPOLOGY_NODE_INFOS_KEY,
)
from nos_tpu.util import metrics
from nos_tpu.util import resources as res
from nos_tpu.util.tracing import TRACER
from nos_tpu.api.v1alpha1 import constants
from nos_tpu.tpu.topology import topology_chips

log = logging.getLogger("nos_tpu.partitioning")


def _gang_of(pod: Pod):
    # Lazy import: scheduler.plugins.gang pulls the KubeStore stack, which
    # the planner's own dependents don't otherwise need.
    from nos_tpu.scheduler.plugins.gang import gang_of

    return gang_of(pod)


def sort_candidate_pods(
    pods: Iterable[Pod],
    aging_chips_per_second: float = 1.0,
    pending_since: "dict | None" = None,
) -> List[Pod]:
    """Priority first, then LARGEST effective slice request, then age,
    then namespace/name.

    Deliberate deviation from the reference (core/util.go:34-71 sorts
    smallest-first "to pack tighter"): on TPU hosts the scarce commodity
    is the contiguous full board — first-fit-DESCENDING places the
    board-sized requests while whole boards are still free, then fills the
    remainder with small slices. Smallest-first hands a freed board to
    fragment-sized pods and forces the next full-board pod to drain a
    node all over again.

    Pure FFD starves the smallest requests under sustained load (every
    round re-sorts them last), so time spent PASSED OVER ages a pod's
    EFFECTIVE size upward at `aging_chips_per_second`: a 1-chip pod left
    behind across re-plans eventually sorts with — then ahead of — the
    board-sized arrivals. `pending_since` maps namespaced_name -> the
    monotonic instant the planner FIRST considered the pod (tracked by
    Planner across plan() calls); a pod's first consideration is age 0, so
    arrival-time spread inside one batch window never turns the sort into
    FIFO and fresh batches keep the pure largest-first packing order.
    Aging never crosses an explicit priority boundary."""
    now = time.monotonic()
    pending_since = pending_since or {}

    def largest_slice_chips(pod: Pod) -> int:
        request = res.compute_pod_request(pod)
        chips = [
            topology_chips(constants.tpu_slice_topology(name))
            for name in request
            if constants.is_tpu_slice_resource(name)
        ]
        plain = int(request.get(constants.RESOURCE_TPU, 0))
        if plain:
            chips.append(plain)
        return max(chips) if chips else 0

    def effective_chips(pod: Pod) -> float:
        age = max(0.0, now - pending_since.get(pod.namespaced_name, now))
        return largest_slice_chips(pod) + age * aging_chips_per_second

    return sorted(
        pods,
        key=lambda p: (
            -p.spec.priority,
            -effective_chips(p),
            p.metadata.namespace,
            p.metadata.name,
        ),
    )


class Planner:
    def __init__(
        self, framework: Framework, aging_chips_per_second: float = 1.0
    ) -> None:
        self.framework = framework
        self.aging_chips_per_second = aging_chips_per_second
        # namespaced_name -> (first_seen, last_seen) monotonic instants.
        # Age for the fairness sort is measured from first_seen — time
        # passed over across plan() calls — never from creation time (a
        # 60s batch window would otherwise make every sort FIFO). Entries
        # survive absence from individual batches (batches are
        # event-triggered subsets; dropping on absence would reset a
        # starved pod's age) and are pruned only after _PENDING_TTL_S
        # without a sighting (pod bound or deleted).
        self._pending_seen: dict = {}
        self._PENDING_TTL_S = 600.0
        # (uid, namespaced_name, accelerator) -> normalized simulation pod.
        # One pod is trialed against many candidate nodes per plan();
        # normalization only depends on the pod spec and the node's
        # generation, so the deepcopy+rewrite is done once per pair.
        # Cleared at every plan() start — pods are immutable within a run.
        self._sim_pod_cache: Dict[Tuple[str, str, str], Pod] = {}

    def plan(self, snapshot: ClusterSnapshot, pending_pods: List[Pod]) -> PartitioningState:
        started = time.monotonic()
        with TRACER.span(
            "partitioner.plan",
            pending_pods=len(pending_pods),
            nodes=len(snapshot.get_nodes()),
        ) as span:
            try:
                return self._plan(snapshot, pending_pods, span)
            finally:
                metrics.PLAN_DURATION.observe(time.monotonic() - started)

    def _plan(
        self, snapshot: ClusterSnapshot, pending_pods: List[Pod], span=None
    ) -> PartitioningState:
        # Pool draw order == claim pre-pass order (first-fit-descending):
        # the tracker and the pre-pass must agree on WHICH pods the
        # existing free slices serve, or a pod could end up neither
        # claim-placed nor carved for this round.
        now = time.monotonic()
        self._sim_pod_cache.clear()
        # Key includes the uid: a recreated pod with a reused name is a NEW
        # pod and must start at age 0, not inherit its predecessor's boost.
        live = {(p.namespaced_name, p.metadata.uid) for p in pending_pods}
        for key in live:
            first, _ = self._pending_seen.get(key, (now, now))
            self._pending_seen[key] = (first, now)
        self._pending_seen = {
            k: v
            for k, v in self._pending_seen.items()
            if now - v[1] <= self._PENDING_TTL_S
        }
        pending_since = {
            k[0]: v[0] for k, v in self._pending_seen.items() if k in live
        }
        candidates = sort_candidate_pods(
            pending_pods,
            aging_chips_per_second=self.aging_chips_per_second,
            pending_since=pending_since,
        )
        # Pods aging has materially promoted (>= 2.5 effective chips of
        # boost): they get the dedicated-carve rescue in _plan_pass —
        # without it, a starved small pod sorts first yet never wins chips,
        # because the free pool only serves exact profiles (a free 2x2
        # cannot serve a 1-chip pod) and freed regions are always claimed
        # whole by exact-fit pods before any carve happens.
        aged = {
            p.namespaced_name
            for p in candidates
            if (now - pending_since.get(p.namespaced_name, now))
            * self.aging_chips_per_second
            >= 2.5
        }
        tracker = SliceTracker(snapshot, candidates)
        if tracker.empty:
            # Nothing is lacking — current geometry already serves every
            # pending pod (planner.go:80-83).
            return snapshot.partitioning_state()

        # Gang fidelity (SURVEY §7 pitfall): a gang member carved for in
        # isolation wastes a slice the gang can never use. Trial-plan on a
        # journaled fork first; any gang that cannot FULLY form (running
        # members + trial placements < size) contributes no pods to the
        # real plan, so no board is re-carved for a half-formable gang.
        # The trial (an outer fork around a full simulation pass — the
        # inner per-node forks nest inside it) only runs when a gang pod
        # is actually in the batch.
        excluded: set = set()
        if any(_gang_of(p) for p in candidates):
            snapshot.fork()
            trial_tracker = SliceTracker(snapshot, candidates)
            # _plan_pass claim-places members the current geometry already
            # serves AND simulates re-carve placements; both land in
            # trial_placed, so it is the complete placeability set.
            trial_placed = self._plan_pass(
                snapshot, trial_tracker, candidates, quiet=True, aged=aged
            )
            snapshot.revert()
            # Counted against the PRISTINE snapshot (post-revert): trial
            # placements must not double as already-bound members.
            excluded = self._half_formable_gangs(
                snapshot, candidates, trial_placed
            )
        if excluded:
            log.info(
                "planner: gangs %s cannot fully form; excluding their pods",
                sorted(excluded),
            )
            candidates = [
                p for p in candidates
                if (_gang_of(p) or (None,))[0] not in excluded
            ]
            if not candidates:
                return snapshot.partitioning_state()
            tracker = SliceTracker(snapshot, candidates)
            if tracker.empty:
                return snapshot.partitioning_state()

        self._plan_pass(snapshot, tracker, candidates, aged=aged)
        if span is not None:
            # The recompute-vs-incremental delta for lacking_totals: with
            # the incremental cache, recomputes stay at one per accelerator
            # per pass while calls scale with candidate nodes.
            span.set_attributes(
                totals_calls=tracker.totals_calls,
                totals_recomputes=tracker.totals_recomputes,
                totals_incremental=tracker.totals_calls - tracker.totals_recomputes,
            )
        return snapshot.partitioning_state()

    def _plan_pass(
        self,
        snapshot: ClusterSnapshot,
        tracker: SliceTracker,
        candidates: List[Pod],
        quiet: bool = False,
        aged: "set | None" = None,
    ) -> List[Pod]:
        placed: List[Pod] = []
        # Aged-rescue pass, BEFORE anyone claims free slices: a starved
        # pod the fairness aging promoted gets a carve aimed at exactly
        # its profile while contested free regions are still free. Sort
        # order is the entitlement order — running this first means an
        # aged 1-chip pod converts the free 2x2 an exact-fit 4-chip pod
        # would otherwise claim, and THAT pod waits a round instead
        # (the inversion aging exists to produce).
        #
        # ONE successful rescue per plan: each conversion fragments a free
        # region only smaller profiles can reuse, so batching several per
        # round costs utilization; plans run every batch window and the
        # queue drains one aged pod per round. Failed attempts don't
        # consume the budget (an unrescuable aged pod must not block the
        # rescuable one behind it) but are capped to bound fork work.
        rescued = attempts = 0
        for pod in candidates:
            if not aged or rescued >= 1 or attempts >= 3:
                break
            if pod.namespaced_name not in aged or pod not in tracker:
                continue
            attempts += 1
            for node_name in snapshot.get_candidate_nodes():
                accelerator = getattr(
                    snapshot.get_node(node_name).partitionable, "accelerator", ""
                )
                with TRACER.span(
                    "plan.trial", node=node_name, rescue=True
                ) as trial:
                    snapshot.fork()
                    if not snapshot.update_geometry_for(
                        node_name, tracker.lacking_for(pod, accelerator)
                    ):
                        trial.set_attributes(
                            committed=False, nodes_copied=snapshot.revert()
                        )
                        continue
                    if self._try_add_pod(snapshot, node_name, pod):
                        tracker.remove(pod)
                        placed.append(pod)
                        rescued += 1
                        trial.set_attributes(
                            committed=True, nodes_copied=snapshot.commit()
                        )
                        if not quiet:
                            log.info(
                                "planner: node %s re-carved (aged rescue) for %s",
                                node_name,
                                pod.namespaced_name,
                            )
                        break
                    trial.set_attributes(
                        committed=False, nodes_copied=snapshot.revert()
                    )

        # Claim pre-pass (TPU-first addition, no reference analogue): pods
        # that existing free slices fully serve will bind onto them without
        # any carve — place them in the snapshot FIRST, so the carve loop
        # below sees their slices as used and can never destroy a free
        # slice a pending pod is entitled to. Without this, a freed full
        # board gets fragmented for small lack while the full-board pod
        # about to bind there goes back to waiting for a drain.
        for pod in candidates:
            if pod in tracker:
                continue
            for node_name in snapshot.get_candidate_nodes():
                if self._try_add_pod(snapshot, node_name, pod):
                    placed.append(pod)
                    break
        for node_name in snapshot.get_candidate_nodes():
            if tracker.empty:
                break
            accelerator = getattr(
                snapshot.get_node(node_name).partitionable, "accelerator", ""
            )
            with TRACER.span("plan.trial", node=node_name) as trial:
                snapshot.fork()
                changed = snapshot.update_geometry_for(
                    node_name, tracker.lacking_totals(accelerator)
                )
                if not changed:
                    trial.set_attributes(
                        committed=False, nodes_copied=snapshot.revert()
                    )
                    continue
                added_any = False
                placed_here = 0
                for pod in candidates:
                    if pod not in tracker:
                        continue
                    if self._try_add_pod(snapshot, node_name, pod):
                        tracker.remove(pod)
                        placed.append(pod)
                        added_any = True
                        placed_here += 1
                if added_any:
                    trial.set_attributes(
                        committed=True,
                        pods_placed=placed_here,
                        nodes_copied=snapshot.commit(),
                    )
                    if not quiet:
                        log.info(
                            "planner: node %s re-carved for pending pods", node_name
                        )
                else:
                    trial.set_attributes(
                        committed=False, nodes_copied=snapshot.revert()
                    )

        return placed

    @staticmethod
    def _half_formable_gangs(
        snapshot: ClusterSnapshot, candidates: List[Pod], trial_placed: List[Pod]
    ) -> set:
        """Gang keys whose running + trial-placed membership < size."""
        sizes = {}
        placed_count: dict = {}
        for pod in candidates:
            gang = _gang_of(pod)
            if gang:
                sizes[gang[0]] = gang[1]
        if not sizes:
            return set()
        for pod in trial_placed:
            gang = _gang_of(pod)
            if gang:
                placed_count[gang[0]] = placed_count.get(gang[0], 0) + 1
        bound_count: dict = {}
        # ALL nodes, not just carve candidates: a member running on a
        # fully-carved node still counts toward gang completeness.
        for snap_node in snapshot.get_nodes().values():
            for pod in snap_node.pods:
                gang = _gang_of(pod)
                if gang:
                    bound_count[gang[0]] = bound_count.get(gang[0], 0) + 1
        return {
            key
            for key, size in sizes.items()
            if bound_count.get(key, 0) + placed_count.get(key, 0) < size
        }

    # ------------------------------------------------------------------

    def _try_add_pod(self, snapshot: ClusterSnapshot, node_name: str, pod: Pod) -> bool:
        # Cheap shortcut: if the cluster still lacks slices for this pod,
        # no point running the scheduler simulation (planner.go:155-175).
        if snapshot.get_lacking_slices(pod):
            return False
        if not self._can_schedule(snapshot, node_name, pod):
            return False
        return snapshot.add_pod(node_name, pod)

    def _can_schedule(self, snapshot: ClusterSnapshot, node_name: str, pod: Pod) -> bool:
        """Run the real scheduler plugins against the forked node view
        (planner.go:178-207) so the plan only contains placements the real
        scheduler would accept."""
        node = snapshot.get_node(node_name)
        accelerator = getattr(node.partitionable, "accelerator", "")
        sim_pod = self._simulation_pod(snapshot, pod, accelerator)
        state = CycleState()
        if (
            sim_pod.spec.topology_spread_constraints
            or sim_pod.spec.pod_affinity
            or sim_pod.spec.pod_anti_affinity
            or snapshot.has_anti_affinity_pods()
        ):
            # Cross-node context for the topology-spread predicate,
            # published the same way the real cycle does (cached on the
            # snapshot across trials). Scope caveat: the snapshot holds
            # only partitionable nodes (mirroring the reference's
            # ClusterState, which caches only partitioning-labeled nodes),
            # so spread domains that exist purely on non-TPU nodes are
            # invisible to the simulation — the real scheduler still
            # enforces them at bind time.
            state[TOPOLOGY_NODE_INFOS_KEY] = snapshot.sim_node_infos()
        # The simulation runs the framework once per (pod, node) trial —
        # thousands of times per plan — so per-plugin spans are suppressed
        # here; the plan.trial spans carry the aggregate story.
        with TRACER.suppress_plugins():
            status = self.framework.run_pre_filter_plugins(state, sim_pod)
            if not status.success:
                return False
            status = self.framework.run_filter_plugins(
                state, sim_pod, node.sim_node_info()
            )
            return status.success

    def _simulation_pod(self, snapshot: ClusterSnapshot, pod: Pod, accelerator: str) -> Pod:
        """Pod with its TPU request normalized to the candidate node's own
        generation, matching the slice-denominated allocatable of the
        simulated node view. Cached per (pod, generation) across the many
        node trials of one plan() call."""
        key = (pod.metadata.uid, pod.namespaced_name, accelerator)
        cached = self._sim_pod_cache.get(key)
        if cached is not None:
            return cached
        sim = pod.deepcopy()
        for container in sim.spec.containers:
            container.requests = snapshot.normalize_request(container.requests, accelerator)
        for container in sim.spec.init_containers:
            container.requests = snapshot.normalize_request(container.requests, accelerator)
        self._sim_pod_cache[key] = sim
        return sim
