"""Planner: the simulate-before-actuate optimization loop.

Reference internal/partitioning/core/planner.go:67-153. For each candidate
node: fork the snapshot, re-carve geometry toward the still-lacking slices,
simulation-schedule each pending pod against the forked node with the real
scheduler framework (PreFilter + Filter, planner.go:178-207), and commit the
fork only if at least one pod landed — otherwise revert. A cheap
lacking-slices shortcut (planner.go:155-175) avoids the framework run when
the cluster still cannot serve the pod at all.

All forking rides the snapshot's copy-on-write journal (snapshot.py): a
candidate-node trial costs one touched-node clone, and the gang trial is a
nested fork around a whole ``_plan_pass`` instead of a full snapshot
deepcopy. Geometry carves go through ``snapshot.update_geometry_for`` so
the journal and the incremental free pool both see them.

The simulation itself is memoized through an equivalence-class verdict
cache (verdict_cache.py, upstream kube-scheduler's equivalence-cache idea):
a PreFilter+Filter verdict for the ``verdict_cacheable`` plugin subset is
keyed by (pod signature, node name, node mutation version) — the snapshot's
never-repeating mutation clock makes the node half of the key O(1) to read
and exact to invalidate, and a reverted trial restores pre-fork versions so
earlier entries become valid again. Lookups are bypassed whenever the pod
or the snapshot carries affinity/topology-spread state (those verdicts read
cross-node context), and plugins that never opted in (external-store
filters) run fresh on every trial after the cached subset. Supporting
memos with the same exactness guarantee: lacking-slices booleans and
candidate-node order keyed by the snapshot-wide ``state_version``,
simulated NodeInfo views keyed by (node, version), and a carve-futility
memo keyed by (node, version, lacking signature) that skips fork+carve
trials ``update_geometry_for`` already proved to be geometry no-ops. All
of it is per-plan state, rebuilt at every ``plan()`` entry — except in
incremental mode, where still-valid version-keyed entries survive.

Incremental replans: callers that maintain ONE persistent base snapshot
across cycles (every out-of-band change applied via
``snapshot.refresh_node``, which stamps a fresh mutation tick) pass
``plan(..., dirty=<changed node names>)``. The plan then runs inside an
outer fork that is reverted after the result is taken — the base snapshot
is left at observed state, node versions restored — and, when the same
snapshot object returns with a small enough dirty fraction,
``_prune_plan_caches`` retains every memo entry whose version key still
matches a live node instead of rebuilding the world: untouched nodes keep
their verdicts, NodeInfo views, candidate order and futility proofs, so a
steady-state replan degenerates to O(nodes) memo probes plus work on the
dirty set. A snapshot-identity change or an oversized dirty set falls back
to a from-scratch pass (still base-preserving); ``dirty=None`` is the
legacy snapshot-consuming path, bit-identical to prior releases.

Diagnosability: every ``_plan`` exit leaves ``last_unserved`` mapping each
still-unserved pending pod to a human-readable reason (its lacking slice
profile, or gang non-formability) — the partitioner controller turns these
into CarveFailed Events.
"""
from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Tuple

from nos_tpu.kube.objects import Pod
from nos_tpu.partitioning.core.partition_state import PartitioningState
from nos_tpu.partitioning.core.snapshot import ClusterSnapshot, SnapshotNode
from nos_tpu.partitioning.core.tracker import SliceTracker
from nos_tpu.partitioning.core.verdict_cache import (
    VerdictCache,
    needs_cluster_context,
    pod_signature,
)
from nos_tpu.scheduler.framework import (
    CycleState,
    Framework,
    NodeInfo,
    TOPOLOGY_NODE_INFOS_KEY,
    is_verdict_cacheable,
)
from nos_tpu.util import metrics
from nos_tpu.util import resources as res
from nos_tpu.util.tracing import TRACER
from nos_tpu.api.v1alpha1 import constants
from nos_tpu.tpu.topology import topology_chips

log = logging.getLogger("nos_tpu.partitioning")


def _retuple(value):
    """Invert JSON's tuple→list flattening on a persisted pod signature.
    Scalars compare and hash identically after the round-trip (8 == 8.0
    in dict keys), so the reconstructed key is interchangeable with the
    one pod_signature would compute live."""
    if isinstance(value, list):
        return tuple(_retuple(item) for item in value)
    return value


def _gang_of(pod: Pod):
    # Lazy import: scheduler.plugins.gang pulls the KubeStore stack, which
    # the planner's own dependents don't otherwise need.
    from nos_tpu.scheduler.plugins.gang import gang_of

    return gang_of(pod)


def sort_candidate_pods(
    pods: "Iterable[Pod]",
    aging_chips_per_second: float = 1.0,
    pending_since: "dict | None" = None,
) -> List[Pod]:
    """Priority first, then LARGEST effective slice request, then age,
    then namespace/name.

    Deliberate deviation from the reference (core/util.go:34-71 sorts
    smallest-first "to pack tighter"): on TPU hosts the scarce commodity
    is the contiguous full board — first-fit-DESCENDING places the
    board-sized requests while whole boards are still free, then fills the
    remainder with small slices. Smallest-first hands a freed board to
    fragment-sized pods and forces the next full-board pod to drain a
    node all over again.

    Pure FFD starves the smallest requests under sustained load (every
    round re-sorts them last), so time spent PASSED OVER ages a pod's
    EFFECTIVE size upward at `aging_chips_per_second`: a 1-chip pod left
    behind across re-plans eventually sorts with — then ahead of — the
    board-sized arrivals. `pending_since` maps namespaced_name -> the
    monotonic instant the planner FIRST considered the pod (tracked by
    Planner across plan() calls); a pod's first consideration is age 0, so
    arrival-time spread inside one batch window never turns the sort into
    FIFO and fresh batches keep the pure largest-first packing order.
    Aging never crosses an explicit priority boundary."""
    now = time.monotonic()
    pending_since = pending_since or {}

    def largest_slice_chips(pod: Pod) -> int:
        request = res.compute_pod_request(pod)
        chips = [
            topology_chips(constants.tpu_slice_topology(name))
            for name in request
            if constants.is_tpu_slice_resource(name)
        ]
        plain = int(request.get(constants.RESOURCE_TPU, 0))
        if plain:
            chips.append(plain)
        return max(chips) if chips else 0

    # Explicit decorate-sort-undecorate: the request walk + topology
    # parsing behind the effective-chips number runs exactly once per pod
    # — the reference's sort.Slice less-func re-derives it per COMPARISON
    # (core/util.go:34-71), an O(n log n) blowup this port must not
    # inherit through a key closure someone later turns into a cmp.
    keyed: List[Tuple[tuple, Pod]] = []
    for pod in pods:
        age = max(0.0, now - pending_since.get(pod.namespaced_name, now))
        effective = largest_slice_chips(pod) + age * aging_chips_per_second
        keyed.append(
            (
                (
                    -pod.spec.priority,
                    -effective,
                    pod.metadata.namespace,
                    pod.metadata.name,
                ),
                pod,
            )
        )
    keyed.sort(key=lambda kv: kv[0])
    return [pod for _, pod in keyed]


class Planner:
    def __init__(
        self,
        framework: Framework,
        aging_chips_per_second: float = 1.0,
        verdict_cache_enabled: bool = True,
        reuse_gang_trial: bool = True,
        futility_memo_enabled: bool = True,
        incremental_dirty_threshold: float = 0.25,
    ) -> None:
        self.framework = framework
        self.aging_chips_per_second = aging_chips_per_second
        # All three knobs exist so the bench and the equivalence tests can
        # run the exact pre-cache code path as the oracle.
        self.verdict_cache_enabled = verdict_cache_enabled
        self.reuse_gang_trial = reuse_gang_trial
        self.futility_memo_enabled = futility_memo_enabled
        # Above this dirty fraction, deriving what survives costs more
        # than replanning: take the from-scratch fallback instead.
        self.incremental_dirty_threshold = incremental_dirty_threshold
        # Mode the most recent plan() executed in — "full", "incremental"
        # or "fallback"; read by the audit shadow check and tests.
        self.last_plan_mode = "full"
        # namespaced_name -> reason for every pending pod the most recent
        # _plan could not serve; read by the partitioner controller for
        # CarveFailed Events. Valid until the next plan() overwrites it.
        self.last_unserved: Dict[str, str] = {}
        # Flight-recorder/auditor taps, valid until the next plan():
        # the effective fairness age per pending pod (recorded so replay
        # can reproduce the aging-dependent sort without this planner's
        # _pending_seen history), and the SliceTracker the final pass ran
        # with (audited against a full lacking recompute).
        self.last_pending_ages: Dict[str, float] = {}
        self.last_tracker: Optional[SliceTracker] = None
        # namespaced_name -> (first_seen, last_seen) monotonic instants.
        # Age for the fairness sort is measured from first_seen — time
        # passed over across plan() calls — never from creation time (a
        # 60s batch window would otherwise make every sort FIFO). Entries
        # survive absence from individual batches (batches are
        # event-triggered subsets; dropping on absence would reset a
        # starved pod's age) and are pruned only after _PENDING_TTL_S
        # without a sighting (pod bound or deleted).
        self._pending_seen: dict = {}
        self._PENDING_TTL_S = 600.0
        # Per-plan memo state; (re)built whenever the snapshot identity
        # changes so direct _try_add_pod/_can_schedule calls (tests) are
        # as correct as the plan() entry point.
        self._cache_snapshot: Optional[ClusterSnapshot] = None
        self._reset_plan_caches(None)

    # ------------------------------------------------------ plan caches

    def _reset_plan_caches(self, snapshot: Optional[ClusterSnapshot]) -> None:
        self._cache_snapshot = snapshot
        self._verdict_cache = VerdictCache()
        # (id(pod), accelerator) -> (pod, sim pod, verdict-cache
        # signature, needs-cross-node-context flag). One pod is trialed
        # against many candidate nodes per plan(); normalization only
        # depends on the pod spec and the node's generation, so the
        # deepcopy+rewrite+signature is done once per pair. Pods are
        # immutable within a run; keying on object identity skips the
        # uid/namespaced-name tuple build on the per-trial hot path, and
        # the pinned pod reference keeps the id from being recycled.
        self._sim_pod_cache: Dict[Tuple[int, str], tuple] = {}
        # (node name, node.version) -> simulated NodeInfo. to_sim_node()
        # deepcopies the kube Node per call; the version key pins geometry
        # and placements exactly, so one view serves every trial the node
        # reaches unchanged (including after a revert restores it).
        self._node_info_cache: Dict[Tuple[str, int], NodeInfo] = {}
        # (request signature, snapshot.state_version) -> bool("still
        # lacking"). _try_add_pod only branches on truthiness, and every
        # free-pool change bumps state_version, so the bool is exact.
        self._lacking_cache: Dict[Tuple[tuple, int], bool] = {}
        # id(pod) -> (pod, sorted compute_pod_request items); the pod ref
        # pins the id.
        self._request_cache: Dict[int, tuple] = {}
        # snapshot.state_version -> candidate-node order (the claim
        # pre-pass asks once per pod; unchanged state means unchanged
        # order).
        self._candidate_cache: Optional[Tuple[int, List[str]]] = None
        # (node name, node.version, sorted lacking items) -> reason string:
        # a carve of THIS node geometry toward THIS lacking profile already
        # proved a geometry no-op (update_geometry_for returned False), so
        # the whole fork+carve trial can be skipped. Exact: a failed carve
        # never stamps the node version, a revert restores pre-fork
        # versions, and every real geometry/placement change bumps the
        # version — a hit would replay a bit-identical no-op. Only the
        # no-geometry-change outcome is memoized; "changed but placed
        # nobody" depends on the pod set and is not keyed here.
        self._futility_cache: Dict[Tuple[str, int, tuple], str] = {}
        self._futility_hits = 0
        # The verdict cache memoizes only the opted-in plugin subset; the
        # rest runs fresh on every trial, after the cached conjunction.
        framework = self.framework
        self._cacheable_pre = [
            p for p in framework.pre_filter_plugins if is_verdict_cacheable(p)
        ]
        self._uncacheable_pre = [
            p for p in framework.pre_filter_plugins if not is_verdict_cacheable(p)
        ]
        self._cacheable_filters = [
            p for p in framework.filter_plugins if is_verdict_cacheable(p)
        ]
        self._uncacheable_filters = [
            p for p in framework.filter_plugins if not is_verdict_cacheable(p)
        ]

    def _ensure_plan_caches(self, snapshot: ClusterSnapshot) -> None:
        # Identity check, not equality: memo keys embed this snapshot's
        # mutation-clock values, which mean nothing against another one.
        if snapshot is not self._cache_snapshot:
            self._reset_plan_caches(snapshot)

    def _prune_plan_caches(
        self, snapshot: ClusterSnapshot, pending_pods: List[Pod]
    ) -> None:
        """Incremental-mode cache retention: evict exactly the entries a
        dirtied key can no longer reach, keep everything else. Soundness
        rests on the mutation clock — ``refresh_node`` stamps a fresh,
        never-repeated tick on every out-of-band change, and the outer
        fork/revert around a base-preserving plan restores pre-plan
        versions — so an entry whose version key still matches the live
        node describes a bit-identical state. Pod-identity-keyed entries
        pin the pod object, so a key found in the current pending set is
        necessarily the same object it was built from. Hit/miss counters
        reset here: stats stay per-plan even when entries don't."""
        self._verdict_cache.reset_stats()
        self._futility_hits = 0
        version_of = snapshot.node_version
        entries = self._verdict_cache.entries
        for key in [k for k in entries if version_of(k[1]) != k[2]]:
            del entries[key]
        infos = self._node_info_cache
        for key in [k for k in infos if version_of(k[0]) != k[1]]:
            del infos[key]
        futility = self._futility_cache
        for key in [k for k in futility if version_of(k[0]) != k[1]]:
            del futility[key]
        state_version = snapshot.state_version
        lacking = self._lacking_cache
        for key in [k for k in lacking if k[1] != state_version]:
            del lacking[key]
        if (
            self._candidate_cache is not None
            and self._candidate_cache[0] != state_version
        ):
            self._candidate_cache = None
        live = {id(p) for p in pending_pods}
        sims = self._sim_pod_cache
        for key in [k for k in sims if k[0] not in live]:
            del sims[key]
        requests = self._request_cache
        for key in [k for k in requests if k not in live]:
            del requests[key]

    def _select_plan_mode(
        self, snapshot: ClusterSnapshot, dirty: "Optional[set]"
    ) -> str:
        if dirty is None:
            return "full"
        if snapshot is not self._cache_snapshot:
            # New snapshot object: every memo key is meaningless (foreign
            # mutation clock). Also the cold-start path of a persistent
            # base — the fallback pass builds caches at base versions,
            # which the revert preserves for the next cycle.
            return "fallback"
        total = snapshot.node_count()
        if total and len(dirty) <= self.incremental_dirty_threshold * total:
            return "incremental"
        return "fallback"

    # ----------------------------------------------------------- entry

    def plan(
        self,
        snapshot: ClusterSnapshot,
        pending_pods: List[Pod],
        pending_ages: Optional[Dict[str, float]] = None,
        dirty: "Optional[set]" = None,
    ) -> PartitioningState:
        """``pending_ages`` (namespaced_name -> seconds pending) overrides
        the planner's own first-seen bookkeeping — replay passes the
        recorded ages so the aging-dependent candidate sort reproduces.

        ``dirty`` opts into base-preserving planning: the caller owns a
        persistent snapshot whose ONLY out-of-band mutations since the
        last plan() went through ``refresh_node``, and ``dirty`` names the
        refreshed nodes. The plan runs in an outer fork reverted before
        returning, so the base stays at observed state. ``dirty=None`` is
        the legacy path: caches rebuilt, snapshot mutated in place."""
        started = time.monotonic()
        mode = self._select_plan_mode(snapshot, dirty)
        with TRACER.span(
            "partitioner.plan",
            pending_pods=len(pending_pods),
            nodes=snapshot.node_count(),
            plan_mode=mode,
            dirty_nodes=-1 if dirty is None else len(dirty),
        ) as span:
            if mode == "incremental":
                self._prune_plan_caches(snapshot, pending_pods)
            else:
                # Full rebuild — for dirty=None also because out-of-band
                # mutations between plan() calls need not pass through
                # the stamped mutators on that legacy contract.
                self._reset_plan_caches(snapshot)
            self.last_plan_mode = mode
            metrics.PLAN_MODE.labels(mode=mode).inc()
            base_preserving = dirty is not None
            if base_preserving:
                # Warm the incremental free pool BEFORE forking: fork
                # checkpoints the pool as-is, and a None checkpoint makes
                # the final revert throw the pool away — the base would
                # then recompute it from every node each cycle (and the
                # refresh_node deltas maintaining it would no-op forever).
                snapshot.free_slice_resources()
                snapshot.fork()
            try:
                return self._plan(snapshot, pending_pods, span, pending_ages)
            finally:
                if base_preserving:
                    snapshot.revert()
                metrics.PLAN_DURATION.observe(time.monotonic() - started)
                self._flush_cache_stats(span)

    def verdict_cache_stats(self) -> Tuple[int, int, int]:
        """(hits, misses, bypasses) accumulated by the most recent plan()
        — valid until the next plan() resets the per-plan caches."""
        return self._verdict_cache.stats()

    # --------------------------------------------- fairness-age carryover

    def adopt_pending_seen(self, other: "Planner") -> None:
        """Carry another planner's first-seen fairness bookkeeping into
        this one. Pool-sharded planning rebuilds per-pool planners when
        the pool partition changes; without this, every partition change
        would reset each starved pod's age to zero and restart the
        aging-promotion clock."""
        for key, value in other._pending_seen.items():
            mine = self._pending_seen.get(key)
            if mine is None or value[0] < mine[0]:
                self._pending_seen[key] = value

    # ----------------------------------------------- warm-state hand-off

    def export_warm_state(self, snapshot: ClusterSnapshot) -> Dict[str, dict]:
        """Per-node memo entries worth persisting across a process
        restart: carve-futility proofs and cacheable scheduler verdicts,
        both re-keyable because their pod half is a content signature (the
        node half — the mutation version — is NOT portable and is
        re-stamped at adoption). Only entries keyed at a node's CURRENT
        (observed) version are exported: a plan's trial forks stamp
        hypothetical mid-carve versions, and a verdict proved against a
        hypothetical geometry must never be re-keyed onto observed state
        (same retention rule as ``_prune_plan_caches``). Object-identity
        keyed memos (sim pods, requests, NodeInfo views) die with the
        process by design."""
        version_of = snapshot.node_version
        out: Dict[str, dict] = {}
        for (node, version, lacking), reason in self._futility_cache.items():
            if version_of(node) != version:
                continue
            out.setdefault(node, {"futility": [], "verdicts": []})[
                "futility"
            ].append([list(lacking), reason])
        for (signature, node, version), verdict in (
            self._verdict_cache.entries.items()
        ):
            if version_of(node) != version:
                continue
            out.setdefault(node, {"futility": [], "verdicts": []})[
                "verdicts"
            ].append([list(signature), bool(verdict)])
        return out

    def adopt_warm_state(
        self, snapshot: ClusterSnapshot, entries: Dict[str, dict]
    ) -> int:
        """Re-key persisted memo entries onto `snapshot`'s live mutation
        versions and make it this planner's cache snapshot. The caller
        (snapcodec.adopt) has already proven, via content signatures, that
        each node's observed state is bit-identical to the state the
        entries were derived from — so re-stamping the version half of the
        keys preserves exactness. Returns the number of entries adopted."""
        self._reset_plan_caches(snapshot)
        adopted = 0
        for node_name, memos in entries.items():
            version = snapshot.node_version(node_name)
            if version < 0:
                continue
            for lacking, reason in memos.get("futility", ()):
                key = (
                    node_name,
                    version,
                    tuple(tuple(item) for item in lacking),
                )
                self._futility_cache[key] = reason
                adopted += 1
            for signature, verdict in memos.get("verdicts", ()):
                key = (_retuple(signature), node_name, version)
                self._verdict_cache.put(key, bool(verdict))
                adopted += 1
        return adopted

    def _flush_cache_stats(self, span=None) -> None:
        """Per-lookup counting happens on unlocked ints owned by the
        VerdictCache; the thread-safe labeled metric family is touched
        once per plan() here, not thousands of times on the trial path."""
        hits, misses, bypasses = self._verdict_cache.stats()
        if hits:
            metrics.PLAN_VERDICT_CACHE.labels(event="hit").inc(hits)
        if misses:
            metrics.PLAN_VERDICT_CACHE.labels(event="miss").inc(misses)
        if bypasses:
            metrics.PLAN_VERDICT_CACHE.labels(event="bypass").inc(bypasses)
        if self._futility_hits:
            metrics.PLAN_CARVE_FUTILITY.inc(self._futility_hits)
        if span is not None:
            span.set_attributes(
                verdict_cache_hits=hits,
                verdict_cache_misses=misses,
                verdict_cache_bypasses=bypasses,
                carve_futility_hits=self._futility_hits,
            )

    def _trial_cache_delta(self, before: Tuple[int, int, int]) -> dict:
        """plan.trial span attributes: this trial's share of the plan-wide
        hit/miss/bypass counters."""
        hits, misses, bypasses = self._verdict_cache.stats()
        return {
            "cache_hits": hits - before[0],
            "cache_misses": misses - before[1],
            "cache_bypasses": bypasses - before[2],
        }

    def _plan(
        self,
        snapshot: ClusterSnapshot,
        pending_pods: List[Pod],
        span=None,
        pending_ages: Optional[Dict[str, float]] = None,
    ) -> PartitioningState:
        # Pool draw order == claim pre-pass order (first-fit-descending):
        # the tracker and the pre-pass must agree on WHICH pods the
        # existing free slices serve, or a pod could end up neither
        # claim-placed nor carved for this round.
        self.last_unserved = {}
        self.last_tracker = None
        now = time.monotonic()
        if pending_ages is not None:
            pending_since = {
                p.namespaced_name: now
                - pending_ages.get(p.namespaced_name, 0.0)
                for p in pending_pods
            }
        else:
            # Key includes the uid: a recreated pod with a reused name is a
            # NEW pod and must start at age 0, not inherit its
            # predecessor's boost.
            live = {(p.namespaced_name, p.metadata.uid) for p in pending_pods}
            for key in live:
                first, _ = self._pending_seen.get(key, (now, now))
                self._pending_seen[key] = (first, now)
            self._pending_seen = {
                k: v
                for k, v in self._pending_seen.items()
                if now - v[1] <= self._PENDING_TTL_S
            }
            pending_since = {
                k[0]: v[0] for k, v in self._pending_seen.items() if k in live
            }
        self.last_pending_ages = {
            k: now - v for k, v in pending_since.items()
        }
        candidates = sort_candidate_pods(
            pending_pods,
            aging_chips_per_second=self.aging_chips_per_second,
            pending_since=pending_since,
        )
        # Pods aging has materially promoted (>= 2.5 effective chips of
        # boost): they get the dedicated-carve rescue in _plan_pass —
        # without it, a starved small pod sorts first yet never wins chips,
        # because the free pool only serves exact profiles (a free 2x2
        # cannot serve a 1-chip pod) and freed regions are always claimed
        # whole by exact-fit pods before any carve happens.
        aged = {
            p.namespaced_name
            for p in candidates
            if (now - pending_since.get(p.namespaced_name, now))
            * self.aging_chips_per_second
            >= 2.5
        }
        tracker = SliceTracker(snapshot, candidates)
        self.last_tracker = tracker
        if tracker.empty:
            # Nothing is lacking — current geometry already serves every
            # pending pod (planner.go:80-83).
            return snapshot.partitioning_state()

        # Gang fidelity (SURVEY §7 pitfall): a gang member carved for in
        # isolation wastes a slice the gang can never use. Trial-plan on a
        # journaled fork first; any gang that cannot FULLY form (running
        # members + trial placements < size) contributes no pods to the
        # real plan, so no board is re-carved for a half-formable gang.
        # The trial (an outer fork around a full simulation pass — the
        # inner per-node forks nest inside it) only runs when a gang pod
        # is actually in the batch.
        excluded: set = set()
        if any(_gang_of(p) for p in candidates):
            # Bound-member counts from the PRISTINE snapshot, BEFORE the
            # fork: trial placements must not double as already-bound
            # members (and the reuse path below never reverts to recount).
            sizes, bound_count = self._gang_membership(snapshot, candidates)
            snapshot.fork()
            trial_tracker = SliceTracker(snapshot, candidates)
            # _plan_pass claim-places members the current geometry already
            # serves AND simulates re-carve placements; both land in
            # trial_placed, so it is the complete placeability set.
            trial_placed = self._plan_pass(
                snapshot, trial_tracker, candidates, quiet=True, aged=aged
            )
            excluded = self._half_formable_gangs(sizes, bound_count, trial_placed)
            if not excluded and self.reuse_gang_trial:
                # No gang was excluded, so the real pass would start from
                # the same pristine state with the same candidate order —
                # _plan_pass is deterministic, so its placements would be
                # bit-identical to the trial's. Keep the trial instead of
                # paying a second full simulation pass.
                self.last_unserved = self._unserved_reasons(
                    trial_tracker, candidates
                )
                self.last_tracker = trial_tracker
                snapshot.commit()
                log.info(
                    "planner: gang trial committed as the real plan "
                    "(no gang excluded; second pass skipped)"
                )
                if span is not None:
                    span.set_attributes(
                        gang_trial_reused=True,
                        totals_calls=trial_tracker.totals_calls,
                        totals_recomputes=trial_tracker.totals_recomputes,
                        totals_incremental=trial_tracker.totals_calls
                        - trial_tracker.totals_recomputes,
                    )
                return snapshot.partitioning_state()
            snapshot.revert()
        excluded_reasons: Dict[str, str] = {}
        if excluded:
            log.info(
                "planner: gangs %s cannot fully form; excluding their pods",
                sorted(excluded),
            )
            excluded_reasons = {
                p.namespaced_name: (
                    f"gang {(_gang_of(p) or ('?',))[0]} cannot fully form; "
                    "no slices are carved for partial gangs"
                )
                for p in candidates
                if (_gang_of(p) or (None,))[0] in excluded
            }
            candidates = [
                p for p in candidates
                if (_gang_of(p) or (None,))[0] not in excluded
            ]
            if not candidates:
                self.last_unserved = excluded_reasons
                return snapshot.partitioning_state()
            tracker = SliceTracker(snapshot, candidates)
            self.last_tracker = tracker
            if tracker.empty:
                self.last_unserved = excluded_reasons
                return snapshot.partitioning_state()

        self._plan_pass(snapshot, tracker, candidates, aged=aged)
        self.last_unserved = self._unserved_reasons(
            tracker, candidates, excluded_reasons
        )
        if span is not None:
            # The recompute-vs-incremental delta for lacking_totals: with
            # the incremental cache, recomputes stay at one per accelerator
            # per pass while calls scale with candidate nodes.
            span.set_attributes(
                totals_calls=tracker.totals_calls,
                totals_recomputes=tracker.totals_recomputes,
                totals_incremental=tracker.totals_calls - tracker.totals_recomputes,
            )
        return snapshot.partitioning_state()

    def _plan_pass(
        self,
        snapshot: ClusterSnapshot,
        tracker: SliceTracker,
        candidates: List[Pod],
        quiet: bool = False,
        aged: "set | None" = None,
    ) -> List[Pod]:
        self._ensure_plan_caches(snapshot)
        placed: List[Pod] = []
        # Aged-rescue pass, BEFORE anyone claims free slices: a starved
        # pod the fairness aging promoted gets a carve aimed at exactly
        # its profile while contested free regions are still free. Sort
        # order is the entitlement order — running this first means an
        # aged 1-chip pod converts the free 2x2 an exact-fit 4-chip pod
        # would otherwise claim, and THAT pod waits a round instead
        # (the inversion aging exists to produce).
        #
        # ONE successful rescue per plan: each conversion fragments a free
        # region only smaller profiles can reuse, so batching several per
        # round costs utilization; plans run every batch window and the
        # queue drains one aged pod per round. Failed attempts don't
        # consume the budget (an unrescuable aged pod must not block the
        # rescuable one behind it) but are capped to bound fork work.
        rescued = attempts = 0
        for pod in candidates:
            if not aged or rescued >= 1 or attempts >= 3:
                break
            if pod.namespaced_name not in aged or pod not in tracker:
                continue
            attempts += 1
            for node_name in self._candidate_nodes(snapshot):
                # Read-only access (get_node would journal under a fork);
                # the version read pins the futility-memo key PRE-fork.
                node = snapshot.get_nodes()[node_name]
                accelerator = getattr(node.partitionable, "accelerator", "")
                lacking = tracker.lacking_for(pod, accelerator)
                futility_key = (
                    node_name,
                    node.version,
                    tuple(sorted(lacking.items())),
                )
                if (
                    self.futility_memo_enabled
                    and futility_key in self._futility_cache
                ):
                    self._futility_hits += 1
                    continue
                stats_before = self._verdict_cache.stats()
                with TRACER.span(
                    "plan.trial", node=node_name, rescue=True
                ) as trial:
                    snapshot.fork()
                    if not snapshot.update_geometry_for(node_name, lacking):
                        if self.futility_memo_enabled:
                            self._futility_cache[futility_key] = (
                                self._lacking_reason(lacking)
                            )
                        trial.set_attributes(
                            committed=False,
                            nodes_copied=snapshot.revert(),
                            **self._trial_cache_delta(stats_before),
                        )
                        continue
                    if self._try_add_pod(snapshot, node_name, pod):
                        tracker.remove(pod)
                        placed.append(pod)
                        rescued += 1
                        trial.set_attributes(
                            committed=True,
                            nodes_copied=snapshot.commit(),
                            **self._trial_cache_delta(stats_before),
                        )
                        if not quiet:
                            log.info(
                                "planner: node %s re-carved (aged rescue) for %s",
                                node_name,
                                pod.namespaced_name,
                            )
                        break
                    trial.set_attributes(
                        committed=False,
                        nodes_copied=snapshot.revert(),
                        **self._trial_cache_delta(stats_before),
                    )

        # Claim pre-pass (TPU-first addition, no reference analogue): pods
        # that existing free slices fully serve will bind onto them without
        # any carve — place them in the snapshot FIRST, so the carve loop
        # below sees their slices as used and can never destroy a free
        # slice a pending pod is entitled to. Without this, a freed full
        # board gets fragmented for small lack while the full-board pod
        # about to bind there goes back to waiting for a drain.
        for pod in candidates:
            if pod in tracker:
                continue
            claims_slices = self._claims_free_slices(pod)
            for node_name in self._candidate_nodes(snapshot):
                # Exhausted nodes sort FIRST in best-fit order (0 free
                # chips) yet can never serve a slice-consuming claim —
                # skipping them here is add_pod's exact no-fit
                # precondition, not a heuristic, and avoids running the
                # simulation against nodes with nothing left to give.
                if claims_slices and not snapshot.node_has_free_slices(node_name):
                    continue
                if self._try_add_pod(snapshot, node_name, pod):
                    placed.append(pod)
                    break
        for node_name in self._candidate_nodes(snapshot):
            if tracker.empty:
                break
            node = snapshot.get_nodes()[node_name]
            accelerator = getattr(node.partitionable, "accelerator", "")
            lacking = tracker.lacking_totals(accelerator)
            futility_key = (
                node_name,
                node.version,
                tuple(sorted(lacking.items())),
            )
            if self.futility_memo_enabled and futility_key in self._futility_cache:
                self._futility_hits += 1
                continue
            stats_before = self._verdict_cache.stats()
            with TRACER.span("plan.trial", node=node_name) as trial:
                snapshot.fork()
                changed = snapshot.update_geometry_for(node_name, lacking)
                if not changed:
                    if self.futility_memo_enabled:
                        self._futility_cache[futility_key] = (
                            self._lacking_reason(lacking)
                        )
                    trial.set_attributes(
                        committed=False,
                        nodes_copied=snapshot.revert(),
                        **self._trial_cache_delta(stats_before),
                    )
                    continue
                added_any = False
                placed_here = 0
                for pod in candidates:
                    if pod not in tracker:
                        continue
                    if self._try_add_pod(snapshot, node_name, pod):
                        tracker.remove(pod)
                        placed.append(pod)
                        added_any = True
                        placed_here += 1
                if added_any:
                    trial.set_attributes(
                        committed=True,
                        pods_placed=placed_here,
                        nodes_copied=snapshot.commit(),
                        **self._trial_cache_delta(stats_before),
                    )
                    if not quiet:
                        log.info(
                            "planner: node %s re-carved for pending pods", node_name
                        )
                else:
                    trial.set_attributes(
                        committed=False,
                        nodes_copied=snapshot.revert(),
                        **self._trial_cache_delta(stats_before),
                    )

        return placed

    @staticmethod
    def _lacking_reason(lacking: dict) -> str:
        """Canonical human-readable form of a lacking profile — the ONE
        formatter behind both the futility-memo reason strings and the
        per-pod unserved reasons, so CarveFailed Events and memoized
        verdicts read identically for the same profile."""
        profile = ", ".join(
            f"{int(qty)}x {name}" for name, qty in sorted(lacking.items())
        )
        return f"no node can be re-carved to yield lacking slices ({profile})"

    def _unserved_reasons(
        self,
        tracker: SliceTracker,
        candidates: List[Pod],
        extra: "Optional[Dict[str, str]]" = None,
    ) -> Dict[str, str]:
        """namespaced_name -> reason for every candidate the pass left in
        the tracker, merged over `extra` (gang-exclusion reasons)."""
        out: Dict[str, str] = dict(extra or {})
        for pod in candidates:
            if pod in tracker:
                out[pod.namespaced_name] = self._lacking_reason(
                    tracker.lacking_for(pod)
                )
        return out

    @staticmethod
    def _gang_membership(
        snapshot: ClusterSnapshot, candidates: List[Pod]
    ) -> Tuple[dict, dict]:
        """(gang key -> declared size, gang key -> bound-member count) over
        the snapshot as it stands NOW — callers take it before forking the
        gang trial so trial placements can't double as bound members."""
        sizes: dict = {}
        for pod in candidates:
            gang = _gang_of(pod)
            if gang:
                sizes[gang[0]] = gang[1]
        bound_count: dict = {}
        if sizes:
            # ALL nodes, not just carve candidates: a member running on a
            # fully-carved node still counts toward gang completeness.
            for snap_node in snapshot.get_nodes().values():
                for pod in snap_node.pods:
                    gang = _gang_of(pod)
                    if gang:
                        bound_count[gang[0]] = bound_count.get(gang[0], 0) + 1
        return sizes, bound_count

    @staticmethod
    def _half_formable_gangs(
        sizes: dict, bound_count: dict, trial_placed: List[Pod]
    ) -> set:
        """Gang keys whose running + trial-placed membership < size."""
        if not sizes:
            return set()
        placed_count: dict = {}
        for pod in trial_placed:
            gang = _gang_of(pod)
            if gang:
                placed_count[gang[0]] = placed_count.get(gang[0], 0) + 1
        return {
            key
            for key, size in sizes.items()
            if bound_count.get(key, 0) + placed_count.get(key, 0) < size
        }

    # ------------------------------------------------------------------

    def _candidate_nodes(self, snapshot: ClusterSnapshot) -> List[str]:
        """get_candidate_nodes, memoized on state_version: the best-fit
        order is a full free-chips sort, and the claim pre-pass asks once
        per pod while placing nothing most of the time."""
        cached = self._candidate_cache
        if cached is not None and cached[0] == snapshot.state_version:
            return cached[1]
        names = snapshot.get_candidate_nodes()
        self._candidate_cache = (snapshot.state_version, names)
        return names

    def _request_signature(self, pod: Pod) -> tuple:
        entry = self._request_cache.get(id(pod))
        if entry is None:
            entry = (pod, tuple(sorted(res.compute_pod_request(pod).items())))
            self._request_cache[id(pod)] = entry
        return entry[1]

    def _claims_free_slices(self, pod: Pod) -> bool:
        """Whether binding this pod must consume a free slice: it names a
        partitionable resource (plain chips, a slice, or a shared slice).
        Such a pod cannot fit a node with no free slices — add_pod either
        takes a free slice or returns False — so the claim pre-pass skips
        exhausted nodes for it. Pods with no partitionable request
        trivially fit anywhere and keep the original probe order."""
        for name, qty in self._request_signature(pod):
            if not qty:
                continue
            if (
                name == constants.RESOURCE_TPU
                or constants.is_tpu_slice_resource(name)
                or constants.is_tpu_shared_resource(name)
            ):
                return True
        return False

    def _has_lacking(self, snapshot: ClusterSnapshot, pod: Pod) -> bool:
        """bool(get_lacking_slices), memoized on (request signature,
        state_version) — the shortcut runs per (pod, node) trial and the
        batch holds few distinct request shapes, so most calls repeat."""
        key = (self._request_signature(pod), snapshot.state_version)
        lacking = self._lacking_cache.get(key)
        if lacking is None:
            lacking = bool(snapshot.get_lacking_slices(pod))
            self._lacking_cache[key] = lacking
        return lacking

    def _try_add_pod(self, snapshot: ClusterSnapshot, node_name: str, pod: Pod) -> bool:
        self._ensure_plan_caches(snapshot)
        # Cheap shortcut: if the cluster still lacks slices for this pod,
        # no point running the scheduler simulation (planner.go:155-175).
        if self._has_lacking(snapshot, pod):
            return False
        if not self._can_schedule(snapshot, node_name, pod):
            return False
        return snapshot.add_pod(node_name, pod)

    def _can_schedule(self, snapshot: ClusterSnapshot, node_name: str, pod: Pod) -> bool:
        """Run the real scheduler plugins against the forked node view
        (planner.go:178-207) so the plan only contains placements the real
        scheduler would accept — through the verdict cache when the trial
        is in a cacheable equivalence class."""
        self._ensure_plan_caches(snapshot)
        # Read-only node access: get_node() would journal (clone) the node
        # under an active fork, but the simulation never mutates it — any
        # actual mutation goes through snapshot.add_pod, which journals.
        node = snapshot.get_nodes()[node_name]
        accelerator = getattr(node.partitionable, "accelerator", "")
        sim_pod, signature, wants_context = self._simulation_pod(
            snapshot, pod, accelerator
        )
        # Cross-node context means no single-node cache key is sound: the
        # pod's own spread/affinity terms, or ANY placed pod with required
        # anti-affinity (symmetric terms reject incoming pods). This
        # condition also covers every cross-node read the cacheable
        # in-tree plugins can perform — that is the bypass contract their
        # verdict_cacheable marks rely on.
        bypass = wants_context or snapshot.has_anti_affinity_pods()
        if not self.verdict_cache_enabled:
            return self._run_simulation(snapshot, node, sim_pod, publish=bypass)
        if bypass:
            self._verdict_cache.bypasses += 1
            return self._run_simulation(snapshot, node, sim_pod, publish=True)
        key = (signature, node_name, node.version)
        verdict = self._verdict_cache.get(key)
        if verdict is None:
            verdict = self._run_simulation(
                snapshot,
                node,
                sim_pod,
                publish=False,
                pre=self._cacheable_pre,
                filters=self._cacheable_filters,
            )
            self._verdict_cache.put(key, verdict)
        if not verdict:
            return False
        # Plugins that never opted in (external-store readers) get a
        # fresh run on every trial; their verdict ANDs with the cached
        # conjunction, so the split never changes the boolean outcome.
        if not self._uncacheable_pre and not self._uncacheable_filters:
            return True
        return self._run_simulation(
            snapshot,
            node,
            sim_pod,
            publish=False,
            pre=self._uncacheable_pre,
            filters=self._uncacheable_filters,
        )

    def _run_simulation(
        self,
        snapshot: ClusterSnapshot,
        node: SnapshotNode,
        sim_pod: Pod,
        publish: bool,
        pre: "Optional[list]" = None,
        filters: "Optional[list]" = None,
    ) -> bool:
        """One PreFilter+Filter chain run (full chains when pre/filters are
        None, else the given subsets) against the node's simulated view."""
        state = CycleState()
        if publish:
            # Cross-node context for the topology-spread predicate,
            # published the same way the real cycle does (cached on the
            # snapshot across trials). Scope caveat: the snapshot holds
            # only partitionable nodes (mirroring the reference's
            # ClusterState, which caches only partitioning-labeled nodes),
            # so spread domains that exist purely on non-TPU nodes are
            # invisible to the simulation — the real scheduler still
            # enforces them at bind time.
            state[TOPOLOGY_NODE_INFOS_KEY] = snapshot.sim_node_infos()
        # The simulation runs the framework once per (pod, node) trial —
        # thousands of times per plan — so per-plugin spans are suppressed
        # here; the plan.trial spans carry the aggregate story.
        with TRACER.suppress_plugins():
            status = self.framework.run_pre_filter_plugins(state, sim_pod, plugins=pre)
            if not status.success:
                return False
            status = self.framework.run_filter_plugins(
                state, sim_pod, self._node_info(node), plugins=filters
            )
            return status.success

    def _node_info(self, node: SnapshotNode) -> NodeInfo:
        """node.sim_node_info() memoized on (name, version): the sim view
        deepcopies the kube Node, and an untouched (or reverted-back) node
        serves every trial from one view. Plugins treat NodeInfo as
        read-only on the filter path, so sharing is safe."""
        key = (node.name, node.version)
        info = self._node_info_cache.get(key)
        if info is None:
            info = node.sim_node_info()
            self._node_info_cache[key] = info
        return info

    def _simulation_pod(
        self, snapshot: ClusterSnapshot, pod: Pod, accelerator: str
    ) -> Tuple[Pod, tuple, bool]:
        """(sim pod, verdict-cache signature, needs-cross-node-context) —
        the pod with its TPU request normalized to the candidate node's own
        generation, matching the slice-denominated allocatable of the
        simulated node view. Cached per (pod, generation) across the many
        node trials of one plan() call."""
        key = (id(pod), accelerator)
        cached = self._sim_pod_cache.get(key)
        if cached is not None:
            return cached[1], cached[2], cached[3]
        sim = pod.deepcopy()
        for container in sim.spec.containers:
            container.requests = snapshot.normalize_request(container.requests, accelerator)
        for container in sim.spec.init_containers:
            container.requests = snapshot.normalize_request(container.requests, accelerator)
        entry = (pod, sim, pod_signature(sim), needs_cluster_context(sim))
        self._sim_pod_cache[key] = entry
        return entry[1], entry[2], entry[3]
