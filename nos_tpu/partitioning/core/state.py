"""ClusterState: the partitioner's mutex-guarded cache of cluster topology.

Reference internal/partitioning/state/state.go:29-222: NodeInfo per node,
pod→node bindings, and a count of nodes per partitioning kind so controllers
can cheaply check whether a mode is enabled at all
(partitioner_controller.go:83 IsPartitioningEnabled).

Two read paths: ``get_node``/``get_nodes`` hand out deepcopies the caller
may mutate freely, while ``read_view`` is the copy-on-read path for the
snapshot takers — it copies only the containers (dict + pod lists) and
shares the Node/Pod objects, which is safe because the state never mutates
a stored object in place (updates replace whole objects; ``remove_pod``
rebinds the list). One reconcile no longer deepcopies the whole cluster.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set

from nos_tpu.api.v1alpha1 import labels as labels_api
from nos_tpu.kube.objects import Node, Pod
from nos_tpu.scheduler.framework import NodeInfo


class ClusterState:
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._nodes: Dict[str, NodeInfo] = {}
        self._bindings: Dict[str, str] = {}  # "ns/name" -> node name
        # node name -> pod keys bound there; the reverse of _bindings, so
        # node deletion is O(pods on that node) instead of a rebuild of
        # the whole bindings dict.
        self._node_pods: Dict[str, Set[str]] = {}
        self._kind_counts: Dict[str, int] = {}

    # ------------------------------------------------------------ updates

    def _bind(self, key: str, node_name: str) -> None:
        previous = self._bindings.get(key)
        if previous is not None and previous != node_name:
            self._node_pods.get(previous, set()).discard(key)
        self._bindings[key] = node_name
        self._node_pods.setdefault(node_name, set()).add(key)

    def _unbind(self, key: str) -> Optional[str]:
        node_name = self._bindings.pop(key, None)
        if node_name is not None:
            self._node_pods.get(node_name, set()).discard(key)
        return node_name

    def update_node(self, node: Node, pods: List[Pod]) -> None:
        with self._lock:
            old = self._nodes.get(node.metadata.name)
            if old is not None:
                self._remove_kind(old.node)
            info = NodeInfo(node=node.deepcopy())
            for pod in pods:
                info.add_pod(pod.deepcopy())
                self._bind(pod.namespaced_name, node.metadata.name)
            self._nodes[node.metadata.name] = info
            self._add_kind(node)

    def delete_node(self, node_name: str) -> None:
        with self._lock:
            info = self._nodes.pop(node_name, None)
            if info is None:
                return
            self._remove_kind(info.node)
            for key in self._node_pods.pop(node_name, set()):
                self._bindings.pop(key, None)

    def update_pod_usage(self, pod: Pod) -> None:
        """Track a pod's binding on node events (reference
        gpupartitioner/pod_controller.go:47-112 UpdateUsage)."""
        with self._lock:
            key = pod.namespaced_name
            node_name = pod.spec.node_name
            previous = self._bindings.get(key)
            if previous and previous != node_name and previous in self._nodes:
                self._nodes[previous].remove_pod(pod)
                self._unbind(key)
            if not node_name or node_name not in self._nodes:
                return
            info = self._nodes[node_name]
            info.remove_pod(pod)  # replace stale copy
            if pod.status.phase in ("Succeeded", "Failed"):
                self._unbind(key)
                return
            info.add_pod(pod.deepcopy())
            self._bind(key, node_name)

    def delete_pod(self, pod: Pod) -> None:
        with self._lock:
            node_name = self._unbind(pod.namespaced_name)
            if node_name and node_name in self._nodes:
                self._nodes[node_name].remove_pod(pod)

    # ------------------------------------------------------------ queries

    def get_node(self, name: str) -> Optional[NodeInfo]:
        with self._lock:
            info = self._nodes.get(name)
            if info is None:
                return None
            return NodeInfo(node=info.node.deepcopy(), pods=[p.deepcopy() for p in info.pods])

    def get_nodes(self) -> Dict[str, NodeInfo]:
        with self._lock:
            return {
                name: NodeInfo(
                    node=info.node.deepcopy(), pods=[p.deepcopy() for p in info.pods]
                )
                for name, info in self._nodes.items()
            }

    def read_view(self) -> Dict[str, NodeInfo]:
        """Point-in-time READ-ONLY view sharing the stored Node/Pod objects
        (containers copied under the lock). Consumers must not mutate the
        objects — the snapshot takers qualify: TpuNode/SharingNode with
        ``owned=True`` never write through to the kube Node, and
        ``to_sim_node`` deepcopies before rewriting allocatable."""
        with self._lock:
            return {
                name: NodeInfo(node=info.node, pods=list(info.pods))
                for name, info in self._nodes.items()
            }

    def is_partitioning_enabled(self, kind: str) -> bool:
        with self._lock:
            if self._kind_counts.get(kind, 0) > 0:
                return True
            # Hybrid nodes participate in both the tpu and sharing passes.
            if kind in (
                labels_api.PartitioningKind.TPU,
                labels_api.PartitioningKind.SHARING,
            ):
                return self._kind_counts.get(labels_api.PartitioningKind.HYBRID, 0) > 0
            return False

    # ------------------------------------------------------------ helpers

    def _add_kind(self, node: Node) -> None:
        kind = labels_api.partitioning_kind(node)
        if kind:
            self._kind_counts[kind] = self._kind_counts.get(kind, 0) + 1

    def _remove_kind(self, node: Node) -> None:
        kind = labels_api.partitioning_kind(node)
        if kind and self._kind_counts.get(kind, 0) > 0:
            self._kind_counts[kind] -= 1
