"""Sharing snapshot taker: ClusterState → snapshot of sharing-labeled nodes.

Counterpart of the MPS snapshot taker (reference
internal/partitioning/mps/snapshot_taker.go): nodes labeled
``nos.nebuly.com/gpu-partitioning=sharing`` become SharingNodes and the
snapshot speaks the shared-resource codec.
"""
from __future__ import annotations

from typing import Dict

from nos_tpu.api.v1alpha1.labels import is_sharing_partitioning_enabled
from nos_tpu.partitioning.core.codec import SharedSliceCodec
from nos_tpu.partitioning.core.snapshot import ClusterSnapshot, SnapshotNode
from nos_tpu.partitioning.core.state import ClusterState
from nos_tpu.tpu.sharing import SharingNode


class SharingSnapshotTaker:
    def take_snapshot_node(self, node, pods) -> "SnapshotNode | None":
        """One node's snapshot entry, or None when the node is outside
        this taker's scope — shared by the full take and the incremental
        per-node refresh path."""
        from nos_tpu.partitioning.tpu.snapshot_taker import _plan_in_flight

        if not is_sharing_partitioning_enabled(node):
            return None
        sharing_node = SharingNode(node, owned=True)
        if not sharing_node.is_sharing_node:
            return None
        return SnapshotNode(
            partitionable=sharing_node,
            pods=list(pods),
            frozen=_plan_in_flight(node),
        )

    def take_snapshot(self, state: ClusterState, store=None) -> ClusterSnapshot:
        from nos_tpu.partitioning.tpu.snapshot_taker import live_cluster_view

        if store is not None:
            view = live_cluster_view(store)
        else:
            # Copy-on-read path — see TpuSnapshotTaker.
            view = {
                name: (info.node, list(info.pods))
                for name, info in state.read_view().items()
            }
        nodes: Dict[str, SnapshotNode] = {}
        for name, (node, pods) in view.items():
            snap_node = self.take_snapshot_node(node, pods)
            if snap_node is not None:
                nodes[name] = snap_node
        return ClusterSnapshot(nodes, codec=SharedSliceCodec())
