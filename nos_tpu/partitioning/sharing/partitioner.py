"""Sharing partitioner: actuation = device-plugin ConfigMap + label flip.

The second actuation style of the reference (MPS,
internal/partitioning/mps/partitioner.go:61-157): instead of asking a
node-local agent to re-carve silicon, the control plane renders the desired
sharing layout into the TPU device plugin's ConfigMap under the key
``<node>-<planId>``, waits for ConfigMap propagation, then points the node
at the new config via the ``google.com/tpu-device-plugin.config`` label.
The device plugin re-registers, exposing the ``google.com/tpu-mem-<N>gb``
replica resources; the node-local sharingagent only reports.
"""
from __future__ import annotations

import json
import logging
import time
from typing import Optional

from nos_tpu.api.v1alpha1 import constants
from nos_tpu.api.v1alpha1.labels import TPU_DEVICE_PLUGIN_CONFIG_LABEL
from nos_tpu.kube.objects import ConfigMap, ObjectMeta
from nos_tpu.kube.store import KubeStore, NotFoundError
from nos_tpu.partitioning.core.partition_state import NodePartitioning

log = logging.getLogger("nos_tpu.partitioning.sharing")

PLUGIN_CONFIG_VERSION = "v1"


def plugin_config_from_partitioning(partitioning: NodePartitioning) -> dict:
    """Render a NodePartitioning as the TPU device plugin's sharing config
    (the analogue of ToPluginConfig, mps/partitioner.go:123-157): one
    replicated-resource entry per (chip, profile), each fraction renamed to
    its HBM-denominated resource and capped at one per container."""
    resources = []
    for board in partitioning.boards:
        for resource, qty in sorted(board.resources.items()):
            if not constants.is_tpu_shared_resource(resource) or qty <= 0:
                continue
            profile = constants.tpu_shared_profile(resource)
            resources.append(
                {
                    "name": constants.RESOURCE_TPU,
                    "rename": resource,
                    "memory_gb": constants.shared_profile_gb(profile),
                    "chips": [board.board_index],
                    "replicas": int(qty),
                }
            )
    return {
        "version": PLUGIN_CONFIG_VERSION,
        "sharing": {
            "fail_requests_greater_than_one": True,
            "resources": resources,
        },
    }


class SharingPartitioner:
    def __init__(
        self,
        store: KubeStore,
        config_map_name: str = "nos-device-plugin-config",
        config_map_namespace: str = "",
        device_plugin_delay_seconds: float = 0.0,
    ) -> None:
        self.store = store
        self.config_map_name = config_map_name
        self.config_map_namespace = config_map_namespace
        self.delay = device_plugin_delay_seconds

    def apply_partitioning(
        self, node_name: str, plan_id: str, partitioning: NodePartitioning
    ) -> None:
        key = f"{node_name}-{plan_id}"
        config = plugin_config_from_partitioning(partitioning)
        # The node's current label names exactly the key it owns — the only
        # safe stale-entry identification (prefix matching would also hit
        # node "a-b" keys while cleaning node "a").
        superseded: Optional[str] = None
        node = self.store.try_get("Node", node_name)
        if node is not None:
            superseded = node.metadata.labels.get(TPU_DEVICE_PLUGIN_CONFIG_LABEL)
        self._write_config(key, config, superseded)

        if self.delay > 0:
            # ConfigMap content propagates to kubelet volumes asynchronously;
            # flipping the label too early would restart the plugin against
            # the previous content (mps/partitioner.go:98-100).
            time.sleep(self.delay)

        try:
            self.store.patch_labels(
                "Node", node_name, "", {TPU_DEVICE_PLUGIN_CONFIG_LABEL: key}
            )
        except NotFoundError:
            log.warning("apply_partitioning: node %s vanished", node_name)
            return
        log.info(
            "apply_partitioning: node %s plan %s -> %d shared resources",
            node_name,
            plan_id,
            len(config["sharing"]["resources"]),
        )

    # ------------------------------------------------------------------

    def _write_config(
        self, key: str, config: dict, superseded: Optional[str]
    ) -> None:
        payload = json.dumps(config, sort_keys=True)
        existing = self.store.try_get(
            "ConfigMap", self.config_map_name, self.config_map_namespace
        )
        if existing is None:
            self.store.create(
                ConfigMap(
                    metadata=ObjectMeta(
                        name=self.config_map_name,
                        namespace=self.config_map_namespace,
                    ),
                    data={key: payload},
                )
            )
            return

        def mutate(cm: ConfigMap) -> None:
            # One live config per node: retire the entry the node's label
            # currently points at, atomically with adding the new one (the
            # plugin treats an unresolvable key as keep-last-state, so this
            # window is benign).
            if superseded and superseded != key:
                cm.data.pop(superseded, None)
            cm.data[key] = payload

        self.store.patch_merge(
            "ConfigMap", self.config_map_name, self.config_map_namespace, mutate
        )
