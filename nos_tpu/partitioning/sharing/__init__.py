from nos_tpu.partitioning.sharing.partitioner import (
    SharingPartitioner,
    plugin_config_from_partitioning,
)
from nos_tpu.partitioning.sharing.snapshot_taker import SharingSnapshotTaker

__all__ = [
    "SharingPartitioner",
    "SharingSnapshotTaker",
    "plugin_config_from_partitioning",
]
