"""Dynamic partitioning engine.

Mirror of reference internal/partitioning/ (SURVEY.md §2.2): a mode-agnostic
core (Planner / Actuator / Snapshot / SliceTracker / ClusterState) bound to
concrete strategies (tpu here; the reference's mig/mps actuation styles both
fit the same Partitioner seam).
"""
