"""Virgin-node initializer: first-contact geometry for new TPU nodes.

Reference internal/partitioning/mig/initializer.go:36-87 + node controller
hook (gpupartitioner/node_controller.go:89-95): a node that just opted into
partitioning and reports no geometry gets the fewest-slices allowed
geometry (whole-board slices for TPUs) so its resources become visible to
the scheduler immediately.
"""
from __future__ import annotations

import logging

from nos_tpu.api.v1alpha1 import constants
from nos_tpu.kube.objects import Node
from nos_tpu.partitioning.core.partition_state import (
    BoardPartitioning,
    NodePartitioning,
)
from nos_tpu.partitioning.tpu.partitioner import TpuPartitioner
from nos_tpu.tpu.node import TpuNode

log = logging.getLogger("nos_tpu.partitioning.tpu")


class TpuNodeInitializer:
    def __init__(self, partitioner: TpuPartitioner, plan_id_fn) -> None:
        self.partitioner = partitioner
        self.plan_id_fn = plan_id_fn

    def is_initialized(self, node: Node) -> bool:
        """A node is initialized once any spec/status geometry exists
        (reference core/util.go:76)."""
        from nos_tpu.api.v1alpha1 import annotations as annot

        spec, status = annot.parse_node_annotations(node.metadata.annotations)
        return bool(spec or status)

    def init_node_partitioning(self, node: Node) -> bool:
        tpu_node = TpuNode(node)
        if not tpu_node.is_tpu_node:
            return False
        boards = []
        changed = False
        for board in tpu_node.boards:
            if board.init_geometry():
                changed = True
            boards.append(
                BoardPartitioning(
                    board_index=board.index,
                    resources={
                        constants.tpu_slice_resource(p): q
                        for p, q in board.geometry.items()
                    },
                )
            )
        if not changed:
            return False
        self.partitioner.apply_partitioning(
            node.metadata.name, self.plan_id_fn(), NodePartitioning(boards=boards)
        )
        log.info("initialized TPU node %s", node.metadata.name)
        return True
