"""TPU snapshot taker: ClusterState → ClusterSnapshot of TPU-managed nodes.

Reference internal/partitioning/mig/snapshot_taker.go:31-53 (snapshot only
MIG-labeled nodes, building mig.Node from annotations); here nodes labeled
``nos.nebuly.com/gpu-partitioning=tpu`` become TpuNodes.
"""
from __future__ import annotations

from typing import Dict

from nos_tpu.api.v1alpha1.labels import is_tpu_partitioning_enabled
from nos_tpu.partitioning.core.snapshot import ClusterSnapshot, SnapshotNode
from nos_tpu.partitioning.core.state import ClusterState
from nos_tpu.tpu.node import TpuNode


class TpuSnapshotTaker:
    def take_snapshot(self, state: ClusterState) -> ClusterSnapshot:
        nodes: Dict[str, SnapshotNode] = {}
        for name, info in state.get_nodes().items():
            if not is_tpu_partitioning_enabled(info.node):
                continue
            tpu_node = TpuNode(info.node, owned=True)
            if not tpu_node.is_tpu_node:
                continue
            # Plan against live pod bindings, not the reporter's (possibly
            # stale) used/free split — see rebuild_usage_from_pods.
            tpu_node.rebuild_usage_from_pods(info.pods)
            nodes[name] = SnapshotNode(partitionable=tpu_node, pods=list(info.pods))
        return ClusterSnapshot(nodes)
