"""TPU snapshot taker: ClusterState → ClusterSnapshot of TPU-managed nodes.

Reference internal/partitioning/mig/snapshot_taker.go:31-53 (snapshot only
MIG-labeled nodes, building mig.Node from annotations); here nodes labeled
``nos.nebuly.com/gpu-partitioning=tpu`` become TpuNodes.
"""
from __future__ import annotations

from typing import Dict

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1.labels import is_tpu_partitioning_enabled
from nos_tpu.partitioning.core.snapshot import ClusterSnapshot, SnapshotNode
from nos_tpu.partitioning.core.state import ClusterState
from nos_tpu.tpu.node import TpuNode


def _plan_in_flight(node) -> bool:
    """True while the node's agent has not acknowledged the current spec
    plan — its geometry is mid-change and must not be re-carved (per-node
    form of the reference's global gate, partitioner_controller.go:118)."""
    ann = node.metadata.annotations
    spec_plan = ann.get(annot.SPEC_PARTITIONING_PLAN)
    return bool(spec_plan) and spec_plan != ann.get(
        annot.STATUS_PARTITIONING_PLAN
    )


def live_cluster_view(store) -> "Dict[str, tuple]":
    """node name -> (node, [bound pods]) straight from the store.

    The reference snapshots its informer cache, which IS the live store
    (client-go shared informers). Our ClusterState is a separately-updated
    copy, so planning from it adds a staleness window the reference never
    had — plans computed there race fresh binds and get clamped by the
    agent. Planning from the store closes the window.

    Read without copying: the store replaces objects on every write and
    never mutates them in place, and the planning pipeline treats them as
    read-only (owned nodes deepcopy before any rewrite), so the per-
    reconcile deepcopy of every Node and Pod is pure waste."""
    out: Dict[str, tuple] = {}
    for node in store.list("Node", copy=False):
        out[node.metadata.name] = (node, [])
    for pod in store.list("Pod", copy=False):
        if pod.spec.node_name in out and pod.status.phase in ("Pending", "Running"):
            out[pod.spec.node_name][1].append(pod)
    return out


class TpuSnapshotTaker:
    def take_snapshot_node(self, node, pods) -> "SnapshotNode | None":
        """One node's snapshot entry, or None when the node is outside
        this taker's scope (not TPU-partitioning-labeled, or not a TPU
        node). Shared by the full take and the incremental per-node
        refresh path, so both build bit-identical SnapshotNodes."""
        if not is_tpu_partitioning_enabled(node):
            return None
        tpu_node = TpuNode(node, owned=True)
        if not tpu_node.is_tpu_node:
            return None
        # Plan against live pod bindings, not the reporter's (possibly
        # stale) used/free split — see rebuild_usage_from_pods.
        tpu_node.rebuild_usage_from_pods(pods)
        return SnapshotNode(
            partitionable=tpu_node,
            pods=list(pods),
            frozen=_plan_in_flight(node),
        )

    def take_snapshot(self, state: ClusterState, store=None) -> ClusterSnapshot:
        if store is not None:
            view = live_cluster_view(store)
        else:
            # Copy-on-read: shares the state's Node/Pod objects; this
            # pipeline only reads them (owned TpuNodes deepcopy before any
            # node rewrite), so deepcopying the cluster per reconcile is
            # pure waste.
            view = {
                name: (info.node, list(info.pods))
                for name, info in state.read_view().items()
            }
        nodes: Dict[str, SnapshotNode] = {}
        for name, (node, pods) in view.items():
            snap_node = self.take_snapshot_node(node, pods)
            if snap_node is not None:
                nodes[name] = snap_node
        return ClusterSnapshot(nodes)
