from nos_tpu.partitioning.tpu.snapshot_taker import TpuSnapshotTaker
from nos_tpu.partitioning.tpu.partitioner import TpuPartitioner
from nos_tpu.partitioning.tpu.initializer import TpuNodeInitializer

__all__ = ["TpuNodeInitializer", "TpuPartitioner", "TpuSnapshotTaker"]
