"""TPU partitioner: actuation = writing spec annotations + plan id to Nodes.

Reference internal/partitioning/mig/partitioner.go:43-94: ApplyPartitioning
patches the Node with nos.nebuly.com/spec-gpu-* annotations and
spec-partitioning-plan=<plan-id>; the node-local agent picks the change up
from its annotation watch. The TPU agent follows the same contract with
spec-tpu-* annotations.
"""
from __future__ import annotations

import logging
from typing import Dict, Optional

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants
from nos_tpu.kube.store import KubeStore, NotFoundError
from nos_tpu.partitioning.core.partition_state import NodePartitioning

log = logging.getLogger("nos_tpu.partitioning.tpu")


class TpuPartitioner:
    def __init__(self, store: KubeStore) -> None:
        self.store = store

    def apply_partitioning(
        self, node_name: str, plan_id: str, partitioning: NodePartitioning
    ) -> None:
        geometries: Dict[int, Dict[str, int]] = {}
        for board in partitioning.boards:
            profile_counts: Dict[str, int] = {}
            for resource, qty in board.resources.items():
                if constants.is_tpu_slice_resource(resource) and qty > 0:
                    profile = constants.tpu_slice_topology(resource)
                    profile_counts[profile] = profile_counts.get(profile, 0) + int(qty)
            geometries[board.board_index] = profile_counts

        desired = annot.spec_from_geometries(geometries)
        try:
            node = self.store.get("Node", node_name)
        except NotFoundError:
            log.warning("apply_partitioning: node %s vanished", node_name)
            return
        patch: Dict[str, Optional[str]] = annot.strip_spec_annotations(
            node.metadata.annotations
        )
        patch.update(desired)
        patch[annot.SPEC_PARTITIONING_PLAN] = plan_id
        self.store.patch_annotations("Node", node_name, "", patch)
        log.info(
            "apply_partitioning: node %s plan %s -> %d spec annotations",
            node_name,
            plan_id,
            len(desired),
        )
