"""Wire codecs: typed nos-tpu objects ↔ Kubernetes API JSON.

The reference talks to the apiserver through client-go's generated
(de)serializers; here the same job is done explicitly for the subset of
core/v1, policy/v1 and nos.nebuly.com/v1alpha1 the suite speaks. Every
kind the KubeStore can hold has a ``to_wire``/``from_wire`` pair, so the
API-backed store (nos_tpu/kube/apistore.py) and the in-memory store hold
identical Python objects.

Quantity convention: chips/slices are plain integers; memory-like
resources ("memory", "*-memory", "storage", "ephemeral-storage") are
floats in Gi units — "16Gi" ↔ 16.0. Milli-quantities parse to fractional
floats ("500m" ↔ 0.5).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from nos_tpu.api.v1alpha1.elasticquota import (
    CompositeElasticQuota,
    CompositeElasticQuotaSpec,
    ElasticQuota,
    ElasticQuotaSpec,
    ElasticQuotaStatus,
)
from nos_tpu.api.v1alpha1.modelserving import (
    ModelServing,
    ModelServingSpec,
    ModelServingStatus,
)
from nos_tpu.kube.objects import (
    ConfigMap,
    Event,
    Service,
    ServicePort,
    ServiceSpec,
    Container,
    NodeAffinity,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodCondition,
    PodDisruptionBudget,
    PodDisruptionBudgetSpec,
    PodSpec,
    PodStatus,
    PodAffinityTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)

# kind -> (api prefix, plural, namespaced)
RESOURCES: Dict[str, Tuple[str, str, bool]] = {
    "Pod": ("/api/v1", "pods", True),
    "Node": ("/api/v1", "nodes", False),
    "ConfigMap": ("/api/v1", "configmaps", True),
    "Service": ("/api/v1", "services", True),
    "Event": ("/api/v1", "events", True),
    "PodDisruptionBudget": ("/apis/policy/v1", "poddisruptionbudgets", True),
    "ElasticQuota": ("/apis/nos.nebuly.com/v1alpha1", "elasticquotas", True),
    "CompositeElasticQuota": (
        "/apis/nos.nebuly.com/v1alpha1",
        "compositeelasticquotas",
        True,
    ),
    "ModelServing": ("/apis/nos.nebuly.com/v1alpha1", "modelservings", True),
}

API_VERSIONS: Dict[str, str] = {
    "Pod": "v1",
    "Node": "v1",
    "ConfigMap": "v1",
    "Service": "v1",
    "Event": "v1",
    "PodDisruptionBudget": "policy/v1",
    "ElasticQuota": "nos.nebuly.com/v1alpha1",
    "CompositeElasticQuota": "nos.nebuly.com/v1alpha1",
    "ModelServing": "nos.nebuly.com/v1alpha1",
}


def resource_path(kind: str, namespace: str = "", name: str = "") -> str:
    prefix, plural, namespaced = RESOURCES[kind]
    path = prefix
    if namespaced and namespace:
        path += f"/namespaces/{namespace}"
    path += f"/{plural}"
    if name:
        path += f"/{name}"
    return path


# ----------------------------------------------------------------- quantity

_BINARY = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50}
_DECIMAL = {"n": 1e-9, "u": 1e-6, "m": 1e-3, "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12}


def parse_quantity(value: Any, memory: bool = False) -> float:
    """K8s quantity → float.

    ``memory=True`` normalizes EVERY spelling to Gi units — "16Gi", "1G",
    "16384Mi" and plain-byte integers all land on the same scale, so a pod
    requesting "1G" and a node advertising "16Gi" compare correctly.
    ``memory=False`` (counts: chips, cpu) keeps natural units, with "500m"
    → 0.5."""
    if isinstance(value, (int, float)):
        return float(value) / 2**30 if memory else float(value)
    s = str(value).strip()
    for suffix, mult in _BINARY.items():
        if s.endswith(suffix):
            v = float(s[: -len(suffix)]) * mult
            return v / 2**30 if memory else v
    for suffix, mult in _DECIMAL.items():
        if s.endswith(suffix):
            v = float(s[: -len(suffix)]) * mult
            return v / 2**30 if memory else v
    return float(s) / 2**30 if memory else float(s)


def _memory_like(name: str) -> bool:
    return "memory" in name or "storage" in name


def format_quantity(name: str, value: float) -> str:
    if _memory_like(name):
        if value == int(value):
            return f"{int(value)}Gi"
        return f"{int(value * 1024)}Mi"
    if value == int(value):
        return str(int(value))
    return f"{int(round(value * 1000))}m"


def _resources_from_wire(d: Optional[Dict[str, Any]]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for k, v in (d or {}).items():
        memory = _memory_like(k)
        q = parse_quantity(v, memory=memory)
        # chips/slices stay integral
        out[k] = int(q) if not memory and q == int(q) else q
    return out


def _resources_to_wire(d: Dict[str, float]) -> Dict[str, str]:
    return {k: format_quantity(k, v) for k, v in d.items()}


# ----------------------------------------------------------------- metadata

_RFC3339 = "%Y-%m-%dT%H:%M:%SZ"


def _ts_to_wire(ts: Optional[float]) -> Optional[str]:
    if ts is None:
        return None
    return time.strftime(_RFC3339, time.gmtime(ts))


def _ts_from_wire(s: Optional[str]) -> Optional[float]:
    if not s:
        return None
    try:
        import calendar

        return float(calendar.timegm(time.strptime(s[:19] + "Z", _RFC3339)))
    except ValueError:
        return None


def _rv_from_wire(rv: Any) -> int:
    try:
        return int(rv)
    except (TypeError, ValueError):
        return 0


def meta_to_wire(m: ObjectMeta) -> Dict[str, Any]:
    out: Dict[str, Any] = {"name": m.name}
    if m.namespace:
        out["namespace"] = m.namespace
    if m.uid:
        out["uid"] = m.uid
    if m.labels:
        out["labels"] = dict(m.labels)
    if m.annotations:
        out["annotations"] = dict(m.annotations)
    if m.resource_version:
        out["resourceVersion"] = str(m.resource_version)
    if m.creation_timestamp:
        out["creationTimestamp"] = _ts_to_wire(m.creation_timestamp)
    if m.deletion_timestamp is not None:
        out["deletionTimestamp"] = _ts_to_wire(m.deletion_timestamp)
    if m.owner_references:
        out["ownerReferences"] = [
            {
                "kind": o.kind,
                "name": o.name,
                "uid": o.uid,
                "controller": o.controller,
                # apiVersion is required on the wire; the suite only
                # follows kind/name.
                "apiVersion": "v1",
            }
            for o in m.owner_references
        ]
    return out


def meta_from_wire(d: Dict[str, Any]) -> ObjectMeta:
    return ObjectMeta(
        name=d.get("name", ""),
        namespace=d.get("namespace", ""),
        uid=d.get("uid", ""),
        labels=dict(d.get("labels") or {}),
        annotations=dict(d.get("annotations") or {}),
        creation_timestamp=_ts_from_wire(d.get("creationTimestamp")) or 0.0,
        resource_version=_rv_from_wire(d.get("resourceVersion")),
        owner_references=[
            OwnerReference(
                kind=o.get("kind", ""),
                name=o.get("name", ""),
                uid=o.get("uid", ""),
                controller=bool(o.get("controller", False)),
            )
            for o in d.get("ownerReferences") or []
        ],
        deletion_timestamp=_ts_from_wire(d.get("deletionTimestamp")),
    )


# ---------------------------------------------------------------------- Pod


def _container_to_wire(c: Container) -> Dict[str, Any]:
    out: Dict[str, Any] = {"name": c.name}
    if c.image:
        out["image"] = c.image
    resources: Dict[str, Any] = {}
    if c.requests:
        resources["requests"] = _resources_to_wire(c.requests)
    if c.limits:
        resources["limits"] = _resources_to_wire(c.limits)
    if resources:
        out["resources"] = resources
    if c.env:
        out["env"] = [{"name": k, "value": v} for k, v in sorted(c.env.items())]
    return out


def _container_from_wire(d: Dict[str, Any]) -> Container:
    res = d.get("resources") or {}
    return Container(
        name=d.get("name", "main"),
        image=d.get("image", ""),
        requests=_resources_from_wire(res.get("requests")),
        limits=_resources_from_wire(res.get("limits")),
        env={
            e["name"]: e.get("value", "")
            for e in d.get("env") or []
            if "name" in e and "valueFrom" not in e
        },
    )


def _pod_terms_to_wire(terms: List[PodAffinityTerm]) -> List[Dict[str, Any]]:
    out = []
    for t in terms:
        entry: Dict[str, Any] = {"topologyKey": t.topology_key}
        selector: Dict[str, Any] = {}
        if t.match_labels:
            selector["matchLabels"] = dict(t.match_labels)
        if t.match_expressions:
            selector["matchExpressions"] = [
                {"key": r.key, "operator": r.operator, "values": list(r.values)}
                for r in t.match_expressions
            ]
        if selector:
            entry["labelSelector"] = selector
        if t.namespaces:
            entry["namespaces"] = list(t.namespaces)
        out.append(entry)
    return out


def _pod_terms_from_wire(block: Optional[Dict[str, Any]]) -> List[PodAffinityTerm]:
    terms = (block or {}).get("requiredDuringSchedulingIgnoredDuringExecution") or []
    out = []
    for t in terms:
        selector = t.get("labelSelector") or {}
        match_labels = dict(selector.get("matchLabels") or {})
        match_expressions = [
            NodeSelectorRequirement(
                key=e.get("key", ""),
                operator=e.get("operator", "In"),
                values=list(e.get("values") or []),
            )
            for e in selector.get("matchExpressions") or []
        ]
        if not match_labels and not match_expressions:
            # empty selectors stay nil (match nothing) — dropping keeps the
            # {}-vs-nil hazard contained at ingest like the spread codec
            continue
        out.append(
            PodAffinityTerm(
                topology_key=t.get("topologyKey", ""),
                match_labels=match_labels,
                match_expressions=match_expressions,
                namespaces=list(t.get("namespaces") or []),
            )
        )
    return out


def _affinity_to_wire(
    a: Optional[NodeAffinity],
    pod_affinity: List[PodAffinityTerm] = (),
    pod_anti_affinity: List[PodAffinityTerm] = (),
) -> Optional[Dict[str, Any]]:
    out: Dict[str, Any] = {}
    if a is not None and a.required_terms:
        out["nodeAffinity"] = {
            "requiredDuringSchedulingIgnoredDuringExecution": {
                "nodeSelectorTerms": [
                    {
                        "matchExpressions": [
                            {"key": r.key, "operator": r.operator, "values": list(r.values)}
                            for r in t.match_expressions
                        ]
                    }
                    for t in a.required_terms
                ]
            }
        }
    if pod_affinity:
        out["podAffinity"] = {
            "requiredDuringSchedulingIgnoredDuringExecution": _pod_terms_to_wire(
                pod_affinity
            )
        }
    if pod_anti_affinity:
        out["podAntiAffinity"] = {
            "requiredDuringSchedulingIgnoredDuringExecution": _pod_terms_to_wire(
                pod_anti_affinity
            )
        }
    return out or None


def _affinity_from_wire(d: Optional[Dict[str, Any]]) -> Optional[NodeAffinity]:
    node_aff = (d or {}).get("nodeAffinity") or {}
    required = node_aff.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
    terms = required.get("nodeSelectorTerms") or []
    if not terms:
        return None
    return NodeAffinity(
        required_terms=[
            NodeSelectorTerm(
                match_expressions=[
                    NodeSelectorRequirement(
                        key=e.get("key", ""),
                        operator=e.get("operator", "In"),
                        values=list(e.get("values") or []),
                    )
                    for e in t.get("matchExpressions") or []
                ]
            )
            for t in terms
        ]
    )


def pod_to_wire(pod: Pod) -> Dict[str, Any]:
    spec: Dict[str, Any] = {
        "containers": [_container_to_wire(c) for c in pod.spec.containers],
    }
    if pod.spec.init_containers:
        spec["initContainers"] = [_container_to_wire(c) for c in pod.spec.init_containers]
    if pod.spec.node_name:
        spec["nodeName"] = pod.spec.node_name
    if pod.spec.scheduler_name:
        spec["schedulerName"] = pod.spec.scheduler_name
    if pod.spec.priority:
        spec["priority"] = pod.spec.priority
    if pod.spec.priority_class_name:
        spec["priorityClassName"] = pod.spec.priority_class_name
    if pod.spec.node_selector:
        spec["nodeSelector"] = dict(pod.spec.node_selector)
    if pod.spec.tolerations:
        spec["tolerations"] = [
            {"key": t.key, "operator": t.operator, "value": t.value, "effect": t.effect}
            for t in pod.spec.tolerations
        ]
    aff = _affinity_to_wire(
        pod.spec.affinity, pod.spec.pod_affinity, pod.spec.pod_anti_affinity
    )
    if aff:
        spec["affinity"] = aff
    if pod.spec.topology_spread_constraints:
        spec["topologySpreadConstraints"] = [
            {
                "maxSkew": c.max_skew,
                "topologyKey": c.topology_key,
                "whenUnsatisfiable": c.when_unsatisfiable,
                # Empty selector stays ABSENT on the wire: the k8s API reads
                # labelSelector:{} as match-ALL, the opposite of the
                # nil-selector (match nothing) semantics modeled here.
                **(
                    {"labelSelector": {"matchLabels": dict(c.match_labels)}}
                    if c.match_labels
                    else {}
                ),
            }
            for c in pod.spec.topology_spread_constraints
        ]
    if pod.spec.hostname:
        spec["hostname"] = pod.spec.hostname
    if pod.spec.subdomain:
        spec["subdomain"] = pod.spec.subdomain
    status: Dict[str, Any] = {"phase": pod.status.phase}
    if pod.status.conditions:
        status["conditions"] = [
            {"type": c.type, "status": c.status, "reason": c.reason, "message": c.message}
            for c in pod.status.conditions
        ]
    if pod.status.nominated_node_name:
        status["nominatedNodeName"] = pod.status.nominated_node_name
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": meta_to_wire(pod.metadata),
        "spec": spec,
        "status": status,
    }


def pod_from_wire(d: Dict[str, Any]) -> Pod:
    spec = d.get("spec") or {}
    status = d.get("status") or {}
    return Pod(
        metadata=meta_from_wire(d.get("metadata") or {}),
        spec=PodSpec(
            containers=[_container_from_wire(c) for c in spec.get("containers") or []],
            init_containers=[
                _container_from_wire(c) for c in spec.get("initContainers") or []
            ],
            node_name=spec.get("nodeName", ""),
            scheduler_name=spec.get("schedulerName", "default-scheduler"),
            priority=int(spec.get("priority") or 0),
            priority_class_name=spec.get("priorityClassName", ""),
            tolerations=[
                Toleration(
                    key=t.get("key", ""),
                    operator=t.get("operator", "Equal"),
                    value=t.get("value", ""),
                    effect=t.get("effect", ""),
                )
                for t in spec.get("tolerations") or []
            ],
            node_selector=dict(spec.get("nodeSelector") or {}),
            affinity=_affinity_from_wire(spec.get("affinity")),
            pod_affinity=_pod_terms_from_wire(
                (spec.get("affinity") or {}).get("podAffinity")
            ),
            pod_anti_affinity=_pod_terms_from_wire(
                (spec.get("affinity") or {}).get("podAntiAffinity")
            ),
            topology_spread_constraints=[
                TopologySpreadConstraint(
                    topology_key=c.get("topologyKey", ""),
                    max_skew=int(c.get("maxSkew") or 1),
                    when_unsatisfiable=c.get("whenUnsatisfiable", "DoNotSchedule"),
                    match_labels=dict(
                        (c.get("labelSelector") or {}).get("matchLabels") or {}
                    ),
                )
                for c in spec.get("topologySpreadConstraints") or []
            ],
            hostname=spec.get("hostname", ""),
            subdomain=spec.get("subdomain", ""),
        ),
        status=PodStatus(
            phase=status.get("phase", "Pending"),
            conditions=[
                PodCondition(
                    type=c.get("type", ""),
                    status=c.get("status", ""),
                    reason=c.get("reason", ""),
                    message=c.get("message", ""),
                )
                for c in status.get("conditions") or []
            ],
            nominated_node_name=status.get("nominatedNodeName", ""),
        ),
    )


# --------------------------------------------------------------------- Node


def node_to_wire(node: Node) -> Dict[str, Any]:
    spec: Dict[str, Any] = {}
    if node.spec.taints:
        spec["taints"] = [
            {"key": t.key, "value": t.value, "effect": t.effect} for t in node.spec.taints
        ]
    if node.spec.unschedulable:
        spec["unschedulable"] = True
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": meta_to_wire(node.metadata),
        "spec": spec,
        "status": {
            "capacity": _resources_to_wire(node.status.capacity),
            "allocatable": _resources_to_wire(node.status.allocatable),
        },
    }


def node_from_wire(d: Dict[str, Any]) -> Node:
    spec = d.get("spec") or {}
    status = d.get("status") or {}
    return Node(
        metadata=meta_from_wire(d.get("metadata") or {}),
        spec=NodeSpec(
            taints=[
                Taint(
                    key=t.get("key", ""),
                    value=t.get("value", ""),
                    effect=t.get("effect", "NoSchedule"),
                )
                for t in spec.get("taints") or []
            ],
            unschedulable=bool(spec.get("unschedulable", False)),
        ),
        status=NodeStatus(
            capacity=_resources_from_wire(status.get("capacity")),
            allocatable=_resources_from_wire(status.get("allocatable")),
        ),
    )


# ---------------------------------------------------------------- ConfigMap


def configmap_to_wire(cm: ConfigMap) -> Dict[str, Any]:
    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": meta_to_wire(cm.metadata),
        "data": dict(cm.data),
    }


def configmap_from_wire(d: Dict[str, Any]) -> ConfigMap:
    return ConfigMap(
        metadata=meta_from_wire(d.get("metadata") or {}),
        data=dict(d.get("data") or {}),
    )


# ------------------------------------------------------------------- Service


def service_to_wire(svc: Service) -> Dict[str, Any]:
    spec: Dict[str, Any] = {}
    if svc.spec.selector:
        spec["selector"] = dict(svc.spec.selector)
    if svc.spec.ports:
        spec["ports"] = [
            {"name": p.name, "port": p.port,
             "targetPort": p.target_port or p.port}
            for p in svc.spec.ports
        ]
    if svc.spec.cluster_ip:
        spec["clusterIP"] = svc.spec.cluster_ip
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": meta_to_wire(svc.metadata),
        "spec": spec,
    }


def service_from_wire(d: Dict[str, Any]) -> Service:
    spec = d.get("spec") or {}
    return Service(
        metadata=meta_from_wire(d.get("metadata") or {}),
        spec=ServiceSpec(
            selector=dict(spec.get("selector") or {}),
            ports=[
                ServicePort(
                    name=p.get("name", ""),
                    port=int(p.get("port") or 0),
                    target_port=int(p.get("targetPort") or 0)
                    if str(p.get("targetPort") or "0").isdigit()
                    else 0,
                )
                for p in spec.get("ports") or []
            ],
            cluster_ip=str(spec.get("clusterIP") or ""),
        ),
    )


# -------------------------------------------------------------------- Event
# Mutable fields (count, lastTimestamp) live TOP-LEVEL on the wire, like
# real core/v1 Events — there is no status subresource, so the recorder's
# dedup bump is a plain main-resource merge-PATCH.


def event_to_wire(ev: Event) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "apiVersion": "v1",
        "kind": "Event",
        "metadata": meta_to_wire(ev.metadata),
        "involvedObject": {
            "kind": ev.involved_kind,
            "namespace": ev.involved_namespace,
            "name": ev.involved_name,
        },
        "reason": ev.reason,
        "message": ev.message,
        "type": ev.type,
        "count": ev.count,
    }
    if ev.first_timestamp:
        out["firstTimestamp"] = _ts_to_wire(ev.first_timestamp)
    if ev.last_timestamp:
        out["lastTimestamp"] = _ts_to_wire(ev.last_timestamp)
    if ev.source_component:
        out["source"] = {"component": ev.source_component}
    return out


def event_from_wire(d: Dict[str, Any]) -> Event:
    involved = d.get("involvedObject") or {}
    return Event(
        metadata=meta_from_wire(d.get("metadata") or {}),
        involved_kind=involved.get("kind", ""),
        involved_namespace=involved.get("namespace", ""),
        involved_name=involved.get("name", ""),
        reason=d.get("reason", ""),
        message=d.get("message", ""),
        type=d.get("type", "Normal"),
        count=int(d.get("count") or 1),
        first_timestamp=_ts_from_wire(d.get("firstTimestamp")) or 0.0,
        last_timestamp=_ts_from_wire(d.get("lastTimestamp")) or 0.0,
        source_component=(d.get("source") or {}).get("component", ""),
    )


# ---------------------------------------------------------------------- PDB


def pdb_to_wire(pdb: PodDisruptionBudget) -> Dict[str, Any]:
    spec: Dict[str, Any] = {"selector": {"matchLabels": dict(pdb.spec.selector)}}
    if pdb.spec.min_available is not None:
        spec["minAvailable"] = pdb.spec.min_available
    if pdb.spec.max_unavailable is not None:
        spec["maxUnavailable"] = pdb.spec.max_unavailable
    return {
        "apiVersion": "policy/v1",
        "kind": "PodDisruptionBudget",
        "metadata": meta_to_wire(pdb.metadata),
        "spec": spec,
    }


def pdb_from_wire(d: Dict[str, Any]) -> PodDisruptionBudget:
    spec = d.get("spec") or {}
    sel = (spec.get("selector") or {}).get("matchLabels") or {}
    return PodDisruptionBudget(
        metadata=meta_from_wire(d.get("metadata") or {}),
        spec=PodDisruptionBudgetSpec(
            selector=dict(sel),
            min_available=spec.get("minAvailable"),
            max_unavailable=spec.get("maxUnavailable"),
        ),
    )


# ------------------------------------------------------------ ElasticQuota


def eq_to_wire(eq: ElasticQuota) -> Dict[str, Any]:
    return {
        "apiVersion": "nos.nebuly.com/v1alpha1",
        "kind": "ElasticQuota",
        "metadata": meta_to_wire(eq.metadata),
        "spec": {
            "min": _resources_to_wire(eq.spec.min),
            "max": _resources_to_wire(eq.spec.max),
        },
        "status": {"used": _resources_to_wire(eq.status.used)},
    }


def eq_from_wire(d: Dict[str, Any]) -> ElasticQuota:
    spec = d.get("spec") or {}
    status = d.get("status") or {}
    return ElasticQuota(
        metadata=meta_from_wire(d.get("metadata") or {}),
        spec=ElasticQuotaSpec(
            min=_resources_from_wire(spec.get("min")),
            max=_resources_from_wire(spec.get("max")),
        ),
        status=ElasticQuotaStatus(used=_resources_from_wire(status.get("used"))),
    )


def ceq_to_wire(ceq: CompositeElasticQuota) -> Dict[str, Any]:
    return {
        "apiVersion": "nos.nebuly.com/v1alpha1",
        "kind": "CompositeElasticQuota",
        "metadata": meta_to_wire(ceq.metadata),
        "spec": {
            "namespaces": list(ceq.spec.namespaces),
            "min": _resources_to_wire(ceq.spec.min),
            "max": _resources_to_wire(ceq.spec.max),
        },
        "status": {"used": _resources_to_wire(ceq.status.used)},
    }


def ceq_from_wire(d: Dict[str, Any]) -> CompositeElasticQuota:
    spec = d.get("spec") or {}
    status = d.get("status") or {}
    return CompositeElasticQuota(
        metadata=meta_from_wire(d.get("metadata") or {}),
        spec=CompositeElasticQuotaSpec(
            namespaces=list(spec.get("namespaces") or []),
            min=_resources_from_wire(spec.get("min")),
            max=_resources_from_wire(spec.get("max")),
        ),
        status=ElasticQuotaStatus(used=_resources_from_wire(status.get("used"))),
    )


# ------------------------------------------------------------ ModelServing


def modelserving_to_wire(ms: ModelServing) -> Dict[str, Any]:
    return {
        "apiVersion": "nos.nebuly.com/v1alpha1",
        "kind": "ModelServing",
        "metadata": meta_to_wire(ms.metadata),
        "spec": {
            "model": ms.spec.model,
            "sliceProfile": ms.spec.slice_profile,
            "minReplicas": ms.spec.min_replicas,
            "maxReplicas": ms.spec.max_replicas,
            "slos": list(ms.spec.slos),
            "scaleToZeroIdleSeconds": ms.spec.scale_to_zero_idle_seconds,
            "coldStartGraceSeconds": ms.spec.cold_start_grace_seconds,
            "targetQueueDepth": ms.spec.target_queue_depth,
            "scaleDownBudgetSurplus": ms.spec.scale_down_budget_surplus,
            "schedulerName": ms.spec.scheduler_name,
        },
        "status": {
            "replicas": ms.status.replicas,
            "readyReplicas": ms.status.ready_replicas,
            "desiredReplicas": ms.status.desired_replicas,
            "lastVerdict": ms.status.last_verdict,
            "lastTransitionTime": ms.status.last_transition_t,
            "coldStartSince": ms.status.cold_start_since,
            "coldStarts": ms.status.cold_starts,
        },
    }


def modelserving_from_wire(d: Dict[str, Any]) -> ModelServing:
    spec = d.get("spec") or {}
    status = d.get("status") or {}
    return ModelServing(
        metadata=meta_from_wire(d.get("metadata") or {}),
        spec=ModelServingSpec(
            model=spec.get("model", ""),
            slice_profile=spec.get("sliceProfile", "2x4"),
            min_replicas=int(spec.get("minReplicas", 0)),
            max_replicas=int(spec.get("maxReplicas", 1)),
            slos=list(spec.get("slos") or []),
            scale_to_zero_idle_seconds=float(
                spec.get("scaleToZeroIdleSeconds", 300.0)
            ),
            cold_start_grace_seconds=float(
                spec.get("coldStartGraceSeconds", 60.0)
            ),
            target_queue_depth=int(spec.get("targetQueueDepth", 4)),
            scale_down_budget_surplus=float(
                spec.get("scaleDownBudgetSurplus", 0.5)
            ),
            scheduler_name=spec.get("schedulerName", "nos-scheduler"),
        ),
        status=ModelServingStatus(
            replicas=int(status.get("replicas", 0)),
            ready_replicas=int(status.get("readyReplicas", 0)),
            desired_replicas=int(status.get("desiredReplicas", 0)),
            last_verdict=status.get("lastVerdict", ""),
            last_transition_t=float(status.get("lastTransitionTime", 0.0)),
            cold_start_since=float(status.get("coldStartSince", 0.0)),
            cold_starts=int(status.get("coldStarts", 0)),
        ),
    )


# ----------------------------------------------------------------- dispatch

_TO_WIRE = {
    "Pod": pod_to_wire,
    "Node": node_to_wire,
    "ConfigMap": configmap_to_wire,
    "Service": service_to_wire,
    "Event": event_to_wire,
    "PodDisruptionBudget": pdb_to_wire,
    "ElasticQuota": eq_to_wire,
    "CompositeElasticQuota": ceq_to_wire,
    "ModelServing": modelserving_to_wire,
}

_FROM_WIRE = {
    "Pod": pod_from_wire,
    "Node": node_from_wire,
    "ConfigMap": configmap_from_wire,
    "Service": service_from_wire,
    "Event": event_from_wire,
    "PodDisruptionBudget": pdb_from_wire,
    "ElasticQuota": eq_from_wire,
    "CompositeElasticQuota": ceq_from_wire,
    "ModelServing": modelserving_from_wire,
}


def to_wire(obj: Any) -> Dict[str, Any]:
    return _TO_WIRE[obj.kind](obj)


def from_wire(d: Dict[str, Any]) -> Any:
    return _FROM_WIRE[d["kind"]](d)
