"""Minimal Kubernetes API client over the standard library.

The reference uses client-go's rest.Config + controller-runtime's client
(/root/reference/cmd/operator/operator.go:50-126). This image carries no
``kubernetes`` Python package, and the API server speaks plain HTTPS+JSON,
so the REST layer is implemented directly: kubeconfig / in-cluster
credential loading, CRUD verbs with apiserver error mapping, and chunked
streaming watches (``?watch=true`` newline-delimited JSON events) — the
same wire surface client-go's rest client covers for this suite.

No third-party dependencies: http.client + ssl + base64 + yaml.
"""
from __future__ import annotations

import base64
import http.client
import json
import logging
import os
import random
import ssl
import tempfile
import threading
import urllib.parse
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional, Tuple

logger = logging.getLogger(__name__)

SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class Backoff:
    """Capped exponential backoff with deterministic, seeded jitter.

    client-go's ``wait.Backoff`` analogue for watch reconnects: every
    failure doubles the delay up to ``cap``; ``reset()`` — called after a
    successful re-list — drops back to ``base``. Jitter spreads a thundering
    herd of reflectors reconnecting after one apiserver hiccup, but is drawn
    from a private ``random.Random(seed)`` so a given (seed, failure
    sequence) always produces the same delays — the chaos harness depends on
    fault timing being a pure function of its seed.
    """

    def __init__(
        self,
        base: float = 0.2,
        cap: float = 30.0,
        factor: float = 2.0,
        jitter: float = 0.5,
        seed: int = 0,
    ) -> None:
        self.base = base
        self.cap = cap
        self.factor = factor
        self.jitter = jitter
        self._rng = random.Random(seed)
        self._failures = 0

    @property
    def failures(self) -> int:
        """Consecutive failures since the last reset."""
        return self._failures

    def next(self) -> float:
        delay = min(self.cap, self.base * (self.factor ** self._failures))
        self._failures += 1
        return delay * (1.0 + self.jitter * self._rng.random())

    def reset(self) -> None:
        self._failures = 0


class ApiError(Exception):
    def __init__(self, status: int, reason: str, body: str = ""):
        super().__init__(f"apiserver returned {status} {reason}: {body[:200]}")
        self.status = status
        self.reason = reason
        self.body = body


@dataclass
class ClusterCredentials:
    """Everything needed to open an authenticated connection."""

    server: str  # e.g. https://10.0.0.1:6443 or http://127.0.0.1:18080
    token: str = ""
    ca_data: Optional[bytes] = None  # PEM
    client_cert_data: Optional[bytes] = None  # PEM
    client_key_data: Optional[bytes] = None  # PEM
    insecure_skip_tls_verify: bool = False

    def ssl_context(self) -> Optional[ssl.SSLContext]:
        if not self.server.startswith("https"):
            return None
        ctx = ssl.create_default_context()
        if self.insecure_skip_tls_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        elif self.ca_data:
            ctx.load_verify_locations(cadata=self.ca_data.decode())
        if self.client_cert_data and self.client_key_data:
            # ssl only loads cert chains from files: stage the PEMs in a
            # private tempdir for the duration of the load.
            with tempfile.TemporaryDirectory(prefix="nos-tpu-tls-") as d:
                cert = os.path.join(d, "cert.pem")
                key = os.path.join(d, "key.pem")
                with open(cert, "wb") as f:
                    f.write(self.client_cert_data)
                with open(key, "wb") as f:
                    f.write(self.client_key_data)
                ctx.load_cert_chain(cert, key)
        return ctx


def _b64_or_file(entry: Dict[str, Any], data_key: str, file_key: str) -> Optional[bytes]:
    if entry.get(data_key):
        return base64.b64decode(entry[data_key])
    if entry.get(file_key):
        with open(entry[file_key], "rb") as f:
            return f.read()
    return None


def load_kubeconfig(
    path: Optional[str] = None, context: Optional[str] = None
) -> ClusterCredentials:
    """Parse a kubeconfig (mirrors client-go clientcmd's order: explicit
    path, $KUBECONFIG, ~/.kube/config)."""
    import yaml

    path = path or os.environ.get("KUBECONFIG") or os.path.expanduser("~/.kube/config")
    with open(path) as f:
        cfg = yaml.safe_load(f) or {}

    ctx_name = context or cfg.get("current-context")
    contexts = {c["name"]: c["context"] for c in cfg.get("contexts") or []}
    if ctx_name not in contexts:
        raise ValueError(f"kubeconfig {path}: context {ctx_name!r} not found")
    ctx = contexts[ctx_name]
    clusters = {c["name"]: c["cluster"] for c in cfg.get("clusters") or []}
    users = {u["name"]: u["user"] for u in cfg.get("users") or []}
    cluster = clusters.get(ctx.get("cluster"), {})
    user = users.get(ctx.get("user"), {})

    token = user.get("token", "")
    if not token and user.get("tokenFile"):
        with open(user["tokenFile"]) as f:
            token = f.read().strip()

    return ClusterCredentials(
        server=cluster.get("server", ""),
        token=token,
        ca_data=_b64_or_file(cluster, "certificate-authority-data", "certificate-authority"),
        client_cert_data=_b64_or_file(user, "client-certificate-data", "client-certificate"),
        client_key_data=_b64_or_file(user, "client-key-data", "client-key"),
        insecure_skip_tls_verify=bool(cluster.get("insecure-skip-tls-verify", False)),
    )


def load_in_cluster() -> ClusterCredentials:
    """Service-account credentials mounted into every pod (what client-go's
    rest.InClusterConfig reads)."""
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    if not host:
        raise RuntimeError("not running in a cluster (KUBERNETES_SERVICE_HOST unset)")
    with open(os.path.join(SERVICE_ACCOUNT_DIR, "token")) as f:
        token = f.read().strip()
    with open(os.path.join(SERVICE_ACCOUNT_DIR, "ca.crt"), "rb") as f:
        ca = f.read()
    return ClusterCredentials(server=f"https://{host}:{port}", token=token, ca_data=ca)


class KubeApiClient:
    """Thin REST client: verbs + watch streaming, per-thread connections."""

    def __init__(self, creds: ClusterCredentials, timeout: float = 30.0):
        self.creds = creds
        self.timeout = timeout
        u = urllib.parse.urlparse(creds.server)
        self._https = u.scheme == "https"
        self._host = u.hostname or "localhost"
        self._port = u.port or (443 if self._https else 80)
        self._ssl = creds.ssl_context()
        self._local = threading.local()

    @classmethod
    def from_kubeconfig(
        cls, path: Optional[str] = None, context: Optional[str] = None
    ) -> "KubeApiClient":
        return cls(load_kubeconfig(path, context))

    @classmethod
    def in_cluster(cls) -> "KubeApiClient":
        return cls(load_in_cluster())

    # ------------------------------------------------------------- plumbing

    def _connect(self, timeout: Optional[float] = None) -> http.client.HTTPConnection:
        if self._https:
            return http.client.HTTPSConnection(
                self._host, self._port, timeout=timeout or self.timeout, context=self._ssl
            )
        return http.client.HTTPConnection(
            self._host, self._port, timeout=timeout or self.timeout
        )

    def _headers(self, content_type: str = "application/json") -> Dict[str, str]:
        h = {"Accept": "application/json", "Content-Type": content_type}
        if self.creds.token:
            h["Authorization"] = f"Bearer {self.creds.token}"
        return h

    def request(
        self,
        method: str,
        path: str,
        body: Optional[Dict[str, Any]] = None,
        params: Optional[Dict[str, str]] = None,
        content_type: str = "application/json",
    ) -> Dict[str, Any]:
        if params:
            path = f"{path}?{urllib.parse.urlencode(params)}"
        conn = getattr(self._local, "conn", None)
        payload = json.dumps(body) if body is not None else None
        for attempt in (0, 1):  # one retry on a stale kept-alive connection
            if conn is None:
                conn = self._connect()
                self._local.conn = conn
            sent = False
            try:
                conn.request(method, path, body=payload, headers=self._headers(content_type))
                sent = True
                resp = conn.getresponse()
                data = resp.read()
                break
            except (http.client.HTTPException, ConnectionError, OSError):
                conn.close()
                self._local.conn = conn = None
                # Writes are only retried when the request never reached the
                # wire (send-phase failure on a stale kept-alive socket); a
                # response-phase failure may mean the server already
                # committed a POST/PUT/DELETE — surfacing beats repeating.
                if attempt or (sent and method != "GET"):
                    raise
        if resp.status >= 400:
            raise ApiError(resp.status, resp.reason or "", data.decode(errors="replace"))
        return json.loads(data) if data else {}

    # ----------------------------------------------------------------- CRUD

    def get(self, path: str, params: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
        return self.request("GET", path, params=params)

    def create(self, path: str, obj: Dict[str, Any]) -> Dict[str, Any]:
        return self.request("POST", path, body=obj)

    def replace(self, path: str, obj: Dict[str, Any]) -> Dict[str, Any]:
        return self.request("PUT", path, body=obj)

    def merge_patch(self, path: str, patch: Dict[str, Any]) -> Dict[str, Any]:
        return self.request(
            "PATCH", path, body=patch, content_type="application/merge-patch+json"
        )

    def delete(self, path: str) -> Dict[str, Any]:
        return self.request("DELETE", path)

    def list(
        self, path: str, params: Optional[Dict[str, str]] = None
    ) -> Tuple[List[Dict[str, Any]], str]:
        """List → (items, resourceVersion) for watch continuation."""
        out = self.get(path, params)
        rv = str((out.get("metadata") or {}).get("resourceVersion", ""))
        return list(out.get("items") or []), rv

    # ---------------------------------------------------------------- watch

    def watch(
        self,
        path: str,
        resource_version: str = "",
        stop: Optional[threading.Event] = None,
        timeout_seconds: int = 300,
    ) -> Iterator[Dict[str, Any]]:
        """Stream watch events ({"type": ..., "object": {...}}) until the
        server closes the window, an error occurs, or `stop` is set.

        The caller loops (re-watching from the last seen resourceVersion)
        exactly like a client-go reflector; a 410 Gone surfaces as ApiError
        telling the caller to relist."""
        params = {
            "watch": "true",
            "timeoutSeconds": str(timeout_seconds),
            "allowWatchBookmarks": "true",
        }
        if resource_version:
            params["resourceVersion"] = resource_version
        qs = urllib.parse.urlencode(params)
        conn = self._connect(timeout=timeout_seconds + 15)
        try:
            conn.request("GET", f"{path}?{qs}", headers=self._headers())
            resp = conn.getresponse()
            if resp.status >= 400:
                raise ApiError(
                    resp.status, resp.reason or "", resp.read().decode(errors="replace")
                )
            buf = b""
            while not (stop and stop.is_set()):
                chunk = resp.read1(65536)
                if not chunk:
                    return
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if not line.strip():
                        continue
                    event = json.loads(line)
                    if event.get("type") == "ERROR":
                        status = event.get("object") or {}
                        raise ApiError(
                            int(status.get("code", 500)),
                            status.get("reason", "watch error"),
                            status.get("message", ""),
                        )
                    yield event
        finally:
            conn.close()
