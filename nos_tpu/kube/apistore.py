"""KubeApiStore: the KubeStore surface backed by a real Kubernetes API.

Reference binaries run against a live apiserver through controller-runtime
managers with list/watch informers and field indexers
(/root/reference/cmd/operator/operator.go:50-126,
/root/reference/cmd/gpupartitioner/gpupartitioner.go:270-292). This class
gives every nos-tpu component the same capability behind the exact store
interface the controllers already speak:

- **reads** (get/list/list_by_index/watch) serve from an informer cache
  kept warm by per-kind list+watch reflector threads — identical to
  controller-runtime's cached client;
- **writes** (create/update/delete/patch_merge) go to the apiserver; the
  local cache applies the response immediately (read-your-writes) and the
  reflector stream deduplicates by resourceVersion;
- **patch_merge** is optimistic-concurrency read-modify-write: GET live,
  mutate, PUT with resourceVersion, retry on 409 — the controller-runtime
  retry-on-conflict idiom.

Store selection is a config switch (`store: {type: kubeconfig | in-cluster
| in-memory}`, nos_tpu/cmd/_component.py): the same helm chart that today
boots the in-memory suite boots cluster-connected components.
"""
from __future__ import annotations

import copy
import logging
import threading
import time
import zlib
from typing import Any, Dict, Iterable, List

from nos_tpu.kube import serde
from nos_tpu.kube.apiclient import ApiError, Backoff, KubeApiClient
from nos_tpu.util import metrics
from nos_tpu.kube.store import (
    ADDED,
    DELETED,
    MODIFIED,
    AdmissionError,
    AlreadyExistsError,
    ConflictError,
    KubeStore,
    NotFoundError,
    WatchEvent,
    _key,
)

logger = logging.getLogger(__name__)

DEFAULT_KINDS = tuple(serde.RESOURCES)

# Kinds whose .status only writes through the /status subresource on a real
# apiserver (core/policy kinds by definition; the EQ/CEQ CRDs declare
# `subresources: status` — config/crd/bases/*.yaml).
STATUS_SUBRESOURCE = {
    "Pod",
    "Node",
    "PodDisruptionBudget",
    "ElasticQuota",
    "CompositeElasticQuota",
}

_MISSING = object()


def _overlay_containers(live_list, projected_list):
    """Projected containers + the live wire's unmodeled per-container
    fields. Lists are atomic in a merge-patch, so when a diff must mention
    spec.containers it has to carry the WHOLE array — this overlay keeps
    everything the projection doesn't model (volumeMounts, probes,
    valueFrom env entries, …) from being wiped by our own patch."""
    by_name = {c.get("name"): c for c in live_list or []}
    out = []
    for c in projected_list or []:
        base = dict(by_name.get(c.get("name"), {}))
        merged = {**base, **{k: v for k, v in c.items() if k != "env"}}
        if "env" in c or "env" in base:
            # env entries merge BY NAME; live valueFrom sources survive
            # unless the projection explicitly overrides that name.
            projected_env = {e["name"]: e for e in c.get("env") or []}
            merged_env = []
            for entry in base.get("env") or []:
                override = projected_env.pop(entry["name"], None)
                merged_env.append(override if override is not None else entry)
            merged_env.extend(projected_env.values())
            if merged_env:
                merged["env"] = merged_env
            else:
                merged.pop("env", None)
        out.append(merged)
    return out


def _merge_diff(old: Dict[str, Any], new: Dict[str, Any]) -> Dict[str, Any]:
    """Minimal JSON merge-patch turning `old` into `new`.

    Both sides are THIS suite's wire projections (serde.to_wire of the same
    object before/after a mutation), so the patch can only ever mention
    fields the suite models — server-side fields we don't model (volumes,
    probes, podCIDR, …) never appear and are therefore never clobbered,
    which is what makes read-modify-PATCH safe against a real apiserver.
    """
    diff: Dict[str, Any] = {}
    for k, v in new.items():
        ov = old.get(k, _MISSING)
        if ov is _MISSING:
            diff[k] = v
        elif isinstance(v, dict) and isinstance(ov, dict):
            sub = _merge_diff(ov, v)
            if sub:
                diff[k] = sub
        elif v != ov:
            diff[k] = v
    for k in old:
        if k not in new:
            diff[k] = None  # merge-patch deletion
    return diff


def _api_error_to_store(e: ApiError) -> Exception:
    if e.status == 404:
        return NotFoundError(str(e))
    if e.status == 409:
        if "AlreadyExists" in e.body or "already exists" in e.body:
            return AlreadyExistsError(str(e))
        return ConflictError(str(e))
    if e.status in (400, 403, 422):
        return AdmissionError(str(e))
    return e


class KubeApiStore(KubeStore):
    """KubeStore-compatible store over a live apiserver."""

    def __init__(
        self,
        client: KubeApiClient,
        kinds: Iterable[str] = DEFAULT_KINDS,
        relist_backoff_s: float = 2.0,
        backoff_seed: int = 0,
    ) -> None:
        super().__init__()
        self._client = client
        self._kinds = tuple(kinds)
        # `relist_backoff_s` is the CAP of the reconnect backoff (the old
        # fixed sleep): the first retry after a hiccup is much faster, and
        # repeated failures grow back up to it.
        self._relist_backoff_s = relist_backoff_s
        self._backoff_seed = backoff_seed
        self._stop_informers = threading.Event()
        # Cache apply-sequence: increments under the lock for every event
        # this cache applies (write path AND reflector). This — not the
        # apiserver resourceVersion — is the revision the flight recorder
        # keys deltas and decision watermarks on: apiserver rvs can reach
        # the cache out of order (reflector backfill after a severed watch,
        # server-side writes like the sim kubelet's phase transitions), and
        # replay must order deltas the way the cache actually saw them or
        # decisions time-travel against state the live process never had.
        self._applied = 0
        self._threads: List[threading.Thread] = []
        self._synced: Dict[str, threading.Event] = {
            k: threading.Event() for k in self._kinds
        }

    # ------------------------------------------------------------ lifecycle

    def start(self, sync_timeout_s: float = 30.0) -> None:
        """Launch one reflector per kind and wait for the initial list."""
        for kind in self._kinds:
            t = threading.Thread(
                target=self._reflector, args=(kind,), name=f"informer-{kind}", daemon=True
            )
            t.start()
            self._threads.append(t)
        deadline = time.monotonic() + sync_timeout_s
        for kind, ev in self._synced.items():
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not ev.wait(remaining):
                raise TimeoutError(f"informer for {kind} did not sync in {sync_timeout_s}s")

    def stop(self) -> None:
        self._stop_informers.set()

    # ------------------------------------------------------------ reflector

    def _reflector(self, kind: str) -> None:
        path = serde.resource_path(kind)
        rv = ""  # last-seen resourceVersion; empty = must (re)list
        # Per-kind seed: reflectors of different kinds jitter differently,
        # but the whole sequence is reproducible from backoff_seed.
        backoff = Backoff(
            base=min(0.1, self._relist_backoff_s),
            cap=self._relist_backoff_s,
            seed=self._backoff_seed ^ zlib.crc32(kind.encode()),
        )
        while not self._stop_informers.is_set():
            try:
                if not rv:
                    items, rv = self._client.list(path)
                    objs = []
                    for item in items:
                        item.setdefault("kind", kind)
                        objs.append(serde.from_wire(item))
                    try:
                        list_rv = int(rv or 0)
                    except ValueError:
                        list_rv = 0
                    self._replace_kind(kind, objs, list_rv=list_rv)
                    self._synced[kind].set()
                    # Successful re-list: the apiserver is healthy again,
                    # so the next failure starts the backoff from scratch.
                    backoff.reset()
                for event in self._client.watch(path, rv, self._stop_informers):
                    etype = event.get("type")
                    wire = event.get("object") or {}
                    ev_rv = str((wire.get("metadata") or {}).get("resourceVersion", ""))
                    if ev_rv:
                        rv = ev_rv
                    if etype == "BOOKMARK":
                        continue
                    wire.setdefault("kind", kind)
                    obj = serde.from_wire(wire)
                    if etype == "DELETED":
                        self._apply_delete(obj)
                    else:
                        self._apply_upsert(obj)
                # Normal watch-window close: resume from the last-seen RV
                # (client-go reflector behavior) — do NOT relist.
                continue
            except ApiError as e:
                if e.status == 410:  # watch window expired: relist
                    logger.info("informer %s: watch expired, relisting", kind)
                    metrics.WATCH_RECONNECTS.labels(kind=kind).inc()
                    rv = ""
                    continue
                if e.status in (403, 404) and not self._synced[kind].is_set():
                    # Kind unavailable (CRD not installed / RBAC gap):
                    # degrade instead of wedging every component at boot —
                    # report synced-empty and keep probing slowly in case
                    # the CRD lands later.
                    logger.warning(
                        "informer %s: kind unavailable (%s); serving empty and retrying",
                        kind, e.status,
                    )
                    self._synced[kind].set()
                    self._stop_informers.wait(max(self._relist_backoff_s, 15.0))
                    rv = ""
                    continue
                logger.warning("informer %s: %s", kind, e)
                metrics.WATCH_RECONNECTS.labels(kind=kind).inc()
                rv = ""
            except Exception as e:  # noqa: BLE001 — reflectors must survive
                if self._stop_informers.is_set():
                    return
                logger.warning("informer %s: %s: %s", kind, type(e).__name__, e)
                metrics.WATCH_RECONNECTS.labels(kind=kind).inc()
                rv = ""
            self._stop_informers.wait(backoff.next())

    # ------------------------------------------------------- cache mutation

    def _replace_kind(self, kind: str, objs: List[Any], list_rv: int = 0) -> None:
        """Initial/relist sync: diff the cache against the listed world."""
        events: List[WatchEvent] = []
        with self._lock:
            fresh = {
                _key(kind, o.metadata.namespace, o.metadata.name): o for o in objs
            }
            stale = [k for k in self._objects if k[0] == kind and k not in fresh]
            for k in stale:
                gone = self._discard_object(k)
                # The object vanished while we were disconnected; the exact
                # deletion rv is lost. The list's collection rv is the
                # tightest bound we have ("deleted by now") — stamping it
                # keeps the recorded delete ordered after every decision
                # that saw the object alive.
                if list_rv > gone.metadata.resource_version:
                    gone.metadata.resource_version = list_rv
                    self._rv = max(self._rv, list_rv)
                self._applied += 1
                events.append(WatchEvent(DELETED, gone, revision=self._applied))
            for k, obj in fresh.items():
                old = self._objects.get(k)
                if old is None:
                    self._store_object(k, obj)
                    self._applied += 1
                    events.append(
                        WatchEvent(ADDED, copy.deepcopy(obj), revision=self._applied)
                    )
                elif old.metadata.resource_version < obj.metadata.resource_version:
                    self._store_object(k, obj)
                    self._applied += 1
                    events.append(
                        WatchEvent(MODIFIED, copy.deepcopy(obj), revision=self._applied)
                    )
                self._rv = max(self._rv, obj.metadata.resource_version)
        for e in events:
            self._notify(e)

    def _apply_upsert(self, obj: Any) -> None:
        k = _key(obj.kind, obj.metadata.namespace, obj.metadata.name)
        with self._lock:
            old = self._objects.get(k)
            if old is not None and old.metadata.resource_version >= obj.metadata.resource_version:
                return  # stale or already applied via write path
            self._store_object(k, copy.deepcopy(obj))
            # Track the apiserver's revision high-water mark: store.revision
            # is the watermark every recorded decision keys on, and it must
            # advance in apiserver mode too or replay ordering collapses to
            # revision 0.
            self._rv = max(self._rv, obj.metadata.resource_version)
            self._applied += 1
            seq = self._applied
            etype = ADDED if old is None else MODIFIED
        self._notify(WatchEvent(etype, copy.deepcopy(obj), revision=seq))

    def _apply_delete(self, obj: Any) -> None:
        k = _key(obj.kind, obj.metadata.namespace, obj.metadata.name)
        with self._lock:
            if k not in self._objects:
                return
            stored = self._discard_object(k)
            # Notify at the DELETION rv (the watch event's), not the cached
            # object's last rv: recorded deltas must order the delete after
            # every decision that saw the object alive.
            if obj.metadata.resource_version > stored.metadata.resource_version:
                stored.metadata.resource_version = obj.metadata.resource_version
            self._rv = max(self._rv, obj.metadata.resource_version)
            self._applied += 1
            seq = self._applied
        self._notify(WatchEvent(DELETED, stored, revision=seq))

    @property
    def revision(self) -> int:
        """The cache apply-sequence, NOT the apiserver resourceVersion.

        Decisions read this at cycle entry as their replay watermark; it
        must promise "every delta numbered <= this was in the cache when I
        decided", which apiserver rvs cannot (backfill applies old rvs
        late). Object rvs in the cache stay authentic apiserver rvs —
        optimistic concurrency is untouched."""
        with self._lock:
            return self._applied

    # ---------------------------------------------------------- write verbs

    def create(self, obj: Any) -> Any:
        self._admit(obj)
        path = serde.resource_path(obj.kind, obj.metadata.namespace)
        try:
            resp = self._client.create(path, serde.to_wire(obj))
        except ApiError as e:
            raise _api_error_to_store(e) from e
        stored = serde.from_wire(resp)
        self._apply_upsert(stored)
        return copy.deepcopy(stored)

    def update(self, obj: Any, check_version: bool = False) -> Any:
        """Replace the modeled projection of the object (diff-and-patch:
        fields outside this suite's model survive untouched)."""
        self._admit(obj)
        kind, ns, name = obj.kind, obj.metadata.namespace, obj.metadata.name
        path = serde.resource_path(kind, ns, name)
        try:
            live_wire = self._client.get(path)
        except ApiError as e:
            raise _api_error_to_store(e) from e
        live = serde.from_wire(live_wire)
        if check_version and live.metadata.resource_version != obj.metadata.resource_version:
            raise ConflictError(f"{kind} {ns}/{name}: resource version conflict")
        diff = _merge_diff(serde.to_wire(live), serde.to_wire(obj))
        diff.get("metadata", {}) and diff["metadata"].pop("resourceVersion", None)
        return self._push_diff(kind, ns, name, live, diff)

    def delete(self, kind: str, name: str, namespace: str = "") -> Any:
        path = serde.resource_path(kind, namespace, name)
        try:
            resp = self._client.delete(path)
        except ApiError as e:
            raise _api_error_to_store(e) from e
        # The apiserver bumps the resourceVersion on delete and returns the
        # deleted object carrying it. Stamp the notified event with THAT rv,
        # not the cached object's last one: the flight recorder keys deltas
        # by rv, and a delete recorded at its pre-delete rv sorts BEFORE
        # decisions that saw the object alive — replay would free the
        # capacity too early and drift.
        deleted_rv = 0
        try:
            deleted_rv = int((resp.get("metadata") or {}).get("resourceVersion", 0))
        except (AttributeError, TypeError, ValueError):
            pass
        with self._lock:
            stored = self._discard_object(_key(kind, namespace, name))
            if stored is not None and deleted_rv:
                stored.metadata.resource_version = deleted_rv
            if deleted_rv:
                self._rv = max(self._rv, deleted_rv)
            if stored is not None:
                self._applied += 1
                seq = self._applied
        if stored is not None:
            self._notify(WatchEvent(DELETED, copy.deepcopy(stored), revision=seq))
        return stored

    def patch_merge(self, kind, name, namespace, mutate, max_retries: int = 5):
        """GET live → mutate → minimal merge-PATCH; retry on 409.

        The patch is the diff of the suite's own projection before/after
        `mutate`, routed the way a real apiserver demands: status changes
        through the /status subresource, Pod binding through /binding,
        everything else as one merge-patch carrying the live
        resourceVersion for optimistic concurrency."""
        path = serde.resource_path(kind, namespace, name)
        last: Exception = ConflictError(f"{kind} {namespace}/{name}: retries exhausted")
        for _ in range(max_retries):
            try:
                live = serde.from_wire(self._client.get(path))
            except ApiError as e:
                raise _api_error_to_store(e) from e
            obj = copy.deepcopy(live)
            mutate(obj)
            self._admit(obj)
            diff = _merge_diff(serde.to_wire(live), serde.to_wire(obj))
            diff.get("metadata", {}) and diff["metadata"].pop("resourceVersion", None)
            try:
                return self._push_diff(kind, namespace, name, live, diff)
            except ConflictError as e:
                last = e
                continue
        raise last

    def _push_diff(self, kind: str, namespace: str, name: str, live: Any, diff: Dict[str, Any]) -> Any:
        """Send a projection diff to the apiserver via the right verbs."""
        path = serde.resource_path(kind, namespace, name)
        if not diff:
            self._apply_upsert(live)
            return copy.deepcopy(live)
        if kind == "Pod" and "containers" in (diff.get("spec") or {}):
            # The containers array is replaced wholesale by a merge-patch:
            # graft the live wire's unmodeled fields back in first.
            try:
                live_wire = self._client.get(path)
            except ApiError as e:
                raise _api_error_to_store(e) from e
            diff["spec"]["containers"] = _overlay_containers(
                (live_wire.get("spec") or {}).get("containers"),
                diff["spec"]["containers"],
            )
        try:
            status_diff = (
                diff.pop("status", None) if kind in STATUS_SUBRESOURCE else None
            )
            # Pod binding is a dedicated subresource: spec.nodeName is
            # immutable through PATCH on a real apiserver.
            spec_diff = diff.get("spec") or {}
            node_name = spec_diff.get("nodeName")
            if kind == "Pod" and node_name and not live.spec.node_name:
                spec_diff.pop("nodeName")
                if not spec_diff:
                    diff.pop("spec", None)
                self._client.create(
                    f"{path}/binding",
                    {
                        "apiVersion": "v1",
                        "kind": "Binding",
                        "metadata": {"name": name, "namespace": namespace},
                        "target": {"apiVersion": "v1", "kind": "Node", "name": node_name},
                    },
                )
            if diff:
                meta = dict(diff.get("metadata") or {})
                meta["resourceVersion"] = str(live.metadata.resource_version)
                self._client.merge_patch(path, {**diff, "metadata": meta})
            if status_diff is not None:
                self._client.merge_patch(f"{path}/status", {"status": status_diff})
            refreshed = serde.from_wire(self._client.get(path))
        except ApiError as e:
            raise _api_error_to_store(e) from e
        self._apply_upsert(refreshed)
        return copy.deepcopy(refreshed)

    # ------------------------------------------------------------- raw path

    def raw_get(self, kind: str, name: str, namespace: str = "") -> Dict[str, Any]:
        """The live WIRE object — full fidelity beyond the typed
        projection (e.g. cloning a pod spec with volumes/probes intact)."""
        try:
            return self._client.get(serde.resource_path(kind, namespace, name))
        except ApiError as e:
            raise _api_error_to_store(e) from e

    def raw_create(self, kind: str, wire: Dict[str, Any]) -> Any:
        """POST a wire object as-is; the typed projection lands in cache."""
        namespace = (wire.get("metadata") or {}).get("namespace", "")
        try:
            resp = self._client.create(serde.resource_path(kind, namespace), wire)
        except ApiError as e:
            raise _api_error_to_store(e) from e
        resp.setdefault("kind", kind)
        stored = serde.from_wire(resp)
        self._apply_upsert(stored)
        return copy.deepcopy(stored)

    # ------------------------------------------------------------ read path
    # get/try_get/list/list_by_index/watch/stop_watch/indexers are inherited:
    # they read the informer cache under the same lock as the in-memory
    # store, which is exactly the cached-client contract controllers expect.
