"""In-memory Kubernetes-compatible substrate.

The reference (nos) is built on controller-runtime and coordinates its
components exclusively through the Kubernetes API server (SURVEY.md §5:
annotations as the spec/status wire protocol). This package provides the
equivalent fabric for the TPU build: typed objects, an API store with
watch/patch/indexer semantics (our "API server" / envtest), and an
event-driven reconciler runtime (our controller-runtime).
"""

from nos_tpu.kube.objects import (
    Container,
    ConfigMap,
    Node,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodCondition,
    PodPhase,
    PodSpec,
    PodStatus,
    NodeStatus,
    ResourceList,
    Toleration,
)
from nos_tpu.kube.store import (
    AlreadyExistsError,
    ConflictError,
    KubeStore,
    NotFoundError,
    WatchEvent,
)
from nos_tpu.kube.controller import Controller, Manager, Request, Result

__all__ = [
    "AlreadyExistsError",
    "ConflictError",
    "ConfigMap",
    "Container",
    "Controller",
    "KubeStore",
    "Manager",
    "Node",
    "NodeStatus",
    "NotFoundError",
    "PodSpec",
    "PodStatus",
    "ObjectMeta",
    "OwnerReference",
    "Pod",
    "PodCondition",
    "PodPhase",
    "Request",
    "ResourceList",
    "Result",
    "Toleration",
    "WatchEvent",
]
