"""The in-memory API store: the suite's single coordination point.

nos components "communicate only through the Kubernetes API server (node
annotations/labels, CRDs, ConfigMaps)" (SURVEY.md §1). KubeStore provides
that contract in-process: CRUD with resource versions, merge-patch helpers,
label/field selection, registered field indexers (reference
cmd/gpupartitioner/gpupartitioner.go:270-292 registers status.phase and
spec.nodeName indexers), and fan-out watch subscriptions that drive the
reconciler runtime.

Objects are deep-copied on write and on read — mutating a returned object
never mutates the store, exactly like talking to a real API server.
"""
from __future__ import annotations

import copy
import logging
import queue
import threading
import time
from copy import deepcopy as _deepcopy
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Set, Tuple

from nos_tpu.util import metrics

log = logging.getLogger("nos_tpu.kube.store")


class NotFoundError(KeyError):
    pass


class AdmissionError(ValueError):
    """Raised by admission validators (the validating-webhook analogue)."""


class AlreadyExistsError(ValueError):
    pass


class ConflictError(RuntimeError):
    pass


ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


@dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    object: Any
    # Store-local apply sequence (0 = unset; fall back to the object's
    # resourceVersion). The in-memory store's rv IS its apply order, but an
    # API-backed cache applies events in an order that can diverge from
    # apiserver rv order (write-path read-your-writes races the reflector;
    # a severed watch backfills old rvs late). Recording THIS stamp as the
    # delta ordering key lets replay reconstruct exactly what the cache
    # contained at any decision watermark, lag included.
    revision: int = 0
    # Monotonic enqueue stamp set by the store at fan-out time (0.0 =
    # unset, e.g. hand-built events in tests). Consumers observe
    # ``time.monotonic() - enqueued`` at dequeue as their watch drain lag
    # (nos_tpu_watch_drain_lag_seconds) — the direct "how far behind is
    # this loop" meter.
    enqueued: float = 0.0

    @property
    def kind(self) -> str:
        return self.object.kind


def _key(kind: str, namespace: str, name: str) -> Tuple[str, str, str]:
    return (kind, namespace or "", name)


class _InstrumentedLock:
    """RLock that meters contended acquisitions.

    The uncontended fast path costs one extra non-blocking try; a caller
    that actually blocks lands its wait in
    ``nos_tpu_store_lock_wait_seconds_total`` — so the counters sample
    exactly the interesting population (waits) at zero hot-path cost.
    """

    __slots__ = ("_lock",)

    def __init__(self) -> None:
        self._lock = threading.RLock()

    def __enter__(self) -> "_InstrumentedLock":
        if not self._lock.acquire(blocking=False):
            start = time.perf_counter()
            self._lock.acquire()
            metrics.STORE_LOCK_CONTENTION.inc()
            metrics.STORE_LOCK_WAIT.inc(time.perf_counter() - start)
        return self

    def __exit__(self, *exc: Any) -> None:
        self._lock.release()


@dataclass
class _Watcher:
    """One watch subscription plus its telemetry state."""

    kind_set: Optional[set]
    queue: "queue.Queue[WatchEvent]"
    label: str
    depth_gauge: Any
    last_warn: float = field(default=0.0)


class KubeStore:
    """Thread-safe object store with watch + indexer semantics."""

    # Slow-watcher visibility: a subscriber whose (unbounded) queue grows
    # past WARN_DEPTH gets a rate-limited warning — a stalled controller
    # becomes diagnosable before its queue eats the heap.
    WATCH_QUEUE_WARN_DEPTH = 1000
    WATCH_QUEUE_WARN_INTERVAL = 30.0

    def __init__(self) -> None:
        self._lock = _InstrumentedLock()
        self._objects: Dict[Tuple[str, str, str], Any] = {}
        self._rv = 0
        self._watchers: List[_Watcher] = []
        # (kind, index_name) -> fn(obj) -> list of index values
        self._indexers: Dict[Tuple[str, str], Callable[[Any], List[str]]] = {}
        # (kind, index_name) -> index value -> set of object keys. Kept in
        # lockstep with _objects by _store_object/_discard_object, so
        # list_by_index is a map lookup instead of a full all-kinds scan.
        self._index_maps: Dict[Tuple[str, str], Dict[str, Set[Tuple[str, str, str]]]] = {}
        # kind -> [validator(obj, store)] run before create/update commits —
        # the validating-webhook admission seam (reference
        # pkg/api/nos.nebuly.com/v1alpha1/elasticquota_webhook.go:31-97).
        self._admission: Dict[str, List[Callable[[Any, "KubeStore"], None]]] = {}
        # Chaos seam: armed only by the chaos harness. When set, the
        # injector's on_store_write(kind, name) runs before every write
        # verb and may raise ConflictError/RuntimeError to model stale-rv
        # conflicts and apiserver write failures. None on every production
        # path — one attribute read of cost.
        self.fault_injector: Optional[Any] = None
        # Health-timeline leak watch: watch queues are unbounded by
        # design (a slow consumer only warns) — the timeline's leak
        # detector over this aggregate depth turns "warned about once"
        # into "failed the soak". Replace-by-name keeps the newest store
        # current (tests build many).
        from nos_tpu.timeline.sizes import SIZES

        SIZES.register(
            "kube.watch_queue_events",
            lambda: sum(w.queue.qsize() for w in list(self._watchers)),
        )

    def register_admission(self, kind: str, fn: Callable[[Any, "KubeStore"], None]) -> None:
        self._admission.setdefault(kind, []).append(fn)

    def _admit(self, obj: Any) -> None:
        for fn in self._admission.get(obj.kind, []):
            fn(obj, self)

    def _chaos_write(self, kind: str, name: str) -> None:
        injector = self.fault_injector
        if injector is not None:
            injector.on_store_write(kind, name)

    # --------------------------------------------------- object mutation
    # Every path that touches _objects goes through these two, which keep
    # the per-(kind, index) maps in lockstep (the apistore's reflector
    # apply paths included). Callers hold the lock.

    def _store_object(self, k: Tuple[str, str, str], obj: Any) -> None:
        old = self._objects.get(k)
        self._objects[k] = obj
        self._index_update(k, old, obj)

    def _discard_object(self, k: Tuple[str, str, str]) -> Optional[Any]:
        old = self._objects.pop(k, None)
        if old is not None:
            self._index_update(k, old, None)
        return old

    def _index_update(self, k: Tuple[str, str, str], old: Any, new: Any) -> None:
        kind = k[0]
        for (i_kind, i_name), fn in self._indexers.items():
            if i_kind != kind:
                continue
            old_values = list(fn(old)) if old is not None else []
            new_values = list(fn(new)) if new is not None else []
            if old_values == new_values:
                continue
            index = self._index_maps[(i_kind, i_name)]
            for value in old_values:
                keys = index.get(value)
                if keys is not None:
                    keys.discard(k)
                    if not keys:
                        del index[value]
            for value in new_values:
                index.setdefault(value, set()).add(k)

    # ------------------------------------------------------------------ CRUD

    def create(self, obj: Any) -> Any:
        self._chaos_write(obj.kind, obj.metadata.name)
        with self._lock:
            k = _key(obj.kind, obj.metadata.namespace, obj.metadata.name)
            if k in self._objects:
                raise AlreadyExistsError(f"{k} already exists")
            self._admit(obj)
            self._rv += 1
            stored = copy.deepcopy(obj)
            stored.metadata.resource_version = self._rv
            self._store_object(k, stored)
            out = copy.deepcopy(stored)
        self._notify(WatchEvent(ADDED, copy.deepcopy(stored)))
        return out

    def get(self, kind: str, name: str, namespace: str = "") -> Any:
        with self._lock:
            k = _key(kind, namespace, name)
            if k not in self._objects:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            return copy.deepcopy(self._objects[k])

    def try_get(self, kind: str, name: str, namespace: str = "") -> Optional[Any]:
        try:
            return self.get(kind, name, namespace)
        except NotFoundError:
            return None

    def update(self, obj: Any, check_version: bool = False) -> Any:
        self._chaos_write(obj.kind, obj.metadata.name)
        with self._lock:
            k = _key(obj.kind, obj.metadata.namespace, obj.metadata.name)
            if k not in self._objects:
                raise NotFoundError(f"{k} not found")
            if check_version and self._objects[k].metadata.resource_version != obj.metadata.resource_version:
                raise ConflictError(f"{k}: resource version conflict")
            self._admit(obj)
            self._rv += 1
            stored = copy.deepcopy(obj)
            stored.metadata.resource_version = self._rv
            self._store_object(k, stored)
            out = copy.deepcopy(stored)
        self._notify(WatchEvent(MODIFIED, copy.deepcopy(stored)))
        return out

    def delete(self, kind: str, name: str, namespace: str = "") -> Any:
        self._chaos_write(kind, name)
        with self._lock:
            k = _key(kind, namespace, name)
            if k not in self._objects:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            stored = self._discard_object(k)
            # Deletes advance the revision too (a real apiserver's
            # deletionTimestamp write does): the flight recorder keys every
            # delta by revision, and an rv-less delete would be unorderable
            # against the writes around it.
            self._rv += 1
            stored.metadata.resource_version = self._rv
        self._notify(WatchEvent(DELETED, copy.deepcopy(stored)))
        return stored

    @property
    def revision(self) -> int:
        """Current store revision — the watermark a control cycle reads at
        entry so replay knows which deltas the decision observed."""
        with self._lock:
            return self._rv

    def apply_event(self, etype: str, obj: Any) -> None:
        """Replay a recorded watch event verbatim: upsert or delete WITHOUT
        re-stamping, preserving the recorded resource_version so replayed
        state is revision-identical to the recording. Idempotent (an ADDED
        for an existing key overwrites), since a recorder attached after
        seeding replays existing objects as ADDED."""
        with self._lock:
            k = _key(obj.kind, obj.metadata.namespace, obj.metadata.name)
            if etype == DELETED:
                self._discard_object(k)
            else:
                self._store_object(k, copy.deepcopy(obj))
            self._rv = max(self._rv, obj.metadata.resource_version)
        self._notify(WatchEvent(etype, copy.deepcopy(obj)))

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        filter_fn: Optional[Callable[[Any], bool]] = None,
        copy: bool = True,
    ) -> List[Any]:
        """List objects of `kind`. ``copy=False`` returns the stored
        objects themselves for read-only consumers (the planner's live
        cluster view): safe because every store write replaces the stored
        object instead of mutating it — but callers must not write through.
        """
        with self._lock:
            out = []
            for (k_kind, k_ns, _), obj in self._objects.items():
                if k_kind != kind:
                    continue
                if namespace is not None and k_ns != namespace:
                    continue
                if label_selector and not all(
                    obj.metadata.labels.get(lk) == lv for lk, lv in label_selector.items()
                ):
                    continue
                if filter_fn and not filter_fn(obj):
                    continue
                out.append(_deepcopy(obj) if copy else obj)
            out.sort(key=lambda o: (o.metadata.namespace, o.metadata.name))
            return out

    # ------------------------------------------------------------- patching

    def patch_merge(self, kind: str, name: str, namespace: str, mutate: Callable[[Any], None]) -> Any:
        """Read-modify-write under the store lock — the analogue of a merge
        patch (client.Patch in controller-runtime)."""
        self._chaos_write(kind, name)
        with self._lock:
            k = _key(kind, namespace, name)
            if k not in self._objects:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            obj = copy.deepcopy(self._objects[k])
            mutate(obj)
            self._admit(obj)
            self._rv += 1
            obj.metadata.resource_version = self._rv
            self._store_object(k, obj)
            stored = copy.deepcopy(obj)
        self._notify(WatchEvent(MODIFIED, stored))
        return copy.deepcopy(stored)

    def patch_annotations(self, kind: str, name: str, namespace: str, annotations: Dict[str, Optional[str]]) -> Any:
        def mutate(obj: Any) -> None:
            for ak, av in annotations.items():
                if av is None:
                    obj.metadata.annotations.pop(ak, None)
                else:
                    obj.metadata.annotations[ak] = av

        return self.patch_merge(kind, name, namespace, mutate)

    def patch_labels(self, kind: str, name: str, namespace: str, labels: Dict[str, Optional[str]]) -> Any:
        def mutate(obj: Any) -> None:
            for lk, lv in labels.items():
                if lv is None:
                    obj.metadata.labels.pop(lk, None)
                else:
                    obj.metadata.labels[lk] = lv

        return self.patch_merge(kind, name, namespace, mutate)

    # ------------------------------------------------------------- indexers

    def add_indexer(self, kind: str, index_name: str, fn: Callable[[Any], List[str]]) -> None:
        """Register an index and backfill it from the objects already
        stored (indexers are usually registered before seeding, but a
        late registration must not serve a partial index)."""
        with self._lock:
            self._indexers[(kind, index_name)] = fn
            index: Dict[str, Set[Tuple[str, str, str]]] = {}
            self._index_maps[(kind, index_name)] = index
            for k, obj in self._objects.items():
                if k[0] != kind:
                    continue
                for value in fn(obj):
                    index.setdefault(value, set()).add(k)

    def list_by_index(
        self, kind: str, index_name: str, value: str, copy: bool = True
    ) -> List[Any]:
        """``copy=False`` has the same read-only contract as ``list``; it
        additionally keeps object identity stable across calls for
        unchanged objects, which the planner's id-keyed pod memos rely on
        between incremental plan cycles.

        Served from the maintained per-(kind, index) map — a lookup plus a
        sort of the hits, not a scan of every object of every kind (the
        before/after rows in BENCH_store.json quantify the difference)."""
        with self._lock:
            if (kind, index_name) not in self._indexers:
                raise KeyError(f"no indexer {index_name!r} for kind {kind!r}")
            keys = self._index_maps[(kind, index_name)].get(value, ())
            out = [
                _deepcopy(self._objects[k]) if copy else self._objects[k]
                for k in keys
            ]
        out.sort(key=lambda o: (o.metadata.namespace, o.metadata.name))
        return out

    # ---------------------------------------------------------------- watch

    def watch(
        self, kinds: Optional[Iterable[str]] = None, name: str = ""
    ) -> "queue.Queue[WatchEvent]":
        """Subscribe to events for the given kinds (None = all). Existing
        objects are replayed as ADDED events first (informer list+watch).
        ``name`` labels the subscriber's queue-depth gauge and slow-watcher
        warnings; anonymous subscribers are labeled by their kind set."""
        q: "queue.Queue[WatchEvent]" = queue.Queue()
        kind_set = set(kinds) if kinds is not None else None
        label = name or ("*" if kind_set is None else "|".join(sorted(kind_set)))
        watcher = _Watcher(
            kind_set=kind_set,
            queue=q,
            label=label,
            depth_gauge=metrics.WATCH_QUEUE_DEPTH.labels(kind_set=label),
        )
        with self._lock:
            now = time.monotonic()
            for (k_kind, _, _), obj in sorted(self._objects.items()):
                if kind_set is None or k_kind in kind_set:
                    q.put(WatchEvent(ADDED, copy.deepcopy(obj), enqueued=now))
            self._watchers.append(watcher)
            watcher.depth_gauge.set(q.qsize())
        return q

    def stop_watch(self, q: "queue.Queue[WatchEvent]") -> None:
        with self._lock:
            for w in self._watchers:
                if w.queue is q:
                    w.depth_gauge.set(0)
            self._watchers = [w for w in self._watchers if w.queue is not q]

    def watch_stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-subscriber label -> {kinds, depth} — the /debug/loops
        watcher rollup."""
        with self._lock:
            watchers = list(self._watchers)
        return {
            w.label: {
                "kinds": sorted(w.kind_set) if w.kind_set is not None else ["*"],
                "depth": w.queue.qsize(),
            }
            for w in watchers
        }

    def _notify(self, event: WatchEvent) -> None:
        event.enqueued = time.monotonic()
        with self._lock:
            watchers = list(self._watchers)
        for w in watchers:
            if w.kind_set is not None and event.kind not in w.kind_set:
                continue
            w.queue.put(event)
            depth = w.queue.qsize()
            w.depth_gauge.set(depth)
            if depth >= self.WATCH_QUEUE_WARN_DEPTH:
                now = time.monotonic()
                if now - w.last_warn >= self.WATCH_QUEUE_WARN_INTERVAL:
                    w.last_warn = now
                    log.warning(
                        "watch subscriber %r is %d events behind (slow "
                        "consumer); its queue is unbounded and memory grows "
                        "until it drains",
                        w.label,
                        depth,
                    )
