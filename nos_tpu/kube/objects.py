"""Typed Kubernetes-shaped objects.

These are the nouns every component of the suite speaks: Pods carry resource
requests (``google.com/tpu``, sliced resources like
``google.com/tpu-slice-2x2``); Nodes carry capacity plus the spec/status
annotation protocol; ConfigMaps carry device-plugin configuration.

The reference uses the real k8s core/v1 types via client-go; here the subset
the suite actually touches is modeled natively (resource requests, phases,
labels/annotations, owner refs, priorities) so the whole control loop runs
in-process and under pytest.
"""
from __future__ import annotations

import copy
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

# Resource quantities. Chips/slices are integers; memory resources are floats
# (GB). A plain dict keeps arithmetic helpers in nos_tpu/util/resources.py.
ResourceList = Dict[str, float]

_uid_counter = itertools.count(1)
_uid_lock = threading.Lock()


def _new_uid() -> str:
    with _uid_lock:
        return f"uid-{next(_uid_counter)}"


class PodPhase:
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    UNKNOWN = "Unknown"


@dataclass
class OwnerReference:
    kind: str
    name: str
    uid: str = ""
    controller: bool = False


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = ""
    uid: str = field(default_factory=_new_uid)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    creation_timestamp: float = field(default_factory=time.time)
    resource_version: int = 0
    owner_references: List[OwnerReference] = field(default_factory=list)
    deletion_timestamp: Optional[float] = None


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # "" matches all effects

    def tolerates(self, taint: "Taint") -> bool:
        """core/v1 toleration semantics: empty key + Exists tolerates
        everything; empty effect matches all effects."""
        if self.effect and self.effect != taint.effect:
            return False
        if not self.key:
            return self.operator == "Exists"
        if self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        return self.value == taint.value


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | NoExecute | PreferNoSchedule


@dataclass
class NodeSelectorRequirement:
    key: str = ""
    operator: str = "In"  # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: List[str] = field(default_factory=list)

    def matches(self, labels: Dict[str, str]) -> bool:
        present = self.key in labels
        value = labels.get(self.key, "")
        if self.operator == "In":
            return present and value in self.values
        if self.operator == "NotIn":
            return not present or value not in self.values
        if self.operator == "Exists":
            return present
        if self.operator == "DoesNotExist":
            return not present
        if self.operator in ("Gt", "Lt"):
            try:
                lhs, rhs = int(value), int(self.values[0])
            except (ValueError, IndexError):
                return False
            return lhs > rhs if self.operator == "Gt" else lhs < rhs
        return False


@dataclass
class NodeSelectorTerm:
    """AND of match_expressions (terms themselves OR together)."""

    match_expressions: List[NodeSelectorRequirement] = field(default_factory=list)

    def matches(self, labels: Dict[str, str]) -> bool:
        return all(r.matches(labels) for r in self.match_expressions)


@dataclass
class NodeAffinity:
    """requiredDuringSchedulingIgnoredDuringExecution: node must match at
    least one term (terms OR, expressions within a term AND)."""

    required_terms: List[NodeSelectorTerm] = field(default_factory=list)

    def matches(self, labels: Dict[str, str]) -> bool:
        if not self.required_terms:
            return True
        return any(t.matches(labels) for t in self.required_terms)


@dataclass
class PodAffinityTerm:
    """requiredDuringSchedulingIgnoredDuringExecution pod (anti-)affinity
    term: selects PODS by labelSelector (matchLabels AND matchExpressions,
    k8s semantics) within a topology domain."""

    topology_key: str = ""
    match_labels: Dict[str, str] = field(default_factory=dict)
    # In/NotIn/Exists/DoesNotExist over pod labels (NodeSelectorRequirement
    # evaluates the same operator set).
    match_expressions: List["NodeSelectorRequirement"] = field(default_factory=list)
    # Empty = the owning pod's own namespace (k8s default).
    namespaces: List[str] = field(default_factory=list)

    def selects(self, pod_labels: Dict[str, str], pod_ns: str, own_ns: str) -> bool:
        if not self.match_labels and not self.match_expressions:
            # nil selector matches NO pods (upstream semantics)
            return False
        allowed = self.namespaces or [own_ns]
        if pod_ns not in allowed:
            return False
        if not all(pod_labels.get(k) == v for k, v in self.match_labels.items()):
            return False
        return all(r.matches(pod_labels) for r in self.match_expressions)


@dataclass
class TopologySpreadConstraint:
    """topologySpreadConstraints entry (DoNotSchedule honored as a filter,
    ScheduleAnyway left to scoring like the in-tree plugin)."""

    topology_key: str = ""
    max_skew: int = 1
    when_unsatisfiable: str = "DoNotSchedule"
    # matchLabels only; matchExpressions are not modeled.
    match_labels: Dict[str, str] = field(default_factory=dict)

    def selects(self, labels: Dict[str, str]) -> bool:
        # Upstream nil-selector semantics: a constraint without a selector
        # matches NO pods (the constraint is a no-op), not every pod.
        if not self.match_labels:
            return False
        return all(labels.get(k) == v for k, v in self.match_labels.items())


@dataclass
class Container:
    name: str = "main"
    image: str = ""
    # requests/limits: resource name -> quantity
    requests: ResourceList = field(default_factory=dict)
    limits: ResourceList = field(default_factory=dict)
    # name -> value (the suite only writes literal values, e.g. the gang's
    # distributed-init coordinates). valueFrom entries are not modeled;
    # the API-backed store grafts them back into any patch that must
    # mention the containers array (apistore._overlay_containers).
    env: Dict[str, str] = field(default_factory=dict)


@dataclass
class PodCondition:
    type: str
    status: str
    reason: str = ""
    message: str = ""


@dataclass
class PodSpec:
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    node_name: str = ""
    scheduler_name: str = "default-scheduler"
    priority: int = 0
    priority_class_name: str = ""
    tolerations: List[Toleration] = field(default_factory=list)
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[NodeAffinity] = None
    # Required-during-scheduling inter-pod terms (k8s nests these under
    # affinity.podAffinity / affinity.podAntiAffinity on the wire).
    pod_affinity: List[PodAffinityTerm] = field(default_factory=list)
    pod_anti_affinity: List[PodAffinityTerm] = field(default_factory=list)
    topology_spread_constraints: List[TopologySpreadConstraint] = field(
        default_factory=list
    )
    # Stable pod DNS under a headless Service (<hostname>.<subdomain>.<ns>
    # .svc) — what makes a gang leader's coordinator address resolvable.
    hostname: str = ""
    subdomain: str = ""


@dataclass
class PodStatus:
    phase: str = PodPhase.PENDING
    conditions: List[PodCondition] = field(default_factory=list)
    nominated_node_name: str = ""


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)
    kind: str = "Pod"

    @property
    def namespaced_name(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def deepcopy(self) -> "Pod":
        return copy.deepcopy(self)

    def is_owned_by_kind(self, kind: str) -> bool:
        return any(o.kind == kind for o in self.metadata.owner_references)

    def unschedulable(self) -> bool:
        """True when the scheduler reported PodScheduled=False/Unschedulable.

        Mirrors the pending∧unschedulable predicate feeding the partitioner
        batch (reference pkg/util/pod/pod.go:25-33).
        """
        for c in self.status.conditions:
            if c.type == "PodScheduled" and c.status == "False" and c.reason == "Unschedulable":
                return True
        return False


@dataclass
class NodeStatus:
    capacity: ResourceList = field(default_factory=dict)
    allocatable: ResourceList = field(default_factory=dict)


@dataclass
class NodeSpec:
    taints: List[Taint] = field(default_factory=list)
    unschedulable: bool = False


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)
    kind: str = "Node"

    def deepcopy(self) -> "Node":
        return copy.deepcopy(self)


@dataclass
class ConfigMap:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: Dict[str, str] = field(default_factory=dict)
    kind: str = "ConfigMap"

    def deepcopy(self) -> "ConfigMap":
        return copy.deepcopy(self)


@dataclass
class ServicePort:
    name: str = ""
    port: int = 0
    target_port: int = 0


@dataclass
class ServiceSpec:
    selector: Dict[str, str] = field(default_factory=dict)
    ports: List[ServicePort] = field(default_factory=list)
    cluster_ip: str = ""  # "None" = headless (per-pod DNS records)


@dataclass
class Service:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ServiceSpec = field(default_factory=ServiceSpec)
    kind: str = "Service"

    def deepcopy(self) -> "Service":
        return copy.deepcopy(self)


@dataclass
class Event:
    """core/v1 Event subset: who it is about (involvedObject), why
    (reason, from the constants table), what happened (message), and the
    dedup bookkeeping (count, first/lastTimestamp) the recorder bumps in
    place of writing a duplicate."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    involved_kind: str = ""
    involved_namespace: str = ""
    involved_name: str = ""
    reason: str = ""
    message: str = ""
    type: str = "Normal"  # Normal | Warning
    count: int = 1
    first_timestamp: float = field(default_factory=time.time)
    last_timestamp: float = field(default_factory=time.time)
    source_component: str = ""
    kind: str = "Event"

    def deepcopy(self) -> "Event":
        return copy.deepcopy(self)


@dataclass
class PodDisruptionBudgetSpec:
    """policy/v1 PDB subset the preemptor consults: a matchLabels selector
    plus exactly one of minAvailable / maxUnavailable (absolute counts)."""

    selector: Dict[str, str] = field(default_factory=dict)
    min_available: Optional[int] = None
    max_unavailable: Optional[int] = None


@dataclass
class PodDisruptionBudget:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodDisruptionBudgetSpec = field(default_factory=PodDisruptionBudgetSpec)
    kind: str = "PodDisruptionBudget"

    def deepcopy(self) -> "PodDisruptionBudget":
        return copy.deepcopy(self)


# ---------------------------------------------------------------------------
# Hand-written copiers. The store deepcopies every object on read, write,
# and watch-notify (kube API semantics: no shared mutable state between
# clients), and generic copy.deepcopy's memo machinery dominated the
# control-loop CPU profile on small hosts (~35% of samples). These build
# the same fully-independent copies several times cheaper. Every MUTABLE
# field must be copied here — update these when a class grows one.


def _copy_nsr(r: NodeSelectorRequirement) -> NodeSelectorRequirement:
    return NodeSelectorRequirement(r.key, r.operator, list(r.values))


def _copy_pat(t: PodAffinityTerm) -> PodAffinityTerm:
    return PodAffinityTerm(
        topology_key=t.topology_key,
        match_labels=dict(t.match_labels),
        match_expressions=[_copy_nsr(r) for r in t.match_expressions],
        namespaces=list(t.namespaces),
    )


def _meta_deepcopy(m: ObjectMeta, memo=None) -> ObjectMeta:
    return ObjectMeta(
        name=m.name,
        namespace=m.namespace,
        uid=m.uid,
        labels=dict(m.labels),
        annotations=dict(m.annotations),
        creation_timestamp=m.creation_timestamp,
        resource_version=m.resource_version,
        owner_references=[
            OwnerReference(o.kind, o.name, o.uid, o.controller)
            for o in m.owner_references
        ],
        deletion_timestamp=m.deletion_timestamp,
    )


def _container_copy(c: Container) -> Container:
    return Container(
        name=c.name,
        image=c.image,
        requests=dict(c.requests),
        limits=dict(c.limits),
        env=dict(c.env),
    )


def _podspec_deepcopy(s: PodSpec, memo=None) -> PodSpec:
    return PodSpec(
        containers=[_container_copy(c) for c in s.containers],
        init_containers=[_container_copy(c) for c in s.init_containers],
        node_name=s.node_name,
        scheduler_name=s.scheduler_name,
        priority=s.priority,
        priority_class_name=s.priority_class_name,
        tolerations=[
            Toleration(t.key, t.operator, t.value, t.effect)
            for t in s.tolerations
        ],
        node_selector=dict(s.node_selector),
        affinity=NodeAffinity(
            required_terms=[
                NodeSelectorTerm(
                    match_expressions=[_copy_nsr(r) for r in t.match_expressions]
                )
                for t in s.affinity.required_terms
            ]
        )
        if s.affinity is not None
        else None,
        pod_affinity=[_copy_pat(t) for t in s.pod_affinity],
        pod_anti_affinity=[_copy_pat(t) for t in s.pod_anti_affinity],
        topology_spread_constraints=[
            TopologySpreadConstraint(
                topology_key=t.topology_key,
                max_skew=t.max_skew,
                when_unsatisfiable=t.when_unsatisfiable,
                match_labels=dict(t.match_labels),
            )
            for t in s.topology_spread_constraints
        ],
        hostname=s.hostname,
        subdomain=s.subdomain,
    )


def _podstatus_deepcopy(s: PodStatus, memo=None) -> PodStatus:
    return PodStatus(
        phase=s.phase,
        conditions=[
            PodCondition(c.type, c.status, c.reason, c.message)
            for c in s.conditions
        ],
        nominated_node_name=s.nominated_node_name,
    )


def _pod_deepcopy(p: Pod, memo=None) -> Pod:
    return Pod(
        metadata=_meta_deepcopy(p.metadata),
        spec=_podspec_deepcopy(p.spec),
        status=_podstatus_deepcopy(p.status),
    )


def _node_deepcopy(n: Node, memo=None) -> Node:
    return Node(
        metadata=_meta_deepcopy(n.metadata),
        spec=NodeSpec(
            taints=[Taint(t.key, t.value, t.effect) for t in n.spec.taints],
            unschedulable=n.spec.unschedulable,
        ),
        status=NodeStatus(
            capacity=dict(n.status.capacity),
            allocatable=dict(n.status.allocatable),
        ),
    )


def _configmap_deepcopy(c: ConfigMap, memo=None) -> ConfigMap:
    return ConfigMap(metadata=_meta_deepcopy(c.metadata), data=dict(c.data))


def _service_deepcopy(s: Service, memo=None) -> Service:
    return Service(
        metadata=_meta_deepcopy(s.metadata),
        spec=ServiceSpec(
            selector=dict(s.spec.selector),
            ports=[
                ServicePort(p.name, p.port, p.target_port) for p in s.spec.ports
            ],
            cluster_ip=s.spec.cluster_ip,
        ),
    )


def _event_deepcopy(e: Event, memo=None) -> Event:
    return Event(
        metadata=_meta_deepcopy(e.metadata),
        involved_kind=e.involved_kind,
        involved_namespace=e.involved_namespace,
        involved_name=e.involved_name,
        reason=e.reason,
        message=e.message,
        type=e.type,
        count=e.count,
        first_timestamp=e.first_timestamp,
        last_timestamp=e.last_timestamp,
        source_component=e.source_component,
    )


def _pdb_deepcopy(p: PodDisruptionBudget, memo=None) -> PodDisruptionBudget:
    return PodDisruptionBudget(
        metadata=_meta_deepcopy(p.metadata),
        spec=PodDisruptionBudgetSpec(
            selector=dict(p.spec.selector),
            min_available=p.spec.min_available,
            max_unavailable=p.spec.max_unavailable,
        ),
    )


ObjectMeta.__deepcopy__ = _meta_deepcopy
PodSpec.__deepcopy__ = _podspec_deepcopy
PodStatus.__deepcopy__ = _podstatus_deepcopy
Pod.__deepcopy__ = _pod_deepcopy
Node.__deepcopy__ = _node_deepcopy
ConfigMap.__deepcopy__ = _configmap_deepcopy
Service.__deepcopy__ = _service_deepcopy
PodDisruptionBudget.__deepcopy__ = _pdb_deepcopy
Event.__deepcopy__ = _event_deepcopy
