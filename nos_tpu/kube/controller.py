"""Event-driven reconciler runtime (controller-runtime analogue).

Every nos component is a set of controller-runtime reconcilers driven by
watches (SURVEY.md §1: "event-driven controller-runtime reconcilers
throughout"). This module provides the same model: a Controller owns a
work queue fed by store watch events through predicates + request mappers,
and a worker that calls ``reconcile(request)`` with requeue support.

A Manager starts/stops a set of controllers against one KubeStore and — for
tests — can block until the whole system is quiescent (``wait_idle``), which
is what envtest's "eventually" assertions amount to.
"""
from __future__ import annotations

import heapq
import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

from nos_tpu.kube.store import KubeStore, WatchEvent
from nos_tpu.util import metrics
from nos_tpu.util.loop_health import LOOPS, BusyMeter
from nos_tpu.util.profiling import PROFILER

log = logging.getLogger("nos_tpu.kube")


@dataclass(frozen=True)
class Request:
    name: str
    namespace: str = ""

    @property
    def namespaced_name(self) -> str:
        return f"{self.namespace}/{self.name}" if self.namespace else self.name


@dataclass
class Result:
    requeue: bool = False
    requeue_after: float = 0.0


Predicate = Callable[[WatchEvent], bool]
Mapper = Callable[[WatchEvent], Sequence[Request]]


def default_mapper(event: WatchEvent) -> Sequence[Request]:
    return [Request(name=event.object.metadata.name, namespace=event.object.metadata.namespace)]


@dataclass
class Watch:
    kind: str
    predicate: Optional[Predicate] = None
    mapper: Mapper = default_mapper


class _WorkQueue:
    """Deduplicating work queue with delayed re-adds.

    Mirrors client-go's rate-limiting workqueue semantics: an item present in
    the queue is not added twice; an item being processed when re-added is
    re-queued after processing finishes.
    """

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._queue: List[Request] = []
        self._dirty: set = set()
        self._processing: set = set()
        self._delayed: List[Tuple[float, int, Request]] = []
        self._seq = 0
        self._shutdown = False

    def add(self, req: Request) -> None:
        with self._cond:
            if req in self._dirty:
                return
            self._dirty.add(req)
            if req not in self._processing:
                self._queue.append(req)
            self._cond.notify()

    def add_after(self, req: Request, delay: float) -> None:
        if delay <= 0:
            self.add(req)
            return
        with self._cond:
            self._seq += 1
            heapq.heappush(self._delayed, (time.monotonic() + delay, self._seq, req))
            self._cond.notify()

    def _promote_due(self) -> None:
        now = time.monotonic()
        while self._delayed and self._delayed[0][0] <= now:
            _, _, req = heapq.heappop(self._delayed)
            if req not in self._dirty:
                self._dirty.add(req)
                if req not in self._processing:
                    self._queue.append(req)

    def get(self, timeout: float = 0.2) -> Optional[Request]:
        with self._cond:
            deadline = time.monotonic() + timeout
            while True:
                self._promote_due()
                if self._queue:
                    req = self._queue.pop(0)
                    self._dirty.discard(req)
                    self._processing.add(req)
                    return req
                if self._shutdown:
                    return None
                wait = deadline - time.monotonic()
                if self._delayed:
                    wait = min(wait, self._delayed[0][0] - time.monotonic())
                if wait <= 0:
                    return None
                self._cond.wait(wait)

    def done(self, req: Request) -> None:
        with self._cond:
            self._processing.discard(req)
            if req in self._dirty:
                self._queue.append(req)
                self._cond.notify()

    def shut_down(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    def idle(self) -> bool:
        with self._cond:
            return not self._queue and not self._processing and not self._dirty


class Controller:
    """One reconciler + its watches, running on two threads (event pump and
    worker), like a controller-runtime controller with MaxConcurrentReconciles=1
    (the reference's node controller raises this to 10 —
    gpupartitioner/node_controller.go; a single worker is enough in-process).
    """

    def __init__(
        self,
        name: str,
        store: KubeStore,
        reconciler: Callable[[Request], Optional[Result]],
        watches: Sequence[Watch],
    ) -> None:
        self.name = name
        self.store = store
        self.reconciler = reconciler
        self.watches = list(watches)
        self.queue = _WorkQueue()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._event_queue: Optional["queue.Queue[WatchEvent]"] = None
        self._busy = BusyMeter(name)
        self._drain_lag = metrics.WATCH_DRAIN_LAG.labels(consumer=name)

    # -- event pump -----------------------------------------------------

    def _dispatch(self, event: WatchEvent) -> None:
        for w in self.watches:
            if w.kind != event.kind:
                continue
            if w.predicate is not None and not w.predicate(event):
                continue
            for req in w.mapper(event):
                self.queue.add(req)

    def _pump(self) -> None:
        from nos_tpu.timeline.watchdog import WATCHDOG

        assert self._event_queue is not None
        PROFILER.register_thread()
        try:
            while not self._stop.is_set():
                WATCHDOG.beat(f"{self.name}-pump")
                try:
                    event = self._event_queue.get(timeout=0.2)
                except queue.Empty:
                    continue
                if event.enqueued:
                    self._drain_lag.observe(time.monotonic() - event.enqueued)
                try:
                    self._dispatch(event)
                except Exception:  # pragma: no cover - defensive
                    log.exception("[%s] dispatch failed", self.name)
        finally:
            PROFILER.unregister_thread()

    # -- worker ---------------------------------------------------------

    def _work(self) -> None:
        from nos_tpu.timeline.watchdog import WATCHDOG

        PROFILER.register_thread()
        try:
            while not self._stop.is_set():
                WATCHDOG.beat(f"{self.name}-work")
                t0 = time.monotonic()
                req = self.queue.get(timeout=0.2)
                t1 = time.monotonic()
                if req is None:
                    self._busy.record(0.0, idle_s=t1 - t0)
                    continue
                try:
                    result = self.reconciler(req)
                except Exception:
                    log.exception("[%s] reconcile %s failed; requeuing", self.name, req.namespaced_name)
                    result = Result(requeue=True, requeue_after=0.05)
                finally:
                    self.queue.done(req)
                    self._busy.record(time.monotonic() - t1, idle_s=t1 - t0)
                if result and result.requeue_after > 0:
                    self.queue.add_after(req, result.requeue_after)
                elif result and result.requeue:
                    self.queue.add(req)
        finally:
            PROFILER.unregister_thread()

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        from nos_tpu.timeline.watchdog import WATCHDOG

        kinds = {w.kind for w in self.watches}
        self._event_queue = self.store.watch(kinds, name=self.name)
        LOOPS.register(self.name, self._loop_stats)
        for target, label in ((self._pump, "pump"), (self._work, "work")):
            # Both loops poll with a timeout, so they beat continuously —
            # but they only *do* work on events, hence periodic=False.
            WATCHDOG.register(
                f"{self.name}-{label}",
                periodic=False,
                thread_name=f"{self.name}-{label}",
            )
            t = threading.Thread(target=target, name=f"{self.name}-{label}", daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        from nos_tpu.timeline.watchdog import WATCHDOG

        self._stop.set()
        self.queue.shut_down()
        LOOPS.unregister(self.name)
        for label in ("pump", "work"):
            WATCHDOG.unregister(f"{self.name}-{label}")
        if self._event_queue is not None:
            self.store.stop_watch(self._event_queue)
        for t in self._threads:
            t.join(timeout=2.0)

    def _loop_stats(self) -> dict:
        eq = self._event_queue
        stats = self._busy.snapshot()
        stats["event_queue_depth"] = eq.qsize() if eq is not None else 0
        stats["workqueue_idle"] = self.queue.idle()
        return stats

    def idle(self) -> bool:
        eq = self._event_queue
        return (eq is None or eq.empty()) and self.queue.idle()


@dataclass
class Manager:
    """Holds the store and a set of controllers (one per nos binary's manager)."""

    store: KubeStore = field(default_factory=KubeStore)
    controllers: List[Controller] = field(default_factory=list)
    _runnables: List[Callable[[], None]] = field(default_factory=list)
    _stoppables: List[Callable[[], None]] = field(default_factory=list)

    def add(self, controller: Controller) -> None:
        self.controllers.append(controller)

    def add_runnable(self, start: Callable[[], None], stop: Callable[[], None]) -> None:
        self._runnables.append(start)
        self._stoppables.append(stop)

    def start(self) -> None:
        for c in self.controllers:
            c.start()
        for r in self._runnables:
            r()

    def stop(self) -> None:
        for s in self._stoppables:
            s()
        for c in self.controllers:
            c.stop()

    def wait_idle(self, timeout: float = 10.0, settle: float = 0.05) -> bool:
        """Block until every controller's queues are empty and stay empty for
        ``settle`` seconds (reconcile cascades included). Test helper standing
        in for envtest's Eventually()."""
        deadline = time.monotonic() + timeout
        idle_since: Optional[float] = None
        while time.monotonic() < deadline:
            if all(c.idle() for c in self.controllers):
                if idle_since is None:
                    idle_since = time.monotonic()
                elif time.monotonic() - idle_since >= settle:
                    return True
            else:
                idle_since = None
            time.sleep(0.01)
        return False
