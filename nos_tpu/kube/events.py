"""Kubernetes-style Event recording.

The reference emits operator-facing Events through controller-runtime's
``record.EventRecorder``, which correlates (dedups + rate-limits) before
anything hits the apiserver. This is the same contract for the in-process
suite: ``EventRecorder.record(obj, reason, message)`` writes a
``v1.Event``-shaped object through whichever store it was given (the
in-memory KubeStore or the API-backed KubeApiStore — same method surface),
bumping ``count``/``lastTimestamp`` on an identical (object, reason,
message) repeat instead of writing a duplicate, and dropping floods
through a per-object token bucket (burst then steady refill, like
client-go's EventSourceObjectSpamFilter).

Reasons come from the single constants table in
``nos_tpu/api/v1alpha1/constants.py`` — an unknown reason raises, and a
lint test keeps call sites honest.
"""
from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, List, Optional

from nos_tpu.api.v1alpha1 import constants
from nos_tpu.api.v1alpha1.annotations import PREFIX
from nos_tpu.kube.objects import Event
from nos_tpu.kube.store import AlreadyExistsError, NotFoundError

# Correlation: every Event carries the trace id of the decision journey
# that emitted it, so `kubectl describe` output links straight into
# /debug/traces. Annotation only — NOT part of the dedup digest, or each
# journey would mint a fresh Event instead of bumping the counter.
TRACE_ID_ANNOTATION = PREFIX + "trace-id"

# client-go spam-filter defaults: a burst of 25 events per object, then
# one more every 5 minutes (qps = 1/300).
DEFAULT_BURST = 25
DEFAULT_REFILL_PER_SECOND = 1.0 / 300.0

# Correlator state is bounded: beyond this many distinct buckets the
# oldest-touched half is dropped (worst case: a flood re-earns its burst).
_MAX_BUCKETS = 4096


class _TokenBucket:
    __slots__ = ("tokens", "last_refill", "last_touch")

    def __init__(self, burst: float, now: float) -> None:
        self.tokens = burst
        self.last_refill = now
        self.last_touch = now


class EventRecorder:
    """Writes deduped, rate-limited ``Event`` objects through a store."""

    def __init__(
        self,
        store: Any,
        component: str = "",
        burst: int = DEFAULT_BURST,
        refill_per_second: float = DEFAULT_REFILL_PER_SECOND,
        clock=time.time,
    ) -> None:
        self.store = store
        self.component = component
        self.burst = float(burst)
        self.refill_per_second = refill_per_second
        self.clock = clock
        self._lock = threading.Lock()
        self._buckets = {}
        self.dropped = 0  # rate-limited records (observable in tests)

    # ------------------------------------------------------------- recording

    def record(
        self,
        obj: Any,
        reason: str,
        message: str,
        type: str = "Normal",
    ) -> Optional[Event]:
        """Record one occurrence; returns the stored Event or None if the
        rate limiter dropped it. ``obj`` is any typed object with ``kind``
        and ``metadata`` (Pod, Node, ElasticQuota, ...)."""
        if reason not in constants.EVENT_REASONS:
            raise ValueError(
                f"event reason {reason!r} is not in "
                "nos_tpu.api.v1alpha1.constants.EVENT_REASONS"
            )
        involved_kind = obj.kind
        involved_ns = obj.metadata.namespace
        involved_name = obj.metadata.name
        if not self._allow(involved_kind, involved_ns, involved_name):
            with self._lock:
                self.dropped += 1
            return None

        now = self.clock()
        name = self._event_name(
            involved_kind, involved_ns, involved_name, reason, message
        )
        # Events about cluster-scoped objects (Nodes) land in "default",
        # like the real apiserver's event sink.
        event_ns = involved_ns or "default"

        from nos_tpu.util.tracing import TRACER

        span = TRACER.current()
        trace_id = span.trace_id if span is not None else ""

        def bump(ev: Event) -> None:
            ev.count += 1
            ev.last_timestamp = now
            if trace_id:
                # A repeat keeps the annotation pointing at its LATEST
                # occurrence's journey — that's the trace still in the
                # ring buffer when an operator goes looking.
                ev.metadata.annotations[TRACE_ID_ANNOTATION] = trace_id

        try:
            return self.store.patch_merge("Event", name, event_ns, bump)
        except NotFoundError:
            pass
        ev = Event(
            involved_kind=involved_kind,
            involved_namespace=involved_ns,
            involved_name=involved_name,
            reason=reason,
            message=message,
            type=type,
            count=1,
            first_timestamp=now,
            last_timestamp=now,
            source_component=self.component,
        )
        ev.metadata.name = name
        ev.metadata.namespace = event_ns
        if trace_id:
            ev.metadata.annotations[TRACE_ID_ANNOTATION] = trace_id
        try:
            return self.store.create(ev)
        except AlreadyExistsError:
            # Raced another recorder thread to the first write.
            return self.store.patch_merge("Event", name, event_ns, bump)

    def events_for(self, obj: Any) -> List[Event]:
        """All stored Events about ``obj``, oldest first."""
        kind, ns, name = obj.kind, obj.metadata.namespace, obj.metadata.name
        out = [
            e
            for e in self.store.list("Event", namespace=ns or "default")
            if e.involved_kind == kind
            and e.involved_namespace == ns
            and e.involved_name == name
        ]
        out.sort(key=lambda e: e.first_timestamp)
        return out

    # ---------------------------------------------------------- rate limiter

    def _allow(self, kind: str, ns: str, name: str) -> bool:
        """One token bucket per involved object: dedup keeps the store
        small, but a hot reconcile loop can still bump one Event forever —
        the bucket caps how often that write happens at all."""
        key = (kind, ns, name)
        now = self.clock()
        with self._lock:
            bucket = self._buckets.get(key)
            if bucket is None:
                if len(self._buckets) >= _MAX_BUCKETS:
                    stale = sorted(
                        self._buckets.items(), key=lambda kv: kv[1].last_touch
                    )[: _MAX_BUCKETS // 2]
                    for k, _ in stale:
                        del self._buckets[k]
                bucket = _TokenBucket(self.burst, now)
                self._buckets[key] = bucket
            else:
                elapsed = max(0.0, now - bucket.last_refill)
                bucket.tokens = min(
                    self.burst, bucket.tokens + elapsed * self.refill_per_second
                )
                bucket.last_refill = now
            bucket.last_touch = now
            if bucket.tokens < 1.0:
                return False
            bucket.tokens -= 1.0
            return True

    @staticmethod
    def _event_name(kind: str, ns: str, name: str, reason: str, message: str) -> str:
        """Deterministic per-(object, reason, message) name so dedup works
        across recorder instances and process restarts."""
        digest = hashlib.sha1(
            "\x00".join((kind, ns, name, reason, message)).encode()
        ).hexdigest()[:12]
        return f"{name}.{digest}"
