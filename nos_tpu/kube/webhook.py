"""Validating admission webhook server — the TLS endpoint a real
apiserver calls.

Reference pkg/api/nos.nebuly.com/v1alpha1/elasticquota_webhook.go:31-97
registers validators on controller-runtime's webhook server; the apiserver
POSTs AdmissionReview v1 documents to it over TLS and enforces the
returned allow/deny. This module is that half: an HTTPS server decoding
AdmissionReview requests, converting the embedded object through the wire
codecs, and running the same validator functions the in-process store
seam uses (nos_tpu/controllers/elasticquota/webhooks.py) against the
informer-backed store — one validation implementation, two transports.

Certificates: production mounts a cert-manager secret (`certFile` /
`keyFile` in the operator config); for demos/tests
``generate_self_signed_cert`` mints one with the ``cryptography`` package.
"""
from __future__ import annotations

import base64
import json
import logging
import ssl
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple

from nos_tpu.kube import serde
from nos_tpu.kube.store import AdmissionError, KubeStore
from nos_tpu.util import metrics

logger = logging.getLogger("nos_tpu.webhook")

# Webhook URL paths, mirroring the reference's controller-runtime
# registrations (one path per validated kind).
PATH_ELASTICQUOTA = "/validate-nos-nebuly-com-v1alpha1-elasticquota"
PATH_COMPOSITEELASTICQUOTA = "/validate-nos-nebuly-com-v1alpha1-compositeelasticquota"
# Mutating path: multi-host slice expansion at pod admission (the only
# point a real apiserver allows the rewrite).
PATH_MUTATE_POD = "/mutate-v1-pod"


def generate_self_signed_cert(
    common_name: str = "nos-tpu-webhook",
    sans: Tuple[str, ...] = ("localhost", "127.0.0.1"),
    days: int = 365,
) -> Tuple[bytes, bytes]:
    """(cert_pem, key_pem) for local serving; production uses cert-manager."""
    import datetime
    import ipaddress

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    alt_names = []
    for san in sans:
        try:
            alt_names.append(x509.IPAddress(ipaddress.ip_address(san)))
        except ValueError:
            alt_names.append(x509.DNSName(san))
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now)
        .not_valid_after(now + datetime.timedelta(days=days))
        .add_extension(x509.SubjectAlternativeName(alt_names), critical=False)
        .sign(key, hashes.SHA256())
    )
    return (
        cert.public_bytes(serialization.Encoding.PEM),
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        ),
    )


class WebhookServer:
    """HTTPS AdmissionReview endpoint bound to validator callables."""

    def __init__(
        self,
        store: KubeStore,
        port: int = 9443,
        host: str = "0.0.0.0",
        cert_pem: Optional[bytes] = None,
        key_pem: Optional[bytes] = None,
        cert_file: str = "",
        key_file: str = "",
    ) -> None:
        self.store = store
        # path -> validator(obj, store) raising AdmissionError to deny
        self._validators: Dict[str, Callable] = {}
        # path -> mutator(wire_obj, store) -> JSONPatch ops | None
        self._mutators: Dict[str, Callable] = {}
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # noqa: N802 — quiet
                pass

            def do_POST(self) -> None:  # noqa: N802
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n)
                path = self.path.partition("?")[0]
                validator = server._validators.get(path)
                mutator = server._mutators.get(path)
                if validator is None and mutator is None:
                    self._respond(404, {"message": f"no webhook at {path}"})
                    return
                try:
                    review = json.loads(body)
                    if mutator is not None:
                        response = server._mutate_review(review, mutator)
                    else:
                        response = server._review(review, validator)
                except Exception as e:  # noqa: BLE001 — malformed reviews
                    self._respond(400, {"message": f"bad AdmissionReview: {e}"})
                    return
                self._respond(200, response)

            def _respond(self, code: int, payload: dict) -> None:
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        if cert_file and key_file:
            ctx.load_cert_chain(cert_file, key_file)
        else:
            import tempfile
            import os

            if cert_pem is None or key_pem is None:
                cert_pem, key_pem = generate_self_signed_cert()
                logger.warning(
                    "webhook: serving with a generated self-signed certificate "
                    "(configure certFile/keyFile for production)"
                )
            self.cert_pem = cert_pem
            with tempfile.TemporaryDirectory(prefix="nos-tpu-webhook-") as d:
                cert_path = os.path.join(d, "tls.crt")
                key_path = os.path.join(d, "tls.key")
                with open(cert_path, "wb") as f:
                    f.write(cert_pem)
                with open(key_path, "wb") as f:
                    f.write(key_pem)
                ctx.load_cert_chain(cert_path, key_path)
        self._httpd.socket = ctx.wrap_socket(self._httpd.socket, server_side=True)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="webhook-server", daemon=True
        )

    # ------------------------------------------------------------ plumbing

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def register(self, path: str, validator: Callable) -> None:
        self._validators[path] = validator

    def register_mutator(self, path: str, mutator: Callable) -> None:
        self._mutators[path] = mutator

    def start(self) -> "WebhookServer":
        self._thread.start()
        logger.info("webhook server listening on :%d (TLS)", self.port)
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    # ------------------------------------------------------------- review

    def _review(self, review: dict, validator: Callable) -> dict:
        request = review.get("request") or {}
        uid = request.get("uid", "")
        wire = request.get("object") or {}
        try:
            obj = serde.from_wire(wire)
            validator(obj, self.store)
            response = {"uid": uid, "allowed": True}
        except AdmissionError as e:
            metrics.WEBHOOK_DENIALS.inc()
            response = {
                "uid": uid,
                "allowed": False,
                "status": {"message": str(e), "code": 403},
            }
        except Exception as e:  # noqa: BLE001 — undecodable objects deny
            metrics.WEBHOOK_DENIALS.inc()
            response = {
                "uid": uid,
                "allowed": False,
                "status": {"message": f"webhook error: {e}", "code": 400},
            }
        return {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "response": response,
        }

    def _mutate_review(self, review: dict, mutator: Callable) -> dict:
        request = review.get("request") or {}
        uid = request.get("uid", "")
        wire = request.get("object") or {}
        response: dict = {"uid": uid, "allowed": True}
        try:
            ops = mutator(wire, self.store)
            if ops:
                response["patchType"] = "JSONPatch"
                response["patch"] = base64.b64encode(
                    json.dumps(ops).encode()
                ).decode()
        except Exception as e:  # noqa: BLE001 — mutation failures must not
            # block unrelated admissions (failurePolicy Ignore semantics
            # server-side too): admit unmodified, log loudly.
            logger.warning("mutating webhook failed, admitting unpatched: %s", e)
        return {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "response": response,
        }


def build_elasticquota_webhook_server(
    store: KubeStore,
    port: int = 9443,
    host: str = "0.0.0.0",
    cert_file: str = "",
    key_file: str = "",
) -> WebhookServer:
    """The operator's webhook server with both quota validators bound
    (reference operator.go:96-117 SetupWebhookWithManager calls)."""
    from nos_tpu.controllers.elasticquota.webhooks import (
        validate_composite_elastic_quota,
        validate_elastic_quota,
    )

    server = WebhookServer(
        store, port=port, host=host, cert_file=cert_file, key_file=key_file
    )
    server.register(PATH_ELASTICQUOTA, validate_elastic_quota)
    server.register(PATH_COMPOSITEELASTICQUOTA, validate_composite_elastic_quota)

    # Multi-host expansion belongs to the partitioner conceptually, but the
    # admission rewrite must happen HERE: pod labels/requests/env are
    # immutable after admission on a real apiserver (the in-memory suite's
    # controller patch path models the same seam without TLS).
    from nos_tpu.controllers.partitioner.multihost import admission_mutate_pod

    server.register_mutator(PATH_MUTATE_POD, admission_mutate_pod)
    return server
