"""Lease-based leader election over the store.

Reference binaries enable controller-runtime leader election so exactly one
replica of each deployment reconciles (`LeaderElection` options built from
the component configs, pkg/api/nos.nebuly.com/config/v1alpha1). The same
semantics here, client-go's resourcelock pattern over a ConfigMap: the
lock object's annotations carry holderIdentity + a renew counter;
acquisition and renewal are optimistic-concurrency patches, so over the
API-backed store (nos_tpu/kube/apistore.py) this is a real distributed
lock — conflicting writers lose the resourceVersion race and observe the
winner.

Clock skew cannot steal a live lease: a challenger times the lease age
from its OWN monotonic clock, starting when it first observes a given
(holder, renew) pair — the remote wall-clock timestamp is informational
only (exactly client-go's observedTime discipline). A leader that cannot
reach the store steps down once its local renew deadline (the lease
duration) passes without a successful renewal.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from nos_tpu.kube.objects import ConfigMap, ObjectMeta
from nos_tpu.kube.store import AlreadyExistsError, ConflictError, KubeStore, NotFoundError
from nos_tpu.util import metrics

logger = logging.getLogger("nos_tpu.leaderelection")

HOLDER_ANNOTATION = "nos.nebuly.com/leader-holder"
RENEW_ANNOTATION = "nos.nebuly.com/leader-renew-time"


class _HeldByOther(Exception):
    def __init__(self, holder: str) -> None:
        super().__init__(f"lease held by {holder}")
        self.holder = holder


class LeaderElector:
    """Acquire/renew a named lease; callbacks fire on transitions."""

    def __init__(
        self,
        store: KubeStore,
        name: str,
        identity: str,
        namespace: str = "nos-system",
        lease_duration_s: float = 15.0,
        renew_period_s: float = 5.0,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ) -> None:
        self.store = store
        self.name = name
        self.identity = identity
        self.namespace = namespace
        self.lease_duration_s = lease_duration_s
        self.renew_period_s = renew_period_s
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.is_leader = False
        # Injectable wall clock: the chaos clock-skew fault points this at
        # a skewed source so RENEW_ANNOTATION stamps diverge from true
        # wall time — expiry must keep working (it only reads local
        # monotonic ages, never remote wall stamps).
        self.wall_clock = time.time
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Serializes a renew attempt (+ the is_leader transition it drives)
        # against release(): without it, a release() from another thread
        # can land mid-renew — it demotes and clears the lock, then the
        # in-flight renew returns True and re-promotes, overlapping with
        # whichever challenger took the freed lease.
        self._lease_lock = threading.Lock()
        # (holder, renew) last observed on the lock + local monotonic time
        # of FIRST observing that exact pair — the skew-free age source.
        self._observed: Optional[tuple] = None
        self._last_renew_ok = 0.0  # local monotonic of our last good renew

    # -------------------------------------------------------------- lease

    def _try_acquire_or_renew(self) -> bool:
        now_mono = time.monotonic()

        def mutate(cm: ConfigMap) -> None:
            holder = cm.metadata.annotations.get(HOLDER_ANNOTATION, "")
            renew = cm.metadata.annotations.get(RENEW_ANNOTATION, "")
            if holder and holder != self.identity:
                observed = self._observed
                if observed is None or observed[0] != holder or observed[1] != renew:
                    # Fresh activity on the lock: restart OUR lease timer.
                    self._observed = (holder, renew, now_mono)
                    raise _HeldByOther(holder)
                if now_mono - observed[2] < self.lease_duration_s:
                    raise _HeldByOther(holder)
                # No renewal for a full local lease duration: expired.
            cm.metadata.annotations[HOLDER_ANNOTATION] = self.identity
            # Wall time is informational (humans, kubectl); expiry never
            # compares it across machines.
            cm.metadata.annotations[RENEW_ANNOTATION] = str(self.wall_clock())

        try:
            self.store.patch_merge("ConfigMap", self.name, self.namespace, mutate)
            return True
        except _HeldByOther as e:
            logger.debug("lease %s held by %s", self.name, e.holder)
            return False
        except ConflictError:
            return False
        except NotFoundError:
            pass
        try:
            self.store.create(
                ConfigMap(
                    metadata=ObjectMeta(
                        name=self.name,
                        namespace=self.namespace,
                        annotations={
                            HOLDER_ANNOTATION: self.identity,
                            RENEW_ANNOTATION: str(self.wall_clock()),
                        },
                    )
                )
            )
            return True
        except AlreadyExistsError:
            return False

    def release(self) -> None:
        """Voluntarily drop the lease: clearing the holder lets the next
        challenger acquire instantly (no lease-duration wait).

        Demotes BEFORE touching the lock: the moment the holder field
        clears, a challenger may acquire — if this elector still reported
        is_leader until its next renew tick, two leaders would overlap for
        up to a renew period. Demoting first errs the safe way (briefly no
        leader, never two)."""
        def mutate(cm: ConfigMap) -> None:
            if cm.metadata.annotations.get(HOLDER_ANNOTATION) != self.identity:
                raise _HeldByOther(cm.metadata.annotations.get(HOLDER_ANNOTATION, ""))
            cm.metadata.annotations[HOLDER_ANNOTATION] = ""
            cm.metadata.annotations[RENEW_ANNOTATION] = "0"

        with self._lease_lock:
            if self.is_leader:
                self.is_leader = False
                logger.info(
                    "lease %s: %s released leadership", self.name, self.identity
                )
                if self.on_stopped_leading:
                    self.on_stopped_leading()
            try:
                self.store.patch_merge("ConfigMap", self.name, self.namespace, mutate)
            except (_HeldByOther, NotFoundError, ConflictError):
                pass
            except Exception as e:  # noqa: BLE001 — releasing must never raise
                logger.warning("lease %s: release failed: %s", self.name, e)

    # --------------------------------------------------------------- loop

    def run(self, stop: Optional[threading.Event] = None) -> None:
        """Block until stopped: acquire, then renew; transition callbacks
        fire on gain/loss. A lost or unrenewable lease stops leadership
        (the controller-runtime leader-elected runnable contract); store
        errors never kill the loop — an unreachable apiserver demotes the
        leader only after the renew deadline."""
        stop = stop or self._stop
        while not stop.is_set():
            # The whole attempt + transition holds the lease lock so a
            # concurrent release() cannot interleave between our renew
            # landing on the store and the is_leader flip it justifies.
            with self._lease_lock:
                try:
                    got = self._try_acquire_or_renew()
                except Exception as e:  # noqa: BLE001 — elector must survive
                    logger.warning(
                        "lease %s: renew attempt failed: %s: %s",
                        self.name, type(e).__name__, e,
                    )
                    # Retain leadership only within the renew deadline.
                    got = (
                        self.is_leader
                        and time.monotonic() - self._last_renew_ok
                        < self.lease_duration_s
                    )
                else:
                    if got:
                        self._last_renew_ok = time.monotonic()
                if got and not self.is_leader:
                    # Counter ticks BEFORE the flag flips: wait_for_leadership
                    # observers must never see is_leader without the count.
                    metrics.LEADER_TRANSITIONS.inc()
                    self.is_leader = True
                    logger.info("lease %s: %s became leader", self.name, self.identity)
                    if self.on_started_leading:
                        self.on_started_leading()
                elif not got and self.is_leader:
                    self.is_leader = False
                    logger.warning(
                        "lease %s: %s LOST leadership", self.name, self.identity
                    )
                    if self.on_stopped_leading:
                        self.on_stopped_leading()
            stop.wait(self.renew_period_s if self.is_leader else self.renew_period_s / 2)
        if self.is_leader:
            self.is_leader = False
            self.release()
            if self.on_stopped_leading:
                self.on_stopped_leading()

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run, name=f"leader-elector-{self.name}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)

    def wait_for_leadership(self, timeout: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.is_leader:
                return True
            time.sleep(0.02)
        return False
