"""Shared standalone-component runner.

Each reference binary is its own Docker image taking exactly one
``--config <file>`` flag (SURVEY.md §2.1); this helper gives every nos-tpu
component the same shape: parse flags, decode the typed config, build the
component onto a manager, serve healthz/readyz/metrics, run until
SIGINT/SIGTERM. A ``stop_event`` can be injected for in-process smoke tests
(signal handlers only work on the main thread).
"""
from __future__ import annotations

import argparse
import logging
import signal
import threading
from typing import Callable, Optional

from nos_tpu.kube.controller import Manager
from nos_tpu.kube.store import KubeStore
from nos_tpu.util.health import HealthServer
from nos_tpu.util.loop_health import LOOPS
from nos_tpu.util.profiling import PROFILER


def build_store(config: dict) -> KubeStore:
    """Store backend from the component config's `store:` block.

    - `type: in-memory` (default) — the in-process suite/test store.
    - `type: kubeconfig` — live apiserver via a kubeconfig
      (`kubeconfig: <path>`, `context: <name>` optional).
    - `type: in-cluster` — pod service-account credentials; what a helm
      install runs (reference binaries always run in-cluster,
      cmd/operator/operator.go:50-126).
    """
    store_cfg = (config.get("store") or {}) if isinstance(config, dict) else {}
    stype = store_cfg.get("type", "in-memory")
    if stype == "in-memory":
        return KubeStore()
    from nos_tpu.kube.apiclient import KubeApiClient
    from nos_tpu.kube.apistore import KubeApiStore

    if stype == "kubeconfig":
        client = KubeApiClient.from_kubeconfig(
            store_cfg.get("kubeconfig") or None, store_cfg.get("context") or None
        )
    elif stype == "in-cluster":
        client = KubeApiClient.in_cluster()
    else:
        raise ValueError(f"unknown store type {stype!r}")
    kinds = store_cfg.get("kinds")
    store = KubeApiStore(client, kinds=kinds) if kinds else KubeApiStore(client)
    store.start(sync_timeout_s=float(store_cfg.get("syncTimeoutSeconds", 30)))
    return store


def component_argparser(name: str) -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description=f"nos-tpu {name}")
    parser.add_argument("--config", default="", help="YAML component config")
    parser.add_argument("--health-port", type=int, default=None)
    parser.add_argument("-v", "--verbose", action="count", default=0)
    return parser


def run_component(
    name: str,
    build: Callable[[Manager, dict], None],
    argv=None,
    stop_event: Optional[threading.Event] = None,
    ready_check: Optional[Callable[[], bool]] = None,
) -> int:
    """`build(manager, config_dict)` wires the component; then serve.

    When `build` returns an object with an ``explain`` callable (the
    scheduler), it is served as ``/debug/explain`` next to /metrics."""
    from nos_tpu.cmd.run import load_config

    parser = component_argparser(name)
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    config = load_config(args.config)

    store = build_store(config)
    manager = Manager(store=store)
    component = build(manager, config)

    manager_cfg = config.get("manager") or {}
    port = args.health_port
    if port is None:
        port = manager_cfg.get("healthProbePort", 8081)
    # Bind all interfaces by default: kubelet probes the pod IP, not
    # loopback (override via manager.healthProbeHost for local runs).
    # manager.metricsLoopbackPort (kube-rbac-proxy mode) moves /metrics to
    # its own loopback listener for the sidecar while probes stay on the
    # pod IP; manager.metricsAuthTokenFile enforces a bearer token
    # re-read per scrape (Secret rotation works without restart; a
    # missing file fails closed with 401, never open).
    metrics_token: "str | object" = ""
    token_file = manager_cfg.get("metricsAuthTokenFile", "")
    if token_file:
        def metrics_token():  # noqa: F811 — provider shadows the default
            try:
                with open(token_file) as fh:
                    return fh.read().strip()
            except OSError:
                return None
    metrics_port = manager_cfg.get("metricsLoopbackPort")
    # Always-on longitudinal health timeline: samples this component's
    # metric families, process vitals, and registered memo/ring sizes;
    # detector findings Event against a well-known ConfigMap identity.
    from nos_tpu.kube.events import EventRecorder
    from nos_tpu.kube.objects import ConfigMap, ObjectMeta
    from nos_tpu.timeline import TimelineStore

    timeline = TimelineStore(
        interval_seconds=float(manager_cfg.get("timelineSampleSeconds", 5.0))
    )
    timeline.attach(
        recorder=EventRecorder(store, component=f"nos-{name}-health-timeline"),
        event_obj=ConfigMap(
            metadata=ObjectMeta(name="nos-health-timeline", namespace="default")
        ),
    )
    health = HealthServer(
        port=port,
        ready_check=ready_check,
        host=manager_cfg.get("healthProbeHost", "0.0.0.0"),
        metrics_token=metrics_token,
        metrics_loopback_port=int(metrics_port) if metrics_port else None,
        explain_fn=getattr(component, "explain", None),
        profiler=PROFILER,
        loops_fn=lambda: LOOPS.payload(store=store),
        # The standalone partitioner's forecaster (None for components
        # without one — the endpoint stays unregistered).
        forecast_fn=(
            getattr(component, "forecaster").debug_payload
            if getattr(component, "forecaster", None) is not None
            else None
        ),
        timeline_fn=lambda window: timeline.debug_payload(window_seconds=window),
    )
    bound = health.start()
    logging.info("%s: health/metrics on 127.0.0.1:%d", name, bound)
    # Always-on control-plane sampling (registered threads only; runtime
    # on/off via /debug/profile?action=).
    PROFILER.start()
    timeline.start()

    stop = stop_event or threading.Event()
    if stop_event is None:
        for sig in (signal.SIGINT, signal.SIGTERM):
            signal.signal(sig, lambda *_: stop.set())

    elector = None
    le_cfg = (config.get("leaderElection") or {}) if isinstance(config, dict) else {}
    if le_cfg.get("enabled", False):
        # One active replica per component: controllers start only once the
        # lease is held, and a lost lease fail-stops the process (the
        # controller-runtime contract; reference components run the same
        # election through their manager options).
        import os
        import socket

        from nos_tpu.kube.leaderelection import LeaderElector

        identity = le_cfg.get("identity") or f"{socket.gethostname()}-{os.getpid()}"
        elector = LeaderElector(
            store,
            name=f"nos-tpu-{name}",
            identity=identity,
            namespace=le_cfg.get("namespace", "nos-system"),
            lease_duration_s=float(le_cfg.get("leaseDurationSeconds", 15)),
            renew_period_s=float(le_cfg.get("renewPeriodSeconds", 5)),
            on_started_leading=manager.start,
            on_stopped_leading=stop.set,
        )
        elector.start()
        logging.info("%s: waiting for leader lease as %s", name, identity)
    else:
        manager.start()
    logging.info("%s running", name)
    try:
        stop.wait()
    finally:
        if elector is not None:
            elector.stop()
        timeline.stop()
        manager.stop()
        PROFILER.stop()
        health.stop()
        if hasattr(store, "stop"):  # KubeApiStore: stop informer threads
            store.stop()
    return 0
