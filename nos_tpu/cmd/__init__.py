"""Component entry points (reference cmd/: six binaries, SURVEY.md §2.1).

Each build_* function wires one component's controllers onto a Manager
against a KubeStore, mirroring each binary's main(). build_cluster()
assembles the full suite in-process — the equivalent of helm-installing
everything onto a kind cluster with the fake device plugin (BASELINE
config #1).
"""

from nos_tpu.cmd.cluster import SimCluster, build_cluster

__all__ = ["SimCluster", "build_cluster"]
