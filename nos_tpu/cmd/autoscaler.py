"""autoscaler: the SLO-driven model-serving replica controller.

No reference binary exists for this one — it is the suite's own closing
of the control-plane/data-plane loop (ROADMAP item 3). It follows the
same builder shape as every other component: wire a reconciler onto the
shared manager's store with watches, hand back the live object.
"""
from __future__ import annotations

from typing import Optional

from nos_tpu.api.config import AutoscalerConfig
from nos_tpu.api.v1alpha1 import labels
from nos_tpu.controllers.autoscaler.controller import (
    ModelServingReconciler,
    pod_to_serving_requests,
)
from nos_tpu.controllers.autoscaler.signals import SignalRegistry
from nos_tpu.kube.controller import Controller, Manager, Watch
from nos_tpu.kube.events import EventRecorder


def build_autoscaler(
    manager: Manager,
    config: Optional[AutoscalerConfig] = None,
    signals: Optional[SignalRegistry] = None,
) -> ModelServingReconciler:
    config = config or AutoscalerConfig()
    config.validate()
    store = manager.store
    reconciler = ModelServingReconciler(
        store,
        config=config,
        signals=signals or SignalRegistry(),
        recorder=EventRecorder(store, component="nos-autoscaler"),
    )
    manager.add(
        Controller(
            "autoscaler",
            store,
            reconciler.reconcile,
            [
                Watch(kind="ModelServing"),
                # Replica pod lifecycle (create/bind/delete) maps back to
                # the owning ModelServing so ready counts stay fresh.
                Watch(
                    kind="Pod",
                    predicate=lambda e: labels.MODEL_SERVING_LABEL
                    in e.object.metadata.labels,
                    mapper=lambda e: pod_to_serving_requests(store, e),
                ),
            ],
        )
    )
    return reconciler
