"""`python -m nos_tpu chaos`: seeded chaos runs against the in-process
suite.

Single-seed mode runs one driver and prints its report; ``--sweep N``
runs N consecutive seeds (the slow soak `make chaos` uses) and fails if
any seed fails to converge or drifts on replay.
"""
from __future__ import annotations

import argparse
import logging
import sys

from nos_tpu.chaos.driver import ChaosConfig, ChaosDriver


def _parse(argv):
    parser = argparse.ArgumentParser(
        description="Run the suite under seeded fault injection"
    )
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--bursts", type=int, default=3)
    parser.add_argument("--nodes", type=int, default=3)
    parser.add_argument(
        "--backend",
        choices=("memory", "apiserver"),
        default="memory",
        help="memory: in-process store; apiserver: everything over the "
        "HTTP stub (enables watch-sever/5xx/latency faults)",
    )
    parser.add_argument("--burst-seconds", type=float, default=2.0)
    parser.add_argument(
        "--pool-backend",
        choices=("", "serial", "thread", "process"),
        default="",
        help="partitioner pool plan backend; 'process' runs one planner "
        "worker per pool and arms the worker-kill fault",
    )
    parser.add_argument(
        "--timeout",
        type=float,
        default=30.0,
        help="per-burst convergence deadline after heal (seconds)",
    )
    parser.add_argument(
        "--record", default="", metavar="PATH", help="export the full JSONL log"
    )
    parser.add_argument(
        "--fixtures-dir",
        default="",
        metavar="DIR",
        help="write an auto-minimized repro fixture here on failure",
    )
    parser.add_argument(
        "--no-minimize",
        action="store_true",
        help="skip ddmin on failure (fast triage)",
    )
    parser.add_argument(
        "--sweep",
        type=int,
        default=0,
        metavar="N",
        help="run seeds [--seed, --seed+N) and aggregate",
    )
    parser.add_argument(
        "--soak",
        action="store_true",
        help="minutes-per-seed soak preset: more nodes, more and longer "
        "bursts, and a longer convergence deadline — the timeline-clean "
        "oracle (no leak/stall finding after the final heal) gets enough "
        "samples to mean something",
    )
    parser.add_argument("-v", "--verbose", action="count", default=0)
    return parser.parse_args(argv)


def _run_one(args, seed: int) -> int:
    config = ChaosConfig(
        seed=seed,
        bursts=args.bursts,
        nodes=args.nodes,
        backend=args.backend,
        burst_s=args.burst_seconds,
        pool_backend=args.pool_backend,
        convergence_timeout_s=args.timeout,
        minimize=not args.no_minimize,
        fixtures_dir=args.fixtures_dir,
        export_path=args.record,
    )
    if args.soak:
        # Preset beats the per-flag defaults but not explicit overrides
        # (argparse defaults compare equal only when the flag was unset).
        if args.bursts == 3:
            config.bursts = 12
        if args.nodes == 3:
            config.nodes = 8
        if args.burst_seconds == 2.0:
            config.burst_s = 5.0
        if args.timeout == 30.0:
            config.convergence_timeout_s = 60.0
    report = ChaosDriver(config).run()
    print(report.render())
    return 0 if report.ok() else 1


def main(argv=None) -> int:
    args = _parse(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose > 1 else
        logging.INFO if args.verbose else logging.WARNING,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    if args.sweep <= 0:
        return _run_one(args, args.seed)
    failed = []
    for seed in range(args.seed, args.seed + args.sweep):
        code = _run_one(args, seed)
        if code != 0:
            failed.append(seed)
    print(
        f"sweep: {args.sweep} seed(s), "
        f"{args.sweep - len(failed)} converged, {len(failed)} failed"
        + (f" (seeds {failed})" if failed else "")
    )
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
