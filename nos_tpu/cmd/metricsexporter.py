"""metricsexporter: anonymized install telemetry snapshot.

Reference cmd/metricsexporter/metricsexporter.go:33-91 + the schema in
cmd/metricsexporter/metrics/metrics.go:8-33: a one-shot job that collects
an installation snapshot and POSTs it to a telemetry endpoint — opt-out
documented (docs/en/docs/telemetry.md). Here the snapshot is written to a
file by default; POSTing requires an explicitly configured endpoint (this
build defaults to no egress).
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from dataclasses import asdict, dataclass, field
from typing import Dict, List

from nos_tpu import __version__
from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.kube.store import KubeStore
from nos_tpu.util.metrics import REGISTRY


@dataclass
class InstallationMetrics:
    """Schema parity with the reference's metrics.go:8-33."""

    version: str = __version__
    timestamp: float = 0.0
    node_count: int = 0
    tpu_node_count: int = 0
    partitioning_modes: List[str] = field(default_factory=list)
    total_tpu_chips: int = 0
    elastic_quota_count: int = 0
    composite_elastic_quota_count: int = 0
    domain_metrics: Dict[str, float] = field(default_factory=dict)


def collect_metrics(store: KubeStore) -> InstallationMetrics:
    m = InstallationMetrics(timestamp=time.time())
    modes = set()
    for node in store.list("Node"):
        m.node_count += 1
        kind = labels.partitioning_kind(node)
        if kind:
            modes.add(kind)
        chips = int(node.status.capacity.get(constants.RESOURCE_TPU, 0))
        if chips:
            m.tpu_node_count += 1
            m.total_tpu_chips += chips
    m.partitioning_modes = sorted(modes)
    m.elastic_quota_count = len(store.list("ElasticQuota"))
    m.composite_elastic_quota_count = len(store.list("CompositeElasticQuota"))
    m.domain_metrics = REGISTRY.snapshot()
    return m


def _post(payload: str, endpoint: str) -> None:
    import urllib.request

    request = urllib.request.Request(
        endpoint, data=payload.encode(), headers={"Content-Type": "application/json"}
    )
    with urllib.request.urlopen(request, timeout=10):  # opt-in only
        pass


def export(metrics: InstallationMetrics, output_path: str = "", endpoint: str = "") -> str:
    payload = json.dumps(asdict(metrics), indent=2)
    if output_path:
        with open(output_path, "w") as f:
            f.write(payload + "\n")
    if endpoint:
        _post(payload, endpoint)
    return payload


def main(argv=None) -> int:
    """One-shot job: read the snapshot file the running suite maintains
    (see cmd/run.py) and forward it — exactly the reference's shape
    (metricsexporter.go reads a metrics JSON file and POSTs it)."""
    parser = argparse.ArgumentParser(description="nos-tpu install telemetry exporter")
    parser.add_argument(
        "--input",
        default="/tmp/nos-tpu-metrics.json",
        help="snapshot file written by the running suite",
    )
    parser.add_argument(
        "--endpoint", default="", help="telemetry endpoint (disabled when empty)"
    )
    args = parser.parse_args(argv)
    try:
        with open(args.input) as f:
            payload = f.read()
    except FileNotFoundError:
        print(
            f"no metrics snapshot at {args.input}; is the suite running with "
            "metrics snapshots enabled?",
            file=sys.stderr,
        )
        return 1
    json.loads(payload)  # validate before forwarding
    if args.endpoint:
        _post(payload, args.endpoint)
    print(payload)
    return 0


if __name__ == "__main__":
    sys.exit(main())
