"""scheduler: the capacity/gang/topology-aware scheduler
(reference cmd/scheduler/scheduler.go:43-59)."""
from __future__ import annotations

import logging

from nos_tpu.api.config import SchedulerConfig
from nos_tpu.kube.controller import Controller, Manager, Request, Result, Watch
from nos_tpu.kube.events import EventRecorder
from nos_tpu.kube.objects import PodPhase
from nos_tpu.scheduler.scheduler import Scheduler, new_framework


def build_scheduler(
    manager: Manager,
    config: SchedulerConfig | None = None,
    flight_recorder=None,
    capacity_ledger=None,
) -> Scheduler:
    config = config or SchedulerConfig()
    config.validate()
    store = manager.store
    framework, capacity, gang = new_framework(
        store, gang_timeout_seconds=config.gang_wait_timeout_seconds
    )
    scheduler = Scheduler(
        store,
        framework,
        capacity=capacity,
        gang=gang,
        retry_seconds=config.retry_seconds,
        scheduler_name=config.scheduler_name,
        recorder=EventRecorder(store, component="nos-scheduler"),
        flight_recorder=flight_recorder,
        capacity_ledger=capacity_ledger,
    )
    if flight_recorder is not None:
        # Session facts replay needs to rebuild an identical scheduler.
        flight_recorder.record_session_meta(
            scheduler_name=config.scheduler_name,
            gang_timeout_seconds=config.gang_wait_timeout_seconds,
        )

    logged_foreign: set = set()

    def _claim_or_log_foreign(pod) -> bool:
        # The watch filter is where a foreign pod is actually dropped in
        # the deployed scheduler (reconcile never sees it), so the
        # diagnosability log for a manifest missing schedulerName must
        # live HERE — once per pod, or the misconfiguration pends
        # silently forever.
        if scheduler.responsible_for(pod):
            return True
        if pod.namespaced_name not in logged_foreign:
            if len(logged_foreign) >= 4096:
                # Bounded memory in a hot watch path: foreign pods churn
                # forever in a busy cluster. Clearing re-logs at worst.
                logged_foreign.clear()
            logged_foreign.add(pod.namespaced_name)
            logging.getLogger("nos_tpu.scheduler").info(
                "scheduler: ignoring %s (schedulerName=%r, ours=%r)",
                pod.namespaced_name,
                pod.spec.scheduler_name,
                scheduler.scheduler_name,
            )
        return False

    def pending_pod_requests():
        return [
            Request(name=p.metadata.name, namespace=p.metadata.namespace)
            for p in store.list("Pod")
            if p.status.phase == PodPhase.PENDING
            and not p.spec.node_name
            and scheduler.responsible_for(p)
        ]

    def node_event_mapper(event):
        # A node change (new slices advertised) can unblock any pending pod.
        return pending_pod_requests()

    def pod_freed_mapper(event):
        # A bound pod finishing (or deleted) frees its slice: retry pending
        # pods immediately instead of waiting out the retry backoff — a
        # same-shaped pending pod binds onto the freed slice with no replan.
        obj = event.object
        if bool(obj.spec.node_name) and (
            event.type == "DELETED"
            or obj.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED)
        ):
            return pending_pod_requests()
        return []

    manager.add(
        Controller(
            "scheduler",
            store,
            scheduler.reconcile,
            [
                Watch(
                    kind="Pod",
                    predicate=lambda e: e.type != "DELETED"
                    and e.object.status.phase == PodPhase.PENDING
                    and _claim_or_log_foreign(e.object),
                ),
                Watch(kind="Pod", mapper=pod_freed_mapper),
                Watch(kind="Node", mapper=node_event_mapper),
            ],
        )
    )
    _add_reservation_janitor(manager, scheduler)
    return scheduler


def _add_reservation_janitor(manager: Manager, scheduler: Scheduler) -> None:
    """Board reservations release on bind; a holder that dies instead
    (deleted, evicted with its node, phase change) orphans the annotation.
    This controller clears invalid reservations level-triggered — on pod
    departure events and on a TTL timer while any reservation exists."""
    reservation = scheduler.reservation
    if reservation is None:
        return
    from nos_tpu.scheduler.plugins.reservation import RESERVED_FOR

    store = manager.store
    sweep_request = [Request(name="sweep")]

    def janitor(req: Request):
        reservation.release_invalid()
        if reservation.any_reserved():
            # Valid reservations expire by wall clock with no event of
            # their own; poll while any annotation remains.
            return Result(requeue_after=max(1.0, reservation.ttl / 2))
        return None

    def reserved_node_mapper(event):
        if RESERVED_FOR in event.object.metadata.annotations:
            return sweep_request
        return []

    def pod_departed_mapper(event):
        obj = event.object
        if event.type == "DELETED" or obj.status.phase not in (PodPhase.PENDING,):
            return sweep_request
        return []

    manager.add(
        Controller(
            "reservation-janitor",
            store,
            janitor,
            [
                Watch(kind="Node", mapper=reserved_node_mapper),
                Watch(kind="Pod", mapper=pod_departed_mapper),
            ],
        )
    )


def main(argv=None) -> int:
    """Standalone scheduler process (`python -m nos_tpu scheduler`)."""
    from nos_tpu.cmd._component import run_component
    from nos_tpu.cmd.run import configs_from

    def build(manager, config):
        _, scheduler_cfg, _, _ = configs_from(config)
        # Returned so run_component serves the scheduler's diagnosis
        # ledger as /debug/explain.
        return build_scheduler(manager, scheduler_cfg)

    return run_component("scheduler", build, argv)
