"""sharingagent: node-local reporter daemon for sharing-mode nodes.

The gpuagent analogue (reference cmd/gpuagent/gpuagent.go:54-152):
reporter only — actuation happens through the device plugin ConfigMap.
Requires the node name (NODE_NAME env in a real daemonset).
"""
from __future__ import annotations

from typing import Optional

from nos_tpu.api.config import TpuAgentConfig
from nos_tpu.controllers.sharingagent import SharingReporter
from nos_tpu.device.sharing import SharedSliceClient
from nos_tpu.kube.controller import Controller, Manager, Request, Watch
from nos_tpu.util.predicates import matching_name


def build_sharingagent(
    manager: Manager,
    node_name: str,
    client: SharedSliceClient,
    config: Optional[TpuAgentConfig] = None,
) -> SharingReporter:
    config = config or TpuAgentConfig()
    config.validate()
    reporter = SharingReporter(
        manager.store,
        client,
        node_name,
        report_interval_seconds=config.report_config_interval_seconds,
    )

    def pod_on_node_mapper(event):
        # Usage changes come from pods binding/terminating on this node.
        if event.object.spec.node_name == node_name:
            return [Request(name=node_name)]
        return []

    def configmap_mapper(event):
        # A new plugin config means new exposed resources: re-report.
        return [Request(name=node_name)]

    manager.add(
        Controller(
            f"sharingagent-reporter-{node_name}",
            manager.store,
            reporter.reconcile,
            [
                Watch(kind="Node", predicate=matching_name(node_name)),
                Watch(kind="Pod", mapper=pod_on_node_mapper),
                Watch(kind="ConfigMap", mapper=configmap_mapper),
            ],
        )
    )
    return reporter


def main(argv=None) -> int:
    """Standalone sharingagent daemon (`python -m nos_tpu sharingagent`).
    Requires NODE_NAME (reference cmd/gpuagent/gpuagent.go)."""
    import os

    from nos_tpu.cmd._component import run_component
    from nos_tpu.cmd.run import configs_from

    node_name = os.environ.get("NODE_NAME", "")
    if not node_name:
        import sys

        print("sharingagent: NODE_NAME env is required", file=sys.stderr)
        return 1

    def build(manager, config):
        _, _, agent_cfg, _ = configs_from(config)
        client = SharedSliceClient(
            manager.store,
            config.get("devicePluginConfigMap", "nos-device-plugin-config"),
        )
        build_sharingagent(manager, node_name, client, agent_cfg)

    return run_component(f"sharingagent[{node_name}]", build, argv)
