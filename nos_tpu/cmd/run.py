"""`python -m nos_tpu run --config <file>`: run the full suite.

Each reference binary takes exactly one ``--config <file>`` flag decoded
into its typed ComponentConfig (cmd/gpupartitioner/gpupartitioner.go:74-101).
The in-process equivalent runs all components against one store (the
kind-style deployment of BASELINE config #1), optionally seeding simulated
TPU nodes, serving healthz/readyz/metrics, until interrupted.
"""
from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading

from nos_tpu.api.config import (
    AutoscalerConfig,
    GpuPartitionerConfig,
    ObservabilityConfig,
    SchedulerConfig,
    TpuAgentConfig,
)
from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.cmd.cluster import build_cluster
from nos_tpu.kube.objects import (
    Container,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
)
from nos_tpu.util.health import HealthServer
from nos_tpu.util.loop_health import LOOPS
from nos_tpu.util.profiling import PROFILER


def load_config(path: str) -> dict:
    if not path:
        return {}
    import yaml

    with open(path) as f:
        return yaml.safe_load(f) or {}


def configs_from(config: dict):
    # `section:` with no sub-keys parses to None — treat like absent.
    p = config.get("partitioner") or {}
    s = config.get("scheduler") or {}
    a = config.get("agent") or {}
    partitioner = GpuPartitionerConfig(
        batch_window_timeout_seconds=p.get("batchWindowTimeoutSeconds", 60.0),
        batch_window_idle_seconds=p.get("batchWindowIdleSeconds", 10.0),
        known_tpu_geometries=p.get("knownTpuGeometries"),
        device_plugin_config_map=p.get(
            "devicePluginConfigMap", "nos-device-plugin-config"
        ),
        device_plugin_delay_seconds=p.get("devicePluginDelaySeconds", 0.0),
        scheduler_config_file=p.get("schedulerConfigFile", ""),
        aging_chips_per_second=p.get("agingChipsPerSecond", 1.0),
        scheduler_name=p.get("schedulerName", constants.SCHEDULER_NAME),
        audit_sample_rate=p.get("auditSampleRate", 0.0),
        incremental_planning=p.get("incrementalPlanning", True),
        incremental_dirty_threshold=p.get("incrementalDirtyThreshold", 0.25),
        pool_sharding=p.get("poolSharding", False),
        pool_parallelism=p.get("poolParallelism", "serial"),
        pool_max_workers=p.get("poolMaxWorkers", 0),
        pool_backend=p.get("poolBackend", ""),
        pool_cycle_timeout_seconds=p.get("poolCycleTimeoutSeconds", 5.0),
        warm_state_path=p.get("warmStatePath", ""),
        warm_state_save_interval_seconds=p.get(
            "warmStateSaveIntervalSeconds", 30.0
        ),
    )
    scheduler = SchedulerConfig(
        retry_seconds=s.get("retrySeconds", 0.5),
        gang_wait_timeout_seconds=s.get("gangWaitTimeoutSeconds", 30.0),
        scheduler_name=s.get("schedulerName", constants.SCHEDULER_NAME),
    )
    agent = TpuAgentConfig(
        report_config_interval_seconds=a.get("reportConfigIntervalSeconds", 10.0)
    )
    # The model autoscaler is opt-in: no `autoscaler:` section, no extra
    # watches (build_cluster skips the component when config is None).
    autoscaler = None
    if "autoscaler" in config:
        u = config.get("autoscaler") or {}
        autoscaler = AutoscalerConfig(
            scale_up_burn_threshold=u.get("scaleUpBurnThreshold", 1.0),
            scale_down_burn_threshold=u.get("scaleDownBurnThreshold", 0.5),
            scale_down_stable_seconds=u.get("scaleDownStableSeconds", 120.0),
            recent_activity_seconds=u.get("recentActivitySeconds", 30.0),
            resync_seconds=u.get("resyncSeconds", 5.0),
        )
    for c in (partitioner, scheduler, agent, autoscaler):
        if c is not None:
            c.validate()
    return partitioner, scheduler, agent, autoscaler


def observability_from(config: dict) -> ObservabilityConfig:
    """ObservabilityConfig from the `observability:` section, e.g.

      observability:
        seriesBudget:
          default: 512                  # per-family fallback budget
          nos_tpu_capacity_node_chips: 3000
        nodeTopK: 50
        traceTailCapacity: 128
        traceBoringSampleN: 4
        traceSlowThresholds:
          pod.journey: 2.0
        debugPageLimit: 500

    The zero-value section (or none at all) leaves everything off:
    unbudgeted families, full per-node exposition, keep-every-trace.
    """
    o = config.get("observability") or {}
    budgets = dict(o.get("seriesBudget") or {})
    # `seriesBudget.default` is the catch-all; every other key names a
    # metric family.
    default = budgets.pop("default", o.get("seriesBudgetDefault"))
    obs = ObservabilityConfig(
        series_budget={str(k): int(v) for k, v in budgets.items()},
        series_budget_default=int(default) if default is not None else None,
        node_top_k=int(o.get("nodeTopK", 0)),
        trace_tail_capacity=int(o.get("traceTailCapacity", 64)),
        trace_boring_sample_n=int(o.get("traceBoringSampleN", 1)),
        trace_slow_thresholds={
            str(k): float(v)
            for k, v in (o.get("traceSlowThresholds") or {}).items()
        },
        debug_page_limit=int(o.get("debugPageLimit", 500)),
    )
    obs.validate()
    return obs


def seed_node(spec: dict) -> Node:
    chips = int(spec.get("chips", 8))
    accelerator = spec.get("accelerator", "tpu-v5-lite-podslice")
    alloc = {constants.RESOURCE_TPU: chips, "cpu": spec.get("cpu", 64), "memory": spec.get("memoryGB", 256)}
    node_labels = {
        labels.GKE_TPU_ACCELERATOR_LABEL: accelerator,
        labels.GKE_TPU_TOPOLOGY_LABEL: spec.get("topology", "2x4"),
        labels.PARTITIONING_LABEL: spec.get("partitioning", "tpu"),
    }
    if "sharedChips" in spec:
        node_labels[labels.SHARED_CHIPS_LABEL] = str(spec["sharedChips"])
    if "nodepool" in spec:
        # Pool membership for the sharded planner (poolSharding /
        # poolBackend drills); unlabeled nodes form one shared pool.
        node_labels[labels.GKE_NODEPOOL_LABEL] = str(spec["nodepool"])
    return Node(
        metadata=ObjectMeta(
            name=spec["name"],
            labels=node_labels,
        ),
        status=NodeStatus(capacity=dict(alloc), allocatable=dict(alloc)),
    )


def seed_pod(spec: dict) -> Pod:
    """A pending workload pod from a `pods:` config entry — the smoke-test
    way to drive the suite end to end without an external client."""
    requests = {constants.RESOURCE_TPU: int(spec.get("chips", 1))}
    if "cpu" in spec:
        requests["cpu"] = spec["cpu"]
    if "memoryGB" in spec:
        requests["memory"] = spec["memoryGB"]
    pod_labels = {}
    if "gang" in spec:
        # Gang membership drives gang scheduling, the ledger's wait
        # clocks, and the placement forecaster's per-gang ETAs.
        from nos_tpu.scheduler.plugins.gang import (
            GANG_NAME_LABEL,
            GANG_SIZE_LABEL,
        )

        pod_labels[GANG_NAME_LABEL] = str(spec["gang"])
        pod_labels[GANG_SIZE_LABEL] = str(spec.get("gangSize", 1))
    node_selector = {}
    if "nodepool" in spec:
        # Pin to one pool so pool partitioning stays decomposed — an
        # unpinned pod reaches every pool and collapses the shards.
        node_selector[labels.GKE_NODEPOOL_LABEL] = str(spec["nodepool"])
    return Pod(
        metadata=ObjectMeta(
            name=spec["name"],
            namespace=spec.get("namespace", "default"),
            labels=pod_labels,
        ),
        spec=PodSpec(
            containers=[Container(requests=dict(requests), limits=dict(requests))],
            scheduler_name=spec.get("schedulerName", constants.SCHEDULER_NAME),
            node_selector=node_selector,
        ),
    )


def seed_modelserving(spec: dict):
    """A ModelServing from a `modelServings:` config entry, e.g.

      modelServings:
        - name: chat
          model: llama-70b
          sliceProfile: 2x4
          minReplicas: 1
          maxReplicas: 3
          slos: ["p95 ttft < 500ms"]
    """
    from nos_tpu.api.v1alpha1.modelserving import ModelServing, ModelServingSpec
    from nos_tpu.kube.objects import ObjectMeta

    ms = ModelServing(
        metadata=ObjectMeta(
            name=spec["name"], namespace=spec.get("namespace", "default")
        ),
        spec=ModelServingSpec(
            model=spec.get("model", spec["name"]),
            slice_profile=spec.get("sliceProfile", "2x4"),
            min_replicas=int(spec.get("minReplicas", 0)),
            max_replicas=int(spec.get("maxReplicas", 1)),
            slos=list(spec.get("slos", [])),
            scale_to_zero_idle_seconds=spec.get("scaleToZeroIdleSeconds", 300.0),
            cold_start_grace_seconds=spec.get("coldStartGraceSeconds", 60.0),
            target_queue_depth=int(spec.get("targetQueueDepth", 4)),
            scale_down_budget_surplus=spec.get("scaleDownBudgetSurplus", 0.5),
            scheduler_name=spec.get("schedulerName", constants.SCHEDULER_NAME),
        ),
    )
    ms.spec.validate()
    return ms


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="Run the nos-tpu suite in-process")
    parser.add_argument("--config", default="", help="YAML component config")
    parser.add_argument("--health-port", type=int, default=None)
    parser.add_argument(
        "--record",
        default="",
        metavar="PATH",
        help="flight-recorder JSONL export path (enables recording)",
    )
    parser.add_argument(
        "--run-seconds",
        type=float,
        default=None,
        help="exit after N seconds instead of waiting for a signal",
    )
    parser.add_argument("-v", "--verbose", action="count", default=0)
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )

    config = load_config(args.config)
    partitioner_cfg, scheduler_cfg, agent_cfg, autoscaler_cfg = configs_from(config)
    obs_cfg = observability_from(config)

    # Apply series budgets + trace retention to the process-wide
    # registry/tracer BEFORE any component registers series, so admission
    # order (and therefore the exact/_other split) is deterministic.
    from nos_tpu.obsplane.apply import apply_observability

    revert_observability = apply_observability(obs_cfg)

    flight_recorder = None
    if args.record:
        from nos_tpu.record import FlightRecorder

        flight_recorder = FlightRecorder()
    # Always-on health timeline: samples every metric family plus process
    # vitals and registered memo/ring sizes; findings become HealthDegraded
    # Events on a well-known ConfigMap identity.
    from nos_tpu.kube.objects import ConfigMap
    from nos_tpu.timeline import TimelineStore

    timeline = TimelineStore(
        interval_seconds=(config.get("manager") or {}).get(
            "timelineSampleSeconds", 5.0
        )
    )
    cluster = build_cluster(
        partitioner_config=partitioner_cfg,
        scheduler_config=scheduler_cfg,
        autoscaler_config=autoscaler_cfg,
        device_backend=config.get("deviceBackend", "sim"),
        tpuctl_dir=config.get("tpuctlDir", "/tmp/nos-tpu"),
        flight_recorder=flight_recorder,
        timeline=timeline,
    )
    if cluster.capacity_ledger is not None and obs_cfg.node_top_k:
        # Tiered exposition: exact pool rollups always; per-node series
        # only for the K worst offenders (idle chips, fragmentation).
        cluster.capacity_ledger.node_top_k = obs_cfg.node_top_k
    from nos_tpu.kube.events import EventRecorder

    timeline.attach(
        flight=flight_recorder,
        recorder=EventRecorder(cluster.store, component="nos-health-timeline"),
        event_obj=ConfigMap(
            metadata=ObjectMeta(name="nos-health-timeline", namespace="default")
        ),
    )
    if flight_recorder is not None:
        # Attach BEFORE seeding: node/pod creation deltas are replay inputs.
        flight_recorder.attach(cluster.store)
    for spec in config.get("nodes", []):
        node = seed_node(spec)
        kind = spec.get("partitioning", "tpu")
        if kind == "sharing":
            cluster.add_sharing_node(node, agent_cfg)
        elif kind == "hybrid":
            cluster.add_hybrid_node(node, agent_cfg)
        else:
            cluster.add_tpu_node(node, agent_cfg)
    for spec in config.get("pods", []):
        cluster.store.create(seed_pod(spec))
    for spec in config.get("modelServings", []):
        cluster.store.create(seed_modelserving(spec))

    port = args.health_port
    if port is None:
        port = (config.get("manager") or {}).get("healthProbePort", 8081)
    health = HealthServer(
        port=port,
        explain_fn=cluster.scheduler.explain,
        record_fn=flight_recorder.records if flight_recorder is not None else None,
        capacity_fn=cluster.capacity_ledger.debug_payload
        if cluster.capacity_ledger is not None
        else None,
        profiler=PROFILER,
        loops_fn=lambda: LOOPS.payload(store=cluster.store),
        autoscaler_fn=cluster.autoscaler.debug_payload
        if cluster.autoscaler is not None
        else None,
        forecast_fn=cluster.partitioner.forecaster.debug_payload
        if getattr(cluster.partitioner, "forecaster", None) is not None
        else None,
        timeline_fn=lambda window, **page: timeline.debug_payload(
            window_seconds=window, **page
        ),
        capacity_stream_fn=cluster.capacity_ledger.debug_stream
        if cluster.capacity_ledger is not None
        else None,
        timeline_stream_fn=timeline.iter_jsonl,
        debug_page_limit=obs_cfg.debug_page_limit,
    )
    bound = health.start()
    logging.info(
        "health/metrics on 127.0.0.1:%d (/healthz /readyz /metrics /debug/explain"
        " /debug/capacity /debug/profile /debug/loops /debug/timeline%s%s%s)",
        bound,
        " /debug/autoscaler" if cluster.autoscaler is not None else "",
        " /debug/record" if flight_recorder is not None else "",
        " /debug/forecast"
        if getattr(cluster.partitioner, "forecaster", None) is not None
        else "",
    )

    # Always-on control-plane sampling: the profiler only sees threads
    # that registered themselves (controller pumps/workers, batch loops),
    # and its measured duty cycle at the default rate is within budget.
    PROFILER.start()
    cluster.start()
    stop = threading.Event()

    # Maintain the telemetry snapshot the metricsexporter job forwards.
    snapshot_path = (config.get("manager") or {}).get(
        "metricsSnapshotPath", "/tmp/nos-tpu-metrics.json"
    )
    snapshot_interval = (config.get("manager") or {}).get("metricsSnapshotSeconds", 60)

    def snapshot_loop():
        from nos_tpu.cmd.metricsexporter import collect_metrics, export

        while not stop.is_set():
            try:
                export(collect_metrics(cluster.store), snapshot_path)
            except OSError:
                logging.exception("metrics snapshot write failed")
            stop.wait(snapshot_interval)

    threading.Thread(target=snapshot_loop, name="metrics-snapshot", daemon=True).start()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    logging.info("nos-tpu suite running; Ctrl-C to stop")
    try:
        if args.run_seconds is not None:
            stop.wait(args.run_seconds)
        else:
            stop.wait()
    finally:
        cluster.stop()
        PROFILER.stop()
        health.stop()
        timeline.close()
        revert_observability()
        if flight_recorder is not None:
            flight_recorder.detach()
            count = flight_recorder.export_jsonl(args.record)
            logging.info("flight record: %d record(s) -> %s", count, args.record)
    return 0


if __name__ == "__main__":
    sys.exit(main())
