"""gpupartitioner: ClusterState + state controllers + the TPU mode
controller with its embedded scheduler framework
(reference cmd/gpupartitioner/gpupartitioner.go:72-268)."""
from __future__ import annotations

import itertools
import time

from nos_tpu.api.config import GpuPartitionerConfig
from nos_tpu.api.v1alpha1 import constants
from nos_tpu.api.v1alpha1.labels import PARTITIONING_LABEL
from nos_tpu.controllers.partitioner import (
    PartitionerController,
    StateNodeController,
    StatePodController,
)
from nos_tpu.kube.controller import Controller, Manager, Watch
from nos_tpu.kube.objects import PodPhase
from nos_tpu.partitioning.core import Actuator, ClusterState, Planner
from nos_tpu.partitioning.sharing import SharingPartitioner, SharingSnapshotTaker
from nos_tpu.partitioning.tpu import (
    TpuNodeInitializer,
    TpuPartitioner,
    TpuSnapshotTaker,
)
from nos_tpu.scheduler.framework import Framework, vanilla_filter_plugins
from nos_tpu.scheduler.plugins.capacity import CapacityScheduling
from nos_tpu.tpu.known import set_known_geometries


def register_indexers(store) -> None:
    """Field indexers every component relies on
    (cmd/gpupartitioner/gpupartitioner.go:270-292)."""
    if (("Pod", constants.INDEX_POD_PHASE)) not in store._indexers:
        store.add_indexer("Pod", constants.INDEX_POD_PHASE, lambda p: [p.status.phase])
        store.add_indexer("Pod", constants.INDEX_POD_NODE, lambda p: [p.spec.node_name])


def build_sim_framework(store) -> Framework:
    """The embedded simulation framework: the same plugin set the real
    scheduler runs, including CapacityScheduling, so plans are never
    refused at scheduling time (gpupartitioner.go:294-318 + SURVEY §7
    "simulation fidelity"). Shared by the live partitioner and the flight
    replay harness — replayed plans must run the exact plugin set the
    recorded ones did.

    The sim includes the ICI co-location filter so the planner never
    carves for a gang member in a pool the scheduler would reject
    (store-bound members pin the pool; members placed WITHIN one plan
    are kept co-located by the gang pre-pass running per node pool's
    nodes in sequence — a cross-pool split inside a single plan resolves
    via permit-timeout + replan, the level-triggered backstop)."""
    from nos_tpu.scheduler.plugins.reservation import BoardReservation
    from nos_tpu.scheduler.plugins.topology import MultihostIciFilter

    capacity = CapacityScheduling(store)
    return Framework(
        pre_filter_plugins=[capacity],
        filter_plugins=vanilla_filter_plugins()
        # Simulation fidelity (SURVEY §7): the planner must not carve for
        # pods the real scheduler would reject — including pods a board
        # reservation keeps off a draining node.
        + [MultihostIciFilter(store), BoardReservation(store)],
    )


def build_partitioner(
    manager: Manager,
    config: GpuPartitionerConfig | None = None,
    flight_recorder=None,
    capacity_ledger=None,
) -> PartitionerController:
    config = config or GpuPartitionerConfig()
    config.validate()
    store = manager.store
    register_indexers(store)
    if config.known_tpu_geometries:
        set_known_geometries(config.known_tpu_geometries)

    from nos_tpu.kube.events import EventRecorder
    from nos_tpu.record.audit import build_auditor

    recorder = EventRecorder(store, component="nos-partitioner")
    if flight_recorder is not None:
        # Replay rebuilds planners with the same aging knob — it shapes
        # the fairness sort every recorded plan used.
        flight_recorder.record_session_meta(
            aging_chips_per_second=config.aging_chips_per_second
        )
    auditor = build_auditor(
        sample_rate=config.audit_sample_rate,
        recorder=recorder,
        flight_recorder=flight_recorder,
    )
    cluster_state = ClusterState()
    # Wall-clock ms + monotonic counter: two plans in the same millisecond
    # must not share an id or the spec/status handshake would false-ack.
    counter = itertools.count(1)
    plan_id_fn = lambda: f"{int(time.time() * 1000)}-{next(counter)}"  # noqa: E731
    tpu_partitioner = TpuPartitioner(store)
    initializer = TpuNodeInitializer(tpu_partitioner, plan_id_fn)

    sim_framework = build_sim_framework(store)

    forecaster = None
    if config.forecast_enabled:
        from nos_tpu.forecast import PlacementForecaster

        # The forecaster gets its OWN planner (and, lazily, its own
        # snapshot maintainer): forecast trials must never clobber the
        # live controller's per-plan caches or incremental base.
        forecaster = PlacementForecaster(
            store,
            cluster_state,
            Planner(
                build_sim_framework(store),
                aging_chips_per_second=config.aging_chips_per_second,
            ),
            TpuSnapshotTaker(),
            kind="tpu",
            capacity_ledger=capacity_ledger,
            flight_recorder=flight_recorder,
            min_interval_seconds=config.forecast_min_interval_seconds,
            max_gangs=config.forecast_max_gangs,
            max_backfill_pairs=config.forecast_max_backfill_pairs,
            small_pod_chips=config.forecast_small_pod_chips,
        )
        manager.add_runnable(forecaster.start, forecaster.stop)

    controller = PartitionerController(
        store=store,
        cluster_state=cluster_state,
        snapshot_taker=TpuSnapshotTaker(),
        planner=Planner(sim_framework, aging_chips_per_second=config.aging_chips_per_second),
        actuator=Actuator(tpu_partitioner),
        kind="tpu",
        batch_timeout_seconds=config.batch_window_timeout_seconds,
        batch_idle_seconds=config.batch_window_idle_seconds,
        scheduler_name=config.scheduler_name,
        plan_id_fn=plan_id_fn,
        recorder=recorder,
        flight_recorder=flight_recorder,
        auditor=auditor,
        incremental_planning=config.incremental_planning,
        incremental_dirty_threshold=config.incremental_dirty_threshold,
        pool_sharding=config.pool_sharding,
        pool_parallelism=config.pool_parallelism,
        pool_max_workers=config.pool_max_workers,
        pool_backend=config.pool_backend,
        pool_cycle_timeout_seconds=config.pool_cycle_timeout_seconds,
        # Warm-state files are per mode: the two controllers' planners
        # memoize against different snapshot shapes.
        warm_state_path=(
            f"{config.warm_state_path}.tpu" if config.warm_state_path else ""
        ),
        warm_state_save_interval_seconds=(
            config.warm_state_save_interval_seconds
        ),
        # The tpu controller alone drives ledger observes: one observer per
        # cluster, or chip-seconds would double-integrate per cycle.
        capacity_ledger=capacity_ledger,
        # Likewise one forecaster, fed by the tpu controller's cycles.
        forecaster=forecaster,
    )

    node_ctrl = StateNodeController(store, cluster_state, initializer=initializer)
    pod_ctrl = StatePodController(store, cluster_state)

    manager.add(
        Controller(
            "state-node",
            store,
            node_ctrl.reconcile,
            [Watch(kind="Node", predicate=lambda e: PARTITIONING_LABEL in e.object.metadata.labels or e.type == "DELETED")],
        )
    )
    manager.add(Controller("state-pod", store, pod_ctrl.reconcile, [Watch(kind="Pod")]))

    # Actuation-divergence feedback: when an agent acknowledges a plan but
    # reports a geometry that differs from spec (the clamp path), replan
    # immediately instead of waiting out the next pod batch window.
    from nos_tpu.util.predicates import annotations_changed_or_added

    manager.add(
        Controller(
            "partitioner-divergence",
            store,
            controller.reconcile_node_divergence,
            [
                Watch(
                    kind="Node",
                    predicate=lambda e: e.type != "DELETED"
                    and annotations_changed_or_added(e),
                )
            ],
        )
    )

    # Capacity-freed feedback: a bound pod finishing (or deleted) frees
    # chips; with pods still pending, replan immediately rather than
    # letting the freed chips idle until the next batch window.
    def _freed_capacity_predicate(e):
        obj = e.object
        return bool(obj.spec.node_name) and (
            e.type == "DELETED"
            or obj.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED)
        )

    manager.add(
        Controller(
            "partitioner-capacity-freed",
            store,
            controller.reconcile_capacity_freed,
            [Watch(kind="Pod", predicate=_freed_capacity_predicate)],
        )
    )

    # Multi-host slice expansion: a plain-chip request exceeding one board
    # becomes a gang of per-host board slices (BASELINE config #5; the
    # admission-mutation seam — see controllers/partitioner/multihost.py).
    from nos_tpu.controllers.partitioner.multihost import (
        MultihostExpander,
        leader_deleted_mapper,
    )

    expander = MultihostExpander(store)
    manager.add(
        Controller(
            "multihost-expander",
            store,
            expander.reconcile,
            [Watch(kind="Pod", mapper=leader_deleted_mapper(store))],
        )
    )
    manager.add(
        Controller(
            "partitioner-tpu",
            store,
            controller.reconcile,
            [
                Watch(
                    kind="Pod",
                    predicate=lambda e: e.type != "DELETED"
                    and e.object.status.phase == PodPhase.PENDING,
                )
            ],
        )
    )
    manager.add_runnable(controller.start, controller.stop)

    # Second mode, second actuation style (reference registers both the MIG
    # and MPS controllers, gpupartitioner.go:214-250): sharing-mode nodes
    # are actuated through the device plugin ConfigMap, not an agent.
    from nos_tpu.partitioning.core.codec import SharedSliceCodec

    sharing_partitioner = SharingPartitioner(
        store,
        config_map_name=config.device_plugin_config_map,
        device_plugin_delay_seconds=config.device_plugin_delay_seconds,
    )
    sharing_codec = SharedSliceCodec()
    sharing_controller = PartitionerController(
        store=store,
        cluster_state=cluster_state,
        snapshot_taker=SharingSnapshotTaker(),
        planner=Planner(sim_framework, aging_chips_per_second=config.aging_chips_per_second),
        actuator=Actuator(sharing_partitioner),
        kind="sharing",
        batch_timeout_seconds=config.batch_window_timeout_seconds,
        batch_idle_seconds=config.batch_window_idle_seconds,
        scheduler_name=config.scheduler_name,
        plan_id_fn=plan_id_fn,
        tracked_resource_fn=sharing_codec.is_tracked,
        recorder=recorder,
        flight_recorder=flight_recorder,
        auditor=auditor,
        incremental_planning=config.incremental_planning,
        incremental_dirty_threshold=config.incremental_dirty_threshold,
        pool_sharding=config.pool_sharding,
        pool_parallelism=config.pool_parallelism,
        pool_max_workers=config.pool_max_workers,
        pool_backend=config.pool_backend,
        pool_cycle_timeout_seconds=config.pool_cycle_timeout_seconds,
        warm_state_path=(
            f"{config.warm_state_path}.sharing"
            if config.warm_state_path
            else ""
        ),
        warm_state_save_interval_seconds=(
            config.warm_state_save_interval_seconds
        ),
    )
    manager.add(
        Controller(
            "partitioner-sharing",
            store,
            sharing_controller.reconcile,
            [
                Watch(
                    kind="Pod",
                    predicate=lambda e: e.type != "DELETED"
                    and e.object.status.phase == PodPhase.PENDING,
                )
            ],
        )
    )
    manager.add_runnable(sharing_controller.start, sharing_controller.stop)
    controller.sharing = sharing_controller
    return controller


def main(argv=None) -> int:
    """Standalone gpupartitioner process (`python -m nos_tpu partitioner`)."""
    from nos_tpu.cmd._component import run_component
    from nos_tpu.cmd.run import configs_from

    def build(manager, config):
        partitioner_cfg, _, _, _ = configs_from(config)
        build_partitioner(manager, partitioner_cfg)

    return run_component("partitioner", build, argv)
