"""SimCluster: the whole suite running in-process against one store.

Equivalent of helm-installing all components onto a kind cluster with the
fake TPU device plugin (BASELINE config #1 / SURVEY.md §7 step 4): the
operator, the partitioner, the scheduler, one tpuagent per TPU node, the
sim kubelet, and the sim device layer.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from nos_tpu.api.config import (
    AutoscalerConfig,
    GpuPartitionerConfig,
    OperatorConfig,
    SchedulerConfig,
    TpuAgentConfig,
)
from nos_tpu.cmd.autoscaler import build_autoscaler
from nos_tpu.cmd.operator import build_operator
from nos_tpu.cmd.partitioner import build_partitioner
from nos_tpu.cmd.scheduler import build_scheduler
from nos_tpu.cmd.sharingagent import build_sharingagent
from nos_tpu.cmd.tpuagent import build_tpuagent
from nos_tpu.controllers.partitioner import PartitionerController
from nos_tpu.device import (
    DevicePluginAdvertiser,
    SimDevicePlugin,
    SimDevicePool,
    SimPodResourcesClient,
    SimTpuDeviceClient,
    TpuClient,
)
from nos_tpu.capacity import CapacityLedger
from nos_tpu.kube.controller import Controller, Manager, Watch
from nos_tpu.kube.objects import Node, PodPhase
from nos_tpu.kube.store import KubeStore
from nos_tpu.scheduler.scheduler import Scheduler
from nos_tpu.sim import SimKubelet


@dataclass
class SimCluster:
    manager: Manager
    store: KubeStore
    pool: SimDevicePool
    partitioner: PartitionerController
    scheduler: Scheduler
    kubelet: Optional[SimKubelet] = None
    capacity_ledger: Optional[CapacityLedger] = None
    # Optional longitudinal health timeline (nos_tpu/timeline/): started
    # and stopped with the cluster so its sampler sees the whole run.
    timeline: Optional[object] = None
    # Set when built with autoscaler_config: the ModelServingReconciler
    # (signals registry at .signals, /debug payload at .debug_payload).
    autoscaler: Optional[object] = None
    device_backend: str = "sim"  # "sim" | "tpuctl" (native C++ slice state)
    tpuctl_dir: str = ""
    device_plugin_config_map: str = "nos-device-plugin-config"
    # node name -> TpuAgentHandles, for harnesses that poke agent
    # internals (the chaos driver's restart-mid-actuation fault).
    agents: Dict[str, object] = field(default_factory=dict)
    _agent_nodes: List[str] = field(default_factory=list)
    _sharing_agent_nodes: List[str] = field(default_factory=list)
    _tpuctl_client: object = None

    def add_tpu_node(self, node: Node, agent_config: Optional[TpuAgentConfig] = None) -> None:
        """Create the node and start its tpuagent (must be called before
        manager.start() for the agent's watches to replay the node)."""
        self.store.create(node)
        self.start_agent(node.metadata.name, agent_config)

    def start_agent(self, node_name: str, agent_config: Optional[TpuAgentConfig] = None) -> None:
        if node_name in self._agent_nodes:
            return
        if self.device_backend == "tpuctl":
            device_client = self._tpuctl(node_name)
            client = TpuClient(
                device_client, SimPodResourcesClient(self.store, device_client.get_slices)
            )
            plugin = DevicePluginAdvertiser(self.store, device_client.geometry)
        else:
            client = TpuClient(
                SimTpuDeviceClient(self.pool),
                SimPodResourcesClient(self.store, self.pool.get),
            )
            plugin = SimDevicePlugin(self.store, self.pool)
        self.agents[node_name] = build_tpuagent(
            self.manager,
            node_name,
            client,
            plugin,
            agent_config or TpuAgentConfig(report_config_interval_seconds=0.5),
        )
        self._agent_nodes.append(node_name)

    def add_sharing_node(self, node: Node, agent_config: Optional[TpuAgentConfig] = None) -> None:
        """Create a sharing-mode node and start its reporter-only agent
        (the gpuagent analogue); actuation rides the device-plugin
        ConfigMap, so no actuator is started."""
        self.store.create(node)
        self._start_sharing_reporter(node.metadata.name, agent_config)

    def _start_sharing_reporter(
        self, name: str, agent_config: Optional[TpuAgentConfig] = None
    ) -> None:
        if name in self._sharing_agent_nodes:
            return
        from nos_tpu.device.sharing import SharedSliceClient

        build_sharingagent(
            self.manager,
            name,
            SharedSliceClient(self.store, self.device_plugin_config_map),
            agent_config or TpuAgentConfig(report_config_interval_seconds=0.5),
        )
        self._sharing_agent_nodes.append(name)

    def add_hybrid_node(self, node: Node, agent_config: Optional[TpuAgentConfig] = None) -> None:
        """Create a hybrid-mode node: slice partitioning is actuated by its
        tpuagent, chip sharing by the device-plugin ConfigMap path. Both
        agents run; each reporter owns only its profile flavor of the
        status annotations (the tpuagent additionally owns the plan
        handshake)."""
        self.store.create(node)
        name = node.metadata.name
        self.start_agent(name, agent_config)
        self._start_sharing_reporter(name, agent_config)

    def _tpuctl(self, node_name: str):
        from nos_tpu.api.v1alpha1 import constants
        from nos_tpu.api.v1alpha1.labels import GKE_TPU_ACCELERATOR_LABEL
        from nos_tpu.device.tpuctl import TpuctlDeviceClient
        from nos_tpu.tpu.known import board_layout

        if self._tpuctl_client is None:
            self._tpuctl_client = TpuctlDeviceClient(self.tpuctl_dir, {})
            if self.kubelet is not None:
                # Native backend: admission arbitrates against tpuctl's
                # slice state instead of the sim pool.
                self.kubelet.geometry_fn = self._tpuctl_client.geometry
        node = self.store.get("Node", node_name)
        accelerator = node.metadata.labels.get(GKE_TPU_ACCELERATOR_LABEL, "")
        chips = int(node.status.capacity.get(constants.RESOURCE_TPU, 0))
        self._tpuctl_client.board_topologies[node_name] = board_layout(accelerator, chips)
        return self._tpuctl_client

    def start(self) -> None:
        self.manager.start()
        if self.capacity_ledger is not None:
            # Sim timescale: cycles are sub-second, so tick accordingly.
            self.capacity_ledger.start_heartbeat(interval_seconds=1.0)
        if self.timeline is not None:
            self.timeline.start()

    def stop(self) -> None:
        if self.timeline is not None:
            self.timeline.stop()
        if self.capacity_ledger is not None:
            self.capacity_ledger.stop_heartbeat()
        self.manager.stop()

    def wait_idle(self, timeout: float = 15.0) -> bool:
        return self.manager.wait_idle(timeout=timeout)


def build_cluster(
    store: Optional[KubeStore] = None,
    partitioner_config: Optional[GpuPartitionerConfig] = None,
    scheduler_config: Optional[SchedulerConfig] = None,
    operator_config: Optional[OperatorConfig] = None,
    autoscaler_config: Optional[AutoscalerConfig] = None,
    autoscaler_signals=None,
    device_backend: str = "sim",
    tpuctl_dir: str = "",
    flight_recorder=None,
    timeline=None,
) -> SimCluster:
    store = store or KubeStore()
    manager = Manager(store=store)
    # ONE ledger for the whole suite: the partitioner drives observes (it
    # knows the unserved demand each cycle), the scheduler stamps gang
    # wait clocks on the same instance.
    ledger = CapacityLedger(store, flight_recorder=flight_recorder)
    build_operator(manager, operator_config, flight_recorder=flight_recorder)
    partitioner_config = partitioner_config or GpuPartitionerConfig(
        batch_window_timeout_seconds=1.0, batch_window_idle_seconds=0.05
    )
    partitioner = build_partitioner(
        manager,
        partitioner_config,
        flight_recorder=flight_recorder,
        capacity_ledger=ledger,
    )
    scheduler = build_scheduler(
        manager,
        scheduler_config,
        flight_recorder=flight_recorder,
        capacity_ledger=ledger,
    )
    # The model autoscaler is opt-in: only serving-aware deployments
    # (run.py with an `autoscaler:` section, bench_autoscale, chaos) pay
    # for the extra watches.
    autoscaler = None
    if autoscaler_config is not None:
        autoscaler = build_autoscaler(
            manager, autoscaler_config, signals=autoscaler_signals
        )
    pool = SimDevicePool()
    # Admission arbitrates against the device inventory (ground truth),
    # the backstop for scheduler-vs-repartitioner races — see SimKubelet.
    kubelet = SimKubelet(store, geometry_fn=pool.geometry)
    manager.add(
        Controller(
            "sim-kubelet",
            store,
            kubelet.reconcile,
            [
                Watch(
                    kind="Pod",
                    predicate=lambda e: e.type != "DELETED"
                    and e.object.status.phase == PodPhase.PENDING
                    and bool(e.object.spec.node_name),
                )
            ],
        )
    )
    # Sharing-mode device plugin: re-advertises allocatable when the
    # SharingPartitioner flips a node's config label (the sim stand-in for
    # the real TPU device plugin re-registering).
    from nos_tpu.api.v1alpha1.labels import TPU_DEVICE_PLUGIN_CONFIG_LABEL
    from nos_tpu.device.sharing import SimSharedDevicePlugin
    from nos_tpu.kube.controller import Request

    shared_plugin = SimSharedDevicePlugin(
        store, config_map_name=partitioner_config.device_plugin_config_map
    )

    def configmap_to_labeled_nodes(event):
        return [
            Request(name=n.metadata.name)
            for n in store.list("Node")
            if TPU_DEVICE_PLUGIN_CONFIG_LABEL in n.metadata.labels
        ]

    manager.add(
        Controller(
            "sim-shared-device-plugin",
            store,
            shared_plugin.reconcile,
            [
                Watch(
                    kind="Node",
                    predicate=lambda e: e.type != "DELETED"
                    and TPU_DEVICE_PLUGIN_CONFIG_LABEL in e.object.metadata.labels,
                ),
                Watch(kind="ConfigMap", mapper=configmap_to_labeled_nodes),
            ],
        )
    )
    return SimCluster(
        manager=manager,
        store=store,
        pool=pool,
        partitioner=partitioner,
        scheduler=scheduler,
        kubelet=kubelet,
        capacity_ledger=ledger,
        autoscaler=autoscaler,
        timeline=timeline,
        device_backend=device_backend,
        tpuctl_dir=tpuctl_dir,
        device_plugin_config_map=partitioner_config.device_plugin_config_map,
    )
