"""operator: EQ/CEQ reconcilers + validating webhooks
(reference cmd/operator/operator.go:50-126)."""
from __future__ import annotations

from nos_tpu.api.config import OperatorConfig
from nos_tpu.controllers.elasticquota import (
    CompositeElasticQuotaReconciler,
    ElasticQuotaReconciler,
    register_elasticquota_webhooks,
)
from nos_tpu.controllers.elasticquota.controller import pod_to_quota_requests
from nos_tpu.kube.controller import Controller, Manager, Watch
from nos_tpu.kube.events import EventRecorder


def build_operator(
    manager: Manager,
    config: OperatorConfig | None = None,
    flight_recorder=None,
) -> None:
    config = config or OperatorConfig()
    config.validate()
    store = manager.store
    register_elasticquota_webhooks(store)

    recorder = EventRecorder(store, component="nos-operator")
    eq = ElasticQuotaReconciler(
        store,
        chip_memory_gb=config.tpu_chip_memory_gb,
        recorder=recorder,
        flight_recorder=flight_recorder,
    )
    ceq = CompositeElasticQuotaReconciler(
        store,
        chip_memory_gb=config.tpu_chip_memory_gb,
        recorder=recorder,
        flight_recorder=flight_recorder,
    )

    manager.add(
        Controller(
            "elasticquota",
            store,
            eq.reconcile,
            [
                Watch(kind="ElasticQuota"),
                Watch(kind="Pod", mapper=lambda e: pod_to_quota_requests(store, e)),
            ],
        )
    )
    manager.add(
        Controller(
            "compositeelasticquota",
            store,
            ceq.reconcile,
            [
                Watch(kind="CompositeElasticQuota"),
                Watch(
                    kind="Pod",
                    mapper=lambda e: [
                        r
                        for r in pod_to_quota_requests(store, e)
                        if store.try_get("CompositeElasticQuota", r.name, r.namespace)
                    ],
                ),
            ],
        )
    )


def main(argv=None) -> int:
    """Standalone operator process (`python -m nos_tpu operator`)."""
    from nos_tpu.cmd._component import run_component

    def build(manager, config):
        operator_cfg = OperatorConfig(
            tpu_chip_memory_gb=int(config.get("tpuChipMemoryGB", 16))
        )
        build_operator(manager, operator_cfg)
        webhook_cfg = config.get("webhook") or {}
        if webhook_cfg.get("enabled", False):
            # The apiserver-facing TLS admission endpoint (reference
            # operator.go:96-117); the in-store seam keeps validating
            # writes made through this process either way. Starts
            # IMMEDIATELY, not behind the leader lease: the webhook
            # Service load-balances over every replica, and a non-leader
            # refusing connections would fail cluster-wide quota writes
            # (controller-runtime runs webhook servers with
            # NeedLeaderElection=false for the same reason).
            from nos_tpu.kube.webhook import build_elasticquota_webhook_server

            server = build_elasticquota_webhook_server(
                manager.store,
                port=int(webhook_cfg.get("port", 9443)),
                host=webhook_cfg.get("host", "0.0.0.0"),
                cert_file=webhook_cfg.get("certFile", ""),
                key_file=webhook_cfg.get("keyFile", ""),
            )
            server.start()
            manager.add_runnable(lambda: None, server.stop)

    return run_component("operator", build, argv)
