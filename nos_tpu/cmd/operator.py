"""operator: EQ/CEQ reconcilers + validating webhooks
(reference cmd/operator/operator.go:50-126)."""
from __future__ import annotations

from nos_tpu.api.config import OperatorConfig
from nos_tpu.controllers.elasticquota import (
    CompositeElasticQuotaReconciler,
    ElasticQuotaReconciler,
    register_elasticquota_webhooks,
)
from nos_tpu.controllers.elasticquota.controller import pod_to_quota_requests
from nos_tpu.kube.controller import Controller, Manager, Watch


def build_operator(manager: Manager, config: OperatorConfig | None = None) -> None:
    config = config or OperatorConfig()
    config.validate()
    store = manager.store
    register_elasticquota_webhooks(store)

    eq = ElasticQuotaReconciler(store, chip_memory_gb=config.tpu_chip_memory_gb)
    ceq = CompositeElasticQuotaReconciler(store, chip_memory_gb=config.tpu_chip_memory_gb)

    manager.add(
        Controller(
            "elasticquota",
            store,
            eq.reconcile,
            [
                Watch(kind="ElasticQuota"),
                Watch(kind="Pod", mapper=lambda e: pod_to_quota_requests(store, e)),
            ],
        )
    )
    manager.add(
        Controller(
            "compositeelasticquota",
            store,
            ceq.reconcile,
            [
                Watch(kind="CompositeElasticQuota"),
                Watch(
                    kind="Pod",
                    mapper=lambda e: [
                        r
                        for r in pod_to_quota_requests(store, e)
                        if store.try_get("CompositeElasticQuota", r.name, r.namespace)
                    ],
                ),
            ],
        )
    )


def main(argv=None) -> int:
    """Standalone operator process (`python -m nos_tpu operator`)."""
    from nos_tpu.cmd._component import run_component

    def build(manager, config):
        operator_cfg = OperatorConfig(
            tpu_chip_memory_gb=int(config.get("tpuChipMemoryGB", 16))
        )
        build_operator(manager, operator_cfg)

    return run_component("operator", build, argv)
