"""operator: EQ/CEQ reconcilers + validating webhooks
(reference cmd/operator/operator.go:50-126)."""
from __future__ import annotations

from nos_tpu.api.config import OperatorConfig
from nos_tpu.controllers.elasticquota import (
    CompositeElasticQuotaReconciler,
    ElasticQuotaReconciler,
    register_elasticquota_webhooks,
)
from nos_tpu.controllers.elasticquota.controller import pod_to_quota_requests
from nos_tpu.kube.controller import Controller, Manager, Watch


def build_operator(manager: Manager, config: OperatorConfig | None = None) -> None:
    config = config or OperatorConfig()
    config.validate()
    store = manager.store
    register_elasticquota_webhooks(store)

    eq = ElasticQuotaReconciler(store)
    ceq = CompositeElasticQuotaReconciler(store)

    manager.add(
        Controller(
            "elasticquota",
            store,
            eq.reconcile,
            [
                Watch(kind="ElasticQuota"),
                Watch(kind="Pod", mapper=lambda e: pod_to_quota_requests(store, e)),
            ],
        )
    )
    manager.add(
        Controller(
            "compositeelasticquota",
            store,
            ceq.reconcile,
            [
                Watch(kind="CompositeElasticQuota"),
                Watch(
                    kind="Pod",
                    mapper=lambda e: [
                        r
                        for r in pod_to_quota_requests(store, e)
                        if store.try_get("CompositeElasticQuota", r.name, r.namespace)
                    ],
                ),
            ],
        )
    )
