"""`python -m nos_tpu replay <record.jsonl>`: deterministic offline replay.

Loads a flight-recorder JSONL export (written by `run --record` or fetched
from `/debug/record?format=jsonl`), rebuilds the cluster history from the
recorded deltas, re-runs every scheduler cycle and partitioner plan against
the state each decision saw live, and exhaustively audits the planner's
incremental structures after every replayed plan.

Exit code 0 means every replayed decision matched the record and every
invariant check passed; nonzero means drift or an audit violation — the
rendered report names each one.
"""
from __future__ import annotations

import argparse
import logging
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Replay a flight-recorder log and diff decisions"
    )
    parser.add_argument("record", help="JSONL flight-recorder export")
    parser.add_argument("-v", "--verbose", action="count", default=0)
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.WARNING,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )

    from nos_tpu.record import ReplaySession
    from nos_tpu.record.recorder import load_jsonl
    from nos_tpu.record.replay import drift_exit_code

    try:
        records = load_jsonl(args.record)
    except OSError as exc:
        print(f"cannot read {args.record}: {exc}", file=sys.stderr)
        return 2
    if not records:
        print(f"{args.record}: no records", file=sys.stderr)
        return 2

    report = ReplaySession(records).run()
    print(report.render())
    return drift_exit_code(report)


if __name__ == "__main__":
    sys.exit(main())
