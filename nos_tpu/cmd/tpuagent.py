"""tpuagent: per-node Reporter + Actuator daemon
(reference cmd/migagent/migagent.go:56-199; the NODE_NAME env selects the
node a real daemonset instance manages)."""
from __future__ import annotations

from nos_tpu.api.config import TpuAgentConfig
from nos_tpu.controllers.tpuagent import SharedState, TpuActuator, TpuReporter
from nos_tpu.device.client import TpuClient
from nos_tpu.kube.controller import Controller, Manager, Request, Watch
from nos_tpu.util.predicates import matching_name


def build_tpuagent(
    manager: Manager,
    node_name: str,
    client: TpuClient,
    device_plugin,
    config: TpuAgentConfig | None = None,
) -> None:
    config = config or TpuAgentConfig()
    config.validate()
    store = manager.store
    shared = SharedState()
    reporter = TpuReporter(
        store,
        client,
        node_name,
        shared,
        report_interval_seconds=config.report_config_interval_seconds,
    )
    actuator = TpuActuator(store, client, device_plugin, node_name, shared)

    def pod_on_node_mapper(event):
        # A pod starting/finishing on this node changes device usage — the
        # report must not wait out the full interval (the reference's
        # NodeResourcesChanged predicate covers this via node updates; our
        # usage source is pods, so watch them directly).
        if event.object.spec.node_name == node_name:
            return [Request(name=node_name)]
        return []

    manager.add(
        Controller(
            f"tpuagent-reporter-{node_name}",
            store,
            reporter.reconcile,
            [
                Watch(kind="Node", predicate=matching_name(node_name)),
                Watch(kind="Pod", mapper=pod_on_node_mapper),
            ],
        )
    )
    manager.add(
        Controller(
            f"tpuagent-actuator-{node_name}",
            store,
            actuator.reconcile,
            [Watch(kind="Node", predicate=matching_name(node_name))],
        )
    )
