"""tpuagent: per-node Reporter + Actuator daemon
(reference cmd/migagent/migagent.go:56-199; the NODE_NAME env selects the
node a real daemonset instance manages)."""
from __future__ import annotations

from dataclasses import dataclass

from nos_tpu.api.config import TpuAgentConfig
from nos_tpu.controllers.tpuagent import SharedState, TpuActuator, TpuReporter
from nos_tpu.device.client import TpuClient
from nos_tpu.kube.controller import Controller, Manager, Request, Watch
from nos_tpu.util.predicates import matching_name


@dataclass
class TpuAgentHandles:
    """The live pieces of one node's agent — returned so harnesses (the
    chaos driver) can reach the process-internal seams: SharedState.reset()
    models a restart, actuator.chaos_interrupt a mid-actuation crash."""

    shared: SharedState
    reporter: TpuReporter
    actuator: TpuActuator
    reporter_controller: Controller


def build_tpuagent(
    manager: Manager,
    node_name: str,
    client: TpuClient,
    device_plugin,
    config: TpuAgentConfig | None = None,
) -> TpuAgentHandles:
    config = config or TpuAgentConfig()
    config.validate()
    store = manager.store
    shared = SharedState()
    reporter = TpuReporter(
        store,
        client,
        node_name,
        shared,
        report_interval_seconds=config.report_config_interval_seconds,
    )
    actuator = TpuActuator(store, client, device_plugin, node_name, shared)

    def pod_on_node_mapper(event):
        # A pod starting/finishing on this node changes device usage — the
        # report must not wait out the full interval (the reference's
        # NodeResourcesChanged predicate covers this via node updates; our
        # usage source is pods, so watch them directly).
        if event.object.spec.node_name == node_name:
            return [Request(name=node_name)]
        return []

    reporter_controller = Controller(
        f"tpuagent-reporter-{node_name}",
        store,
        reporter.reconcile,
        [
            Watch(kind="Node", predicate=matching_name(node_name)),
            Watch(kind="Pod", mapper=pod_on_node_mapper),
        ],
    )
    manager.add(reporter_controller)
    # Report immediately after every apply: a clamped-to-no-op apply changes
    # no devices (no plugin restart, no node event), so without this nudge
    # its ack would wait out the full report interval.
    shared.add_apply_listener(
        lambda _plan_id: reporter_controller.queue.add(Request(name=node_name))
    )
    manager.add(
        Controller(
            f"tpuagent-actuator-{node_name}",
            store,
            actuator.reconcile,
            [Watch(kind="Node", predicate=matching_name(node_name))],
        )
    )
    return TpuAgentHandles(
        shared=shared,
        reporter=reporter,
        actuator=actuator,
        reporter_controller=reporter_controller,
    )


def main(argv=None) -> int:
    """Standalone tpuagent daemon (`python -m nos_tpu tpuagent`).

    Requires NODE_NAME (reference cmd/migagent/migagent.go:71). The device
    backend comes from config: `tpuctl` drives the native slice-state
    library; `sim` (default) an in-process pool — real hardware actuation
    is wired per-site behind the same TpuClient interface.
    """
    import os

    from nos_tpu.cmd._component import run_component
    from nos_tpu.cmd.run import configs_from

    node_name = os.environ.get("NODE_NAME", "")
    if not node_name:
        import sys

        print("tpuagent: NODE_NAME env is required", file=sys.stderr)
        return 1

    def build(manager, config):
        _, _, agent_cfg, _ = configs_from(config)
        backend = config.get("deviceBackend", "sim")
        if backend == "tpuctl":
            from nos_tpu.api.v1alpha1 import constants as const
            from nos_tpu.api.v1alpha1.labels import GKE_TPU_ACCELERATOR_LABEL
            from nos_tpu.device.sim import DevicePluginAdvertiser, SimPodResourcesClient
            from nos_tpu.device.tpuctl import TpuctlDeviceClient
            from nos_tpu.tpu.known import board_layout
            from nos_tpu.util.predicates import matching_name

            device = TpuctlDeviceClient(config.get("tpuctlDir", "/var/run/nos-tpu"), {})

            # Learn this node's board layout from its labels/capacity before
            # any actuation (the SimCluster path does the same,
            # cluster.py _tpuctl); without it every create fails with
            # "unknown board".
            def sync_topology(req):
                node = manager.store.try_get("Node", node_name)
                if node is not None:
                    accelerator = node.metadata.labels.get(GKE_TPU_ACCELERATOR_LABEL, "")
                    chips = int(node.status.capacity.get(const.RESOURCE_TPU, 0))
                    device.board_topologies[node_name] = board_layout(accelerator, chips)
                return None

            manager.add(
                Controller(
                    f"tpuagent-topology-{node_name}",
                    manager.store,
                    sync_topology,
                    [Watch(kind="Node", predicate=matching_name(node_name))],
                )
            )
            socket = config.get("podResourcesSocket", "")
            if socket:
                # Real kubelet: allocation ground truth from the
                # pod-resources gRPC API (reference pkg/resource/client.go).
                from nos_tpu.device.podresources import KubeletPodResourcesClient

                pod_resources = KubeletPodResourcesClient(socket_path=socket)
            else:
                pod_resources = SimPodResourcesClient(manager.store, device.get_slices)
            client = TpuClient(device, pod_resources)
            plugin = DevicePluginAdvertiser(manager.store, device.geometry)
        else:
            from nos_tpu.device.sim import (
                DevicePluginAdvertiser,
                SimDevicePlugin,
                SimDevicePool,
                SimPodResourcesClient,
                SimTpuDeviceClient,
            )

            pool = SimDevicePool()
            client = TpuClient(
                SimTpuDeviceClient(pool), SimPodResourcesClient(manager.store, pool.get)
            )
            plugin = SimDevicePlugin(manager.store, pool)
        build_tpuagent(manager, node_name, client, plugin)

    return run_component(f"tpuagent[{node_name}]", build, argv)
