"""TpuBoard: one host-local TPU chip grid and its slice geometry.

The analogue of the reference's ``mig.GPU`` (pkg/gpu/mig/gpu.go:27-259):
tracks used/free slices and searches the allowed geometries for one that
serves lacking slice profiles without destroying used slices
(UpdateGeometryFor, gpu.go:158-212). Init picks the fewest-slices geometry
(gpu.go:118-127) — for TPUs that is the whole-board slice.
"""
from __future__ import annotations

import copy
from typing import Optional

from nos_tpu.tpu.geometry import (
    Geometry,
    geometry_add,
    geometry_chips,
    geometry_fits,
    geometry_subtract,
)
from nos_tpu.tpu.known import KNOWN_ACCELERATORS, allowed_geometries
from nos_tpu.tpu.topology import topology_chips


class TpuBoard:
    def __init__(
        self,
        index: int,
        accelerator: str,
        used: Optional[Geometry] = None,
        free: Optional[Geometry] = None,
        board_topology: Optional[str] = None,
    ) -> None:
        if accelerator not in KNOWN_ACCELERATORS:
            raise ValueError(f"unknown TPU accelerator {accelerator!r}")
        self.index = index
        self.accelerator = accelerator
        # Undersized hosts (4-chip v5e workers of a multi-host podslice) carry
        # a smaller board than the generation default.
        self.board_topology = board_topology or KNOWN_ACCELERATORS[accelerator].board_topology
        self.used: Geometry = dict(used or {})
        self.free: Geometry = dict(free or {})

    # ------------------------------------------------------------ queries

    @property
    def geometry(self) -> Geometry:
        return geometry_add(self.used, self.free)

    @property
    def chips(self) -> int:
        return topology_chips(self.board_topology)

    @property
    def used_chips(self) -> int:
        return geometry_chips(self.used)

    @property
    def free_chips(self) -> int:
        return geometry_chips(self.free)

    def has_free_capacity(self) -> bool:
        """Free slices exist, or spare chips could be (re)carved into some."""
        if self.free:
            return True
        return self.used_chips < self.chips

    def clone(self) -> "TpuBoard":
        return copy.deepcopy(self)

    def plan_clone(self) -> "TpuBoard":
        """Cheap clone for snapshot fork journals: the only state a planning
        trial mutates is used/free, so copying those two small dicts (the
        constructor already does) is a full clone."""
        return TpuBoard(
            index=self.index,
            accelerator=self.accelerator,
            used=self.used,
            free=self.free,
            board_topology=self.board_topology,
        )

    # ---------------------------------------------------------- mutation

    def init_geometry(self) -> bool:
        """Apply the fewest-slices allowed geometry to a virgin board."""
        if self.geometry:
            return False
        geometries = allowed_geometries(self.accelerator, self.board_topology)
        if not geometries:
            return False
        self.free = dict(geometries[0])
        return True

    def allocate(self, profile: str, quantity: int = 1) -> bool:
        if self.free.get(profile, 0) < quantity:
            return False
        self.free[profile] -= quantity
        if self.free[profile] == 0:
            del self.free[profile]
        self.used[profile] = self.used.get(profile, 0) + quantity
        return True

    def update_geometry_for(self, lacking: Geometry) -> bool:
        """Re-carve free chips to serve `lacking`, never touching used slices.

        Scans allowed geometries, keeps only those that still contain every
        used slice, and picks the one providing the most lacking slices
        (ties → fewest total slices, i.e. least fragmentation). Returns True
        iff the geometry changed. Reference pkg/gpu/mig/gpu.go:158-212.
        """
        wanted = {p: n for p, n in lacking.items() if n > 0}
        if not wanted:
            return False

        # `wanted` is net of the cluster's existing free slices, so a board
        # that already holds free slices of a wanted profile must aim for
        # free + wanted of it — scoring against `wanted` alone would count
        # its own free slices as new supply and refuse to carve.
        # Scoring is CHIP-weighted — a deviation from the reference's
        # slice count (pkg/gpu/mig/gpu.go:158-212): counting slices makes
        # a free full board prefer carving eight 1x1s over one wanted
        # full-board slice whenever more small slices are lacking, and
        # board-sized slices are the scarce commodity on TPU hosts.
        def provided(geometry: Geometry) -> int:
            free_after = geometry_subtract(geometry, self.used)
            return sum(
                min(free_after.get(p, 0), self.free.get(p, 0) + n)
                * topology_chips(p)
                for p, n in wanted.items()
            )

        current_score = sum(
            self.free.get(p, 0) * topology_chips(p) for p in wanted
        )
        best: Optional[Geometry] = None
        best_score = current_score
        for candidate in allowed_geometries(self.accelerator, self.board_topology):
            if not geometry_fits(candidate, self.used):
                continue
            score = provided(candidate)
            if score > best_score:
                best, best_score = candidate, score
        if best is None:
            return False
        self.free = geometry_subtract(best, self.used)
        return True
