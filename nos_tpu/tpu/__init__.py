"""TPU device domain model.

The TPU analogue of the reference's pkg/gpu + pkg/gpu/mig + pkg/gpu/slicing
(SURVEY.md §2.5): slice profiles are ICI topologies (1x1, 2x2, 2x4, 2x2x1…)
instead of MIG profiles; the allowed-geometry tables of known_configs.go are
*computed* by exactly tiling a board's chip grid with ICI-valid sub-slices
rather than hard-coded; nodes are modeled from GKE TPU labels instead of
NVIDIA GFD labels.
"""

from nos_tpu.tpu.topology import Topology
from nos_tpu.tpu.geometry import (
    Geometry,
    geometry_add,
    geometry_chips,
    geometry_fits,
    geometry_subtract,
)
from nos_tpu.tpu.known import (
    AcceleratorSpec,
    KNOWN_ACCELERATORS,
    allowed_geometries,
    board_layout,
    profile_for_chips,
    set_known_geometries,
)
from nos_tpu.tpu.board import TpuBoard
from nos_tpu.tpu.node import TpuNode

__all__ = [
    "AcceleratorSpec",
    "Geometry",
    "KNOWN_ACCELERATORS",
    "Topology",
    "TpuBoard",
    "TpuNode",
    "allowed_geometries",
    "board_layout",
    "geometry_add",
    "geometry_chips",
    "geometry_fits",
    "geometry_subtract",
    "profile_for_chips",
    "set_known_geometries",
]
