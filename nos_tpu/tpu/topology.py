"""ICI topology math: parsing, chip counts, and exact slice tilings.

The MIG analogue is the hard-coded allowed-geometry table per GPU model
(reference pkg/gpu/mig/known_configs.go:24-185). TPU slice validity is
geometric — a sub-slice must be a contiguous axis-aligned block of the
board's chip grid so its ICI links stay internal — so instead of tables we
*enumerate exact tilings* of the board topology by the generation's allowed
slice shapes. The result plays the same role (the search space of
``UpdateGeometryFor``) but is provably ICI-valid and extends to any
topology without new tables.
"""
from __future__ import annotations

import itertools
from functools import lru_cache
from typing import Dict, FrozenSet, List, Tuple


class Topology:
    """An ICI topology like '2x4' (v5e) or '2x2x1' (v4/v5p)."""

    __slots__ = ("dims",)

    def __init__(self, spec: "str | Tuple[int, ...]") -> None:
        if isinstance(spec, str):
            try:
                dims = tuple(int(d) for d in spec.split("x"))
            except ValueError as e:
                raise ValueError(f"invalid topology {spec!r}") from e
        else:
            dims = tuple(spec)
        if not dims or any(d < 1 for d in dims):
            raise ValueError(f"invalid topology {spec!r}")
        self.dims = dims

    @property
    def chips(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    @property
    def rank(self) -> int:
        return len(self.dims)

    def __str__(self) -> str:
        return "x".join(str(d) for d in self.dims)

    def __repr__(self) -> str:
        return f"Topology({str(self)!r})"

    def __eq__(self, other) -> bool:
        return isinstance(other, Topology) and self.dims == other.dims

    def __hash__(self) -> int:
        return hash(self.dims)

    def orientations(self) -> List[Tuple[int, ...]]:
        """Distinct axis permutations (a 1x2 slice may lie along either axis)."""
        return sorted(set(itertools.permutations(self.dims)))


@lru_cache(maxsize=4096)
def parse_topology(spec: str) -> Topology:
    """Memoized Topology parse. Profile strings recur endlessly in the
    planner's hot paths (every free slice of every node per candidate
    scan), and Topology is immutable after construction, so instances are
    safe to share."""
    return Topology(spec)


@lru_cache(maxsize=4096)
def topology_chips(spec: str) -> int:
    """Chip count of a profile string, memoized — the single most frequent
    topology query in the partitioning engine."""
    return parse_topology(spec).chips


def _cells(dims: Tuple[int, ...]) -> List[Tuple[int, ...]]:
    return list(itertools.product(*(range(d) for d in dims)))


def _placements_at(
    dims: Tuple[int, ...], anchor: Tuple[int, ...], shape: Tuple[int, ...]
) -> "FrozenSet[Tuple[int, ...]] | None":
    """Cells covered by `shape` anchored (min corner) at `anchor`, or None if
    it overflows the grid."""
    for a, s, d in zip(anchor, shape, dims):
        if a + s > d:
            return None
    ranges = [range(a, a + s) for a, s in zip(anchor, shape)]
    return frozenset(itertools.product(*ranges))


@lru_cache(maxsize=None)
def enumerate_tilings(
    host: str, shapes: Tuple[str, ...]
) -> Tuple[Dict[str, int], ...]:
    """All distinct multisets of `shapes` that exactly tile `host`.

    Returns a tuple of geometries (profile string → count). Grids are tiny
    (≤16 cells for any single host), so backtracking over the first empty
    cell is instant. Orientation variants of a shape count as the same
    profile (a 1x2 slice is a 1x2 slice however it lies).
    """
    host_t = Topology(host)
    dims = host_t.dims
    shape_ts = [Topology(s) for s in shapes]
    for s in shape_ts:
        if s.rank != host_t.rank:
            raise ValueError(
                f"shape {s} rank {s.rank} != host {host_t} rank {host_t.rank}"
            )

    all_cells = _cells(dims)
    results: Dict[Tuple[Tuple[str, int], ...], Dict[str, int]] = {}

    def solve(uncovered: FrozenSet[Tuple[int, ...]], counts: Dict[str, int]) -> None:
        if not uncovered:
            key = tuple(sorted(counts.items()))
            results[key] = dict(counts)
            return
        # Anchor on the lexicographically-first uncovered cell: every tiling
        # covers it exactly once, so this enumerates each tiling once per
        # distinct placement (geometry-level dedup happens via `results`).
        anchor = min(uncovered)
        for shape_t in shape_ts:
            name = str(shape_t)
            for orient in shape_t.orientations():
                covered = _placements_at(dims, anchor, orient)
                if covered is None or not covered <= uncovered:
                    continue
                counts[name] = counts.get(name, 0) + 1
                solve(uncovered - covered, counts)
                counts[name] -= 1
                if counts[name] == 0:
                    del counts[name]

    solve(frozenset(all_cells), {})
    # Stable order: fewest slices first (biggest profiles preferred), then name.
    ordered = sorted(
        results.values(), key=lambda g: (sum(g.values()), sorted(g.items()))
    )
    return tuple(ordered)
