"""Known TPU accelerator generations and their slice search spaces.

Analogue of the reference's known MIG geometry tables
(pkg/gpu/mig/known_configs.go:24-185: A30 / A100-40GB / A100-80GB) with the
same override hook (`SetKnownGeometries`, loaded from a YAML file at
cmd/gpupartitioner/gpupartitioner.go:370-380). Here the per-generation data
is the *board topology* + *allowed slice shapes*; allowed geometries are
derived by exact tiling (nos_tpu/tpu/topology.py) and can still be
overridden wholesale for exotic deployments.
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache
from typing import Dict, List, Optional, Tuple

from nos_tpu.tpu.geometry import Geometry
from nos_tpu.tpu.topology import Topology, enumerate_tilings


@dataclass(frozen=True)
class AcceleratorSpec:
    """One TPU generation as seen from a single host.

    `board_topology` is the chip grid local to one host/board (the unit the
    partitioner can re-carve without node-pool operations); `slice_shapes`
    are the ICI-valid sub-slice topologies the device plugin can expose.
    """

    name: str  # value of cloud.google.com/gke-tpu-accelerator
    board_topology: str
    slice_shapes: Tuple[str, ...]
    # Per-chip HBM capacity: the budget the sharing mode carves into
    # google.com/tpu-mem-<N>gb fractions (the TPU analogue of a GPU's
    # memory budget in reference pkg/gpu/slicing/gpu.go).
    hbm_gb: int = 16
    # ICI-valid topologies spanning SEVERAL hosts (each an exact tiling of
    # board_topology). A plain-chip request exceeding one board expands to
    # a gang of per-host board slices over one of these shapes
    # (controllers/partitioner/multihost.py).
    multihost_shapes: Tuple[str, ...] = ()

    @property
    def board_chips(self) -> int:
        return Topology(self.board_topology).chips


# GKE accelerator label values → per-host slicing capability.
KNOWN_ACCELERATORS: Dict[str, AcceleratorSpec] = {
    # v5e: 8 chips per host laid out 2x4; single-host slice configs
    # 1x1 (1 chip), 1x2 (2), 2x2 (4), 2x4 (8).
    "tpu-v5-lite-podslice": AcceleratorSpec(
        name="tpu-v5-lite-podslice",
        board_topology="2x4",
        slice_shapes=("1x1", "1x2", "2x2", "2x4"),
        hbm_gb=16,
        multihost_shapes=("4x4", "4x8", "8x8", "8x16", "16x16"),
    ),
    # v5e single-host device nodes (ct5l): 4 chips, 2x2.
    "tpu-v5-lite-device": AcceleratorSpec(
        name="tpu-v5-lite-device",
        board_topology="2x2",
        slice_shapes=("1x1", "1x2", "2x2"),
        hbm_gb=16,
    ),
    # v4: 4 chips per host (2x2x1 local cube face).
    "tpu-v4-podslice": AcceleratorSpec(
        name="tpu-v4-podslice",
        board_topology="2x2x1",
        slice_shapes=("1x1x1", "1x2x1", "2x2x1"),
        hbm_gb=32,
        multihost_shapes=("2x2x2", "2x2x4", "2x4x4", "4x4x4"),
    ),
    # v5p: 4 chips per host.
    "tpu-v5p-slice": AcceleratorSpec(
        name="tpu-v5p-slice",
        board_topology="2x2x1",
        slice_shapes=("1x1x1", "1x2x1", "2x2x1"),
        hbm_gb=95,
        multihost_shapes=("2x2x2", "2x2x4", "2x4x4", "4x4x4"),
    ),
    # v6e (Trillium): 8 chips per host, 2x4, same slice configs as v5e.
    "tpu-v6e-slice": AcceleratorSpec(
        name="tpu-v6e-slice",
        board_topology="2x4",
        slice_shapes=("1x1", "1x2", "2x2", "2x4"),
        hbm_gb=32,
        multihost_shapes=("4x4", "4x8", "8x8"),
    ),
}

# Optional wholesale override (config-file analogue of KnownMigGeometriesFile).
_geometry_overrides: Dict[str, List[Geometry]] = {}


def set_known_geometries(overrides: Optional[Dict[str, List[Geometry]]]) -> None:
    """Replace the computed allowed-geometry list for given accelerators.

    Reference mig.SetKnownGeometries (pkg/gpu/mig/known_configs.go:144-150).
    Pass None to clear all overrides.
    """
    global _geometry_overrides
    _geometry_overrides = dict(overrides) if overrides else {}


def known_geometry_overrides() -> Dict[str, List[Geometry]]:
    """The live override map (JSON-shaped) — the process pool backend
    ships it to worker processes, which must derive the same boards the
    parent does despite not sharing this module global."""
    return dict(_geometry_overrides)


def allowed_geometries(accelerator: str, board_topology: Optional[str] = None) -> List[Geometry]:
    """All ICI-valid slice geometries for one board of `accelerator`,
    ordered fewest-slices-first. Unknown accelerators yield [].

    `board_topology` overrides the generation's default board shape for
    undersized hosts (e.g. a 4-chip v5e host is a 2x2 board, not 2x4).
    File-based geometry overrides apply only to the default board shape.
    """
    spec = KNOWN_ACCELERATORS.get(accelerator)
    if spec is None:
        return []
    board = board_topology or spec.board_topology
    if board == spec.board_topology and accelerator in _geometry_overrides:
        return [dict(g) for g in _geometry_overrides[accelerator]]
    return [dict(g) for g in enumerate_tilings(board, spec.slice_shapes)]


def board_layout(accelerator: str, capacity_chips: int) -> List[str]:
    """Board topologies modeling a node that exposes `capacity_chips` chips.

    A node advertising a multiple of the generation's board size gets that
    many full boards; an undersized remainder (multi-host podslice workers,
    smaller machine types) gets a board of the exact-size slice shape. A
    capacity no combination models (or 0 — device plugin not registered
    yet) yields [] so the planner never carves phantom chips.
    """
    return list(_board_layout(accelerator, capacity_chips))


@lru_cache(maxsize=4096)
def _board_layout(accelerator: str, capacity_chips: int) -> Tuple[str, ...]:
    spec = KNOWN_ACCELERATORS.get(accelerator)
    if spec is None or capacity_chips <= 0:
        return ()
    layouts: List[str] = []
    remaining = capacity_chips
    while remaining >= spec.board_chips:
        layouts.append(spec.board_topology)
        remaining -= spec.board_chips
    if remaining > 0:
        exact = [
            s
            for s in spec.slice_shapes
            if Topology(s).chips == remaining
        ]
        if not exact:
            return ()
        # Largest-area shapes are equal here; pick deterministic first.
        layouts.append(sorted(exact)[0])
    return tuple(layouts)


@lru_cache(maxsize=4096)
def profile_for_chips(chips: int, accelerator: str) -> Optional[str]:
    """Smallest slice profile of `accelerator` with ≥ `chips` chips.

    This is how plain ``google.com/tpu: N`` requests are normalized to slice
    requests at the planner/scheduler boundary (the reference equivalent is
    users requesting nvidia.com/mig-Ng.Mgb directly; TPU UX per BASELINE is
    chip counts)."""
    spec = KNOWN_ACCELERATORS.get(accelerator)
    if spec is None:
        return None
    candidates = sorted(
        (Topology(s) for s in spec.slice_shapes), key=lambda t: (t.chips, str(t))
    )
    for t in candidates:
        if t.chips >= chips:
            return str(t)
    return None


def hbm_gb_per_chip(accelerator: str) -> int:
    """Per-chip HBM budget the sharing mode may carve; 0 when unknown."""
    spec = KNOWN_ACCELERATORS.get(accelerator)
    return spec.hbm_gb if spec is not None else 0


def multihost_profile_for_chips(chips: int, accelerator: str):
    """(shape, n_hosts) of the smallest multi-host topology holding
    ``chips`` chips, or None.

    Only meaningful when the request exceeds one board (single-host
    requests go through profile_for_chips); each shape tiles exactly into
    per-host boards, so n_hosts = shape chips / board chips."""
    spec = KNOWN_ACCELERATORS.get(accelerator)
    if spec is None:
        return None
    candidates = sorted(
        (Topology(s) for s in spec.multihost_shapes), key=lambda t: (t.chips, str(t))
    )
    for t in candidates:
        if t.chips >= chips:
            return str(t), t.chips // spec.board_chips
    return None
