"""Geometry: a multiset of slice profiles on one board.

Equivalent of the reference's ``gpu.Geometry = map[Slice]int``
(pkg/gpu/partitioning.go:28-143). Profiles are topology strings ("2x2").
"""
from __future__ import annotations

from typing import Dict

from nos_tpu.tpu.topology import Topology

Geometry = Dict[str, int]


def geometry_chips(g: Geometry) -> int:
    return sum(Topology(p).chips * n for p, n in g.items())


def geometry_add(a: Geometry, b: Geometry) -> Geometry:
    out = dict(a)
    for p, n in b.items():
        out[p] = out.get(p, 0) + n
    return {p: n for p, n in out.items() if n != 0}


def geometry_subtract(a: Geometry, b: Geometry) -> Geometry:
    """a - b; negative counts are kept (caller checks with geometry_fits)."""
    out = dict(a)
    for p, n in b.items():
        out[p] = out.get(p, 0) - n
    return {p: n for p, n in out.items() if n != 0}


def geometry_fits(container: Geometry, content: Geometry) -> bool:
    """True when `container` has at least `content` of every profile."""
    return all(container.get(p, 0) >= n for p, n in content.items())
