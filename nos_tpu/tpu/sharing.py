"""Shared-chip domain model: HBM-fraction slicing of TPU chips.

The TPU analogue of the reference's MPS slicing domain
(pkg/gpu/slicing/gpu.go:27-265, node.go:32-215): instead of carving a chip
into ICI sub-topologies, the sharing mode time-multiplexes one chip among
several pods, each holding a ``google.com/tpu-mem-<N>gb`` fraction of the
chip's HBM. Geometry search is a memory-budget bin problem per chip: first
create missing slices from spare HBM, then sacrifice free slices to make
room (reference slicing/gpu.go:162-220), never touching used slices.
"""
from __future__ import annotations

import copy
from typing import Dict, List

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.kube.objects import Node, Pod, ResourceList
from nos_tpu.tpu.geometry import Geometry, geometry_add
from nos_tpu.tpu.known import hbm_gb_per_chip
from nos_tpu.util import resources as res


def _profile_gb(profile: str) -> int:
    return constants.shared_profile_gb(profile)


class SharedChip:
    """One TPU chip with an HBM budget carved into shared slices.

    Mirrors reference slicing.GPU: `used`/`free` map profile ("8gb") to
    slice count; the invariant is Σ(profile_gb · count) ≤ hbm_gb.
    """

    def __init__(
        self,
        index: int,
        hbm_gb: int,
        used: Geometry | None = None,
        free: Geometry | None = None,
    ) -> None:
        self.index = index
        self.hbm_gb = hbm_gb
        self.used: Geometry = dict(used or {})
        self.free: Geometry = dict(free or {})

    # ----------------------------------------------------------- queries

    @property
    def geometry(self) -> Geometry:
        return geometry_add(self.used, self.free)

    def committed_memory_gb(self) -> int:
        """HBM held by existing slices, used or free."""
        return sum(_profile_gb(p) * q for p, q in self.used.items()) + sum(
            _profile_gb(p) * q for p, q in self.free.items()
        )

    def spare_memory_gb(self) -> int:
        return self.hbm_gb - self.committed_memory_gb()

    def has_free_capacity(self) -> bool:
        return bool(self.free) or self.spare_memory_gb() >= constants.MIN_SHARED_SLICE_GB

    def plan_clone(self) -> "SharedChip":
        """Cheap clone for snapshot fork journals (used/free are the only
        mutable state; the constructor copies both dicts)."""
        return SharedChip(
            index=self.index, hbm_gb=self.hbm_gb, used=self.used, free=self.free
        )

    # ---------------------------------------------------------- mutation

    def _create(self, profile: str, quantity: int = 1) -> int:
        """Create up to `quantity` free slices of `profile` from spare
        memory; returns how many were created."""
        gb = _profile_gb(profile)
        created = 0
        for _ in range(quantity):
            if gb < constants.MIN_SHARED_SLICE_GB or gb > self.spare_memory_gb():
                break
            self.free[profile] = self.free.get(profile, 0) + 1
            created += 1
        return created

    def allocate(self, profile: str) -> bool:
        """Move one free slice to used (a pod binding to it)."""
        if self.free.get(profile, 0) <= 0:
            return False
        self.free[profile] -= 1
        if self.free[profile] == 0:
            del self.free[profile]
        self.used[profile] = self.used.get(profile, 0) + 1
        return True

    def update_geometry_for(self, required: Geometry) -> bool:
        """Re-carve the chip toward `required` (profile → wanted count)
        without destroying used slices. Shape of reference
        slicing/gpu.go:162-220 — smaller profiles first, spare memory
        first, then trade free slices away, restoring what still fits —
        but the trade never sacrifices free slices a required profile
        still needs (the reference can destroy slices it just created for
        an earlier required profile). Returns True when geometry changed."""
        missing: Dict[str, int] = {}
        for profile, qty in required.items():
            diff = qty - self.free.get(profile, 0)
            if diff > 0:
                missing[profile] = diff
        if not missing:
            return False

        updated = False
        for profile in sorted(missing, key=_profile_gb):
            created = self._create(profile, missing[profile])
            missing[profile] -= created
            if created:
                updated = True
            if missing[profile] <= 0:
                continue
            if self._trade_for(profile, missing[profile], required):
                updated = True
        return updated

    def _trade_for(self, profile: str, quantity: int, required: Geometry) -> bool:
        """Sacrifice expendable free slices — profiles nobody requires, or
        counts beyond a profile's required quota — to make room for
        `quantity` slices of `profile`; whatever was sacrificed but not
        consumed is restored afterwards."""
        gb = _profile_gb(profile)
        sacrificed: Dict[str, int] = {}
        created_any = False
        for _ in range(quantity):
            while self.spare_memory_gb() < gb:
                victim = self._pick_expendable(required)
                if victim is None:
                    break
                self.free[victim] -= 1
                if self.free[victim] == 0:
                    del self.free[victim]
                sacrificed[victim] = sacrificed.get(victim, 0) + 1
            if self._create(profile) != 1:
                break
            created_any = True
        # Put back sacrificed slices that still fit (largest first keeps
        # restoration deterministic; leftovers simply stay spare).
        for victim in sorted(sacrificed, key=_profile_gb, reverse=True):
            self._create(victim, sacrificed[victim])
        return created_any

    def _pick_expendable(self, required: Geometry) -> "str | None":
        """A free slice safe to destroy: smallest non-required profile
        first, then the smallest required profile with free count above
        its requirement."""
        candidates = [p for p in self.free if p not in required]
        if not candidates:
            candidates = [
                p for p in self.free if self.free[p] > required.get(p, 0)
            ]
        if not candidates:
            return None
        return min(candidates, key=_profile_gb)


class SharingNode:
    """PartitionableNode over shared chips — the sharing-mode counterpart
    of TpuNode (reference slicing.Node, pkg/gpu/slicing/node.go:32-215).
    Chips play the role boards play in the tpu mode: status annotations are
    keyed by chip index."""

    def __init__(self, node: Node, owned: bool = False) -> None:
        self.name = node.metadata.name
        self.node = node if owned else node.deepcopy()
        self.accelerator = node.metadata.labels.get(labels.GKE_TPU_ACCELERATOR_LABEL, "")
        self.chips: List[SharedChip] = []
        self.consistent = True
        self._build_chips(node)

    def _build_chips(self, node: Node) -> None:
        hbm = hbm_gb_per_chip(self.accelerator)
        total_chips = int(node.status.capacity.get(constants.RESOURCE_TPU, 0))
        shared = labels.shared_chip_count(node, total_chips)
        if hbm <= 0 or shared <= 0:
            return
        # On hybrid nodes the sharing pool is the highest-indexed chips
        # (the rest are slice boards); chip indices stay global so device
        # ids and annotations never collide across the two passes.
        offset = total_chips - shared
        chip_count = total_chips
        _, status = annot.parse_node_annotations(node.metadata.annotations)
        free_by_chip: Dict[int, Geometry] = {}
        used_by_chip: Dict[int, Geometry] = {}
        for s in status:
            if not s.profile.endswith("gb"):
                continue  # tpu-mode annotation on a relabeled node: not ours
            if not (offset <= s.board_index < chip_count):
                self.consistent = False
                continue
            target = free_by_chip if s.status == annot.STATUS_FREE else used_by_chip
            chip = target.setdefault(s.board_index, {})
            chip[s.profile] = chip.get(s.profile, 0) + s.quantity
        for i in range(offset, chip_count):
            self.chips.append(
                SharedChip(
                    index=i,
                    hbm_gb=hbm,
                    used=used_by_chip.get(i, {}),
                    free=free_by_chip.get(i, {}),
                )
            )

    # ----------------------------------------------------------- queries

    @property
    def is_sharing_node(self) -> bool:
        return bool(self.chips)

    def geometry(self) -> Dict[int, Geometry]:
        return {c.index: c.geometry for c in self.chips}

    def has_free_capacity(self) -> bool:
        if not self.consistent:
            return False
        return any(c.has_free_capacity() for c in self.chips)

    def free_slices(self) -> Geometry:
        out: Geometry = {}
        for c in self.chips:
            out = geometry_add(out, c.free)
        return out

    def clone(self) -> "SharingNode":
        return copy.deepcopy(self)

    def plan_clone(self) -> "SharingNode":
        """Cheap clone for snapshot fork journals — chip used/free state is
        copied, the kube Node (never mutated by planning) is shared. See
        TpuNode.plan_clone."""
        clone = object.__new__(SharingNode)
        clone.name = self.name
        clone.node = self.node
        clone.accelerator = self.accelerator
        clone.consistent = self.consistent
        clone.chips = [c.plan_clone() for c in self.chips]
        return clone

    # ---------------------------------------------------------- mutation

    def update_geometry_for(self, lacking_slices: ResourceList) -> bool:
        """Chips are visited in order, each serving whatever is still
        lacking after its predecessors (same walk as TpuNode boards)."""
        if not self.consistent:
            return False
        remaining: Geometry = {}
        for name, qty in lacking_slices.items():
            if constants.is_tpu_shared_resource(name):
                remaining[constants.tpu_shared_profile(name)] = int(qty)
        if not remaining:
            return False
        changed = False
        for chip in self.chips:
            if not remaining:
                break
            if chip.update_geometry_for(remaining):
                changed = True
            for profile in list(remaining):
                remaining[profile] -= chip.free.get(profile, 0)
                if remaining[profile] <= 0:
                    del remaining[profile]
        return changed

    def add_pod(self, pod: Pod) -> bool:
        """Consume free shared slices for the pod's tpu-mem requests;
        returns False (node untouched) when it does not fit."""
        request = res.compute_pod_request(pod)
        needed: Geometry = {}
        for name, qty in request.items():
            if constants.is_tpu_shared_resource(name):
                needed[constants.tpu_shared_profile(name)] = int(qty)
        if not needed:
            return True
        plan: List[tuple] = []
        free = {c.index: dict(c.free) for c in self.chips}
        for profile, qty in needed.items():
            for _ in range(qty):
                placed = False
                for c in self.chips:
                    if free[c.index].get(profile, 0) > 0:
                        free[c.index][profile] -= 1
                        plan.append((c, profile))
                        placed = True
                        break
                if not placed:
                    return False
        for chip, profile in plan:
            chip.allocate(profile)
        return True

    # ------------------------------------------------------- projections

    def scalar_resources(self) -> ResourceList:
        out: ResourceList = {}
        for c in self.chips:
            for profile, qty in c.geometry.items():
                name = constants.tpu_shared_resource(profile)
                out[name] = out.get(name, 0) + qty
        return out

    def to_sim_node(self) -> Node:
        """Node view for scheduler simulation: shared slices advertised,
        chips carrying any slice no longer plain-requestable."""
        node = self.node.deepcopy()
        alloc = {
            k: v
            for k, v in node.status.allocatable.items()
            if not constants.is_tpu_shared_resource(k) and k != constants.RESOURCE_TPU
        }
        plain_chips = sum(1 for c in self.chips if not c.geometry)
        if labels.partitioning_kind(node) == labels.PartitioningKind.HYBRID:
            # Hybrid chips are never plain-requestable (see the device
            # plugin advertisers, which zero the scalar the same way).
            plain_chips = 0
        merged = res.sum_resources(alloc, self.scalar_resources())
        merged[constants.RESOURCE_TPU] = plain_chips
        node.status.allocatable = merged
        return node
