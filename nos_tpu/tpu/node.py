"""TpuNode: a partitionable node modeled from GKE labels + status annotations.

The analogue of the reference's ``mig.Node`` (pkg/gpu/mig/node.go:26-222):
built from the Node object's GKE TPU labels (accelerator/topology — replacing
NVIDIA GFD labels) plus the status annotations the tpuagent reported; it
implements the PartitionableNode protocol the partitioning engine drives
(UpdateGeometryFor / Geometry / AddPod / HasFreeCapacity / Clone) and can
recompute the node's scalar resources after a geometry change
(node.go:173-195) for scheduler simulation.
"""
from __future__ import annotations

import copy
from typing import Dict, List

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.kube.objects import Node, Pod, PodPhase, ResourceList
from nos_tpu.tpu.board import TpuBoard
from nos_tpu.tpu.geometry import Geometry, geometry_add
from nos_tpu.tpu.known import KNOWN_ACCELERATORS, board_layout
from nos_tpu.util import resources as res


class TpuNode:
    def __init__(self, node: Node, owned: bool = False) -> None:
        """`owned=True` means the caller hands over a private copy (e.g. the
        snapshot taker, whose ClusterState read already deep-copied), so the
        defensive copy here can be skipped."""
        self.name = node.metadata.name
        self.node = node if owned else node.deepcopy()
        self.accelerator = node.metadata.labels.get(labels.GKE_TPU_ACCELERATOR_LABEL, "")
        self.boards: List[TpuBoard] = []
        # False when status annotations reference boards this node cannot
        # have (stale agent state, mid-resize): the planner must neither
        # carve nor place on a node whose reported state it cannot model.
        self.consistent = True
        if self.accelerator in KNOWN_ACCELERATORS:
            self._build_boards(node)

    # ------------------------------------------------------------- build

    def _build_boards(self, node: Node) -> None:
        capacity_chips = int(node.status.capacity.get(constants.RESOURCE_TPU, 0))
        # On hybrid nodes the highest-indexed chips belong to the sharing
        # pass; only the remainder is carved into boards here.
        capacity_chips -= labels.shared_chip_count(node, capacity_chips)
        layouts = board_layout(self.accelerator, capacity_chips)
        if not layouts:
            # Device plugin not registered yet (capacity 0) or capacity no
            # board combination models: expose nothing rather than carve
            # phantom chips.
            return

        _, status = annot.parse_node_annotations(node.metadata.annotations)
        free_by_board: Dict[int, Geometry] = {}
        used_by_board: Dict[int, Geometry] = {}
        for s in status:
            if "x" not in s.profile:
                # Sharing-mode ("<N>gb") annotation left over from a
                # relabeled node: not a topology, not ours to model.
                continue
            if s.board_index >= len(layouts):
                self.consistent = False
                continue
            target = free_by_board if s.status == annot.STATUS_FREE else used_by_board
            board = target.setdefault(s.board_index, {})
            board[s.profile] = board.get(s.profile, 0) + s.quantity

        for i, topology in enumerate(layouts):
            self.boards.append(
                TpuBoard(
                    index=i,
                    accelerator=self.accelerator,
                    used=used_by_board.get(i, {}),
                    free=free_by_board.get(i, {}),
                    board_topology=topology,
                )
            )

    # ----------------------------------------------------------- queries

    @property
    def is_tpu_node(self) -> bool:
        return bool(self.boards)

    def geometry(self) -> Dict[int, Geometry]:
        """Board index → total geometry (used+free)."""
        return {b.index: b.geometry for b in self.boards}

    def has_free_capacity(self) -> bool:
        if not self.consistent:
            return False
        return any(b.has_free_capacity() for b in self.boards)

    def free_slices(self) -> Geometry:
        out: Geometry = {}
        for b in self.boards:
            out = geometry_add(out, b.free)
        return out

    def clone(self) -> "TpuNode":
        return copy.deepcopy(self)

    def plan_clone(self) -> "TpuNode":
        """Cheap clone for snapshot fork journals. Planning mutates only
        board used/free state, never the underlying kube Node (to_sim_node
        deepcopies before rewriting), so the Node object is shared and only
        the boards are copied — this is what makes CoW fork cost
        proportional to touched nodes, not cluster object graphs."""
        clone = object.__new__(TpuNode)
        clone.name = self.name
        clone.node = self.node
        clone.accelerator = self.accelerator
        clone.consistent = self.consistent
        clone.boards = [b.plan_clone() for b in self.boards]
        return clone

    # ---------------------------------------------------------- mutation

    def update_geometry_for(self, lacking_slices: ResourceList) -> bool:
        """Try to re-carve boards so the cluster lacks fewer of
        `lacking_slices` (a ResourceList of slice resources). Boards are
        visited in order, each serving whatever is still lacking after its
        predecessors (reference pkg/gpu/mig/node.go:145-171)."""
        if not self.consistent:
            return False
        remaining: Geometry = {}
        for name, qty in lacking_slices.items():
            if constants.is_tpu_slice_resource(name):
                remaining[constants.tpu_slice_topology(name)] = int(qty)
        if not remaining:
            return False
        changed = False
        for board in self.boards:
            if not remaining:
                break
            if board.update_geometry_for(remaining):
                changed = True
            for profile in list(remaining):
                remaining[profile] -= board.free.get(profile, 0)
                if remaining[profile] <= 0:
                    del remaining[profile]
        return changed

    def rebuild_usage_from_pods(self, pods: List[Pod]) -> None:
        """Re-derive the used/free split from the pods actually bound to
        this node (API-store truth), keeping only the reported *geometry*
        from the status annotations.

        The reporter's used/free split lags binds by up to a report
        interval; planning against a stale "free" can carve away a slice a
        just-bound pod occupies, letting the scheduler double-book the
        board's chips. If some bound pod's profile has no device in the
        reported geometry, the node is mid-transition: mark it inconsistent
        so the planner leaves it alone until the agent re-reports
        (tpu/node.py consistency contract, reference node.go:34-37
        analogue).
        """
        demand: Geometry = {}
        for pod in pods:
            if pod.status.phase not in (PodPhase.PENDING, PodPhase.RUNNING):
                continue
            request = res.normalize_tpu_request(
                res.compute_pod_request(pod), self.accelerator
            )
            for name, qty in request.items():
                if constants.is_tpu_slice_resource(name):
                    profile = constants.tpu_slice_topology(name)
                    demand[profile] = demand.get(profile, 0) + int(qty)
        for board in self.boards:
            board.free = geometry_add(board.free, board.used)
            board.used = {}
        for profile in sorted(demand):
            for _ in range(demand[profile]):
                for board in self.boards:
                    if board.allocate(profile):
                        break
                else:
                    self.consistent = False
                    return

    def add_pod(self, pod: Pod) -> bool:
        """Consume free slices for the pod's (normalized) TPU request.
        Returns False — leaving the node untouched — when it does not fit."""
        request = res.normalize_tpu_request(res.compute_pod_request(pod), self.accelerator)
        if int(request.get(constants.RESOURCE_TPU, 0)) > 0:
            # Normalization left a plain-chip request: no single-board profile
            # holds it, so this node cannot serve it by carving (that is the
            # multi-host gang-scheduling path, not slice allocation).
            return False
        needed: Geometry = {}
        for name, qty in request.items():
            if constants.is_tpu_slice_resource(name):
                needed[constants.tpu_slice_topology(name)] = int(qty)
        if not needed:
            return True
        plan: List[tuple] = []
        free = {b.index: dict(b.free) for b in self.boards}
        for profile, qty in needed.items():
            for _ in range(qty):
                placed = False
                for b in self.boards:
                    if free[b.index].get(profile, 0) > 0:
                        free[b.index][profile] -= 1
                        plan.append((b, profile))
                        placed = True
                        break
                if not placed:
                    return False
        for board, profile in plan:
            board.allocate(profile)
        return True

    # ------------------------------------------------------- projections

    def scalar_resources(self) -> ResourceList:
        """Slice resources this node's current geometry exposes — what the
        device plugin would advertise, used to refresh allocatable in
        scheduler simulation (reference node.go:173-195)."""
        out: ResourceList = {}
        for b in self.boards:
            for profile, qty in b.geometry.items():
                name = constants.tpu_slice_resource(profile)
                out[name] = out.get(name, 0) + qty
        return out

    def to_sim_node(self) -> Node:
        """Node object with allocatable rewritten to the current geometry,
        for feeding the in-process scheduler framework."""
        node = self.node.deepcopy()
        alloc = {
            k: v
            for k, v in node.status.allocatable.items()
            if not constants.is_tpu_slice_resource(k) and k != constants.RESOURCE_TPU
        }
        node.status.allocatable = res.sum_resources(alloc, self.scalar_resources())
        return node
