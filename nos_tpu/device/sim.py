"""Simulated TPU device layer — the fake device plugin of BASELINE config #1.

Plays the role real hardware + kubelet play in production: SimDevicePool is
the per-node "silicon" (carved slices), SimPodResourcesClient derives which
devices the scheduled pods hold, and SimDevicePlugin re-advertises the
pool's slices into Node.status.allocatable (what a device-plugin restart
does in the reference, pkg/gpu/client.go:51-135).
"""
from __future__ import annotations

import itertools
import threading
from typing import Dict, List

from nos_tpu.api.v1alpha1 import constants
from nos_tpu.device.types import TpuSliceDevice
from nos_tpu.kube.objects import PodPhase
from nos_tpu.kube.store import KubeStore, NotFoundError
from nos_tpu.tpu.topology import Topology
from nos_tpu.util import resources as res


class SimDevicePool:
    """In-memory carved-slice registry per node (the 'hardware')."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        # node -> device_id -> TpuSliceDevice (status field unused here)
        self._slices: Dict[str, Dict[str, TpuSliceDevice]] = {}
        self._counter = itertools.count(1)

    def get(self, node_name: str) -> List[TpuSliceDevice]:
        with self._lock:
            return list(self._slices.get(node_name, {}).values())

    def create(self, node_name: str, board_index: int, profile: str, quantity: int) -> None:
        with self._lock:
            node = self._slices.setdefault(node_name, {})
            for _ in range(quantity):
                device_id = f"tpu-{node_name}-{board_index}-{profile}-{next(self._counter)}"
                node[device_id] = TpuSliceDevice(
                    device_id=device_id, board_index=board_index, profile=profile
                )

    def delete(self, node_name: str, device_id: str) -> None:
        with self._lock:
            node = self._slices.get(node_name, {})
            if device_id not in node:
                raise NotFoundError(f"device {device_id} not found on {node_name}")
            del node[device_id]

    def geometry(self, node_name: str) -> Dict[int, Dict[str, int]]:
        with self._lock:
            out: Dict[int, Dict[str, int]] = {}
            for device in self._slices.get(node_name, {}).values():
                board = out.setdefault(device.board_index, {})
                board[device.profile] = board.get(device.profile, 0) + 1
            return out


class SimTpuDeviceClient:
    """TpuDeviceClient over a SimDevicePool."""

    def __init__(self, pool: SimDevicePool) -> None:
        self.pool = pool

    def get_slices(self, node_name: str) -> List[TpuSliceDevice]:
        return self.pool.get(node_name)

    def create_slices(self, node_name: str, board_index: int, profile: str, quantity: int) -> None:
        self.pool.create(node_name, board_index, profile, quantity)

    def delete_slice(self, node_name: str, device_id: str) -> None:
        self.pool.delete(node_name, device_id)


class SimPodResourcesClient:
    """Derives used device ids from the pods bound to the node, assigning
    free devices of the requested profile deterministically (smallest id
    first) — the sim stand-in for kubelet's allocation records. Works over
    any slice source: a callable node → devices."""

    def __init__(self, store: KubeStore, slices_fn) -> None:
        self.store = store
        self._slices_fn = slices_fn

    def get_used_device_ids(self, node_name: str) -> List[str]:
        from nos_tpu.api.v1alpha1 import labels

        accelerator = ""
        node = self.store.try_get("Node", node_name)
        if node is not None:
            accelerator = node.metadata.labels.get(labels.GKE_TPU_ACCELERATOR_LABEL, "")
        demand: Dict[str, int] = {}
        for pod in self.store.list("Pod"):
            if pod.spec.node_name != node_name:
                continue
            if pod.status.phase not in (PodPhase.PENDING, PodPhase.RUNNING):
                continue
            request = res.compute_pod_request(pod)
            if accelerator:
                # Plain-chip pods hold carved slices (same normalization the
                # scheduler applies when binding them).
                request = res.normalize_tpu_request(request, accelerator)
            for name, qty in request.items():
                if constants.is_tpu_slice_resource(name):
                    profile = constants.tpu_slice_topology(name)
                    demand[profile] = demand.get(profile, 0) + int(qty)
        used: List[str] = []
        devices = sorted(self._slices_fn(node_name), key=lambda d: d.device_id)
        for device in devices:
            if demand.get(device.profile, 0) > 0:
                demand[device.profile] -= 1
                used.append(device.device_id)
        return used


class DevicePluginAdvertiser:
    """Re-advertises carved slices on the Node object — what a device-plugin
    restart accomplishes in the reference (pkg/gpu/client.go:51-135). The
    slice source is any callable node → {board: {profile: count}}, so the
    same advertiser serves the sim pool and the native tpuctl backend."""

    def __init__(self, store: KubeStore, geometry_fn) -> None:
        self.store = store
        self.geometry_fn = geometry_fn

    def restart(self, node_name: str) -> None:
        geometry = self.geometry_fn(node_name)
        try:
            self.store.get("Node", node_name)  # existence probe only
        except NotFoundError:
            return

        slice_resources: Dict[str, int] = {}
        chips_exposed = 0
        for board in geometry.values():
            for profile, qty in board.items():
                name = constants.tpu_slice_resource(profile)
                slice_resources[name] = slice_resources.get(name, 0) + qty
                chips_exposed += Topology(profile).chips * qty

        def mutate(n):
            from nos_tpu.api.v1alpha1 import labels

            # Capacity stays the physical chip inventory (TpuNode derives its
            # board layout from it); only allocatable carries the advertised
            # scheduling view, where chips folded into slices are no longer
            # directly requestable.
            target = n.status.allocatable
            total_chips = int(n.status.capacity.get(constants.RESOURCE_TPU, 0))
            for key in [k for k in target if constants.is_tpu_slice_resource(k)]:
                del target[key]
            target.update(slice_resources)
            if labels.partitioning_kind(n) == labels.PartitioningKind.HYBRID:
                # Hybrid: every chip is denominated as a slice or a shared
                # fraction (plain requests are normalized by the scheduler);
                # neither advertiser may re-expose the other pool's chips.
                target[constants.RESOURCE_TPU] = 0
            else:
                target[constants.RESOURCE_TPU] = max(0, total_chips - chips_exposed)

        self.store.patch_merge("Node", node_name, "", mutate)


class SimDevicePlugin(DevicePluginAdvertiser):
    def __init__(self, store: KubeStore, pool: SimDevicePool) -> None:
        super().__init__(store, pool.geometry)
        self.pool = pool
