"""Device-access layer: the native boundary of the suite.

Reference parity (SURVEY.md §2.5, §2.8): nos touches hardware through
exactly one native component — the CGO NVML client (pkg/gpu/nvml/client.go)
— composed with a kubelet pod-resources gRPC client (pkg/resource/) into
mig.Client. Here the same seam is `TpuDeviceClient` (slice enumeration and
carve/destroy: backed by the C++ `tpuctl` library on real hosts, by
SimDevicePool in tests and kind-style dry runs) composed with
`PodResourcesClient` (which devices pods actually hold) into `TpuClient`.
"""

from nos_tpu.device.types import DeviceStatus, TpuSliceDevice
from nos_tpu.device.client import TpuClient
from nos_tpu.device.sim import (
    DevicePluginAdvertiser,
    SimDevicePlugin,
    SimDevicePool,
    SimPodResourcesClient,
    SimTpuDeviceClient,
)

__all__ = [
    "DevicePluginAdvertiser",
    "DeviceStatus",
    "SimDevicePlugin",
    "SimDevicePool",
    "SimPodResourcesClient",
    "SimTpuDeviceClient",
    "TpuClient",
    "TpuSliceDevice",
]
