"""Sharing-mode device plugin simulation + shared-slice client.

Plays the part the nebuly-fork NVIDIA device plugin plays for MPS in the
reference: it watches the config-selection label the SharingPartitioner
flips (``google.com/tpu-device-plugin.config``), loads the referenced
entry from the plugin ConfigMap, and re-advertises the node's allocatable
as ``google.com/tpu-mem-<N>gb`` replica resources. SharedSliceClient is
the sharingagent's read path (the slicing.Client analogue,
pkg/gpu/slicing/client.go): it derives per-chip free/used shared slices
from the active plugin config plus the pods bound to the node.
"""
from __future__ import annotations

import json
import logging
from typing import Dict, List, Optional

from nos_tpu.api.v1alpha1 import constants
from nos_tpu.api.v1alpha1.labels import TPU_DEVICE_PLUGIN_CONFIG_LABEL
from nos_tpu.device.types import DeviceStatus, TpuSliceDevice
from nos_tpu.kube.controller import Request, Result
from nos_tpu.kube.objects import PodPhase
from nos_tpu.kube.store import KubeStore, NotFoundError
from nos_tpu.util import resources as res

log = logging.getLogger("nos_tpu.device.sharing")


def load_plugin_config(
    store: KubeStore,
    node_name: str,
    config_map_name: str,
    config_map_namespace: str = "",
) -> Optional[dict]:
    """The active sharing config for a node: label → ConfigMap key → JSON.
    None when the node has no config label, the key is gone (superseded
    plan), or the payload does not parse."""
    node = store.try_get("Node", node_name)
    if node is None:
        return None
    key = node.metadata.labels.get(TPU_DEVICE_PLUGIN_CONFIG_LABEL, "")
    if not key:
        return None
    cm = store.try_get("ConfigMap", config_map_name, config_map_namespace)
    if cm is None or key not in cm.data:
        return None
    try:
        return json.loads(cm.data[key])
    except json.JSONDecodeError:
        log.warning("plugin config %s for node %s is not valid JSON", key, node_name)
        return None


def _config_entries(config: Optional[dict]) -> List[dict]:
    if not config:
        return []
    return list(config.get("sharing", {}).get("resources", []))


class SimSharedDevicePlugin:
    """Reconciles a node's allocatable against its active sharing config —
    what the real TPU device plugin does when its config label flips."""

    def __init__(
        self,
        store: KubeStore,
        config_map_name: str = "nos-device-plugin-config",
        config_map_namespace: str = "",
    ) -> None:
        self.store = store
        self.config_map_name = config_map_name
        self.config_map_namespace = config_map_namespace

    def reconcile(self, req: Request) -> Optional[Result]:
        node = self.store.try_get("Node", req.name)
        if node is None:
            return None
        config = load_plugin_config(
            self.store, req.name, self.config_map_name, self.config_map_namespace
        )
        if config is None:
            # No loadable config (label missing, or it points at a key that
            # is gone mid-rollover): keep serving the last advertised state,
            # exactly like a real device plugin that cannot reload. Wiping
            # here would momentarily re-expose carved chips as plain.
            return None
        entries = _config_entries(config)

        shared: Dict[str, int] = {}
        covered_chips = set()
        for entry in entries:
            rename = entry.get("rename", "")
            if not constants.is_tpu_shared_resource(rename):
                continue
            shared[rename] = shared.get(rename, 0) + int(entry.get("replicas", 0))
            covered_chips.update(entry.get("chips", []))

        def mutate(n):
            from nos_tpu.api.v1alpha1 import labels

            target = n.status.allocatable
            total_chips = int(n.status.capacity.get(constants.RESOURCE_TPU, 0))
            for key in [k for k in target if constants.is_tpu_shared_resource(k)]:
                del target[key]
            target.update(shared)
            if labels.partitioning_kind(n) == labels.PartitioningKind.HYBRID:
                # Hybrid: slice boards own the non-shared chips; never
                # re-expose them as plain (see DevicePluginAdvertiser).
                target[constants.RESOURCE_TPU] = 0
            else:
                # Chips folded into shared fractions stop being plain-requestable.
                target[constants.RESOURCE_TPU] = max(0, total_chips - len(covered_chips))

        try:
            self.store.patch_merge("Node", req.name, "", mutate)
        except NotFoundError:
            return None
        return None


class SharedSliceClient:
    """Per-chip shared-slice inventory for the sharingagent reporter.

    Used counts come from the pods bound to the node (the sim stand-in for
    kubelet pod-resources allocation records), assigned to config entries
    deterministically (chip order)."""

    def __init__(
        self,
        store: KubeStore,
        config_map_name: str = "nos-device-plugin-config",
        config_map_namespace: str = "",
    ) -> None:
        self.store = store
        self.config_map_name = config_map_name
        self.config_map_namespace = config_map_namespace

    def get_devices(self, node_name: str) -> List[TpuSliceDevice]:
        config = load_plugin_config(
            self.store, node_name, self.config_map_name, self.config_map_namespace
        )
        entries = _config_entries(config)
        if not entries:
            return []

        demand: Dict[str, int] = {}
        for pod in self.store.list("Pod"):
            if pod.spec.node_name != node_name:
                continue
            if pod.status.phase not in (PodPhase.PENDING, PodPhase.RUNNING):
                continue
            for name, qty in res.compute_pod_request(pod).items():
                if constants.is_tpu_shared_resource(name):
                    demand[name] = demand.get(name, 0) + int(qty)

        devices: List[TpuSliceDevice] = []
        ordered = sorted(
            entries, key=lambda e: (min(e.get("chips", [0]) or [0]), e.get("rename", ""))
        )
        serial = 0
        for entry in ordered:
            rename = entry.get("rename", "")
            if not constants.is_tpu_shared_resource(rename):
                continue
            profile = constants.tpu_shared_profile(rename)
            chips = entry.get("chips", [0]) or [0]
            for _ in range(int(entry.get("replicas", 0))):
                serial += 1
                status = DeviceStatus.FREE
                if demand.get(rename, 0) > 0:
                    demand[rename] -= 1
                    status = DeviceStatus.USED
                devices.append(
                    TpuSliceDevice(
                        device_id=f"tpushare-{node_name}-{chips[0]}-{profile}-{serial}",
                        board_index=int(chips[0]),
                        profile=profile,
                        status=status,
                    )
                )
        return devices
