"""ctypes binding for the native tpuctl library.

`TpuctlDeviceClient` implements the TpuDeviceClient protocol (the
nvml.Client-shaped seam, reference pkg/gpu/nvml/interface.go:23-36) on top
of libtpuctl.so: per-node state files under a base directory, with the C++
side owning locking, atomic persistence, and concrete ICI-contiguous chip
placement. The library is built on demand from native/ (no pip deps).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional

from nos_tpu.device.types import TpuSliceDevice

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libtpuctl.so")
_build_lock = threading.Lock()


class TpuctlError(RuntimeError):
    pass


class TpuctlUnavailableError(TpuctlError):
    """Library missing and not buildable (no toolchain)."""


def build_library(force: bool = False) -> str:
    """Build libtpuctl.so via make; returns its path. make is always
    invoked (its mtime check makes it a no-op when current), so editing
    tpuctl.cpp never leaves a stale library silently loaded."""
    with _build_lock:
        try:
            subprocess.run(
                ["make", "-C", _NATIVE_DIR] + (["-B"] if force else []),
                check=True,
                capture_output=True,
                text=True,
            )
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            if os.path.exists(_LIB_PATH) and not force:
                return _LIB_PATH  # prebuilt library, no toolchain: best effort
            detail = getattr(e, "stderr", "") or str(e)
            raise TpuctlUnavailableError(f"cannot build libtpuctl.so: {detail}")
        return _LIB_PATH


def load_library() -> ctypes.CDLL:
    lib = ctypes.CDLL(build_library())
    lib.tpuctl_enumerate.restype = ctypes.c_int
    lib.tpuctl_enumerate.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
    lib.tpuctl_list_slices.restype = ctypes.c_int
    lib.tpuctl_list_slices.argtypes = [
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_int,
    ]
    lib.tpuctl_create_slices.restype = ctypes.c_int
    lib.tpuctl_create_slices.argtypes = [
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_int,
    ]
    lib.tpuctl_create_slices_batch.restype = ctypes.c_int
    lib.tpuctl_create_slices_batch.argtypes = [
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_int,
    ]
    lib.tpuctl_delete_slice.restype = ctypes.c_int
    lib.tpuctl_delete_slice.argtypes = [
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_int,
    ]
    lib.tpuctl_delete_all_except.restype = ctypes.c_int
    lib.tpuctl_delete_all_except.argtypes = [
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_int,
    ]
    return lib


_ERR_CAP = 1024
_OUT_CAP = 1 << 20


class TpuctlDeviceClient:
    """TpuDeviceClient over libtpuctl.so.

    `board_topologies` maps node name → board topologies (index = board),
    mirroring what the agent derives from GKE labels; state files live at
    ``<base_dir>/<node>.slices``.
    """

    def __init__(
        self,
        base_dir: str,
        board_topologies: Dict[str, List[str]],
        lib: Optional[ctypes.CDLL] = None,
    ) -> None:
        self.base_dir = base_dir
        self.board_topologies = board_topologies
        os.makedirs(base_dir, exist_ok=True)
        self.lib = lib if lib is not None else load_library()

    # ------------------------------------------------------------ paths

    def _state_path(self, node_name: str) -> bytes:
        return os.path.join(self.base_dir, f"{node_name}.slices").encode()

    # ------------------------------------------------------- operations

    def _list_lines(self, node_name: str) -> List[List[str]]:
        """Parsed '<id> <board> <profile> <chips>' records from the lib."""
        out = ctypes.create_string_buffer(_OUT_CAP)
        err = ctypes.create_string_buffer(_ERR_CAP)
        rc = self.lib.tpuctl_list_slices(
            self._state_path(node_name), out, _OUT_CAP, err, _ERR_CAP
        )
        if rc < 0:
            raise TpuctlError(err.value.decode())
        return [
            parts
            for line in out.value.decode().splitlines()
            if len(parts := line.split()) == 4
        ]

    def get_slices(self, node_name: str) -> List[TpuSliceDevice]:
        return [
            TpuSliceDevice(device_id=p[0], board_index=int(p[1]), profile=p[2])
            for p in self._list_lines(node_name)
        ]

    def create_slices(
        self, node_name: str, board_index: int, profile: str, quantity: int
    ) -> None:
        boards = self.board_topologies.get(node_name, [])
        if not 0 <= board_index < len(boards):
            raise TpuctlError(f"{node_name}: unknown board {board_index}")
        err = ctypes.create_string_buffer(_ERR_CAP)
        rc = self.lib.tpuctl_create_slices(
            self._state_path(node_name),
            boards[board_index].encode(),
            board_index,
            profile.encode(),
            quantity,
            err,
            _ERR_CAP,
        )
        if rc < 0:
            raise TpuctlError(err.value.decode())

    def create_slices_batch(
        self, node_name: str, board_index: int, profiles: Dict[str, int]
    ) -> None:
        """Atomically place a whole set of slices on one board: the C++
        backtracking search is order-independent, unlike sequential
        first-fit creates."""
        boards = self.board_topologies.get(node_name, [])
        if not 0 <= board_index < len(boards):
            raise TpuctlError(f"{node_name}: unknown board {board_index}")
        spec = ",".join(f"{p}:{q}" for p, q in sorted(profiles.items()) if q > 0)
        if not spec:
            return
        err = ctypes.create_string_buffer(_ERR_CAP)
        rc = self.lib.tpuctl_create_slices_batch(
            self._state_path(node_name),
            boards[board_index].encode(),
            board_index,
            spec.encode(),
            err,
            _ERR_CAP,
        )
        if rc < 0:
            raise TpuctlError(err.value.decode())

    def delete_slice(self, node_name: str, device_id: str) -> None:
        err = ctypes.create_string_buffer(_ERR_CAP)
        rc = self.lib.tpuctl_delete_slice(
            self._state_path(node_name), device_id.encode(), err, _ERR_CAP
        )
        if rc < 0:
            raise TpuctlError(err.value.decode())

    def delete_all_except(self, node_name: str, keep_ids: List[str]) -> None:
        """Startup cleanup of orphaned slices (reference
        cmd/migagent/migagent.go:190-199)."""
        err = ctypes.create_string_buffer(_ERR_CAP)
        rc = self.lib.tpuctl_delete_all_except(
            self._state_path(node_name), ",".join(keep_ids).encode(), err, _ERR_CAP
        )
        if rc < 0:
            raise TpuctlError(err.value.decode())

    def geometry(self, node_name: str) -> Dict[int, Dict[str, int]]:
        """{board: {profile: count}} for the device-plugin advertiser."""
        out: Dict[int, Dict[str, int]] = {}
        for device in self.get_slices(node_name):
            board = out.setdefault(device.board_index, {})
            board[device.profile] = board.get(device.profile, 0) + 1
        return out

    def chip_assignment(self, node_name: str) -> Dict[str, List[int]]:
        """Device id → concrete chip indices (for the device plugin)."""
        return {
            p[0]: [int(c) for c in p[3].split(",") if c]
            for p in self._list_lines(node_name)
        }

    def enumerate_host(self, dev_root: str = "/dev") -> Dict[str, object]:
        out = ctypes.create_string_buffer(_OUT_CAP)
        rc = self.lib.tpuctl_enumerate(dev_root.encode(), out, _OUT_CAP)
        if rc < 0:
            raise TpuctlError("enumerate failed")
        lines = out.value.decode().splitlines()
        count = int(lines[0]) if lines else 0
        env = {}
        names = []
        for line in lines[1:]:
            if line.startswith("env ") and "=" in line:
                key, value = line[4:].split("=", 1)
                env[key] = value
            elif line:
                names.append(line)
        return {"device_count": count, "devices": names, "env": env}
