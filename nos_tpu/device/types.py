"""Device-layer types (reference pkg/resource/device.go:26-68 +
pkg/gpu/device.go Device/DeviceList)."""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable


class DeviceStatus:
    FREE = "free"
    USED = "used"


@dataclass(frozen=True)
class TpuSliceDevice:
    """One carved TPU slice as exposed by the device plugin."""

    device_id: str
    board_index: int
    profile: str  # topology string, e.g. "2x2"
    status: str = DeviceStatus.FREE


def group_geometries(
    devices: Iterable[TpuSliceDevice],
) -> Dict[str, Dict[int, Dict[str, int]]]:
    """Devices → {status: {board: {profile: count}}} for annotation building
    (reference pkg/gpu/device.go:98-120 AsStatusAnnotation)."""
    out: Dict[str, Dict[int, Dict[str, int]]] = {
        DeviceStatus.FREE: {},
        DeviceStatus.USED: {},
    }
    for d in devices:
        board = out[d.status].setdefault(d.board_index, {})
        board[d.profile] = board.get(d.profile, 0) + 1
    return out
