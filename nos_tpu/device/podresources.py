"""Kubelet pod-resources gRPC client.

The reference's device layer learns which devices are in use from the
kubelet itself, not from the apiserver: pkg/resource/client.go:40-87 dials
the pod-resources unix socket with a connection timeout, and
pkg/resource/lister.go:30-38 maps the List() response to used device ids
for a resource-name prefix. This module is that client for TPU slices,
implementing the same ``get_used_device_ids(node)`` protocol as
``SimPodResourcesClient`` (nos_tpu/device/sim.py), so the tpuagent composes
either (config: ``podResourcesSocket``).

Messages are generated from nos_tpu/device/proto/podresources.proto (the
public kubelet v1 API subset); the method stub is wired directly on the
channel — no grpc codegen plugin needed.
"""
from __future__ import annotations

import logging
from typing import Callable, List, Optional

from nos_tpu.device.proto import podresources_pb2 as pb

log = logging.getLogger("nos_tpu.device.podresources")

DEFAULT_SOCKET = "/var/lib/kubelet/pod-resources/kubelet.sock"
LIST_METHOD = "/v1.PodResourcesLister/List"


def _tracks_tpu(resource_name: str) -> bool:
    """TPU device-plugin resources: plain chips and carved slices."""
    from nos_tpu.api.v1alpha1 import constants

    return resource_name == constants.RESOURCE_TPU or constants.is_tpu_slice_resource(
        resource_name
    )


class KubeletPodResourcesClient:
    """gRPC client over the kubelet's node-local pod-resources socket."""

    def __init__(
        self,
        socket_path: str = DEFAULT_SOCKET,
        timeout_seconds: float = 10.0,
        tracks: Optional[Callable[[str], bool]] = None,
        target: Optional[str] = None,
    ) -> None:
        import grpc

        self.timeout = timeout_seconds
        self.tracks = tracks or _tracks_tpu
        self._channel = grpc.insecure_channel(target or f"unix://{socket_path}")
        self._list = self._channel.unary_unary(
            LIST_METHOD,
            request_serializer=pb.ListPodResourcesRequest.SerializeToString,
            response_deserializer=pb.ListPodResourcesResponse.FromString,
        )

    def close(self) -> None:
        self._channel.close()

    def list_pod_resources(self) -> "pb.ListPodResourcesResponse":
        return self._list(pb.ListPodResourcesRequest(), timeout=self.timeout)

    def get_used_device_ids(self, node_name: str = "") -> List[str]:
        """Device ids of tracked TPU resources allocated to pods on THIS
        node (the kubelet is node-local; ``node_name`` exists only for
        protocol compatibility with the sim client)."""
        response = self.list_pod_resources()
        used: set = set()
        for pod in response.pod_resources:
            for container in pod.containers:
                for device in container.devices:
                    if self.tracks(device.resource_name):
                        used.update(device.device_ids)
        return sorted(used)
