"""TpuClient: composed device view (reference pkg/gpu/mig/client.go:42-95,
which composes nvml.Client + resource.Client)."""
from __future__ import annotations

from typing import List, Protocol

from nos_tpu.device.types import DeviceStatus, TpuSliceDevice


class TpuDeviceClient(Protocol):
    """Carve-level access — the nvml.Client analogue
    (pkg/gpu/nvml/interface.go:23-36). Implementations: the C++ tpuctl
    binding on real hosts, SimTpuDeviceClient elsewhere."""

    def get_slices(self, node_name: str) -> List[TpuSliceDevice]: ...

    def create_slices(self, node_name: str, board_index: int, profile: str, quantity: int) -> None: ...

    def delete_slice(self, node_name: str, device_id: str) -> None: ...


class PodResourcesClient(Protocol):
    """Which device ids pods actually hold — the kubelet pod-resources
    analogue (pkg/resource/client.go:27-30)."""

    def get_used_device_ids(self, node_name: str) -> List[str]: ...


class TpuClient:
    def __init__(self, device_client: TpuDeviceClient, pod_resources: PodResourcesClient) -> None:
        self.device_client = device_client
        self.pod_resources = pod_resources

    def get_devices(self, node_name: str) -> List[TpuSliceDevice]:
        """Carved slices with free/used status resolved."""
        used_ids = set(self.pod_resources.get_used_device_ids(node_name))
        out: List[TpuSliceDevice] = []
        for device in self.device_client.get_slices(node_name):
            status = DeviceStatus.USED if device.device_id in used_ids else DeviceStatus.FREE
            out.append(
                TpuSliceDevice(
                    device_id=device.device_id,
                    board_index=device.board_index,
                    profile=device.profile,
                    status=status,
                )
            )
        return out

    def create_slices(self, node_name: str, board_index: int, profile: str, quantity: int) -> None:
        self.device_client.create_slices(node_name, board_index, profile, quantity)

    def create_slices_batch(self, node_name: str, board_index: int, profiles) -> None:
        """One board's creates as a unit. Placement-aware backends (tpuctl)
        solve the whole batch at once — sequential creates are
        order-dependent on a chip grid; placement-free backends just loop."""
        batch = getattr(self.device_client, "create_slices_batch", None)
        if batch is not None:
            batch(node_name, board_index, profiles)
            return
        for profile, quantity in sorted(profiles.items()):
            if quantity > 0:
                self.device_client.create_slices(node_name, board_index, profile, quantity)

    def delete_slice(self, node_name: str, device_id: str) -> None:
        self.device_client.delete_slice(node_name, device_id)
