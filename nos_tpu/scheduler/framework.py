"""Minimal scheduler framework: the extension-point contract k8s plugins use.

Shapes mirror k8s.io/kubernetes scheduler framework as used by the reference
(pkg/scheduler/plugins/capacityscheduling/capacity_scheduling.go:92-96
implements PreFilter, PreFilterExtensions, PostFilter, Reserve, Unreserve):
plugins register per extension point, a CycleState dict carries data across
points within one scheduling cycle, and Status codes signal
Success/Unschedulable/Error.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Protocol, Sequence

from nos_tpu.kube.objects import Node, Pod, ResourceList, Taint
from nos_tpu.util import resources as res
from nos_tpu.util.tracing import TRACER


class StatusCode:
    SUCCESS = "Success"
    UNSCHEDULABLE = "Unschedulable"
    WAIT = "Wait"  # Permit: hold the pod (gang scheduling)
    ERROR = "Error"


@dataclass
class Status:
    code: str = StatusCode.SUCCESS
    message: str = ""
    plugin: str = ""

    @property
    def success(self) -> bool:
        return self.code == StatusCode.SUCCESS

    @staticmethod
    def ok() -> "Status":
        return Status()

    @staticmethod
    def unschedulable(message: str, plugin: str = "") -> "Status":
        return Status(StatusCode.UNSCHEDULABLE, message, plugin)

    @staticmethod
    def wait(message: str, plugin: str = "") -> "Status":
        return Status(StatusCode.WAIT, message, plugin)

    @staticmethod
    def error(message: str, plugin: str = "") -> "Status":
        return Status(StatusCode.ERROR, message, plugin)


class CycleState(dict):
    """Per-scheduling-cycle scratch space shared between extension points."""


@dataclass
class Diagnosis:
    """Per-node, per-plugin rejection ledger for one failed scheduling
    cycle — the kube-scheduler Diagnosis analogue. One struct drives every
    operator surface: the PodScheduled=False condition message, the
    FailedScheduling Event, the unschedulable metric, and
    ``/debug/explain`` (which adds the linked trace id)."""

    pod: str = ""  # namespaced name
    num_nodes: int = 0  # nodes the cycle considered
    node_statuses: Dict[str, Status] = field(default_factory=dict)
    trace_id: str = ""
    timestamp: float = 0.0

    def grouped(self) -> List[tuple]:
        """(count, plugin, message) per distinct rejection, most-frequent
        first (ties broken lexically for a deterministic message)."""
        counts: Dict[tuple, int] = {}
        for status in self.node_statuses.values():
            key = (status.plugin, status.message)
            counts[key] = counts.get(key, 0) + 1
        return sorted(
            ((n, plugin, msg) for (plugin, msg), n in counts.items()),
            key=lambda t: (-t[0], t[1], t[2]),
        )

    def aggregate_message(self) -> str:
        """Canonical ``0/N nodes are available: X <reason>, Y <reason>.``"""
        groups = self.grouped()
        if not groups:
            return f"0/{self.num_nodes} nodes are available: no nodes."
        parts = ", ".join(f"{n} {msg}" for n, _, msg in groups)
        return f"0/{self.num_nodes} nodes are available: {parts}."

    def to_dict(self) -> Dict:
        return {
            "pod": self.pod,
            "message": self.aggregate_message(),
            "nodes": {
                name: {"plugin": s.plugin, "message": s.message}
                for name, s in sorted(self.node_statuses.items())
            },
            "traceId": self.trace_id,
            "timestamp": self.timestamp,
        }


@dataclass
class NodeInfo:
    """A node plus everything scheduled onto it — the framework's unit of
    placement state (mirrors framework.NodeInfo cached by the reference's
    ClusterState, internal/partitioning/state/state.go:29-222)."""

    node: Node
    pods: List[Pod] = field(default_factory=list)

    @property
    def name(self) -> str:
        return self.node.metadata.name

    def requested(self) -> ResourceList:
        from nos_tpu.api.v1alpha1 import labels

        node_labels = self.node.metadata.labels
        accelerator = ""
        if node_labels.get(labels.PARTITIONING_LABEL) in (
            labels.PartitioningKind.TPU,
            labels.PartitioningKind.HYBRID,
        ):
            accelerator = node_labels.get(labels.GKE_TPU_ACCELERATOR_LABEL, "")
        total: ResourceList = {}
        for pod in self.pods:
            request = res.compute_pod_request(pod)
            if accelerator:
                # Bound plain-chip pods occupy carved slices: account them in
                # the same denomination the node advertises, or they would
                # not deplete slice allocatable (double-booking).
                request = res.normalize_tpu_request(request, accelerator)
            total = res.sum_resources(total, request)
        return total

    def available(self) -> ResourceList:
        return res.subtract_resources(self.node.status.allocatable, self.requested())

    def add_pod(self, pod: Pod) -> None:
        self.pods.append(pod)

    def remove_pod(self, pod: Pod) -> None:
        self.pods = [
            p
            for p in self.pods
            if not (
                p.metadata.namespace == pod.metadata.namespace
                and p.metadata.name == pod.metadata.name
            )
        ]


# ---------------------------------------------------------------- plugins


class PreFilterPlugin(Protocol):
    name: str

    def pre_filter(self, state: CycleState, pod: Pod) -> Status: ...


# Verdict-cache opt-in contract (nos_tpu/partitioning/core/verdict_cache.py):
# a PreFilter/Filter plugin may set a class attribute
# ``verdict_cacheable = True`` to promise its SIMULATION verdict is a pure
# function of (a) the pod fields covered by ``verdict_cache.pod_signature``
# and (b) the candidate node's own state — no external stores, no
# cross-plugin CycleState reads, and any cross-NODE reads fully covered by
# the planner's affinity/topology bypass. Plugins without the attribute
# (default) always run fresh on every trial.
def is_verdict_cacheable(plugin) -> bool:
    return bool(getattr(plugin, "verdict_cacheable", False))


class FilterPlugin(Protocol):
    name: str

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status: ...


class PostFilterPlugin(Protocol):
    name: str

    def post_filter(
        self, state: CycleState, pod: Pod, filtered_nodes: Dict[str, Status]
    ) -> Optional[str]:
        """Attempt to make the pod schedulable (preemption); returns a
        nominated node name or None."""
        ...


class ReservePlugin(Protocol):
    name: str

    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status: ...

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None: ...


class ScorePlugin(Protocol):
    name: str

    def score(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> int:
        """0-100; higher is better."""
        ...


class PermitPlugin(Protocol):
    name: str

    def permit(self, state: CycleState, pod: Pod, node_name: str) -> Status: ...


class Framework:
    """Plugin registry + per-extension-point runners."""

    def __init__(
        self,
        pre_filter_plugins: Sequence[PreFilterPlugin] = (),
        filter_plugins: Sequence[FilterPlugin] = (),
        post_filter_plugins: Sequence[PostFilterPlugin] = (),
        reserve_plugins: Sequence[ReservePlugin] = (),
        permit_plugins: Sequence[PermitPlugin] = (),
        score_plugins: Sequence[ScorePlugin] = (),
    ) -> None:
        self.pre_filter_plugins = list(pre_filter_plugins)
        self.filter_plugins = list(filter_plugins)
        self.post_filter_plugins = list(post_filter_plugins)
        self.reserve_plugins = list(reserve_plugins)
        self.permit_plugins = list(permit_plugins)
        self.score_plugins = list(score_plugins)

    # Every runner wraps each plugin call in TRACER.plugin_span: a no-op
    # unless a scheduling-cycle span is active (bare framework calls and
    # the planner's suppressed simulation add zero spans), otherwise one
    # child span per plugin so a trace shows where the cycle's time went.

    def run_pre_filter_plugins(
        self,
        state: CycleState,
        pod: Pod,
        plugins: Optional[Sequence[PreFilterPlugin]] = None,
    ) -> Status:
        # `plugins` narrows the run to a subset (planner's verdict cache
        # splits the chain into cacheable/uncacheable halves); None runs
        # the full registered chain.
        for p in self.pre_filter_plugins if plugins is None else plugins:
            with TRACER.plugin_span(f"plugin.{p.name}", point="pre_filter") as sp:
                status = p.pre_filter(state, pod)
                if not status.success:
                    status.plugin = status.plugin or p.name
                    sp.set_attributes(rejected=True, message=status.message)
                    return status
        return Status.ok()

    def run_filter_plugins(
        self,
        state: CycleState,
        pod: Pod,
        node_info: NodeInfo,
        plugins: Optional[Sequence[FilterPlugin]] = None,
    ) -> Status:
        for p in self.filter_plugins if plugins is None else plugins:
            with TRACER.plugin_span(
                f"plugin.{p.name}", point="filter", node=node_info.name
            ) as sp:
                status = p.filter(state, pod, node_info)
                if not status.success:
                    status.plugin = status.plugin or p.name
                    sp.set_attributes(rejected=True, message=status.message)
                    return status
        return Status.ok()

    def run_post_filter_plugins(
        self, state: CycleState, pod: Pod, filtered_nodes: Dict[str, Status]
    ) -> Optional[str]:
        for p in self.post_filter_plugins:
            with TRACER.plugin_span(f"plugin.{p.name}", point="post_filter") as sp:
                nominated = p.post_filter(state, pod, filtered_nodes)
                if nominated:
                    sp.set_attributes(nominated=nominated)
                    return nominated
        return None

    def run_reserve_plugins(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        for i, p in enumerate(self.reserve_plugins):
            with TRACER.plugin_span(f"plugin.{p.name}", point="reserve") as sp:
                status = p.reserve(state, pod, node_name)
                if not status.success:
                    for done in self.reserve_plugins[:i]:
                        done.unreserve(state, pod, node_name)
                    status.plugin = status.plugin or p.name
                    sp.set_attributes(rejected=True, message=status.message)
                    return status
        return Status.ok()

    def run_unreserve_plugins(self, state: CycleState, pod: Pod, node_name: str) -> None:
        for p in self.reserve_plugins:
            p.unreserve(state, pod, node_name)

    def run_score_plugins(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> int:
        total = 0
        for p in self.score_plugins:
            with TRACER.plugin_span(
                f"plugin.{p.name}", point="score", node=node_info.name
            ):
                total += p.score(state, pod, node_info)
        return total

    def run_permit_plugins(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        for p in self.permit_plugins:
            with TRACER.plugin_span(f"plugin.{p.name}", point="permit") as sp:
                status = p.permit(state, pod, node_name)
                if not status.success:
                    status.plugin = status.plugin or p.name
                    sp.set_attributes(code=status.code, message=status.message)
                    return status
        return Status.ok()


class NodeResourcesFit:
    """Stock resource-fit filter (the part of the vanilla scheduler the
    simulation relies on: SURVEY.md §3.2 'NodeResourcesFit sees the
    partitioned scalar resources').

    On TPU-partitioned nodes a plain ``google.com/tpu: N`` request is
    normalized to the node generation's slice profile first: sub-host chip
    requests are only satisfiable through carved slices (GKE exposes whole
    hosts; slicing is this suite's job), so a virgin node's raw chip
    allocatable must not admit partial-chip pods behind the planner's back.
    """

    name = "NodeResourcesFit"
    # Pure in (signed pod requests, node allocatable + placed pods — both
    # pinned by the node's mutation version).
    verdict_cacheable = True

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        from nos_tpu.api.v1alpha1 import constants, labels

        request = res.compute_pod_request(pod)
        node_labels = node_info.node.metadata.labels
        if node_labels.get(labels.PARTITIONING_LABEL) in (
            labels.PartitioningKind.TPU,
            labels.PartitioningKind.HYBRID,
        ):
            accelerator = node_labels.get(labels.GKE_TPU_ACCELERATOR_LABEL, "")
            if accelerator:
                request = res.normalize_tpu_request(request, accelerator)
                if request.get(constants.RESOURCE_TPU, 0) > 0:
                    return Status.unschedulable(
                        "TPU request exceeds any single-host slice profile "
                        "(multi-host gang required)",
                        self.name,
                    )
        available = node_info.available()
        for resource, qty in request.items():
            if qty > available.get(resource, 0):
                return Status.unschedulable(
                    f"insufficient {resource}: requested {qty}, available "
                    f"{available.get(resource, 0)}",
                    self.name,
                )
        return Status.ok()


class NodeSelectorFit:
    """Node-selector / nodeName filter (enough of the vanilla predicates for
    simulation fidelity)."""

    name = "NodeSelector"
    verdict_cacheable = True  # signed nodeName/nodeSelector vs node labels

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        if pod.spec.node_name and pod.spec.node_name != node_info.name:
            return Status.unschedulable("pod bound to a different node", self.name)
        node_labels = node_info.node.metadata.labels
        for key, value in pod.spec.node_selector.items():
            if node_labels.get(key) != value:
                return Status.unschedulable(
                    f"node selector {key}={value} not satisfied", self.name
                )
        return Status.ok()


class NodeAffinityFit:
    """Required node-affinity filter: the node's labels must satisfy at
    least one nodeSelectorTerm (the in-tree NodeAffinity predicate the
    reference's embedded simulation inherits from the full plugin set,
    cmd/gpupartitioner/gpupartitioner.go:294-318)."""

    name = "NodeAffinity"
    verdict_cacheable = True  # signed required terms vs node labels

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        affinity = pod.spec.affinity
        if affinity is None or affinity.matches(node_info.node.metadata.labels):
            return Status.ok()
        return Status.unschedulable("required node affinity not satisfied", self.name)


class TaintTolerationFit:
    """NoSchedule/NoExecute taints must each be tolerated (in-tree
    TaintToleration predicate; PreferNoSchedule only affects scoring and is
    ignored here like the vanilla filter does)."""

    name = "TaintToleration"
    verdict_cacheable = True  # signed tolerations vs node taints

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        for taint in node_info.node.spec.taints:
            if taint.effect not in ("NoSchedule", "NoExecute"):
                continue
            if not any(t.tolerates(taint) for t in pod.spec.tolerations):
                return Status.unschedulable(
                    f"untolerated taint {taint.key}={taint.value}:{taint.effect}",
                    self.name,
                )
        return Status.ok()


class NodeUnschedulableFit:
    """Cordoned nodes (`kubectl cordon` → spec.unschedulable) admit nothing
    without an explicit unschedulable toleration."""

    name = "NodeUnschedulable"
    verdict_cacheable = True  # node spec.unschedulable vs signed tolerations

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        if not node_info.node.spec.unschedulable:
            return Status.ok()
        cordon = Taint(key="node.kubernetes.io/unschedulable", effect="NoSchedule")
        if any(t.tolerates(cordon) for t in pod.spec.tolerations):
            return Status.ok()
        return Status.unschedulable("node is cordoned (unschedulable)", self.name)


# CycleState key under which the cycle driver (scheduler loop or planner
# simulation) publishes every NodeInfo of the cluster view the cycle runs
# against, for plugins that need cross-node context (topology spread).
TOPOLOGY_NODE_INFOS_KEY = "topology_node_infos"


class PodTopologySpreadFit:
    """DoNotSchedule topologySpreadConstraints (in-tree PodTopologySpread
    predicate). Skew for a domain = matching pods in that domain (pod
    included if its own labels match the selector) minus the minimum over
    all observed domains; placement is refused when any constraint's skew
    would exceed maxSkew.

    Needs the whole cluster view, which a per-node filter doesn't get, so
    the cycle driver publishes it in CycleState under
    ``TOPOLOGY_NODE_INFOS_KEY`` (the in-tree plugin does the same thing via
    its PreFilter snapshot). Per-domain counts are computed once per cycle
    and cached in CycleState; each filter call then only recounts the
    candidate NodeInfo it was handed, which also honors trial views that
    differ from the published cluster (preemption simulates victim
    eviction by passing a NodeInfo with victims removed — its counts must
    win over the published, pre-eviction one). Domains are approximated as
    "every published node carrying the topology key" — node-affinity
    eligibility narrowing is not modeled. ScheduleAnyway constraints are
    ignored (scoring-only upstream).
    """

    name = "PodTopologySpread"
    # Cacheable ONLY because the planner bypasses the verdict cache for any
    # pod carrying topologySpreadConstraints: on the cached path the plugin
    # is a constant ok() (no DoNotSchedule constraints to evaluate), so the
    # cross-node reads it performs otherwise never happen under a cache key.
    verdict_cacheable = True
    _CACHE_KEY = "pod_topology_spread_counts"

    @staticmethod
    def _matching(info: NodeInfo, constraint) -> int:
        return sum(1 for p in info.pods if constraint.selects(p.metadata.labels))

    def _cycle_counts(self, state: CycleState, constraints) -> List[Dict]:
        """Per constraint: {'domains': {domain: matching}, 'per_node':
        {node: (domain, matching)}} over the published cluster view."""
        cached = state.get(self._CACHE_KEY)
        if cached is not None:
            return cached
        all_infos: Sequence[NodeInfo] = state.get(TOPOLOGY_NODE_INFOS_KEY) or []
        computed = []
        for c in constraints:
            domains: Dict[str, int] = {}
            per_node: Dict[str, tuple] = {}
            for info in all_infos:
                domain = info.node.metadata.labels.get(c.topology_key)
                if domain is None:
                    continue
                n = self._matching(info, c)
                domains[domain] = domains.get(domain, 0) + n
                per_node[info.name] = (domain, n)
            computed.append({"domains": domains, "per_node": per_node})
        state[self._CACHE_KEY] = computed
        return computed

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        constraints = [
            c
            for c in pod.spec.topology_spread_constraints
            if c.when_unsatisfiable == "DoNotSchedule"
        ]
        if not constraints:
            return Status.ok()
        cycle = self._cycle_counts(state, constraints)
        node_labels = node_info.node.metadata.labels
        for c, cached in zip(constraints, cycle):
            if c.topology_key not in node_labels:
                return Status.unschedulable(
                    f"node has no {c.topology_key} label", self.name
                )
            counts = dict(cached["domains"])
            candidate = node_labels[c.topology_key]
            # Substitute the handed-in view of this node for the published
            # one: identical on the normal path, differs under preemption's
            # trial (victims removed) — the trial must be what's counted.
            pub_domain, pub_n = cached["per_node"].get(node_info.name, (candidate, 0))
            counts[pub_domain] = counts.get(pub_domain, 0) - pub_n
            counts.setdefault(candidate, 0)
            counts[candidate] += self._matching(node_info, c)
            if c.selects(pod.metadata.labels):
                counts[candidate] += 1
            skew = counts[candidate] - min(counts.values())
            if skew > c.max_skew:
                return Status.unschedulable(
                    f"placing on {c.topology_key}={candidate} would skew "
                    f"{skew} > maxSkew {c.max_skew}",
                    self.name,
                )
        return Status.ok()


class InterPodAffinityFit:
    """Required pod affinity / anti-affinity (in-tree InterPodAffinity
    predicate, matchLabels subset), over the published cluster view:

    - podAffinity term: the candidate node's topology domain must already
      hold a matching pod — with the upstream bootstrap carve-out that a
      term matching the INCOMING pod's own labels is satisfiable when no
      pod matches anywhere (the first replica of a self-affine group).
    - podAntiAffinity term: no matching pod may share the candidate's
      domain. Symmetry is enforced like upstream: an EXISTING pod's
      required anti-affinity also rejects the incoming pod from its
      domain. (Existing pods' positive affinity is not symmetric.)

    Per-cycle indexes are cached in CycleState — the symmetric
    anti-affinity entries AND per-term match locations for the incoming
    pod's own terms — so each node filter costs O(pods on the candidate
    node) for the trial-view rescan, never a cluster scan.
    """

    name = "InterPodAffinity"
    # Cacheable ONLY under the planner's bypass contract: lookups are
    # skipped while the pod has (anti-)affinity terms OR any placed pod has
    # required anti-affinity (the symmetric check). On the cached path both
    # halves are vacuous, so the verdict is the constant ok().
    verdict_cacheable = True
    _CACHE_KEY = "inter_pod_affinity_index"
    _TERM_CACHE_KEY = "inter_pod_affinity_term_index"

    def _index(self, state: CycleState):
        """Precomputed per-node list of anti-affinity entries
        [(term, owner_ns, domain)] for the symmetric check — it runs per
        filter call, so it must cost O(anti-affine pods), not a full
        cluster scan. Kept per-node so filter() can substitute the
        handed-in trial NodeInfo for its published entry — preemption
        simulates victim eviction through that substitution, exactly like
        PodTopologySpreadFit."""
        cached = state.get(self._CACHE_KEY)
        if cached is not None:
            return cached
        all_infos: Sequence[NodeInfo] = state.get(TOPOLOGY_NODE_INFOS_KEY) or []
        anti_by_node = {}
        for info in all_infos:
            anti_by_node[info.name] = self._anti_entries(info)
        cached = {"anti_by_node": anti_by_node}
        state[self._CACHE_KEY] = cached
        return cached

    def _term_index(self, state: CycleState, pod: Pod):
        """Per-term match locations over the published cluster for the
        incoming pod's own affinity/anti-affinity terms, computed once per
        cycle: for each term, which nodes hold a matching pod
        (``node_hit``), how many such nodes sit in each topology domain
        (``domain_hits``) and in total (``total_hits``). filter() then
        answers matched-here/matched-any by subtracting the candidate's
        published contribution and rescanning only the candidate's trial
        view (so preemption victim-eviction is still honored)."""
        cached = state.get(self._TERM_CACHE_KEY)
        if cached is not None:
            return cached
        all_infos: Sequence[NodeInfo] = state.get(TOPOLOGY_NODE_INFOS_KEY) or []
        own_ns = pod.metadata.namespace
        cached = {}
        for term in list(pod.spec.pod_affinity) + list(pod.spec.pod_anti_affinity):
            key = id(term)
            if key in cached:
                continue
            node_hit = {}
            domain_hits: Dict[str, int] = {}
            total_hits = 0
            for info in all_infos:
                hit = any(
                    term.selects(p.metadata.labels, p.metadata.namespace, own_ns)
                    for p in info.pods
                )
                node_hit[info.name] = hit
                if hit:
                    total_hits += 1
                    domain = info.node.metadata.labels.get(term.topology_key)
                    if domain is not None:
                        domain_hits[domain] = domain_hits.get(domain, 0) + 1
            cached[key] = (node_hit, domain_hits, total_hits)
        state[self._TERM_CACHE_KEY] = cached
        return cached

    @staticmethod
    def _anti_entries(info: NodeInfo):
        entries = []
        n_labels = info.node.metadata.labels
        for p in info.pods:
            for term in p.spec.pod_anti_affinity:
                domain = n_labels.get(term.topology_key)
                if domain is not None:
                    entries.append((term, p.metadata.namespace, domain))
        return entries

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        has_terms = pod.spec.pod_affinity or pod.spec.pod_anti_affinity
        index = self._index(state)
        node_labels = node_info.node.metadata.labels
        own_ns = pod.metadata.namespace

        # Symmetric anti-affinity applies to EVERY incoming pod, terms or
        # not: an existing pod's required anti-affinity rejects the
        # incoming pod from its domain. Precomputed entries (candidate
        # node's recomputed from the trial view).
        for name, entries in index["anti_by_node"].items():
            if name == node_info.name:
                continue
            for term, owner_ns, domain in entries:
                if node_labels.get(term.topology_key) == domain and term.selects(
                    pod.metadata.labels, own_ns, owner_ns
                ):
                    return Status.unschedulable(
                        f"an existing pod's anti-affinity "
                        f"({term.topology_key}={domain}) excludes this pod",
                        self.name,
                    )
        for term, owner_ns, domain in self._anti_entries(node_info):
            if node_labels.get(term.topology_key) == domain and term.selects(
                pod.metadata.labels, own_ns, owner_ns
            ):
                return Status.unschedulable(
                    f"an existing pod's anti-affinity "
                    f"({term.topology_key}={domain}) excludes this pod",
                    self.name,
                )
        if not has_terms:
            return Status.ok()
        # Per-term published-cluster index, minus the candidate's published
        # contribution, plus a rescan of ONLY the candidate's trial view —
        # on the normal path they're identical; under preemption the trial
        # has victims removed and THAT is what must be matched against.
        term_index = self._term_index(state, pod)

        def trial_hit(term) -> bool:
            return any(
                term.selects(p.metadata.labels, p.metadata.namespace, own_ns)
                for p in node_info.pods
            )

        for term in pod.spec.pod_affinity:
            domain = node_labels.get(term.topology_key)
            if domain is None:
                return Status.unschedulable(
                    f"node has no {term.topology_key} label", self.name
                )
            node_hit, domain_hits, total_hits = term_index[id(term)]
            cand_pub = 1 if node_hit.get(node_info.name) else 0
            here = trial_hit(term)
            matched_here = here or domain_hits.get(domain, 0) - cand_pub > 0
            if not matched_here:
                matched_any = here or total_hits - cand_pub > 0
                # bootstrap: a self-affine group's first replica
                if not matched_any and term.selects(
                    pod.metadata.labels, own_ns, own_ns
                ):
                    continue
                return Status.unschedulable(
                    f"no pod matching affinity term in {term.topology_key}="
                    f"{domain}",
                    self.name,
                )
        for term in pod.spec.pod_anti_affinity:
            domain = node_labels.get(term.topology_key)
            if domain is None:
                continue  # no domain -> nothing to collide with (upstream)
            node_hit, domain_hits, total_hits = term_index[id(term)]
            cand_pub = 1 if node_hit.get(node_info.name) else 0
            if trial_hit(term) or domain_hits.get(domain, 0) - cand_pub > 0:
                return Status.unschedulable(
                    f"anti-affinity: matching pod already in "
                    f"{term.topology_key}={domain}",
                    self.name,
                )
        return Status.ok()


class TaintTolerationScoring:
    """PreferNoSchedule taints affect scoring, not filtering (the in-tree
    TaintToleration score half the filter above deliberately ignores):
    nodes with fewer untolerated soft taints score higher."""

    name = "TaintTolerationScore"

    def score(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> int:
        untolerated = sum(
            1
            for taint in node_info.node.spec.taints
            if taint.effect == "PreferNoSchedule"
            and not any(t.tolerates(taint) for t in pod.spec.tolerations)
        )
        return max(0, 20 - 10 * untolerated)


class PodTopologySpreadScoring:
    """ScheduleAnyway topologySpreadConstraints (the soft half the filter
    ignores): domains with fewer matching pods score higher, pulling new
    replicas toward the emptiest domain without ever blocking placement.
    Domain counts are computed once per cycle and cached in CycleState
    (own key — the Fit plugin's cache covers DoNotSchedule constraints),
    so each per-node score call is a dict lookup."""

    name = "PodTopologySpreadScore"
    _CACHE_KEY = "pod_topology_spread_score_counts"

    def _domain_counts(self, state: CycleState, constraints) -> List[Dict[str, int]]:
        cached = state.get(self._CACHE_KEY)
        if cached is not None:
            return cached
        all_infos: Sequence[NodeInfo] = state.get(TOPOLOGY_NODE_INFOS_KEY) or []
        computed = []
        for c in constraints:
            domains: Dict[str, int] = {}
            for info in all_infos:
                domain = info.node.metadata.labels.get(c.topology_key)
                if domain is not None:
                    domains[domain] = domains.get(domain, 0) + (
                        PodTopologySpreadFit._matching(info, c)
                    )
            computed.append(domains)
        state[self._CACHE_KEY] = computed
        return computed

    def score(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> int:
        constraints = [
            c
            for c in pod.spec.topology_spread_constraints
            if c.when_unsatisfiable == "ScheduleAnyway"
        ]
        if not constraints:
            return 0
        counts = self._domain_counts(state, constraints)
        total = 0
        for c, domains in zip(constraints, counts):
            domain = node_info.node.metadata.labels.get(c.topology_key)
            if domain is None:
                continue
            count = domains.get(domain, PodTopologySpreadFit._matching(node_info, c))
            total += round(20 / (1 + count))
        return total // len(constraints)


def vanilla_filter_plugins() -> List[FilterPlugin]:
    """The in-tree predicate set both the real scheduler and the planner's
    embedded simulation run — keeping the two aligned is what prevents the
    planner from carving slices the scheduler would then refuse to use."""
    return [
        NodeUnschedulableFit(),
        TaintTolerationFit(),
        NodeAffinityFit(),
        NodeSelectorFit(),
        PodTopologySpreadFit(),
        InterPodAffinityFit(),
        NodeResourcesFit(),
    ]
