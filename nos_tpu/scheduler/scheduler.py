"""The scheduler: a full scheduling cycle per pending pod.

Plays the role of the reference's `scheduler` binary (cmd/scheduler/
scheduler.go:43-59 — vanilla kube-scheduler + CapacityScheduling): watch
pending pods, PreFilter → Filter over all nodes → Score → Reserve → Permit
→ Bind, with PostFilter preemption when filtering leaves nothing, and
Permit-wait for gang formation. Failure marks the pod's PodScheduled
condition Unschedulable — exactly the signal the partitioner controller
batches on, closing the carve-and-retry loop.
"""
from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from nos_tpu.api.v1alpha1 import constants
from nos_tpu.kube.controller import Request, Result
from nos_tpu.kube.objects import Pod, PodCondition, PodPhase
from nos_tpu.kube.store import KubeStore, NotFoundError
from nos_tpu.scheduler.framework import (
    CycleState,
    Diagnosis,
    Framework,
    NodeInfo,
    PodTopologySpreadScoring,
    TaintTolerationScoring,
    TOPOLOGY_NODE_INFOS_KEY,
    vanilla_filter_plugins,
    Status,
    StatusCode,
)
from nos_tpu.scheduler.plugins.capacity import CapacityScheduling
from nos_tpu.scheduler.plugins.gang import GangScheduling
from nos_tpu.scheduler.plugins.topology import MultihostIciFilter
from nos_tpu.scheduler.plugins.topology import IciTopologyScoring
from nos_tpu.util import metrics
from nos_tpu.util.tracing import TRACER

log = logging.getLogger("nos_tpu.scheduler")


def _reason_label(message: str) -> str:
    """Low-cardinality metric label from a rejection message: everything
    before the first ':' (per-pod quantities live after it)."""
    return message.split(":", 1)[0].strip() or "unknown"


def new_framework(
    store: KubeStore, gang_timeout_seconds: float = 30.0
) -> "tuple[Framework, CapacityScheduling, GangScheduling]":
    """Default plugin wiring (the in-tree registry + nos plugins, reference
    cmd/gpupartitioner/gpupartitioner.go:294-318 and cmd/scheduler)."""
    from nos_tpu.scheduler.plugins.reservation import (
        AutoscalerGraceScoring,
        BoardReservation,
    )

    capacity = CapacityScheduling(store)
    gang = GangScheduling(store, wait_timeout_seconds=gang_timeout_seconds)
    reservation = BoardReservation(store)
    framework = Framework(
        pre_filter_plugins=[capacity],
        filter_plugins=vanilla_filter_plugins()
        + [MultihostIciFilter(store, gang), reservation],
        post_filter_plugins=[capacity],
        reserve_plugins=[capacity],
        permit_plugins=[gang],
        score_plugins=[
            IciTopologyScoring(store),
            TaintTolerationScoring(),
            PodTopologySpreadScoring(),
            AutoscalerGraceScoring(),
        ],
    )
    capacity.framework = framework  # preemption re-runs the filters
    framework.reservation = reservation
    return framework, capacity, gang


@dataclass
class CycleOutcome:
    """One scheduling cycle's decision, separated from its application.

    ``_decide`` produces it (mutating only in-memory bookkeeping plus the
    preemption/reservation store writes); ``_apply_outcome`` performs the
    bind/nominate/fail store writes and metrics. The flight recorder
    captures the outcome between the two, and replay runs ``_decide``
    alone — a no-write shadow of the recorded cycle.
    """

    decision: str  # bind | wait | nominate | fail
    node: str = ""
    to_bind: List[Tuple[Pod, str]] = field(default_factory=list)
    victims: List[str] = field(default_factory=list)
    diagnosis: Optional[Diagnosis] = None
    message: str = ""
    start: float = 0.0


class Scheduler:
    def __init__(
        self,
        store: KubeStore,
        framework: Framework,
        capacity: Optional[CapacityScheduling] = None,
        gang: Optional[GangScheduling] = None,
        retry_seconds: float = 0.5,
        scheduler_name: str = "",
        recorder=None,
        flight_recorder=None,
        capacity_ledger=None,
    ) -> None:
        self.store = store
        self.framework = framework
        self.capacity = capacity
        self.gang = gang
        # Optional kube/events.py EventRecorder: Scheduled on bind,
        # FailedScheduling (deduped, count-bumped) on every failed cycle.
        # Threaded onto the capacity plugin (like framework/reservation
        # above) so the Preemptor can emit Preempted with its victim list.
        self.recorder = recorder
        if capacity is not None and recorder is not None:
            capacity.recorder = recorder
        # Optional record.FlightRecorder: one decision record per cycle,
        # written between _decide and _apply_outcome.
        self.flight_recorder = flight_recorder
        # Optional capacity.CapacityLedger: per-gang wait clocks (arrival →
        # first-feasible → bound) feeding nos_tpu_gang_wait_seconds. None
        # in replayed schedulers, so replay never double-observes waits.
        self.capacity_ledger = capacity_ledger
        # Latest Diagnosis per pod, served by /debug/explain. Bounded:
        # oldest entry falls off so a churning cluster can't grow it.
        self._diagnoses: Dict[str, dict] = {}
        self._max_diagnoses = 1024
        # Non-empty: only pods whose spec.schedulerName matches are ours;
        # the rest belong to the default scheduler (coexistence, reference
        # cmd/scheduler/scheduler.go:43-59). Empty: claim everything.
        self.scheduler_name = scheduler_name
        self._skip_logged: set = set()
        self.reservation = getattr(framework, "reservation", None)
        self.retry = retry_seconds
        self.pods_scheduled = 0
        # Assume cache: pods reserved on a node but not yet bound (gang
        # members waiting in Permit). Without it, concurrent cycles would
        # stack every waiting member onto the same node.
        self._assumed: Dict[str, tuple] = {}  # pod key -> (pod, node_name)
        # Cycle-phase histogram children, cached (labels() locks the
        # registry; the cycle runs per pending pod event).
        self._phase_decide = metrics.SCHEDULER_PHASE.labels(phase="decide")
        self._phase_settle = metrics.SCHEDULER_PHASE.labels(phase="settle")

    # --------------------------------------------------------- reconcile

    def responsible_for(self, pod: Pod) -> bool:
        return (
            not self.scheduler_name
            or pod.spec.scheduler_name == self.scheduler_name
        )

    def reconcile(self, req: Request) -> Optional[Result]:
        self._handle_gang_timeouts()
        pod = self.store.try_get("Pod", req.name, req.namespace)
        if pod is None:
            # Deleted — possibly before this scheduler ever observed it
            # bound. Any in-flight quota reservation and assume-cache entry
            # must die with the pod, or the leaked reservation inflates the
            # quota's used for the rest of the process lifetime and every
            # later pod in the namespace fails admission against phantom
            # usage.
            key = f"{req.namespace}/{req.name}" if req.namespace else req.name
            self._assumed.pop(key, None)
            if self.capacity is not None:
                self.capacity.forget_key(key)
            return None
        if not self.responsible_for(pod):
            # Another scheduler's pod: binding it here would double-bind
            # against the cluster's default scheduler. Logged once per pod
            # so a manifest missing schedulerName is diagnosable rather
            # than silently pending forever.
            if (
                pod.status.phase == PodPhase.PENDING
                and pod.namespaced_name not in self._skip_logged
            ):
                self._skip_logged.add(pod.namespaced_name)
                log.info(
                    "scheduler: ignoring %s (schedulerName=%r, ours=%r)",
                    pod.namespaced_name,
                    pod.spec.scheduler_name,
                    self.scheduler_name,
                )
            return None
        if pod.spec.node_name or pod.status.phase != PodPhase.PENDING:
            if self.capacity is not None:
                self.capacity.forget(pod)
            return None
        if pod.namespaced_name in self._assumed:
            # Gang member validly waiting in Permit: its reservation holds;
            # re-running the cycle would see its own assumed claim and
            # falsely mark it unschedulable.
            return Result(requeue_after=self.retry)
        return self.schedule_one(pod)

    # ------------------------------------------------------------ cycle

    def schedule_one(self, pod: Pod) -> Optional[Result]:
        # The journey root may already exist (partitioner observed the pod
        # first); otherwise this cycle starts it. Parenting the cycle span
        # on it stitches the scheduler's repeated attempts into the one
        # trace that answers "where did the pod's wait go".
        root = TRACER.journey_root(
            ("pod", pod.namespaced_name),
            "pod.journey",
            pod=pod.namespaced_name,
            namespace=pod.metadata.namespace,
        )
        with TRACER.span(
            "scheduler.cycle", parent=root, pod=pod.namespaced_name
        ) as cycle:
            result = self._schedule_cycle(pod, cycle)
        return result

    def _schedule_cycle(self, pod: Pod, cycle) -> Optional[Result]:
        # Watermark BEFORE the cycle's own writes: replay applies deltas up
        # to this revision, then re-decides — the cycle's writes are the
        # decision's consequences, not its inputs.
        revision = self.store.revision
        t_decide = time.monotonic()
        outcome = self._decide(pod)
        self._phase_decide.observe(time.monotonic() - t_decide)
        # Record only after the outcome's store writes land. A bind whose
        # write fails (apiserver conflict or outage) must not be recorded
        # as if it happened: replay's settle would bind the pod in the
        # replay store with no delta to back it, and every later decision
        # about that pod would drift. The decision itself is still recorded
        # (settled=False) because _decide's in-memory effects — assume
        # cache, gang formation — did happen and replay must re-run decide
        # to accumulate them; it just skips settle.
        t_settle = time.monotonic()
        try:
            result = self._apply_outcome(pod, outcome)
        except Exception:
            self._record_cycle(pod, revision, outcome, settled=False)
            raise
        finally:
            self._phase_settle.observe(time.monotonic() - t_settle)
        self._record_cycle(pod, revision, outcome)
        return result

    def decide(self, pod: Pod) -> CycleOutcome:
        """Replay entrypoint: the full decision pipeline without the
        bind/nominate/fail store writes. In-memory bookkeeping (assume
        cache, gang state) still mutates so a decision sequence replays the
        way it recorded; preemption's victim deletes and the board
        reservation's annotations also still write, converging with the
        recorded deltas."""
        return self._decide(pod)

    def _gang_key(self, pod: Pod) -> Optional[str]:
        from nos_tpu.scheduler.plugins.gang import gang_of

        membership = gang_of(pod)
        return membership[0] if membership else None

    def _decide(self, pod: Pod) -> CycleOutcome:
        start = time.monotonic()
        if self.capacity_ledger is not None:
            gang_key = self._gang_key(pod)
            if gang_key is not None:
                # Idempotent: the first cycle that sees any member starts
                # the gang's wait clock.
                self.capacity_ledger.note_gang_arrival(gang_key, time.time())
        if self.capacity is not None:
            self.capacity.last_victims = []
        state = CycleState()
        # Published before ANY extension point: the PreFilter-failure
        # preemption path below also runs filter plugins (victim trials),
        # and those need the same cluster view as the normal filter pass.
        node_infos = self._node_infos()
        state[TOPOLOGY_NODE_INFOS_KEY] = list(node_infos.values())
        # The CapacityScheduling PreFilter IS the elastic-quota admission
        # decision, so the span carries the quota stage name.
        with TRACER.span("quota.admission") as quota_span:
            status = self.framework.run_pre_filter_plugins(state, pod)
            if not status.success:
                quota_span.set_attributes(
                    rejected=True, plugin=status.plugin, message=status.message
                )
        if not status.success:
            metrics.FILTER_REJECTIONS.labels(
                plugin=status.plugin or "PreFilter"
            ).inc()
            # PreFilter rejection (e.g. quota max) still gets a preemption
            # attempt — evicting victims may change the quota math
            # (capacity_scheduling.go PostFilter runs on any failure).
            filtered = {name: status for name in node_infos}
            nominated = self.framework.run_post_filter_plugins(state, pod, filtered)
            if nominated:
                return CycleOutcome(
                    "nominate",
                    node=nominated,
                    victims=self._last_victims(),
                    start=start,
                )
            diagnosis = self._diagnosis(pod, node_infos, filtered)
            return CycleOutcome(
                "fail",
                diagnosis=diagnosis,
                message=diagnosis.aggregate_message(),
                start=start,
            )

        feasible: List[NodeInfo] = []
        filtered: Dict[str, Status] = {}
        with TRACER.span("scheduler.filter", nodes=len(node_infos)) as filter_span:
            for info in node_infos.values():
                node_status = self.framework.run_filter_plugins(state, pod, info)
                if node_status.success:
                    feasible.append(info)
                else:
                    filtered[info.name] = node_status
                    metrics.FILTER_REJECTIONS.labels(
                        plugin=node_status.plugin or "Filter"
                    ).inc()
            filter_span.set_attributes(feasible=len(feasible))

        if not feasible:
            with TRACER.span("scheduler.post_filter") as pf_span:
                nominated = self.framework.run_post_filter_plugins(
                    state, pod, filtered
                )
                pf_span.set_attributes(nominated=nominated or "")
            if nominated:
                return CycleOutcome(
                    "nominate",
                    node=nominated,
                    victims=self._last_victims(),
                    start=start,
                )
            if self.reservation is not None:
                # Fragmentation-blocked full-board pod: reserve the node
                # closest to draining so the board frees deterministically
                # instead of by luck (no-op for sub-board requests).
                self.reservation.try_reserve(pod, node_infos)
            diagnosis = self._diagnosis(pod, node_infos, filtered)
            return CycleOutcome(
                "fail",
                diagnosis=diagnosis,
                message=diagnosis.aggregate_message(),
                start=start,
            )

        with TRACER.span("scheduler.score", feasible=len(feasible)) as score_span:
            best = max(
                feasible,
                key=lambda info: (
                    self.framework.run_score_plugins(state, pod, info),
                    info.name,
                ),
            )
            score_span.set_attributes(best=best.name)
        with TRACER.span("scheduler.reserve", node=best.name):
            status = self.framework.run_reserve_plugins(state, pod, best.name)
        if not status.success:
            diagnosis = self._diagnosis(pod, node_infos, {best.name: status})
            return CycleOutcome(
                "fail",
                diagnosis=diagnosis,
                message=diagnosis.aggregate_message(),
                start=start,
            )

        with TRACER.span("scheduler.permit", node=best.name):
            permit = self.framework.run_permit_plugins(state, pod, best.name)
        if permit.code == StatusCode.WAIT:
            # Gang forming: reservation held, pod stays pending but its
            # claim on the node must be visible to later cycles.
            self._assumed[pod.namespaced_name] = (pod, best.name)
            return CycleOutcome(
                "wait", node=best.name, message=permit.message, start=start
            )
        if not permit.success:
            self.framework.run_unreserve_plugins(state, pod, best.name)
            diagnosis = self._diagnosis(pod, node_infos, {best.name: permit})
            return CycleOutcome(
                "fail",
                diagnosis=diagnosis,
                message=diagnosis.aggregate_message(),
                start=start,
            )

        # Bind — and release any gang members waiting on this quorum.
        to_bind = [(pod, best.name)]
        if self.gang is not None:
            released = self.gang.release(pod)
            if released:
                to_bind = released
                if all(key[0].namespaced_name != pod.namespaced_name for key in released):
                    to_bind.append((pod, best.name))
        return CycleOutcome("bind", node=best.name, to_bind=to_bind, start=start)

    def settle(self, outcome: CycleOutcome) -> None:
        """Replay companion to decide(): the in-memory consequences of a
        bind (assume-cache pop, capacity reservation release) without the
        store writes — those arrive as recorded deltas."""
        if outcome.decision != "bind":
            return
        for bind_pod, _ in outcome.to_bind:
            self._assumed.pop(bind_pod.namespaced_name, None)
            if self.capacity is not None:
                self.capacity.forget(bind_pod)

    def _last_victims(self) -> List[str]:
        return list(getattr(self.capacity, "last_victims", None) or [])

    def _record_cycle(
        self, pod: Pod, revision: int, outcome: CycleOutcome, settled: bool = True
    ) -> None:
        if self.flight_recorder is None:
            return
        root = TRACER.journey(("pod", pod.namespaced_name))
        self.flight_recorder.record_scheduler_cycle(
            pod=pod.namespaced_name,
            revision=revision,
            decision=outcome.decision,
            node=outcome.node,
            bound=[[p.namespaced_name, n] for p, n in outcome.to_bind],
            victims=list(outcome.victims),
            message=outcome.message,
            trace_id=root.trace_id if root is not None else "",
            diagnosis=outcome.diagnosis.to_dict() if outcome.diagnosis else None,
            settled=settled,
        )

    def _apply_outcome(self, pod: Pod, outcome: CycleOutcome) -> Optional[Result]:
        if self.capacity_ledger is not None and outcome.decision in (
            "wait",
            "bind",
        ):
            # A member passing Permit (wait) or releasing the gang (bind)
            # means the whole gang found feasible nodes this cycle.
            gang_key = self._gang_key(pod)
            if gang_key is not None:
                self.capacity_ledger.note_gang_feasible(gang_key, time.time())
        if outcome.decision == "nominate":
            self._set_nominated(pod, outcome.node)
            # Victims are terminating; retry shortly.
            return Result(requeue_after=self.retry / 2)
        if outcome.decision == "fail":
            self._fail_cycle(pod, outcome.diagnosis)
            return Result(requeue_after=self.retry)
        if outcome.decision == "wait":
            log.info(
                "scheduler: %s waiting (%s)", pod.namespaced_name, outcome.message
            )
            return Result(requeue_after=self.retry)
        with TRACER.span("scheduler.bind", pods=len(outcome.to_bind)):
            for bind_pod, node_name in outcome.to_bind:
                self._assumed.pop(bind_pod.namespaced_name, None)
                self._bind(bind_pod, node_name)
                if self.reservation is not None:
                    self.reservation.release_for(bind_pod)
        metrics.SCHEDULE_LATENCY.labels(namespace=pod.metadata.namespace).observe(
            time.monotonic() - outcome.start
        )
        if self.gang is not None and len(outcome.to_bind) > 1:
            metrics.GANGS_SCHEDULED.inc()
        if self.capacity_ledger is not None:
            for bound_pod, _ in outcome.to_bind:
                gang_key = self._gang_key(bound_pod)
                if gang_key is not None:
                    self.capacity_ledger.note_gang_bound(gang_key, time.time())
        return None

    # --------------------------------------------------------- diagnosis

    @staticmethod
    def _diagnosis(
        pod: Pod, node_infos: Dict[str, NodeInfo], filtered: Dict[str, Status]
    ) -> Diagnosis:
        return Diagnosis(
            pod=pod.namespaced_name,
            num_nodes=len(node_infos),
            node_statuses=dict(filtered),
        )

    def _fail_cycle(self, pod: Pod, diagnosis: Diagnosis) -> None:
        """Every operator surface for one failed cycle, fed by one ledger:
        metric, FailedScheduling Event, /debug/explain store, the journey
        trace's `diagnosis` attribute, and the PodScheduled condition.
        Runs BEFORE _mark_unschedulable's churn guard on purpose — a retry
        cycle must still bump the deduped Event count."""
        diagnosis.timestamp = time.time()
        message = diagnosis.aggregate_message()
        root = TRACER.journey(("pod", pod.namespaced_name))
        if root is not None:
            diagnosis.trace_id = root.trace_id
            root.set_attributes(diagnosis=message)
        for count, plugin, msg in diagnosis.grouped():
            metrics.SCHEDULING_UNSCHEDULABLE.labels(
                plugin=plugin or "unknown", reason=_reason_label(msg)
            ).inc(count)
        self._diagnoses.pop(pod.namespaced_name, None)
        while len(self._diagnoses) >= self._max_diagnoses:
            self._diagnoses.pop(next(iter(self._diagnoses)), None)
        self._diagnoses[pod.namespaced_name] = diagnosis.to_dict()
        if self.recorder is not None:
            self.recorder.record(
                pod,
                constants.EVENT_REASON_FAILED_SCHEDULING,
                message,
                type="Warning",
            )
        self._mark_unschedulable(pod, message)

    def explain(self, pod_key: str) -> Optional[dict]:
        """Latest Diagnosis for `ns/name`, or None — the /debug/explain
        backend."""
        return self._diagnoses.get(pod_key)

    # ----------------------------------------------------------- helpers

    def _node_infos(self) -> Dict[str, NodeInfo]:
        infos: Dict[str, NodeInfo] = {}
        for node in self.store.list("Node"):
            infos[node.metadata.name] = NodeInfo(node=node)
        for p in self.store.list("Pod"):
            if p.spec.node_name in infos and p.status.phase in (
                PodPhase.PENDING,
                PodPhase.RUNNING,
            ):
                infos[p.spec.node_name].add_pod(p)
        for key, (assumed_pod, node_name) in self._assumed.items():
            if node_name in infos and all(
                p.namespaced_name != key for p in infos[node_name].pods
            ):
                infos[node_name].add_pod(assumed_pod)
        return infos

    def _bind(self, pod: Pod, node_name: str) -> None:
        def mutate(p):
            p.spec.node_name = node_name
            p.status.nominated_node_name = ""
            p.status.conditions = [
                c for c in p.status.conditions if c.type != "PodScheduled"
            ]
            p.status.conditions.append(
                PodCondition(type="PodScheduled", status="True")
            )

        try:
            self.store.patch_merge("Pod", pod.metadata.name, pod.metadata.namespace, mutate)
        except NotFoundError:
            return
        self.pods_scheduled += 1
        metrics.PODS_SCHEDULED.labels(namespace=pod.metadata.namespace).inc()
        # Binding completes the journey: the root span's duration IS
        # time-to-schedulable. The kubelet's admission runs after bind —
        # a link lets it append its span to the already-stored trace.
        journey_key = ("pod", pod.namespaced_name)
        root = TRACER.journey(journey_key)
        if root is not None:
            TRACER.link(("admit", pod.namespaced_name), root)
        TRACER.end_journey(journey_key, node=node_name)
        if self.recorder is not None:
            self.recorder.record(
                pod,
                constants.EVENT_REASON_SCHEDULED,
                f"Successfully assigned {pod.namespaced_name} to {node_name}",
            )
        log.info("scheduler: bound %s to %s", pod.namespaced_name, node_name)

    def _mark_unschedulable(self, pod: Pod, message: str) -> None:
        # A nominated pod reaching here had its post-preemption retry and
        # STILL cannot fit — on partitioned TPU nodes that means the freed
        # chips need a re-carve, which the partitioner refuses to do for
        # "preempting" pods. Clearing the nomination hands the pod back to
        # the partitioner's batch (level-triggered handoff; upstream
        # clears nominatedNodeName on the same condition).
        clear_nomination = bool(pod.status.nominated_node_name)
        if pod.unschedulable() and not clear_nomination:
            return  # already marked; avoid patch churn

        def mutate(p):
            if clear_nomination:
                p.status.nominated_node_name = ""
            p.status.conditions = [
                c for c in p.status.conditions if c.type != "PodScheduled"
            ]
            p.status.conditions.append(
                PodCondition(
                    type="PodScheduled",
                    status="False",
                    reason="Unschedulable",
                    message=message,
                )
            )

        try:
            self.store.patch_merge("Pod", pod.metadata.name, pod.metadata.namespace, mutate)
        except NotFoundError:
            pass

    def _set_nominated(self, pod: Pod, node_name: str) -> None:
        def mutate(p):
            p.status.nominated_node_name = node_name

        try:
            self.store.patch_merge("Pod", pod.metadata.name, pod.metadata.namespace, mutate)
        except NotFoundError:
            pass

    def _handle_gang_timeouts(self) -> None:
        if self.gang is None:
            return
        for members in self.gang.expired_gangs():
            for member_pod, node_name in members:
                if self.capacity_ledger is not None:
                    gang_key = self._gang_key(member_pod)
                    if gang_key is not None:
                        # The gang will never bind: a dead clock would
                        # otherwise pollute the wait histogram at re-arrival.
                        self.capacity_ledger.drop_gang(gang_key)
                state = CycleState()
                self._assumed.pop(member_pod.namespaced_name, None)
                self.framework.run_unreserve_plugins(state, member_pod, node_name)
                self._mark_unschedulable(member_pod, "gang formation timed out")
                log.info(
                    "scheduler: gang timeout, released %s", member_pod.namespaced_name
                )
