"""Scheduler framework + plugins.

The reference embeds the real kube-scheduler framework twice: once in the
`scheduler` binary (CapacityScheduling plugin, SURVEY.md §2.4) and once
in-process inside the gpupartitioner for plan simulation
(cmd/gpupartitioner/gpupartitioner.go:294-318). This package provides the
same: a scheduling framework with the PreFilter/Filter/PostFilter/Reserve
extension points, stock resource-fit filtering, and the nos plugins.
"""

from nos_tpu.scheduler.framework import (
    CycleState,
    Framework,
    NodeInfo,
    Status,
    StatusCode,
)

__all__ = ["CycleState", "Framework", "NodeInfo", "Status", "StatusCode"]
