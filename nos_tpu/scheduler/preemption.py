"""Preemption evaluator (reference capacity_scheduling.go:371-675).

Victim selection per elastic-quota semantics (SelectVictimsOnNode,
:468-675): same-quota victims must have lower priority than the preemptor;
cross-quota victims must be running over-quota (label written by the
operator) and the preemptor must still be within its guaranteed share
(min + fair redistribution of unused min). The reprieve loop then re-adds
victims (highest priority first) while the pod stays feasible, minimizing
evictions; the reference's PDB-aware reprieve (:626-674) reduces to this
without PodDisruptionBudgets.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

from nos_tpu.kube.objects import Pod, PodPhase
from nos_tpu.kube.store import KubeStore, NotFoundError
from nos_tpu.scheduler.framework import CycleState, NodeInfo, Status
from nos_tpu.util import metrics
from nos_tpu.util import pod as podutil

log = logging.getLogger("nos_tpu.scheduler.preemption")


class Preemptor:
    def __init__(self, store: KubeStore, plugin, infos) -> None:
        self.store = store
        self.plugin = plugin  # CapacityScheduling (provides .framework)
        self.infos = infos

    # ----------------------------------------------------------- entry

    def preempt(
        self, state: CycleState, pod: Pod, filtered_nodes: Dict[str, Status]
    ) -> Optional[str]:
        framework = getattr(self.plugin, "framework", None)
        if framework is None:
            return None
        best: Optional[Tuple[str, List[Pod]]] = None
        for node_name in sorted(filtered_nodes):
            node_info = self._node_info(node_name)
            if node_info is None:
                continue
            victims = self.select_victims_on_node(state, pod, node_info, framework)
            if victims is None:
                continue
            key = (len(victims), max((v.spec.priority for v in victims), default=0))
            if best is None or key < (
                len(best[1]),
                max((v.spec.priority for v in best[1]), default=0),
            ):
                best = (node_name, victims)
        if best is None:
            return None
        node_name, victims = best
        for victim in victims:
            log.info(
                "preempting %s on %s for %s",
                victim.namespaced_name,
                node_name,
                pod.namespaced_name,
            )
            try:
                self.store.delete("Pod", victim.metadata.name, victim.metadata.namespace)
                metrics.PREEMPTIONS.inc()
            except NotFoundError:
                pass
        return node_name

    # ---------------------------------------------------------- victims

    def select_victims_on_node(
        self, state: CycleState, pod: Pod, node_info: NodeInfo, framework
    ) -> Optional[List[Pod]]:
        eligible = [v for v in node_info.pods if self._eligible(pod, v)]
        if not eligible:
            return None
        from nos_tpu.scheduler.plugins.capacity import CapacityScheduling, quota_request

        # Feasibility is node filters AND the quota admission re-evaluated
        # against simulated usage — a victim whose eviction only relieves
        # quota pressure (node has headroom) must not be reprieved.
        sim_infos = self.infos.clone()

        def feasible(trial: NodeInfo) -> bool:
            if not framework.run_filter_plugins(state, pod, trial).success:
                return False
            return CapacityScheduling.check_quota(pod, sim_infos).success

        def evict_sim(victim: Pod) -> None:
            v_info = sim_infos.for_namespace(victim.metadata.namespace)
            if v_info is not None:
                v_info.remove_pod(victim.namespaced_name, quota_request(victim))

        def restore_sim(victim: Pod) -> None:
            v_info = sim_infos.for_namespace(victim.metadata.namespace)
            if v_info is not None:
                v_info.add_pod(victim.namespaced_name, quota_request(victim))

        trial = NodeInfo(node=node_info.node, pods=list(node_info.pods))
        for v in eligible:
            trial.remove_pod(v)
            evict_sim(v)
        if not feasible(trial):
            return None
        # Reprieve: re-add victims (highest priority, then newest first)
        # while the pod stays feasible.
        victims: List[Pod] = []
        for v in sorted(
            eligible,
            key=lambda p: (-p.spec.priority, -p.metadata.creation_timestamp),
        ):
            trial.add_pod(v)
            restore_sim(v)
            if feasible(trial):
                continue  # reprieved
            trial.remove_pod(v)
            evict_sim(v)
            victims.append(v)
        return victims if victims else None

    def _eligible(self, preemptor: Pod, victim: Pod) -> bool:
        if victim.status.phase not in (PodPhase.PENDING, PodPhase.RUNNING):
            return False
        p_info = self.infos.for_namespace(preemptor.metadata.namespace)
        v_info = self.infos.for_namespace(victim.metadata.namespace)
        same_quota = (
            p_info is not None and v_info is not None and p_info.name == v_info.name
        ) or (p_info is None and v_info is None and
              preemptor.metadata.namespace == victim.metadata.namespace)
        if same_quota:
            # Intra-quota: plain priority preemption (:468-541).
            return victim.spec.priority < preemptor.spec.priority
        # Cross-quota: only over-quota (borrowed) capacity is reclaimable,
        # and only by a preemptor still entitled to guaranteed capacity.
        if not podutil.is_over_quota(victim):
            return False
        if p_info is None:
            return False
        from nos_tpu.scheduler.plugins.capacity import quota_request

        return self.infos.within_guaranteed_with(p_info.name, quota_request(preemptor))

    # ----------------------------------------------------------- helpers

    def _node_info(self, node_name: str) -> Optional[NodeInfo]:
        node = self.store.try_get("Node", node_name)
        if node is None:
            return None
        pods = [
            p
            for p in self.store.list("Pod")
            if p.spec.node_name == node_name
            and p.status.phase in (PodPhase.PENDING, PodPhase.RUNNING)
        ]
        return NodeInfo(node=node, pods=pods)
