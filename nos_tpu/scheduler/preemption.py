"""Preemption evaluator (reference capacity_scheduling.go:371-675).

Victim selection per elastic-quota semantics (SelectVictimsOnNode,
:468-675): same-quota victims must have lower priority than the preemptor;
cross-quota victims must be running over-quota (label written by the
operator) and the preemptor must still be within its guaranteed share
(min + fair redistribution of unused min). The reprieve loop then re-adds
victims while the pod stays feasible, minimizing evictions, honoring
PodDisruptionBudgets the way the reference does (:626-674): victims whose
eviction would violate a PDB are reprieved first, and nodes are compared by
fewest PDB violations before fewest evictions.

TPU extension (SURVEY.md §7 hard part): victims are *units*, not pods. A
multi-host gang (nos.nebuly.com/gang) holds one ICI slice across several
nodes; evicting one member deadlocks the rest on their chips. So a gang is
selected, reprieved, and evicted atomically — eviction cascades to members
on other nodes, and the quota simulation frees the whole gang's usage.
"""
from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from nos_tpu.kube.objects import Pod, PodPhase
from nos_tpu.kube.store import KubeStore, NotFoundError
from nos_tpu.scheduler.framework import CycleState, NodeInfo, Status
from nos_tpu.scheduler.plugins.gang import gang_of
from nos_tpu.util import metrics
from nos_tpu.util import pod as podutil

log = logging.getLogger("nos_tpu.scheduler.preemption")


@dataclass
class VictimUnit:
    """The atom of preemption: one pod, or one gang's pods.

    ``local`` are the members on the candidate node (they free node
    capacity); ``members`` is the cluster-wide set (they all get evicted and
    all free quota usage).
    """

    local: List[Pod]
    members: List[Pod]
    gang_key: Optional[str] = None

    @property
    def max_priority(self) -> int:
        return max((p.spec.priority for p in self.members), default=0)

    @property
    def newest_creation(self) -> float:
        return max((p.metadata.creation_timestamp for p in self.members), default=0.0)


@dataclass
class _NodeVictims:
    units: List[VictimUnit]
    num_pdb_violations: int

    @property
    def pods(self) -> List[Pod]:
        return [p for u in self.units for p in u.members]


class _PdbLedger:
    """Tracks remaining allowed disruptions per PodDisruptionBudget.

    Mirrors the reference's filterPodsWithPDBViolation: a victim "violates"
    a PDB when, given the evictions already charged, the budget has run out.
    """

    def __init__(self, store: Optional[KubeStore]) -> None:
        # [namespace, selector, remaining allowed disruptions] per PDB.
        self._budgets: List[list] = []
        if store is None:
            return
        pdbs = list(store.list("PodDisruptionBudget"))
        pods_by_ns: Dict[str, list] = {}
        for pdb in pdbs:
            ns = pdb.metadata.namespace
            if ns not in pods_by_ns:
                pods_by_ns[ns] = list(store.list("Pod", namespace=ns))
        for pdb in pdbs:
            selector = dict(pdb.spec.selector)
            matching = [
                p
                for p in pods_by_ns[pdb.metadata.namespace]
                if selector.items() <= p.metadata.labels.items()
                # Terminal pods are outside the PDB's expected count — a
                # pile of Succeeded pods must not shrink desiredHealthy
                # (round-1 advisory; matches the disruption controller's
                # expectedCount over non-terminal pods).
                and p.status.phase in (PodPhase.PENDING, PodPhase.RUNNING)
            ]
            healthy = sum(1 for p in matching if p.status.phase == PodPhase.RUNNING)
            if pdb.spec.min_available is not None:
                allowed = healthy - pdb.spec.min_available
            elif pdb.spec.max_unavailable is not None:
                # disruptionsAllowed = currentHealthy - desiredHealthy, with
                # desiredHealthy = expected - maxUnavailable (policy/v1):
                # already-unavailable pods consume the budget.
                allowed = healthy - (len(matching) - pdb.spec.max_unavailable)
            else:
                allowed = healthy
            self._budgets.append([pdb.metadata.namespace, selector, max(0, allowed)])

    def clone(self) -> "_PdbLedger":
        c = _PdbLedger(None)
        c._budgets = [list(b) for b in self._budgets]
        return c

    def _matching(self, pod: Pod):
        for budget in self._budgets:
            ns, selector, _ = budget
            if pod.metadata.namespace == ns and selector.items() <= pod.metadata.labels.items():
                yield budget

    def would_violate(self, unit: VictimUnit) -> bool:
        charges: Dict[int, int] = {}
        for pod in unit.members:
            for budget in self._matching(pod):
                key = id(budget)
                charges[key] = charges.get(key, 0) + 1
                if charges[key] > budget[2]:
                    return True
        return False

    def charge(self, unit: VictimUnit) -> None:
        for pod in unit.members:
            for budget in self._matching(pod):
                budget[2] = max(0, budget[2] - 1)


class Preemptor:
    def __init__(self, store: KubeStore, plugin, infos) -> None:
        self.store = store
        self.plugin = plugin  # CapacityScheduling (provides .framework)
        self.infos = infos
        # Quota requests in the simulation must be denominated exactly like
        # the infos were built, or evict/restore drift (CapacitySchedulingArgs
        # chip-memory knob).
        self.chip_memory_gb = getattr(plugin, "chip_memory_gb", None)
        # Per-cycle caches: store/infos are fixed for one preemption cycle,
        # so request aggregation, entitlement math, and gang membership are
        # computed once, not per victim per node.
        self._request_cache: Dict[str, dict] = {}
        self._entitled_cache: Dict[str, bool] = {}
        self._victim_quota_cache: Dict[str, bool] = {}
        self._gang_cache: Dict[str, List[Pod]] = {}

    def _quota_request(self, pod: Pod):
        from nos_tpu.scheduler.plugins.capacity import quota_request

        key = pod.namespaced_name
        if key not in self._request_cache:
            self._request_cache[key] = quota_request(pod, self.chip_memory_gb)
        return self._request_cache[key]

    # ----------------------------------------------------------- entry

    def preempt(
        self, state: CycleState, pod: Pod, filtered_nodes: Dict[str, Status]
    ) -> Optional[str]:
        framework = getattr(self.plugin, "framework", None)
        if framework is None:
            return None
        best: Optional[Tuple[str, _NodeVictims]] = None
        best_key = None
        ledger = _PdbLedger(self.store)
        for node_name in sorted(filtered_nodes):
            node_info = self._node_info(node_name)
            if node_info is None:
                continue
            victims = self.select_victims_on_node(
                state, pod, node_info, framework, ledger=ledger.clone()
            )
            if victims is None:
                continue
            # Node comparison in the upstream pickOneNodeForPreemption
            # order: fewest PDB violations, lowest top victim priority,
            # smallest priority sum, then fewest evicted pods (round-1
            # advisory: victim importance outranks victim count).
            key = (
                victims.num_pdb_violations,
                max((v.spec.priority for v in victims.pods), default=0),
                sum(v.spec.priority for v in victims.pods),
                len(victims.pods),
            )
            if best is None or key < best_key:
                best, best_key = (node_name, victims), key
        if best is None:
            return None
        node_name, victims = best
        # Stash the victim list on the (persistent) capacity plugin — this
        # Preemptor is per-cycle, but the flight recorder reads the victims
        # after post_filter returns only the nominated node name.
        self.plugin.last_victims = sorted(
            v.namespaced_name for v in victims.pods
        )
        for victim in victims.pods:
            log.info(
                "preempting %s (node %s) for %s",
                victim.namespaced_name,
                victim.spec.node_name or node_name,
                pod.namespaced_name,
            )
            try:
                self.store.delete("Pod", victim.metadata.name, victim.metadata.namespace)
                metrics.PREEMPTIONS.labels(
                    namespace=victim.metadata.namespace
                ).inc()
            except NotFoundError:
                pass
        recorder = getattr(self.plugin, "recorder", None)
        if recorder is not None:
            from nos_tpu.api.v1alpha1 import constants

            recorder.record(
                pod,
                constants.EVENT_REASON_PREEMPTED,
                "Preempted {} on {} to fit {}: {}".format(
                    len(victims.pods),
                    node_name,
                    pod.namespaced_name,
                    ", ".join(sorted(v.namespaced_name for v in victims.pods)),
                ),
                type="Warning",
            )
        return node_name

    # ---------------------------------------------------------- victims

    def select_victims_on_node(
        self,
        state: CycleState,
        pod: Pod,
        node_info: NodeInfo,
        framework,
        ledger: Optional[_PdbLedger] = None,
    ) -> Optional[_NodeVictims]:
        units = self._eligible_units(pod, node_info)
        if not units:
            return None
        from nos_tpu.scheduler.plugins.capacity import CapacityScheduling

        # Feasibility is node filters AND the quota admission re-evaluated
        # against simulated usage — a victim whose eviction only relieves
        # quota pressure (node has headroom) must not be reprieved.
        sim_infos = self.infos.clone()

        # Gang evictions remove pods from OTHER nodes too; the spread
        # predicate counts the whole cluster, so those remote removals must
        # be visible in its published view or cross-node evictions could
        # never resolve (or falsely resolve) a skew violation. Trial copies
        # of affected remote nodes are kept here and overlaid per feasible()
        # call; the candidate node itself is handled by the filter's own
        # trial-substitution.
        from nos_tpu.scheduler.framework import (
            TOPOLOGY_NODE_INFOS_KEY,
            InterPodAffinityFit,
            PodTopologySpreadFit,
        )

        has_spread = (
            any(
                c.when_unsatisfiable == "DoNotSchedule"
                for c in pod.spec.topology_spread_constraints
            )
            or bool(pod.spec.pod_affinity or pod.spec.pod_anti_affinity)
            # victims' own anti-affinity is SYMMETRIC: a remote gang member
            # whose term excludes the preemptor must disappear from the
            # published view when its unit is trial-evicted, or feasible()
            # keeps seeing the conflict eviction would resolve
            or any(
                m.spec.pod_anti_affinity for u in units for m in u.members
            )
        )
        published = state.get(TOPOLOGY_NODE_INFOS_KEY) if has_spread else None
        remote_trials: Dict[str, NodeInfo] = {}

        def _remote_trial(node_name: str) -> Optional[NodeInfo]:
            if published is None or node_name == node_info.name:
                return None
            if node_name not in remote_trials:
                for info in published:
                    if info.name == node_name:
                        remote_trials[node_name] = NodeInfo(
                            node=info.node, pods=list(info.pods)
                        )
                        break
            return remote_trials.get(node_name)

        def filter_state() -> CycleState:
            if published is None or not remote_trials:
                return state
            overlay = CycleState(state)
            overlay[TOPOLOGY_NODE_INFOS_KEY] = [
                remote_trials.get(i.name, i) for i in published
            ]
            overlay.pop(PodTopologySpreadFit._CACHE_KEY, None)
            overlay.pop(InterPodAffinityFit._CACHE_KEY, None)
            overlay.pop(InterPodAffinityFit._TERM_CACHE_KEY, None)
            return overlay

        def feasible(trial: NodeInfo) -> bool:
            if not CapacityScheduling.check_quota(
                pod, sim_infos, self.chip_memory_gb
            ).success:
                return False
            fs = filter_state()
            if framework.run_filter_plugins(fs, pod, trial).success:
                return True
            # Dynamic-partitioning awareness: on a TPU-partitioned node the
            # current slice denominations are NOT the constraint — freed
            # boards get re-carved by the partitioner the moment the victim
            # dies (level-triggered batch). Compare in chip units instead,
            # and still require every non-resource predicate to hold.
            headroom = self._tpu_chips_headroom(trial)
            if headroom is None:
                return False
            import nos_tpu.util.resources as resources

            needed = resources.tpu_chips_in(resources.compute_pod_request(pod))
            if needed <= 0 or needed > headroom:
                return False
            from nos_tpu.scheduler.framework import NodeResourcesFit

            return all(
                plugin.filter(fs, pod, trial).success
                for plugin in framework.filter_plugins
                if not isinstance(plugin, NodeResourcesFit)
            )

        def evict_sim(unit: VictimUnit) -> None:
            # The whole gang dies, so the whole gang's quota usage frees —
            # including members on other nodes.
            for victim in unit.members:
                v_info = sim_infos.for_namespace(victim.metadata.namespace)
                if v_info is not None:
                    v_info.remove_pod(victim.namespaced_name, self._quota_request(victim))
                if victim.spec.node_name:
                    remote = _remote_trial(victim.spec.node_name)
                    if remote is not None:
                        remote.remove_pod(victim)

        def restore_sim(unit: VictimUnit) -> None:
            for victim in unit.members:
                v_info = sim_infos.for_namespace(victim.metadata.namespace)
                if v_info is not None:
                    v_info.add_pod(victim.namespaced_name, self._quota_request(victim))
                if victim.spec.node_name:
                    remote = _remote_trial(victim.spec.node_name)
                    if remote is not None:
                        remote.add_pod(victim)

        trial = NodeInfo(node=node_info.node, pods=list(node_info.pods))
        for unit in units:
            for p in unit.local:
                trial.remove_pod(p)
            evict_sim(unit)
        if not feasible(trial):
            return None

        # Reprieve (reference :626-674): PDB-violating units first, then the
        # rest; within each class highest priority, then newest first. The
        # classification pass charges the shared budgets cumulatively (the
        # reference's filterPodsWithPDBViolation decrements pdbsAllowed as
        # it walks), so two victims that individually fit a budget of one
        # are correctly split into one non-violating and one violating.
        if ledger is None:
            ledger = _PdbLedger(self.store)
        violating: List[VictimUnit] = []
        non_violating: List[VictimUnit] = []
        for unit in sorted(
            units, key=lambda u: (-u.max_priority, -u.newest_creation)
        ):
            violates = ledger.would_violate(unit)
            # Budgets are charged unconditionally (clamped at zero), like the
            # reference's filterPodsWithPDBViolation: a violating victim that
            # matches several PDBs still consumes the ones with room left.
            ledger.charge(unit)
            (violating if violates else non_violating).append(unit)

        victims: List[VictimUnit] = []
        num_violations = 0
        for unit, violates in [(u, True) for u in violating] + [
            (u, False) for u in non_violating
        ]:
            for p in unit.local:
                trial.add_pod(p)
            restore_sim(unit)
            if feasible(trial):
                continue  # reprieved
            for p in unit.local:
                trial.remove_pod(p)
            evict_sim(unit)
            victims.append(unit)
            if violates:
                # Count violating PODS (an 8-pod gang disrupts 8), matching
                # the reference's pickOneNodeForPreemption comparison.
                num_violations += len(unit.members)
        if not victims:
            return None
        return _NodeVictims(units=victims, num_pdb_violations=num_violations)

    # ------------------------------------------------------------ units

    def _eligible_units(self, preemptor: Pod, node_info: NodeInfo) -> List[VictimUnit]:
        """Group the node's pods into atomic victim units; a unit is
        eligible only if every one of its cluster-wide members is (a gang
        cannot be half-evicted)."""
        singles: List[Pod] = []
        gangs: Dict[str, List[Pod]] = {}
        for p in node_info.pods:
            gang = gang_of(p)
            if gang is None:
                singles.append(p)
            else:
                gangs.setdefault(gang[0], []).append(p)

        units: List[VictimUnit] = []
        for p in singles:
            if self._eligible(preemptor, p):
                units.append(VictimUnit(local=[p], members=[p]))
        for key, local in gangs.items():
            members = self._gang_members(key)
            if members and all(self._eligible(preemptor, m) for m in members):
                units.append(VictimUnit(local=local, members=members, gang_key=key))
        return units

    def _gang_members(self, gang_key: str) -> List[Pod]:
        # Membership via gang_of, matching _eligible_units' grouping: a pod
        # with a gang name but a malformed size is NOT a member (it schedules
        # solo), so it can never sit in two victim units at once. Cached for
        # the cycle — the same gang shows up on every candidate node.
        if gang_key in self._gang_cache:
            return self._gang_cache[gang_key]
        ns, _ = gang_key.split("/", 1)
        members = []
        for p in self.store.list("Pod", namespace=ns):
            gang = gang_of(p)
            if (
                gang is not None
                and gang[0] == gang_key
                and p.status.phase in (PodPhase.PENDING, PodPhase.RUNNING)
            ):
                # Unbound pending members belong to the unit too: the gang
                # dies as a whole, or its survivors deadlock waiting on a
                # quorum that can never re-form (round-1 advisory).
                members.append(p)
        self._gang_cache[gang_key] = members
        return members

    def _eligible(self, preemptor: Pod, victim: Pod) -> bool:
        """Mirrors the reference's SelectVictimsOnNode eligibility branches
        (capacity_scheduling.go:512-598), keyed on whether serving the
        preemptor would take its quota over min."""
        if victim.status.phase not in (PodPhase.PENDING, PodPhase.RUNNING):
            return False
        p_info = self.infos.for_namespace(preemptor.metadata.namespace)
        v_info = self.infos.for_namespace(victim.metadata.namespace)
        if p_info is None:
            # Preemptor outside any quota: plain priority preemption among
            # non-quota pods only (:585-598).
            return v_info is None and victim.spec.priority < preemptor.spec.priority
        if v_info is None:
            return False
        request = self._quota_request(preemptor)
        if p_info.used_over_min_with(request):
            # Preemptor would borrow: same-quota lower-priority victims
            # (:536-541); cross-quota over-quota pods, but only while the
            # preemptor stays within min + its guaranteed fair share and
            # the victim's quota exceeds its own (:543-564).
            if v_info.name == p_info.name:
                return victim.spec.priority < preemptor.spec.priority
            if not podutil.is_over_quota(victim):
                return False
            if p_info.name not in self._entitled_cache:
                self._entitled_cache[p_info.name] = self.infos.within_guaranteed_with(
                    p_info.name, request
                )
            if v_info.name not in self._victim_quota_cache:
                self._victim_quota_cache[v_info.name] = self.infos.used_over_entitled(
                    v_info.name
                )
            return self._entitled_cache[p_info.name] and self._victim_quota_cache[v_info.name]
        # Preemptor within guaranteed min: its capacity is being borrowed —
        # reclaim from any borrowing quota's over-quota pods (:566-581).
        if v_info.name == p_info.name:
            return False
        return podutil.is_over_quota(victim) and v_info.is_borrowing()

    # ----------------------------------------------------------- helpers

    @staticmethod
    def _tpu_chips_headroom(trial: NodeInfo) -> Optional[int]:
        """Physical chips minus chips held by the trial's surviving pods,
        for TPU-partitioned nodes (None elsewhere): the capacity a
        re-carve could reshape into any profile."""
        from nos_tpu.api.v1alpha1 import constants, labels
        import nos_tpu.util.resources as resources

        node = trial.node
        if node.metadata.labels.get(labels.PARTITIONING_LABEL) not in (
            labels.PartitioningKind.TPU,
            labels.PartitioningKind.HYBRID,
        ):
            return None
        total = int(node.status.capacity.get(constants.RESOURCE_TPU, 0))
        if total <= 0:
            return None
        used = sum(
            resources.tpu_chips_in(resources.compute_pod_request(p))
            for p in trial.pods
        )
        return total - used

    def _node_info(self, node_name: str) -> Optional[NodeInfo]:
        node = self.store.try_get("Node", node_name)
        if node is None:
            return None
        pods = [
            p
            for p in self.store.list("Pod")
            if p.spec.node_name == node_name
            and p.status.phase in (PodPhase.PENDING, PodPhase.RUNNING)
        ]
        return NodeInfo(node=node, pods=pods)
