from nos_tpu.scheduler.plugins.capacity import CapacityScheduling, ElasticQuotaInfo, ElasticQuotaInfos
from nos_tpu.scheduler.plugins.gang import GangScheduling, gang_of
from nos_tpu.scheduler.plugins.topology import IciTopologyScoring

__all__ = [
    "CapacityScheduling",
    "ElasticQuotaInfo",
    "ElasticQuotaInfos",
    "GangScheduling",
    "IciTopologyScoring",
    "gang_of",
]
