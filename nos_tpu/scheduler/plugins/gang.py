"""Gang scheduling: all-or-nothing placement for multi-host JAX jobs.

The reference has no gang plugin — this is the TPU-specific extension the
build plan requires (SURVEY.md §7 step 6): a multi-host job (e.g. a JAX
training Pod per TPU worker) must either get all its workers placed inside
one ICI domain or none, otherwise the placed subset deadlocks chips.

Implemented in the coscheduling style over the Permit extension point:
each member reserves resources and WAITs; when the last member arrives the
whole gang is released for binding; a forming gang that cannot complete
within the timeout is failed and unreserved as a unit.

Pods declare membership with labels:
  nos.nebuly.com/gang       = <gang name, unique per namespace>
  nos.nebuly.com/gang-size  = "<member count>"
"""
from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from nos_tpu.kube.objects import Pod, PodPhase
from nos_tpu.kube.store import KubeStore
from nos_tpu.scheduler.framework import CycleState, Status

log = logging.getLogger("nos_tpu.scheduler.gang")

GANG_NAME_LABEL = "nos.nebuly.com/gang"
GANG_SIZE_LABEL = "nos.nebuly.com/gang-size"


def gang_of(pod: Pod) -> Optional[Tuple[str, int]]:
    """(gang key, size) or None. Malformed sizes mean no gang."""
    name = pod.metadata.labels.get(GANG_NAME_LABEL)
    if not name:
        return None
    try:
        size = int(pod.metadata.labels.get(GANG_SIZE_LABEL, ""))
    except ValueError:
        return None
    if size < 1:
        return None
    return f"{pod.metadata.namespace}/{name}", size


@dataclass
class _WaitingGang:
    size: int
    deadline: float
    members: Dict[str, Tuple[Pod, str]] = field(default_factory=dict)  # key -> (pod, node)


class GangScheduling:
    name = "GangScheduling"

    def __init__(self, store: KubeStore, wait_timeout_seconds: float = 30.0) -> None:
        self.store = store
        self.timeout = wait_timeout_seconds
        self._lock = threading.Lock()
        self._waiting: Dict[str, _WaitingGang] = {}

    # ----------------------------------------------------------- permit

    def permit(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        gang = gang_of(pod)
        if gang is None:
            return Status.ok()
        key, size = gang
        bound = self._bound_members(key)
        with self._lock:
            waiting = self._waiting.setdefault(
                key, _WaitingGang(size=size, deadline=time.monotonic() + self.timeout)
            )
            waiting.members[pod.namespaced_name] = (pod, node_name)
            arrived = len(waiting.members) + bound
            if arrived >= size:
                return Status.ok()
            return Status.wait(
                f"gang {key}: {arrived}/{size} members placed", self.name
            )

    def release(self, pod: Pod) -> List[Tuple[Pod, str]]:
        """On a successful permit, the whole waiting gang binds together.
        Returns the other members to bind (the permitted pod included)."""
        gang = gang_of(pod)
        if gang is None:
            return []
        key, _ = gang
        with self._lock:
            waiting = self._waiting.pop(key, None)
        if waiting is None:
            return []
        return list(waiting.members.values())

    # ---------------------------------------------------------- timeout

    def expired_gangs(self) -> List[List[Tuple[Pod, str]]]:
        """Gangs whose formation timed out: their members must be
        unreserved and marked unschedulable as a unit."""
        now = time.monotonic()
        out: List[List[Tuple[Pod, str]]] = []
        with self._lock:
            for key in [k for k, g in self._waiting.items() if g.deadline <= now]:
                out.append(list(self._waiting.pop(key).members.values()))
        return out

    def waiting_count(self) -> int:
        with self._lock:
            return sum(len(g.members) for g in self._waiting.values())

    def waiting_members(self, gang_key: str) -> List[Tuple[Pod, str]]:
        """(pod, node) pairs reserved in Permit for this gang — placement
        state invisible in the store (no bind yet), consulted by the ICI
        co-location filter."""
        with self._lock:
            waiting = self._waiting.get(gang_key)
            return list(waiting.members.values()) if waiting else []

    # ----------------------------------------------------------- helpers

    def _bound_members(self, gang_key: str) -> int:
        ns, name = gang_key.split("/", 1)
        return sum(
            1
            for p in self.store.list("Pod", namespace=ns)
            if p.metadata.labels.get(GANG_NAME_LABEL) == name
            and p.spec.node_name
            and p.status.phase in (PodPhase.PENDING, PodPhase.RUNNING)
        )
