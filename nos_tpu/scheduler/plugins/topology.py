"""ICI topology scoring: placement quality for TPU slices.

The TPU extension of the reference's plugin set (SURVEY.md §7 step 6: "keep
a job's chips in one contiguous slice/domain"). Three signals:

1. exact-fit: a node holding a free slice of exactly the requested profile
   beats one that would strand a bigger slice;
2. consolidation: prefer filling already-carved nodes, keeping virgin
   boards whole for future large slices (bin packing);
3. gang/ICI affinity: members of the same gang score higher on nodes of the
   node pool where members already landed — multi-host slice workers share
   a GKE node pool, which is the ICI domain boundary.
"""
from __future__ import annotations

from typing import Optional

from nos_tpu.api.v1alpha1 import constants
from nos_tpu.kube.objects import Pod, PodPhase
from nos_tpu.kube.store import KubeStore
from nos_tpu.scheduler.framework import CycleState, NodeInfo
from nos_tpu.scheduler.plugins.gang import GANG_NAME_LABEL, gang_of
from nos_tpu.tpu.topology import Topology
from nos_tpu.util import resources as res

GKE_NODEPOOL_LABEL = "cloud.google.com/gke-nodepool"


class IciTopologyScoring:
    name = "IciTopologyScoring"

    def __init__(self, store: Optional[KubeStore] = None) -> None:
        self.store = store

    def score(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> int:
        total = 0
        request = res.compute_pod_request(pod)
        available = node_info.available()

        requested_profiles = {
            constants.tpu_slice_topology(name): qty
            for name, qty in request.items()
            if constants.is_tpu_slice_resource(name)
        }
        if requested_profiles:
            # 1. exact-fit: every requested profile available as-is.
            if all(
                available.get(constants.tpu_slice_resource(p), 0) >= qty
                for p, qty in requested_profiles.items()
            ):
                total += 50
            # 2. consolidation: fraction of the node's slice chips in use.
            slice_chips = sum(
                Topology(constants.tpu_slice_topology(name)).chips * int(qty)
                for name, qty in node_info.node.status.allocatable.items()
                if constants.is_tpu_slice_resource(name)
            )
            if slice_chips > 0:
                free_chips = sum(
                    Topology(constants.tpu_slice_topology(name)).chips * int(qty)
                    for name, qty in available.items()
                    if constants.is_tpu_slice_resource(name) and qty > 0
                )
                total += int(30 * (1 - free_chips / slice_chips))

        # 3. gang/ICI affinity via shared node pool.
        gang = gang_of(pod)
        if gang and self.store is not None:
            pool = node_info.node.metadata.labels.get(GKE_NODEPOOL_LABEL)
            if pool:
                ns, name = gang[0].split("/", 1)
                for member in self.store.list("Pod", namespace=ns):
                    if (
                        member.metadata.labels.get(GANG_NAME_LABEL) == name
                        and member.spec.node_name
                        and member.status.phase in (PodPhase.PENDING, PodPhase.RUNNING)
                    ):
                        member_node = self.store.try_get("Node", member.spec.node_name)
                        if (
                            member_node is not None
                            and member_node.metadata.labels.get(GKE_NODEPOOL_LABEL) == pool
                        ):
                            total += 20
                            break
        return total


class MultihostIciFilter:
    """HARD co-location for multi-host slices: every member of one slice
    must land inside one GKE node pool — the ICI domain boundary. The
    soft gang-affinity score above cannot guarantee this (a busy pool
    would silently strand members across domains where ICI does not
    reach, producing a slice that can never form a JAX mesh)."""

    name = "MultihostIci"

    def __init__(self, store: KubeStore, gang=None) -> None:
        self.store = store
        self.gang = gang  # GangScheduling: exposes Permit-reserved members

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo):
        from nos_tpu.controllers.partitioner.multihost import (
            MULTIHOST_TOPOLOGY_ANNOTATION,
        )
        from nos_tpu.scheduler.framework import Status

        if not pod.metadata.annotations.get(MULTIHOST_TOPOLOGY_ANNOTATION):
            return Status.ok()
        gang = gang_of(pod)
        if gang is None:
            return Status.ok()
        key, _ = gang
        ns, name = key.split("/", 1)
        placed_pools = set()

        def pool_of(node_name: str) -> None:
            node = self.store.try_get("Node", node_name)
            if node is not None:
                placed_pools.add(node.metadata.labels.get(GKE_NODEPOOL_LABEL, ""))

        for member in self.store.list("Pod", namespace=ns):
            if (
                member.metadata.labels.get(GANG_NAME_LABEL) == name
                and member.spec.node_name
                and member.status.phase in (PodPhase.PENDING, PodPhase.RUNNING)
            ):
                pool_of(member.spec.node_name)
        if self.gang is not None:
            for _, node_name in self.gang.waiting_members(key):
                pool_of(node_name)
        placed_pools.discard("")  # unlabeled sim nodes: no constraint
        if not placed_pools:
            return Status.ok()
        pool = node_info.node.metadata.labels.get(GKE_NODEPOOL_LABEL, "")
        if pool in placed_pools:
            return Status.ok()
        return Status.unschedulable(
            f"multi-host slice pinned to node pool {sorted(placed_pools)[0]!r}",
            self.name,
        )
