"""Board reservation: drain-aware scheduling for full-board requests.

A pod whose request spans a whole physical board (e.g. 8 chips on a v5e
2x4 host) can starve indefinitely on a busy cluster: a board only drains
by luck, because every freed fragment is immediately re-carved for
smaller pending pods, and the planner cannot migrate running workloads
(neither can the reference — its planner only re-shapes FREE devices,
internal/partitioning/core/planner.go). Upstream kube attacks the
analogous problem with nominated nodes; preemption does not apply here
(equal priorities). The TPU answer is an explicit drain reservation:

- When a full-board pod is unschedulable and NO node has enough
  re-carvable headroom (physical chips minus chips held by running pods),
  the scheduler reserves the node closest to draining by writing
  ``nos.nebuly.com/reserved-for: <ns/name>`` (+ ``reserved-at``) on it.
- The filter keeps every other pod off a validly reserved node — in the
  real scheduler AND in the partitioner's simulation framework, so the
  planner never carves for other pods there either (SURVEY §7
  "simulation fidelity").
- The board drains, the partitioner re-carves it for the holder, the
  holder binds, and the bind releases the reservation.
- A TTL bounds leakage when the holder vanishes without an event; a
  reservation whose holder is no longer a pending unbound pod is invalid
  immediately.
"""
from __future__ import annotations

import logging
import time
from typing import Dict, Optional

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.kube.objects import Pod, PodPhase
from nos_tpu.kube.store import KubeStore, NotFoundError
from nos_tpu.scheduler.framework import CycleState, NodeInfo, Status
from nos_tpu.tpu.known import board_layout
from nos_tpu.tpu.topology import Topology
from nos_tpu.util import metrics
from nos_tpu.util import resources as res

log = logging.getLogger("nos_tpu.scheduler")

RESERVED_FOR = annot.PREFIX + "reserved-for"
RESERVED_AT = annot.PREFIX + "reserved-at"

_VALID_CACHE_KEY = "board_reservation_valid"


class BoardReservation:
    name = "BoardReservation"

    def __init__(
        self,
        store: KubeStore,
        ttl_seconds: float = 30.0,
        min_wait_seconds: float = 10.0,
    ) -> None:
        self.store = store
        self.ttl = ttl_seconds
        # Reservation is a starvation safety net, not a fast path: a drain
        # deliberately idles chips, and measured on the steady-stream bench
        # it costs ~8 utilization points when applied to every full-board
        # pod. First-fit-descending planning + best-fit node ordering land
        # full-board pods organically in the common case; only a pod that
        # has ALREADY waited this long gets a node drained for it.
        self.min_wait = min_wait_seconds

    # ------------------------------------------------------------ filter

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        ann = node_info.node.metadata.annotations
        if RESERVED_FOR not in ann:
            return Status.ok()
        cache: Dict[str, Optional[str]] = state.setdefault(_VALID_CACHE_KEY, {})
        name = node_info.name
        if name not in cache:
            cache[name] = self._valid_holder(node_info.node)
        holder = cache[name]
        if holder is not None and holder != pod.namespaced_name:
            return Status.unschedulable(
                f"node draining, reserved for {holder}", self.name
            )
        return Status.ok()

    def _valid_holder(self, node) -> Optional[str]:
        holder = node.metadata.annotations.get(RESERVED_FOR, "")
        if not holder:
            return None
        try:
            ts = float(node.metadata.annotations.get(RESERVED_AT, "0") or 0)
        except ValueError:
            ts = 0.0
        if time.time() - ts > self.ttl:
            return None
        ns, _, name = holder.partition("/")
        pod = self.store.try_get("Pod", name, ns)
        if (
            pod is None
            or pod.spec.node_name
            or pod.status.phase != PodPhase.PENDING
        ):
            return None
        return holder

    # ----------------------------------------------------------- reserve

    def try_reserve(self, pod: Pod, node_infos: Dict[str, NodeInfo]) -> bool:
        """Called when `pod` came out of a cycle unschedulable with no
        preemption nomination. Reserves at most one node; no-op unless the
        request is fragmentation-prone (>= a full board) and genuinely
        blocked (no node has re-carvable headroom)."""
        age = time.time() - pod.metadata.creation_timestamp
        if age < self.min_wait:
            return False
        needed = res.tpu_chips_in(res.compute_pod_request(pod))
        if needed <= 0:
            return False
        key = pod.namespaced_name
        # Single-drain policy: at most one node drains cluster-wide.
        # Full-board pods queue through the one drained board (and reuse
        # it back-to-back); concurrent drains multiply idle chips for no
        # extra throughput.
        other_drain = False
        best = None  # (running chips, name, node)
        for info in sorted(node_infos.values(), key=lambda i: i.name):
            node = info.node
            if node.metadata.labels.get(labels.PARTITIONING_LABEL) not in (
                labels.PartitioningKind.TPU,
                labels.PartitioningKind.HYBRID,
            ):
                continue
            capacity = int(node.status.capacity.get(constants.RESOURCE_TPU, 0))
            if capacity < needed:
                continue
            layouts = board_layout(
                node.metadata.labels.get(labels.GKE_TPU_ACCELERATOR_LABEL, ""),
                capacity,
            )
            if not layouts:
                continue
            board_chips = max(Topology(t).chips for t in layouts)
            if needed < board_chips:
                # Sub-board fragments re-carve out of normal churn; a
                # reservation would idle chips for nothing.
                continue
            running = sum(
                res.tpu_chips_in(res.compute_pod_request(p)) for p in info.pods
            )
            if capacity - running >= needed:
                # Enough re-carvable headroom already exists somewhere:
                # the partitioner will serve this pod without a drain.
                return False
            if any(p.status.phase == PodPhase.PENDING for p in info.pods):
                # A pending pod on the node means an in-flight gang/assume
                # claim: the node is contested, not draining — reserving it
                # would deadlock two formations against each other.
                continue
            holder = self._valid_holder(node)
            if holder == key:
                # Already reserved by this pod; refresh the TTL when half
                # spent so a slow drain is not stolen mid-way.
                try:
                    ts = float(
                        node.metadata.annotations.get(RESERVED_AT, "0") or 0
                    )
                except ValueError:
                    ts = 0.0
                if time.time() - ts > self.ttl / 2:
                    self._annotate(node.metadata.name, key)
                return True
            if holder is not None:
                other_drain = True
                continue  # validly held by another pod
            if best is None or (running, info.name) < best[:2]:
                best = (running, info.name, node)
        if best is None or other_drain:
            return False
        _, node_name, _ = best
        self._annotate(node_name, key)
        metrics.BOARD_RESERVATIONS.inc()
        log.info(
            "scheduler: reserved %s for %s (%d chips need a drained board)",
            node_name,
            key,
            needed,
        )
        return True

    def _annotate(self, node_name: str, holder: str) -> None:
        try:
            self.store.patch_annotations(
                "Node",
                node_name,
                "",
                {RESERVED_FOR: holder, RESERVED_AT: str(time.time())},
            )
        except NotFoundError:
            pass

    # ----------------------------------------------------------- release

    def release_invalid(self) -> int:
        """Clear reservation annotations whose holder is no longer valid
        (holder deleted/bound/finished, or TTL expired).

        ``release_for`` only fires on bind; a holder that dies instead —
        evicted with its node, deleted by its owner — used to leave the
        annotation on the node forever. The filter tolerates that (an
        invalid reservation rejects nobody), but the stale annotation costs
        a holder lookup per node per cycle and reads as a live drain to
        operators and oracles. The janitor controller calls this on pod
        deletions/phase changes and on a TTL timer."""
        cleared = 0
        for node in self.store.list("Node"):
            if RESERVED_FOR not in node.metadata.annotations:
                continue
            if self._valid_holder(node) is not None:
                continue
            try:
                self.store.patch_annotations(
                    "Node",
                    node.metadata.name,
                    "",
                    {RESERVED_FOR: None, RESERVED_AT: None},
                )
            except NotFoundError:
                continue
            cleared += 1
            log.info(
                "scheduler: cleared orphaned reservation on %s (holder %s "
                "no longer valid)",
                node.metadata.name,
                node.metadata.annotations.get(RESERVED_FOR, ""),
            )
        return cleared

    def any_reserved(self) -> bool:
        return any(
            RESERVED_FOR in n.metadata.annotations for n in self.store.list("Node")
        )

    def release_for(self, pod: Pod) -> None:
        """Clear any reservation held by `pod` (called on bind; deletion
        and phase changes fall back to holder-validity + TTL)."""
        key = pod.namespaced_name
        for node in self.store.list("Node"):
            if node.metadata.annotations.get(RESERVED_FOR) == key:
                try:
                    self.store.patch_annotations(
                        "Node",
                        node.metadata.name,
                        "",
                        {RESERVED_FOR: None, RESERVED_AT: None},
                    )
                except NotFoundError:
                    pass
                log.info(
                    "scheduler: released reservation of %s held by %s",
                    node.metadata.name,
                    key,
                )


class AutoscalerGraceScoring:
    """Cold-start grace reservations steer placement softly: a node a
    scaled-to-zero model vacated stays carved for that model's return
    (annot.AUTOSCALER_RESERVED, written by the model autoscaler). The
    returning model's replicas score highest there — the cold start
    re-lands on a board that needs no re-carve — while unrelated pods
    prefer unreserved nodes, so the grace hold is not silently consumed
    the moment anything else scales up. A score, not a filter: under
    genuine pressure the reserved board is still usable, the hold only
    loses ties. Expiry is the autoscaler's sweep's job — scoring reads no
    clock, keeping cycles replayable."""

    name = "AutoscalerGraceScore"

    def score(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> int:
        holder = node_info.node.metadata.annotations.get(
            annot.AUTOSCALER_RESERVED, ""
        )
        if not holder:
            return 30
        if pod.metadata.labels.get(labels.MODEL_SERVING_LABEL, "") == holder:
            return 50
        return 0
