"""CapacityScheduling: elastic-quota enforcement + fair-share preemption.

Reference pkg/scheduler/plugins/capacityscheduling/capacity_scheduling.go
(PreFilter :190-278, PostFilter/preemption :323-341 + :468-675, Reserve
:343-369) and elasticquotainfo.go:30-361. Quota semantics:

- a namespace may always use up to its guaranteed ``min``;
- it may *borrow* beyond min up to ``max``, but only from the cluster-wide
  pool of unused guaranteed quota (the aggregated-min check);
- pods running beyond min are labeled over-quota by the operator and are
  preemptible by namespaces still below their guaranteed share, where the
  guaranteed share includes the fair redistribution of unused min:
  guaranteed_overquota_i = floor(min_i/Σmin · Σ_j max(0, min_j - used_j))
  (elasticquotainfo.go:81-152).
"""
from __future__ import annotations

import logging
import math
from typing import Dict, List, Optional, Set

from nos_tpu.kube.objects import Pod, PodPhase, ResourceList
from nos_tpu.kube.store import KubeStore
from nos_tpu.scheduler.framework import CycleState, Status
from nos_tpu.util import resources as res

log = logging.getLogger("nos_tpu.scheduler.capacity")

STATE_KEY = "capacity-scheduling"


class ElasticQuotaInfo:
    def __init__(
        self,
        name: str,
        namespaces: Set[str],
        min_resources: ResourceList,
        max_resources: Optional[ResourceList],
    ) -> None:
        self.name = name
        self.namespaces = set(namespaces)
        self.min = dict(min_resources)
        self.max = dict(max_resources) if max_resources else None
        self.used: ResourceList = {}
        self.pods: Set[str] = set()

    # ------------------------------------------------------- accounting

    def add_pod(self, key: str, request: ResourceList) -> None:
        if key in self.pods:
            return
        self.pods.add(key)
        self.used = res.sum_resources(self.used, request)

    def remove_pod(self, key: str, request: ResourceList) -> None:
        if key not in self.pods:
            return
        self.pods.discard(key)
        self.used = res.subtract_resources(self.used, request)

    # ----------------------------------------------------------- checks

    def used_over_min_with(self, request: ResourceList) -> bool:
        return any(
            self.used.get(k, 0) + v > self.min.get(k, 0)
            for k, v in request.items()
            if k in self.min
        )

    def is_borrowing(self) -> bool:
        """Using beyond guaranteed min for any tracked resource — the quota
        is living on borrowed capacity (capacity_scheduling.go:566-581)."""
        return any(self.used.get(k, 0) > v for k, v in self.min.items())

    def used_over_max_with(self, request: ResourceList) -> bool:
        if self.max is None:
            return False
        return any(
            self.used.get(k, 0) + v > self.max[k]
            for k, v in request.items()
            if k in self.max
        )

    def clone(self) -> "ElasticQuotaInfo":
        c = ElasticQuotaInfo(self.name, self.namespaces, self.min, self.max)
        c.used = dict(self.used)
        c.pods = set(self.pods)
        return c


class ElasticQuotaInfos:
    def __init__(self, infos: List[ElasticQuotaInfo]) -> None:
        self._infos = {i.name: i for i in infos}
        self._by_namespace: Dict[str, ElasticQuotaInfo] = {}
        for info in infos:
            for ns in info.namespaces:
                self._by_namespace[ns] = info

    def __iter__(self):
        return iter(self._infos.values())

    def get(self, name: str) -> Optional[ElasticQuotaInfo]:
        return self._infos.get(name)

    def for_namespace(self, ns: str) -> Optional[ElasticQuotaInfo]:
        return self._by_namespace.get(ns)

    def clone(self) -> "ElasticQuotaInfos":
        return ElasticQuotaInfos([i.clone() for i in self._infos.values()])

    # -------------------------------------------------- aggregate math

    def aggregated_min(self, resource: str) -> float:
        return sum(i.min.get(resource, 0) for i in self._infos.values())

    def aggregated_used(self, resource: str) -> float:
        return sum(i.used.get(resource, 0) for i in self._infos.values())

    def aggregated_used_over_min_with(self, request: ResourceList) -> bool:
        """True when serving `request` would push cluster-wide usage of any
        quota-tracked resource beyond the sum of guaranteed minimums — i.e.
        the borrowing pool is exhausted (capacity_scheduling.go:268-275)."""
        for resource, qty in request.items():
            agg_min = self.aggregated_min(resource)
            if agg_min == 0:
                continue
            if self.aggregated_used(resource) + qty > agg_min:
                return True
        return False

    def guaranteed_overquota(self, name: str, resource: str) -> float:
        """floor(min_i/Σmin · Σ_j max(0, min_j-used_j)) — quota `name`'s fair
        share of currently-unused guaranteed capacity
        (elasticquotainfo.go:81-152)."""
        info = self._infos.get(name)
        if info is None:
            return 0
        agg_min = self.aggregated_min(resource)
        if agg_min == 0:
            return 0
        unused = sum(
            max(0.0, i.min.get(resource, 0) - i.used.get(resource, 0))
            for i in self._infos.values()
        )
        return math.floor(info.min.get(resource, 0) / agg_min * unused)

    def used_over_entitled(self, name: str) -> bool:
        """used > min + guaranteed_overquota for any tracked resource: the
        quota holds more than its fair entitlement and is preemptible by an
        entitled borrower (capacity_scheduling.go:556-563)."""
        info = self._infos.get(name)
        if info is None:
            return False
        return any(
            info.used.get(k, 0) > info.min.get(k, 0) + self.guaranteed_overquota(name, k)
            for k in info.min
        )

    def within_guaranteed_with(self, name: str, request: ResourceList) -> bool:
        """used+request ≤ min + guaranteed_overquota for every requested
        quota resource: the preemptor is entitled to this capacity."""
        info = self._infos.get(name)
        if info is None:
            return False
        for resource, qty in request.items():
            if resource not in info.min:
                continue
            entitled = info.min.get(resource, 0) + self.guaranteed_overquota(name, resource)
            if info.used.get(resource, 0) + qty > entitled:
                return False
        return True


def build_quota_infos(
    store: KubeStore, chip_memory_gb: "int | None" = None
) -> ElasticQuotaInfos:
    """Informer-bridge analogue (capacityscheduling/informer.go:57-300):
    CEQs cover their namespace lists and shadow per-namespace EQs; usage is
    rebuilt from pods bound to nodes."""
    infos: List[ElasticQuotaInfo] = []
    covered: Set[str] = set()
    for ceq in store.list("CompositeElasticQuota"):
        infos.append(
            ElasticQuotaInfo(
                name=f"ceq/{ceq.metadata.name}",
                namespaces=set(ceq.spec.namespaces),
                min_resources=ceq.spec.min,
                max_resources=ceq.spec.max or None,
            )
        )
        covered.update(ceq.spec.namespaces)
    for eq in store.list("ElasticQuota"):
        if eq.metadata.namespace in covered:
            continue
        infos.append(
            ElasticQuotaInfo(
                name=f"eq/{eq.metadata.namespace}/{eq.metadata.name}",
                namespaces={eq.metadata.namespace},
                min_resources=eq.spec.min,
                max_resources=eq.spec.max or None,
            )
        )
    result = ElasticQuotaInfos(infos)
    for pod in store.list("Pod"):
        if not pod.spec.node_name or pod.status.phase not in (
            PodPhase.PENDING,
            PodPhase.RUNNING,
        ):
            continue
        info = result.for_namespace(pod.metadata.namespace)
        if info is not None:
            info.add_pod(
                pod.namespaced_name,
                quota_request(pod, chip_memory_gb),
            )
    return result


def quota_request(pod: Pod, chip_memory_gb: "int | None" = None) -> ResourceList:
    """Pod request with the aggregate chip resource injected, so quotas can
    be expressed in nos.nebuly.com/tpu-chips (the reference injects
    nos.nebuly.com/gpu-memory, pkg/gpu/util/resource.go:60-86).
    `chip_memory_gb` is the CapacitySchedulingArgs knob (reference
    pkg/api/scheduler/types.go NvidiaGpuResourceMemoryGB)."""
    from nos_tpu.api.v1alpha1 import constants

    return res.with_aggregate_tpu_chips(
        res.compute_pod_request(pod),
        chip_memory_gb or constants.DEFAULT_TPU_CHIP_MEMORY_GB,
    )


class CapacityScheduling:
    name = "CapacityScheduling"

    def __init__(self, store: KubeStore, chip_memory_gb: "int | None" = None) -> None:
        self.store = store
        # CapacitySchedulingArgs knob (reference pkg/api/scheduler/types.go).
        self.chip_memory_gb = chip_memory_gb
        # Reservations in flight (bound this cycle but possibly not yet
        # re-listed): quota name -> pod key -> request.
        self._reserved: Dict[str, Dict[str, ResourceList]] = {}
        # Quota usage charged OUTSIDE this store's visibility: quota name
        # -> synthetic pod key -> request. A pool-planner worker process
        # only replicates its own pool's bound pods, so the parent ships
        # the out-of-pool aggregates here each cycle and snapshot() folds
        # them exactly like in-flight reservations.
        self._external: Dict[str, Dict[str, ResourceList]] = {}

    # -------------------------------------------------------- snapshot

    def snapshot(self) -> ElasticQuotaInfos:
        infos = build_quota_infos(self.store, self.chip_memory_gb)
        for reserved in (self._reserved, self._external):
            for quota_name, pods in reserved.items():
                info = infos.get(quota_name)
                if info is None:
                    continue
                for key, request in pods.items():
                    info.add_pod(key, request)
        return infos

    def set_external_usage(
        self, usage: "Dict[str, Dict[str, int]]"
    ) -> None:
        """Replace the externally-charged usage wholesale (per cycle, from
        the wire): ``{quota name: {resource: quantity}}``. Each quota's
        aggregate is folded as one synthetic pod so the arithmetic path is
        identical to reservations."""
        self._external = {
            quota_name: {f"__external__/{quota_name}": dict(request)}
            for quota_name, request in usage.items()
            if request
        }

    # -------------------------------------------------------- prefilter

    def pre_filter(self, state: CycleState, pod: Pod) -> Status:
        infos = self.snapshot()
        state[STATE_KEY] = infos
        return self.check_quota(pod, infos, self.chip_memory_gb)

    @staticmethod
    def check_quota(
        pod: Pod, infos: ElasticQuotaInfos, chip_memory_gb: "int | None" = None
    ) -> Status:
        """The quota admission decision, reusable against simulated infos
        (preemption evaluates victims by re-running this)."""
        info = infos.for_namespace(pod.metadata.namespace)
        if info is None:
            return Status.ok()
        request = quota_request(pod, chip_memory_gb)
        tracked = {
            k: v for k, v in request.items() if k in info.min or (info.max and k in info.max)
        }
        if not tracked:
            return Status.ok()
        if info.used_over_max_with(request):
            return Status.unschedulable(
                f"quota {info.name}: max exceeded", CapacityScheduling.name
            )
        if info.used_over_min_with(request) and infos.aggregated_used_over_min_with(
            {k: v for k, v in request.items() if k in info.min}
        ):
            return Status.unschedulable(
                f"quota {info.name}: cluster guaranteed pool exhausted",
                CapacityScheduling.name,
            )
        return Status.ok()

    # --------------------------------------------------------- reserve

    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        infos = state.get(STATE_KEY) or self.snapshot()
        info = infos.for_namespace(pod.metadata.namespace)
        if info is not None:
            self._reserved.setdefault(info.name, {})[pod.namespaced_name] = quota_request(pod, self.chip_memory_gb)
        return Status.ok()

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        self.forget_key(pod.namespaced_name)

    def forget(self, pod: Pod) -> None:
        """Drop any reservation once the pod is visibly bound in the store."""
        self.forget_key(pod.namespaced_name)

    def forget_key(self, key: str) -> None:
        """Drop a reservation by pod key — for pods that vanished from the
        store entirely (deleted before their bound state was ever observed):
        without this, the in-flight reservation leaks and inflates the
        quota's used forever."""
        for pods in self._reserved.values():
            pods.pop(key, None)

    # ------------------------------------------------------ postfilter

    def post_filter(
        self, state: CycleState, pod: Pod, filtered_nodes: Dict[str, Status]
    ) -> Optional[str]:
        """Preemption: find a node where evicting eligible victims makes the
        pod schedulable; evict them and nominate the node."""
        from nos_tpu.scheduler.preemption import Preemptor

        infos: ElasticQuotaInfos = state.get(STATE_KEY) or self.snapshot()
        preemptor = Preemptor(self.store, self, infos)
        return preemptor.preempt(state, pod, filtered_nodes)
