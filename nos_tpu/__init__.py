"""nos_tpu — TPU-native dynamic partitioning, elastic quotas and capacity scheduling.

A from-scratch rebuild of the capability set of nebuly-ai/nos (reference at
/root/reference, surveyed in SURVEY.md) for Google TPUs: a cluster-scope
partitioner carves TPU pods into ICI-valid slice topologies in real time from
pending Pods' ``google.com/tpu`` requests; a node-local tpuagent reports and
actuates slice state; an ICI-topology-aware scheduler plugin enforces elastic
quotas and gang-schedules multi-host JAX jobs.
"""

__version__ = "0.1.0"
