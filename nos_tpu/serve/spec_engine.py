"""Speculative continuous batching: draft lookahead inside the engine.

Combines the two serving accelerators: continuous batching (all slots
share each target weight read) and speculative decoding (each target
read commits up to k+1 tokens per row). Every scheduling round runs ONE
jitted speculative round over the whole batch — the draft scans k
cheap steps, the target verifies the chain in one ``decode_chunk``, and
per-row acceptance advances each slot at its own pace (models/
speculative.py holds the round math; this module gives it slots,
admission, and the sync-horizon chaining of serve/engine.py).

Differences from the base Engine, all forced by the round math:
- Admission is ALWAYS chunked (physical == logical positions; the
  speculative round has no left-pad notion), and each admission also
  ingests the prompt into a per-slot DRAFT KV cache — the draft cache
  invariant (holds every committed token but the last) starts true.
- Greedy only: speculative acceptance is defined against the target's
  argmax. ``temperature > 0`` is rejected at submit.
- A slot's physical frontier can overshoot its budget by up to k per
  round, so capacity is prompt + budget + k + 1 (enforced at submit);
  finished riders clamp at max_len - k - 1 exactly like
  ``speculative_generate``.

The per-round accepted counts are data-dependent, so the host cannot
mirror positions arithmetically: each horizon's single pull returns the
device positions alongside the committed tokens.
"""
from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from nos_tpu.models.generate import decode_chunk, init_kv_cache
from nos_tpu.models.llama import LlamaConfig
from nos_tpu.models.speculative import _spec_round
from nos_tpu.serve.engine import Engine, GenRequest
from nos_tpu.util import metrics


class SpecEngine(Engine):
    """Engine whose decode path is speculative rounds over a draft model.

    ``run``/``submit``/``step`` keep the base contracts; completions are
    the TARGET's greedy tokens (up to chunk-vs-step float drift on
    near-tied argmaxes — the speculative contract), so a good draft only
    adds speed and a bad one only costs it. ``stats()`` reports rounds
    and mean accepted drafts per active row-round."""

    def __init__(
        self,
        params,
        config: LlamaConfig,
        draft_params,
        draft_config: LlamaConfig,
        k: int = 4,
        **kwargs,
    ) -> None:
        if kwargs.get("rolling"):
            raise ValueError(
                "rolling cache is not supported with speculation (the "
                "round's chunk verify assumes physical == logical)"
            )
        if kwargs.get("kv_quant"):
            raise ValueError(
                "int8 KV cache is not wired for speculation (acceptance "
                "compares target logits tick-for-tick; quantization "
                "noise would silently change what 'match' means)"
            )
        from nos_tpu.models.lora import n_adapters

        if n_adapters(params) or n_adapters(draft_params):
            raise ValueError(
                "multi-tenant LoRA is not supported with speculation "
                "(the jitted round closes over the param tree at init, "
                "so per-admission adapter re-pointing cannot reach it)"
            )
        super().__init__(params, config, **kwargs)
        self.d_params = draft_params
        self.d_config = draft_config
        self.k = k
        # One speculative round commits 1..k+1 tokens per row;
        # _sync_horizon chains the GUARANTEED round count, so the
        # divisor is the full-acceptance commit size.
        self._tokens_per_sync = k + 1
        # The deepest draft write (the d_k ingest at pos+k) lands at
        # max_len-1: live rows by the submit-time capacity check, riders
        # by step()'s clamp to max_len-k-1. Same length as the target's.
        self._d_cache = init_kv_cache(draft_config, self.slots_n, self.max_len)
        self._round = jax.jit(
            _spec_round(params, draft_params, config, draft_config, k),
            donate_argnums=(0, 1),
        )

        def _d_ingest(d_params, row_cache, start, piece, mask):
            return decode_chunk(
                d_params, row_cache, start, piece, draft_config,
                write_mask=mask,
            )

        self._d_ingest = jax.jit(_d_ingest, donate_argnums=(1,))

        def _d_splice(cache, row_cache, b):
            return [
                {
                    key: jax.lax.dynamic_update_slice(
                        layer[key],
                        row[key][:, : self.max_len],
                        (b, 0, 0, 0),
                    )
                    for key in ("k", "v")
                }
                for layer, row in zip(cache, row_cache)
            ]

        self._d_splice = jax.jit(_d_splice, donate_argnums=(0,))
        self.rounds = 0
        self._accepted_total = 0
        self._active_row_rounds = 0

    # ---------------------------------------------------------- frontend

    def submit(
        self, request: GenRequest, submit_at: "float | None" = None
    ) -> int:
        if request.temperature > 0:
            raise ValueError(
                "speculative acceptance is defined against the target's "
                "argmax; sampling requests need the base Engine"
            )
        request.id = next(self._ids)
        self._validate_submit(
            request, len(request.prompt) + request.max_new_tokens + self.k + 1
        )
        self._queue.append(request)
        self.telemetry.on_submit(
            request, self._bucket(len(request.prompt)), submit_at=submit_at
        )
        metrics.SERVE_QUEUE_DEPTH.set(len(self._queue))
        return request.id

    def stats(self) -> dict:
        return {
            "rounds": self.rounds,
            "mean_accepted": self._accepted_total
            / max(1, self._active_row_rounds),
        }

    # -------------------------------------------------------- admission

    def _admit(self, b: int, request: GenRequest) -> None:
        # Chunked target admission (physical == logical, prefix cache
        # applies); then the SAME prompt ingests into the draft row
        # through the shared piece loop.
        self._admit_chunked(b, request)
        prompt = list(request.prompt)
        n = min(self.prefill_chunk, self._bucket(len(prompt)))
        row = init_kv_cache(self.d_config, 1, self.max_len + 1)
        with self.telemetry.prefill_span(request, len(prompt), "draft"):
            _, row = self._ingest_pieces(
                self._d_ingest, self.d_params, row, prompt, n
            )
        self._d_cache = self._d_splice(
            self._d_cache, row, jnp.asarray(b, jnp.int32)
        )

    # ------------------------------------------------------------- tick

    def step(self, chunks: "int | None" = 1) -> None:
        for b in range(self.slots_n):
            if self._slots[b] is None and self._queue:
                request = self._queue.pop(0)
                with self.telemetry.admit_span(request):
                    self._admit(b, request)
        # Speculative rounds sync every horizon anyway (counts are
        # data-dependent); admission firsts always resolve eagerly.
        self._resolve_admissions()
        for b in range(self.slots_n):
            self._retire(b)
        if not any(s is not None for s in self._slots):
            return
        rounds = self._sync_horizon() if chunks is None else max(1, chunks)
        self.ticks += rounds
        self.rounds += rounds
        live = [b for b in range(self.slots_n) if self._slots[b] is not None]
        with self.telemetry.decode_span(rounds, len(live)):
            pos = jnp.asarray(self._pos)
            last = jnp.asarray(self._last)
            # Idle slots must not claim MoE expert capacity (their rows are
            # garbage); a slot finishing MID-horizon keeps its flag for the
            # remaining chained rounds — bounded, and exact whenever
            # capacity is overflow-free (the serving contract).
            row_valid = jnp.asarray(
                [s is not None and not s.done for s in self._slots]
            )
            outs: List[jax.Array] = []
            counts: List[jax.Array] = []
            for _ in range(rounds):
                # Finished riders advance up to k+1 per round; the clamp
                # keeps their chunk writes in-bounds (live rows never reach
                # it by the submit-time capacity check).
                pos = jnp.minimum(pos, self.max_len - self.k - 1)
                (self._cache, self._d_cache, pos, last,
                 _, out, count) = self._round(
                    self._cache, self._d_cache, pos, last, row_valid
                )
                outs.append(out)
                counts.append(count)
            pulled = jax.device_get([pos, last] + outs + counts)
        pos_np, last_np = pulled[0], pulled[1]
        outs_np = pulled[2:2 + rounds]
        counts_np = pulled[2 + rounds:]
        # Virtual-clock cost: one speculative round is the decode unit.
        self.telemetry.on_decode_ticks(rounds)
        metrics.SERVE_TICKS.inc(rounds)
        metrics.SERVE_SLOT_TICKS_ACTIVE.inc(rounds * len(live))
        metrics.SERVE_QUEUE_DEPTH.set(len(self._queue))
        self._pos = pos_np.astype(np.int32).copy()
        self._rope = self._pos.copy()  # chunked path: logical == physical
        self._last = last_np.astype(np.int32).copy()
        row_rounds = 0
        accepted = 0
        for r in range(rounds):
            for b in live:
                slot = self._slots[b]
                if slot.done:
                    continue
                row_rounds += 1
                committed = int(counts_np[r][b])
                accepted += committed - 1
                for j in range(committed):
                    if slot.done:
                        break
                    self._emit(b, int(outs_np[r][b, j]))
        self._active_row_rounds += row_rounds
        self._accepted_total += accepted
        if row_rounds:
            metrics.SERVE_SPEC_ROUNDS.inc(row_rounds)
            metrics.SERVE_SPEC_DRAFT_TOKENS.inc(row_rounds * self.k)
            metrics.SERVE_SPEC_ACCEPTED_TOKENS.inc(accepted)
        for b in live:
            self._retire(b)
        for b in range(self.slots_n):
            if self._slots[b] is None:
                self._pos[b] = 0
                self._rope[b] = 0
