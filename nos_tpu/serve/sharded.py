"""Tensor-parallel serving: one Engine spanning a tp device mesh.

A carved multi-chip slice (the partitioner's product) serves one model
replica larger or faster than a single chip allows. The engine's host
scheduling loop is unchanged — tensor parallelism enters purely through
array placement: params shard Megatron-style (parallel/sharding.py) and
the KV cache shards its HEAD axis over tp, so every attention head's
cache row lives with the chips that compute it. XLA inserts the one
per-layer psum on the residual path from the NamedShardings; decode,
prefill, splice, and sampling all run SPMD with zero code changes in
the engine (the reference has no serving stack — SURVEY.md §5 maps the
workload layer to the TPU build's own ground).

Usage::

    mesh = mesh_from_devices((tp,), ("tp",), jax.devices()[:tp])
    params = shard_for_serving(params, mesh, config)
    eng = Engine(params, config, mesh=mesh, ...)

Works with dense bf16 trees and int8/int4 quantized trees
(quantize_params / quantize_params_int4) alike.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
from jax.sharding import Mesh, NamedSharding

from nos_tpu.models.llama import LlamaConfig


def kv_cache_sharding(mesh: Mesh, config: LlamaConfig) -> NamedSharding:
    """KV cache rows [slots, max_len, Hkv, hd] shard the head axis over
    tp — attention is head-local, so cache reads/writes never cross
    chips. tp must divide the KV head count (GQA replicates query heads
    onto their KV shard automatically via the wq sharding)."""
    from nos_tpu.parallel.mesh import partition_spec

    tp = mesh.shape.get("tp", 1)
    if config.n_kv_heads % tp:
        raise ValueError(
            f"tp={tp} must divide n_kv_heads={config.n_kv_heads} "
            f"(head-sharded KV cache)"
        )
    return NamedSharding(mesh, partition_spec(mesh, None, None, "tp", None))


def _is_quantized(params: Dict[str, Any]) -> bool:
    from nos_tpu.models.quantize import (
        QuantizedEmbedding,
        QuantizedLinear,
        QuantizedLinear4,
    )

    return isinstance(
        params.get("embed"),
        (QuantizedLinear, QuantizedLinear4, QuantizedEmbedding),
    )


def shard_for_serving(
    params: Dict[str, Any], mesh: Mesh, config: LlamaConfig
) -> Dict[str, Any]:
    """device_put the param tree with its serving sharding: dense trees
    use the Megatron rules, quantized trees the scale-aware rules (the
    int4 group size is read off the tree so packing and placement can't
    disagree)."""
    from nos_tpu.models.quantize import QuantizedLinear4
    from nos_tpu.parallel.sharding import (
        llama_param_sharding,
        llama_quantized_sharding,
    )

    if _is_quantized(params):
        q4 = [
            leaf
            for leaf in jax.tree.leaves(
                params, is_leaf=lambda x: isinstance(x, QuantizedLinear4)
            )
            if isinstance(leaf, QuantizedLinear4)
        ]
        if q4:
            sharding = llama_quantized_sharding(
                mesh, config, bits=4, group=q4[0].group
            )
        else:
            sharding = llama_quantized_sharding(mesh, config, bits=8)
    else:
        sharding = llama_param_sharding(mesh, config)
    return jax.device_put(params, sharding)
