"""Continuous-batching serving engine.

The slot-level scheduler a serving replica runs on its carved slice:
requests with different prompt lengths and generation budgets share one
fixed-shape batched decode program. A finishing request frees its slot
mid-flight and the next queued request is admitted without draining the
batch — decode utilization stays near the slot count instead of sawtoothing
to the slowest member (the reference has no serving stack; this implements
the workload the sharing demo and BASELINE's serving north star describe).

TPU-first mechanics, all static shapes:
- One KV cache of [slots, max_len, Hkv, hd] per layer; each row decodes at
  its own depth via per-row scatter writes and a per-row attention
  frontier (models/generate.decode_step with pos [B]).
- Admission prefills a single row (left-padded to a power-of-two bucket,
  one compiled prefill per bucket) and splices its K/V rows into the
  batch cache at the free slot — running rows are untouched.
- Decode is ONE jitted per-row step for all slots every tick; idle slots
  ride along fully masked (their attention sees zero valid keys), so the
  program never recompiles as traffic changes.
- Multi-step scheduling: ``ticks_per_sync`` decode ticks run inside one
  ``lax.scan`` dispatch before the host sees the tokens — dispatch/sync
  latency (PCIe, or a whole network RTT on tunneled chips) amortizes over
  the chunk instead of taxing every token. A request finishing mid-chunk
  wastes at most ticks_per_sync-1 ticks of its own slot; its tokens are
  trimmed host-side and the slot frees at the chunk boundary.
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from nos_tpu.models.generate import (
    decode_chunk,
    decode_step,
    pick_tokens_per_row,
    prefill,
)
from nos_tpu.models.llama import LlamaConfig
from nos_tpu.serve.telemetry import ServeClock, ServeTelemetry
from nos_tpu.util import metrics

# Left-pad bucket: token id that can never appear in a real prompt.
PAD_ID = -1


@dataclass
class GenRequest:
    prompt: List[int]
    max_new_tokens: int
    eos_id: Optional[int] = None
    # Sampling (per request, rows mix freely in one batch): greedy when
    # temperature == 0; otherwise temperature sampling with optional
    # top-k / nucleus filtering. Sampled streams draw from the engine's
    # key sequence, so they are reproducible per (engine seed, request
    # id) but not bitwise equal to a solo generate() run.
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    # Streaming: called as on_token(request_id, token) for each emitted
    # token, from the host thread at sync points. A streaming slot
    # bounds the sync horizon (like eos_id), so bursts are at most a
    # few ticks_per_sync chunks — tune ticks_per_sync down for lower
    # streaming latency, up for throughput. Trimmed surplus (post-EOS /
    # post-budget ride-along) is never delivered.
    on_token: Optional[Callable[[int, int], None]] = None
    # Multi-tenant LoRA (engine built over stack_lora_adapters): which
    # stacked adapter this request's rows apply; 0 = the bare base.
    adapter: int = 0
    id: int = -1


@dataclass
class _Slot:
    request: GenRequest
    out: List[int] = field(default_factory=list)
    done: bool = False


@dataclass
class Completion:
    id: int
    tokens: List[int]


class Engine:
    """Greedy continuous-batching engine over a fixed slot count.

    ``submit`` enqueues; ``step`` admits + decodes one tick; ``run`` drains
    everything and returns completions keyed by request id.
    """

    def __init__(
        self,
        params,
        config: LlamaConfig,
        max_slots: int = 4,
        max_len: int = 512,
        ticks_per_sync: int = 8,
        prefill_chunk: int = 256,
        seed: int = 0,
        prefix_cache_entries: int = 0,
        mesh=None,
        rolling: bool = False,
        kv_quant: bool = False,
        model: str = "default",
        telemetry: Optional[ServeTelemetry] = None,
        clock: Optional[ServeClock] = None,
    ) -> None:
        self.params = params
        self.config = config
        # Per-request observability (serve/telemetry.py): journey spans +
        # submit/admit/first-token/retire stamps + latency histograms.
        # ``model`` labels this replica's series; ``clock`` swaps the
        # wall clock for a virtual one (the deterministic bench driver).
        self.telemetry = telemetry or ServeTelemetry(model=model, clock=clock)
        # Tensor-parallel serving (serve/sharded.py): params arrive
        # sharded (shard_for_serving) and the KV cache shards its head
        # axis here; everything else is ordinary SPMD propagation.
        self.mesh = mesh
        # Rolling sliding-window cache: physical slot = logical position
        # mod C (C = max_len - 1; the last slot stays the chunked
        # ingest's pad target), so prompt + budget are UNBOUNDED — a
        # stream of any length serves from O(window) HBM. Requires a
        # sliding_window config; incompatible with the prefix cache
        # (cached segments assume physical == logical).
        self.rolling = rolling
        # int8 KV cache: half the cache HBM and decode read bandwidth;
        # dequant folds into attention (see models/generate.init_kv_cache).
        # Lossy by design — tokens can drift from the bf16-cache engine
        # on near-tie logits, the standard KV-quant tradeoff.
        self.kv_quant = kv_quant
        if kv_quant and mesh is not None:
            raise ValueError(
                "kv_quant + mesh is not wired (the scale arrays need "
                "their own head-sharding rules); pick one"
            )
        if rolling:
            if config.sliding_window is None:
                raise ValueError("rolling cache requires a sliding_window config")
            if prefix_cache_entries:
                raise ValueError(
                    "prefix cache assumes physical == logical positions; "
                    "disable it with rolling=True"
                )
            if max_len - 1 < config.sliding_window + 8:
                # 8 = the minimum ingest piece width (_bucket floor)
                raise ValueError(
                    f"rolling cache needs max_len - 1 >= sliding_window + 8 "
                    f"({max_len - 1} < {config.sliding_window + 8})"
                )
            # a chunk's writes must never evict keys its own queries
            # still need: C >= window + piece width
            prefill_chunk = min(
                prefill_chunk, max_len - 1 - config.sliding_window
            )
        self.slots_n = max_slots
        self.max_len = max_len
        self.ticks_per_sync = max(1, ticks_per_sync)
        # Tokens a slot is guaranteed per inner dispatch — what
        # _sync_horizon divides budgets by. Subclasses with a different
        # decode round (SpecEngine: k+1 per speculative round) override
        # this instead of the horizon policy.
        self._tokens_per_sync = self.ticks_per_sync
        # Prompts whose bucket exceeds this ingest via fixed-size
        # decode_chunk pieces (O(chunk x T) peak attention memory instead
        # of the one-shot prefill's O(bucket^2)).
        self.prefill_chunk = max(8, prefill_chunk)
        # Prefix cache (chunked path only — its positions are
        # physical==logical, so K/V for a shared prompt prefix is exact
        # for every request repeating it; the padded path's left-pad
        # breaks that alignment). LRU over completed chunk-boundary
        # prefixes; 0 disables. Prefill is deterministic, so a hit is
        # bitwise identical to recomputation — greedy parity holds.
        self.prefix_cache_entries = prefix_cache_entries
        from collections import OrderedDict

        self._prefix_cache: "OrderedDict[tuple, list]" = OrderedDict()
        c = config
        from nos_tpu.models.generate import init_kv_cache

        if mesh is not None:
            from nos_tpu.serve.sharded import kv_cache_sharding

            ns = kv_cache_sharding(mesh, config)
            # device= allocates each shard in place — a cache sized to
            # the whole mesh must never materialize unsharded on one chip
            self._cache = [
                {
                    key: jnp.zeros(arr.shape, arr.dtype, device=ns)
                    for key, arr in layer.items()
                }
                for layer in init_kv_cache(c, max_slots, max_len)
            ]
        else:
            self._cache = init_kv_cache(
                c, max_slots, max_len, quant=kv_quant
            )
        # Host-side control state (tiny; device round-trips once per tick).
        self._pos = np.zeros(max_slots, np.int32)  # next physical write slot
        self._rope = np.zeros(max_slots, np.int32)  # logical position (no pads)
        self._key_valid = np.zeros((max_slots, max_len), bool)
        self._last = np.zeros(max_slots, np.int32)
        self._temp = np.zeros(max_slots, np.float32)
        self._topk = np.zeros(max_slots, np.int32)
        self._topp = np.ones(max_slots, np.float32)
        # Per-slot PRNG streams: a request's key chain is derived ONLY from
        # (engine seed, request id), so its sampled tokens are reproducible
        # regardless of co-tenants, slot placement, or arrival order.
        self._base_key = jax.random.key(seed)
        self._row_keys = jax.vmap(
            lambda i: jax.random.fold_in(jax.random.key(seed), i)
        )(jnp.arange(max_slots))
        # Multi-tenant LoRA: with MultiLoraLinear nodes in the tree,
        # each slot selects its request's adapter; decode passes the
        # tree re-pointed at the slots' ids (weight arrays shared by
        # reference — only the [B] selector leaf changes per admission).
        from nos_tpu.models.lora import n_adapters

        self._n_adapters = n_adapters(params)
        self._adapter_rows = np.zeros(max_slots, np.int32)
        self._slots: List[Optional[_Slot]] = [None] * max_slots
        self._queue: List[GenRequest] = []
        self._done: List[Completion] = []
        self._ids = itertools.count()
        # (slot, device-scalar token) pairs from admissions this round;
        # resolved with ONE host sync per step (each eager int() pull is
        # a full network RTT on tunneled chips — r05 on-chip measurement
        # had per-admission pulls eating ~3/4 of steady-state wall time).
        self._pending_first: List[tuple] = []
        self.ticks = 0
        metrics.SERVE_SLOTS.set(max_slots)

        ticks = self.ticks_per_sync

        def _decode_greedy(params, cache, pos, last, rope, key_valid):
            def tick(carry, _):
                cache, pos, last, rope = carry
                logits, cache = decode_step(
                    params, cache, pos, last, config,
                    rope_pos=rope, key_valid=key_valid,
                    rolling=self.rolling,
                )
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (cache, pos + 1, nxt, rope + 1), nxt

            (cache, pos, last, rope), toks = jax.lax.scan(
                tick, (cache, pos, last, rope), None, length=ticks
            )
            # Control state returns as DEVICE arrays so step() can chain
            # chunk dispatches back-to-back without a host round-trip.
            return toks, cache, pos, last, rope  # toks [ticks, B]

        def _decode_sampled(
            params, cache, pos, last, rope, key_valid, temp, topk, topp, keys
        ):
            def tick(carry, _):
                cache, pos, last, rope, keys = carry
                logits, cache = decode_step(
                    params, cache, pos, last, config,
                    rope_pos=rope, key_valid=key_valid,
                    rolling=self.rolling,
                )
                both = jax.vmap(jax.random.split)(keys)  # [B, 2] keys
                nxt = pick_tokens_per_row(logits, temp, topk, topp, both[:, 1])
                return (cache, pos + 1, nxt, rope + 1, both[:, 0]), nxt

            (cache, pos, last, rope, keys), toks = jax.lax.scan(
                tick, (cache, pos, last, rope, keys), None, length=ticks
            )
            return toks, cache, pos, last, rope, keys

        # Two programs so the default all-greedy workload never pays the
        # sampling sorts; step() picks by whether any live slot samples.
        self._decode_greedy = jax.jit(_decode_greedy, donate_argnums=(1,))
        self._decode_sampled = jax.jit(_decode_sampled, donate_argnums=(1,))
        self._prefill_cache: Dict[int, object] = {}

        def _ingest(params, row_cache, start, piece, mask):
            return decode_chunk(
                params, row_cache, start, piece, config, write_mask=mask,
                rolling=self.rolling,
            )

        self._ingest = jax.jit(_ingest, donate_argnums=(1,))

        def _splice(cache, row_cache, b):
            # donated in-place row writes: without this, each of the
            # 2*n_layers eager dynamic_update_slice calls would copy the
            # whole batch cache through HBM per admission. Iterates the
            # layer's keys rank-aware so int8 caches' 3-D scale planes
            # splice alongside the K/V.
            return [
                {
                    key: jax.lax.dynamic_update_slice(
                        layer[key], row[key][:, : self.max_len],
                        (b,) + (0,) * (layer[key].ndim - 1),
                    )
                    for key in layer
                }
                for layer, row in zip(cache, row_cache)
            ]

        self._splice = jax.jit(_splice, donate_argnums=(0,))

        def _prefix_restore(row_cache, entry):
            # same rationale as _splice: donated, fused writes — eager
            # per-layer dynamic_update_slice would copy the whole row
            # cache through HBM 2*n_layers times per cache hit
            return [
                {
                    key: jax.lax.dynamic_update_slice(
                        layer[key], cached[key], (0,) * layer[key].ndim
                    )
                    for key in layer
                }
                for layer, cached in zip(row_cache, entry)
            ]

        self._prefix_restore = jax.jit(_prefix_restore, donate_argnums=(0,))

        def _prefix_snapshot(row_cache, store_at):
            return [
                {
                    key: jax.lax.dynamic_slice(
                        layer[key],
                        (0,) * layer[key].ndim,
                        (1, store_at, *layer[key].shape[2:]),
                    )
                    for key in layer
                }
                for layer in row_cache
            ]

        self._prefix_snapshot = jax.jit(
            _prefix_snapshot, static_argnums=(1,)
        )

    # ---------------------------------------------------------- frontend

    def _validate_submit(self, request: GenRequest, need: int) -> None:
        """Shared submit-time contract: degenerate requests fail loudly
        here, never mid-batch. ``need`` is the engine-specific worst-case
        physical frontier the request can reach before its slot frees."""
        if not request.prompt:
            # an empty prompt has no admission logits: the chunked path
            # would crash mid-run and the padded path would emit garbage
            raise ValueError("prompt must contain at least one token")
        if request.max_new_tokens < 1:
            # admission always emits the prefill token, so 0 cannot be
            # honored as a budget
            raise ValueError("max_new_tokens must be >= 1")
        if request.adapter and not (0 <= request.adapter < max(1, self._n_adapters)):
            raise ValueError(
                f"adapter {request.adapter} out of range: the tree stacks "
                f"{self._n_adapters} adapters (0 = base)"
            )
        if self.rolling:
            # the rolling layout bounds nothing: any prompt ingests
            # through C-bounded pieces and any budget decodes in place
            return
        if len(request.prompt) > self.max_len:
            # _bucket clamps to max_len, so downstream chunk math would
            # wave an over-long prompt through and crash mid-run instead.
            raise ValueError(
                f"prompt length {len(request.prompt)} > engine max_len "
                f"{self.max_len}"
            )
        if need > self.max_len:
            raise ValueError(
                f"request needs {need} cache slots > engine max_len "
                f"{self.max_len}"
            )

    def submit(
        self, request: GenRequest, submit_at: Optional[float] = None
    ) -> int:
        """Enqueue a request. ``submit_at`` back-dates the telemetry
        submit stamp (in the engine clock's timeline) — the open-loop
        driver stamps the request's generated ARRIVAL time so queue wait
        reflects the workload, not the driver's hand-off loop."""
        request.id = next(self._ids)
        # Decode advances in whole chunks; a slot's physical frontier can
        # reach the admission frontier + ceil((max_new-1)/ticks)*ticks
        # before it frees. The admission frontier is the pow2 bucket on
        # the padded-prefill path but the RAW length on the chunked path
        # (no left pad) — using the bucket there would reject exactly the
        # long prompts chunked admission exists for.
        t = self.ticks_per_sync
        chunks = -(-max(0, request.max_new_tokens - 1) // t)
        bucket = self._bucket(len(request.prompt))
        chunked = bucket > self.prefill_chunk or self.config.sliding_window is not None
        frontier = len(request.prompt) if chunked else bucket
        self._validate_submit(request, frontier + chunks * t)
        self._queue.append(request)
        self.telemetry.on_submit(request, bucket, submit_at=submit_at)
        metrics.SERVE_QUEUE_DEPTH.set(len(self._queue))
        return request.id

    @property
    def busy(self) -> bool:
        """Anything queued or occupying a slot (the drain condition)."""
        return bool(self._queue) or any(s is not None for s in self._slots)

    def _decode_params(self):
        """The param tree decode dispatches on: with stacked LoRA
        adapters, re-pointed at the slots' adapter ids (weights shared
        by reference — only the [slots] selector leaf changes)."""
        if not self._n_adapters:
            return self.params
        from nos_tpu.models.lora import with_adapter_rows

        return with_adapter_rows(self.params, self._adapter_rows)

    def _admission_params(self, adapter: int):
        """Single-row variant for prefill/ingest programs (B = 1)."""
        if not self._n_adapters:
            return self.params
        from nos_tpu.models.lora import with_adapter_rows

        return with_adapter_rows(self.params, [adapter])

    def run(self) -> Dict[int, List[int]]:
        """Drain queue + slots; returns {request id: generated tokens}.

        Chains decode chunks between host syncs: a sync is only useful
        when its outcome can change a scheduling decision — a slot
        freeing while requests wait to be admitted, or the drain ending.
        Chunks until then are computable from the remaining budgets
        (exactly, when no live request can EOS early), so that many
        dispatches go out back-to-back and the device→host pull — a full
        network RTT per sync on tunneled chips — amortizes over the whole
        horizon instead of taxing every chunk."""
        while self._queue or any(s is not None for s in self._slots):
            self.step(chunks=None)
        out = {c.id: c.tokens for c in self._done}
        self._done.clear()
        return out

    def _sync_horizon(self, pending: frozenset = frozenset()) -> int:
        """Decode chunks until the next host decision point. A request
        with an eos_id can finish any tick, so its horizon is its budget
        only when nothing is queued behind it (a late EOS then wastes
        ride-along ticks, never admission latency); with a queue it
        bounds the horizon to one chunk so the freed slot turns over.
        ``pending``: slots whose admission first-token is deferred into
        this round's pull — already spent from the budget, not yet in
        ``out``."""
        t = self._tokens_per_sync
        horizons = []
        for b, s in enumerate(self._slots):
            if s is None or s.done:
                continue
            spent = len(s.out) + (1 if b in pending else 0)
            rem = max(1, s.request.max_new_tokens - spent)
            budget = -(-rem // t)
            if self.rolling:
                # rolling budgets are unbounded — without a cap one
                # step() would queue the whole completion's dispatches
                # and sync nothing until it finishes
                budget = min(budget, 16)
            if s.request.eos_id is not None or s.request.on_token is not None:
                # An EOS can land any tick; decoding the full budget
                # blind would turn an early finish into worst-case wall
                # time. A few chunks per sync keeps the RTT amortization
                # while bounding post-EOS waste; with a queue behind it,
                # every chunk matters for slot turnover. Streaming
                # (on_token) slots take the same bound — tokens only
                # reach the host at syncs, so an unbounded horizon would
                # deliver the whole completion in one terminal burst.
                budget = min(budget, 1 if self._queue else 4)
            horizons.append(budget)
        if not horizons:
            return 1
        if self._queue:
            return min(horizons)
        if len(horizons) > 1:
            # No queue: running to the LARGEST budget would have every
            # shorter co-tenant riding (and discarding) decode chunks
            # until the longest slot's horizon. Syncing at the
            # second-largest budget retires the shorter slots at their
            # own frontier; the longest slot just takes another round
            # (same shape as the rolling 16-chunk cap above).
            return sorted(horizons)[-2]
        return horizons[0]

    # ---------------------------------------------------------- scheduling

    def _bucket(self, n: int) -> int:
        # the rolling layout never one-shot-prefills, so its bucket only
        # sizes ingest pieces — cap at the (C - window)-bounded chunk
        b = 8
        while b < n:
            b *= 2
        return min(b, self.prefill_chunk if self.rolling else self.max_len)

    def _prefill_for(self, bucket: int):
        """One compiled prefill per prompt-length bucket."""
        if bucket not in self._prefill_cache:
            cfg = self.config

            def _pre(params, prompt):
                logits, cache = prefill(
                    params, prompt, cfg, bucket, pad_id=PAD_ID,
                    quant=self.kv_quant,
                )
                first = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                return first, logits[:, -1], cache

            self._prefill_cache[bucket] = jax.jit(_pre)
        return self._prefill_cache[bucket]

    def _admit(self, b: int, request: GenRequest) -> None:
        bucket = self._bucket(len(request.prompt))
        if bucket > self.prefill_chunk or self.config.sliding_window is not None:
            # sliding-window configs always take the chunked path: its
            # positions are physical==logical (no left pad), which the
            # window mask requires
            self._admit_chunked(b, request)
            return
        pad = bucket - len(request.prompt)
        padded = jnp.asarray(
            [[PAD_ID] * pad + list(request.prompt)], jnp.int32
        )
        with self.telemetry.prefill_span(request, bucket, "padded"):
            first, first_logits, row_cache = self._prefill_for(bucket)(
                self._admission_params(request.adapter), padded
            )
        self._adapter_rows[b] = request.adapter
        self._cache = self._splice(self._cache, row_cache, jnp.asarray(b, jnp.int32))
        slot = _Slot(request=request)
        self._slots[b] = slot
        self._pos[b] = bucket
        self._rope[b] = len(request.prompt)
        self._key_valid[b, :pad] = False
        self._key_valid[b, pad:] = True
        self._set_sampling(b, request)
        self._pending_first.append(
            (b, self._first_token(b, request, argmax=first[0], raw=first_logits))
        )

    def _admit_chunked(self, b: int, request: GenRequest) -> None:
        """Long-prompt admission: ingest the prompt through fixed-size
        decode_chunk pieces into a fresh single-row cache (positions
        [0, L), no left pad — the final RIGHT-padded piece masks its
        writes to the row cache's sacrificial trailing slot), then splice
        the row into the batch cache."""
        from nos_tpu.models.generate import init_kv_cache

        c = self.config
        prompt = list(request.prompt)
        length = len(prompt)
        # short prompts (windowed configs route here too) use bucket-sized
        # pieces, not the full prefill_chunk width
        n = min(self.prefill_chunk, self._bucket(length))
        # rolling rows match the batch layout exactly (modulus C =
        # max_len - 1, pad slot max_len - 1); the physical==logical
        # layout keeps its sacrificial slot OUTSIDE max_len instead
        row_cache = init_kv_cache(
            c, 1, self.max_len if self.rolling else self.max_len + 1,
            quant=self.kv_quant,
        )
        logits = None
        # Longest cached prefix at one of THIS request's chunk
        # boundaries; the final piece always recomputes (its logits seed
        # generation), so only boundaries strictly before the last piece
        # qualify.
        resume = 0
        if self.prefix_cache_entries > 0:
            boundary = ((length - 1) // n) * n
            while boundary > 0:
                key = (request.adapter, tuple(prompt[:boundary]))
                entry = self._prefix_cache.get(key)
                if entry is not None:
                    self._prefix_cache.move_to_end(key)
                    with self.telemetry.prefix_restore_span(request, boundary):
                        row_cache = self._prefix_restore(row_cache, entry)
                    resume = boundary
                    metrics.SERVE_PREFIX_HITS.inc()
                    metrics.SERVE_PREFIX_TOKENS_REUSED.inc(boundary)
                    break
                boundary -= n
        with self.telemetry.prefill_span(request, length - resume, "chunked"):
            logits, row_cache = self._ingest_pieces(
                self._ingest, self._admission_params(request.adapter),
                row_cache, prompt, n, resume,
            )
        self._adapter_rows[b] = request.adapter
        if self.prefix_cache_entries > 0:
            store_at = ((length - 1) // n) * n
            if store_at > 0:
                key = (request.adapter, tuple(prompt[:store_at]))
                if key not in self._prefix_cache:
                    self._prefix_cache[key] = self._prefix_snapshot(
                        row_cache, store_at
                    )
                    while len(self._prefix_cache) > self.prefix_cache_entries:
                        self._prefix_cache.popitem(last=False)
        last_idx = (length - 1) % n
        first = jnp.argmax(logits[0, last_idx]).astype(jnp.int32)
        self._cache = self._splice(self._cache, row_cache, jnp.asarray(b, jnp.int32))
        slot = _Slot(request=request)
        self._slots[b] = slot
        self._pos[b] = length
        self._rope[b] = length
        self._key_valid[b, :] = True
        self._set_sampling(b, request)
        self._pending_first.append(
            (b, self._first_token(b, request, argmax=first,
                                  raw=logits[0, last_idx][None]))
        )

    @staticmethod
    def _ingest_pieces(ingest, params, row_cache, prompt, n, resume=0):
        """THE prompt-chunking loop: slice n-token pieces from ``resume``,
        RIGHT-pad the final piece with its writes masked to the row
        cache's sacrificial trailing slot. Target and draft (SpecEngine)
        ingestion share this so their piece math can never diverge."""
        logits = None
        for start in range(resume, len(prompt), n):
            piece = prompt[start:start + n]
            real = len(piece)
            piece = piece + [0] * (n - real)
            mask = jnp.asarray([[True] * real + [False] * (n - real)])
            logits, row_cache = ingest(
                params,
                row_cache,
                jnp.asarray([start], jnp.int32),
                jnp.asarray([piece], jnp.int32),
                mask,
            )
        return logits, row_cache

    def _set_sampling(self, b: int, request: GenRequest) -> None:
        self._temp[b] = request.temperature
        self._topk[b] = request.top_k
        self._topp[b] = request.top_p

    def _first_token(self, b: int, request: GenRequest, argmax, raw):
        """First generated token from the admission logits as a DEVICE
        scalar (step() resolves all of a round's admissions in one host
        sync), and the slot's key chain: both derive from fold_in(engine
        seed, request id) ONLY, so a request's sampled stream survives
        any co-tenancy."""
        req_key = jax.random.fold_in(self._base_key, request.id)
        carry, sub = jax.random.split(req_key)
        self._row_keys = self._row_keys.at[b].set(carry)
        if request.temperature <= 0:
            return jnp.asarray(argmax, jnp.int32)
        tok = pick_tokens_per_row(
            jnp.asarray(raw, jnp.float32).reshape(1, -1),
            jnp.asarray([request.temperature], jnp.float32),
            jnp.asarray([request.top_k], jnp.int32),
            jnp.asarray([request.top_p], jnp.float32),
            sub[None],
        )
        return tok[0].astype(jnp.int32)

    def _resolve_admissions(self) -> None:
        """ONE device->host pull for every admission this round: emit each
        pending first token and free any slot it already satisfies."""
        if not self._pending_first:
            return
        toks = np.asarray(jnp.stack([t for _, t in self._pending_first]))
        for (b, _), tok in zip(self._pending_first, toks):
            self._last[b] = int(tok)
            self._emit(b, int(tok))
        self._pending_first.clear()

    def _must_resolve_eagerly(self) -> bool:
        """A pending first token must be pulled BEFORE decoding only when
        its value can change scheduling: a budget of 1 (slot frees
        without decoding) or an eos_id (prefill's token may end the
        request). Otherwise resolution defers into the round's single
        end-of-chunk pull — admissions then cost zero extra round-trips."""
        for b, _ in self._pending_first:
            req = self._slots[b].request
            if req.max_new_tokens == 1 or req.eos_id is not None:
                return True
        return False

    def _emit(self, b: int, token: int) -> None:
        """Append one token; marks (but does not free) a finished slot —
        chunk processing frees at the boundary."""
        slot = self._slots[b]
        if not slot.out:
            # TTFT stamps HERE — when the token reaches the host — not at
            # admission: a deferred first token rides the round's decode
            # chunk and honestly pays that sync's latency.
            self.telemetry.on_first_token(slot.request)
        slot.out.append(token)
        req = slot.request
        if req.on_token is not None:
            req.on_token(req.id, token)
        if len(slot.out) >= req.max_new_tokens or (
            req.eos_id is not None and token == req.eos_id
        ):
            slot.done = True

    # ------------------------------------------------------------- tick

    def step(self, chunks: "int | None" = 1) -> None:
        """One scheduling round: admit into free slots, then run
        ``chunks`` ticks_per_sync decode chunks back-to-back with ONE
        device→host sync at the end (None: pick the horizon from the
        admitted slots' budgets, see _sync_horizon). Each chunk's control
        state (pos, last token, rope) feeds the next dispatch as device
        arrays, so chaining costs zero extra round-trips; host mirrors
        advance arithmetically. A slot whose request completes
        mid-horizon rides the remaining chunks harmlessly (scatter writes
        past its frontier drop, its surplus tokens are trimmed
        host-side)."""
        for b in range(self.slots_n):
            if self._slots[b] is None and self._queue:
                request = self._queue.pop(0)
                with self.telemetry.admit_span(request):
                    self._admit(b, request)
        deferred: List[tuple] = []
        if self._pending_first and self._must_resolve_eagerly():
            self._resolve_admissions()
            for b in range(self.slots_n):
                # Admission can satisfy a whole request (max_new_tokens=1,
                # or an immediate EOS from prefill): free before decoding.
                self._retire(b)
        else:
            # No admission can finish on its first token: its resolve
            # merges into this round's end-of-chunk pull.
            deferred = self._pending_first
            self._pending_first = []
        if not any(s is not None for s in self._slots):
            return
        pending_b = frozenset(b for b, _ in deferred)
        chunks = (
            self._sync_horizon(pending_b) if chunks is None else max(1, chunks)
        )
        self.ticks += chunks
        active_slots = sum(1 for s in self._slots if s is not None)
        with self.telemetry.decode_span(chunks, active_slots):
            pos = jnp.asarray(self._pos)
            last = jnp.asarray(self._last)
            rope = jnp.asarray(self._rope)
            key_valid = jnp.asarray(self._key_valid)
            for b, tok in deferred:
                # Traced scalar index: ONE compiled set-program serves every
                # slot and admission count (a vectorized stack/scatter would
                # compile per distinct admission count — on tunneled
                # backends each new executable costs whole seconds).
                last = last.at[jnp.asarray(b, jnp.int32)].set(tok)
            admit_last = last
            tok_chunks = []
            if (self._temp > 0).any():
                temp = jnp.asarray(self._temp)
                topk = jnp.asarray(self._topk)
                topp = jnp.asarray(self._topp)
                keys = self._row_keys
                dec_params = self._decode_params()
                for _ in range(chunks):
                    toks, self._cache, pos, last, rope, keys = self._decode_sampled(
                        dec_params, self._cache, pos, last, rope,
                        key_valid, temp, topk, topp, keys,
                    )
                    tok_chunks.append(toks)
                self._row_keys = keys
            else:
                dec_params = self._decode_params()
                for _ in range(chunks):
                    toks, self._cache, pos, last, rope = self._decode_greedy(
                        dec_params, self._cache, pos, last, rope, key_valid,
                    )
                    tok_chunks.append(toks)
            # ONE transfer for the whole round: the chunk token arrays (and
            # any deferred admission firsts) come back in a single
            # device_get — no on-device concat (that would compile a new
            # program per distinct chunk count).
            if deferred:
                first_row, *np_chunks = jax.device_get([admit_last] + tok_chunks)
            else:
                first_row = None
                np_chunks = jax.device_get(tok_chunks)
        tokens = np.concatenate(np_chunks)  # [chunks * ticks_per_sync, B]
        ticks = tokens.shape[0]
        # Clock cost BEFORE any emit: deferred first tokens only reached
        # the host in this round's pull, so their TTFT includes it.
        self.telemetry.on_decode_ticks(ticks)
        for b, _ in deferred:
            self._emit(b, int(first_row[b]))
        metrics.SERVE_TICKS.inc(ticks)
        metrics.SERVE_SLOT_TICKS_ACTIVE.inc(ticks * active_slots)
        metrics.SERVE_QUEUE_DEPTH.set(len(self._queue))
        # Host state mirrors the device chunk exactly: every row advanced
        # `ticks` positions whether its tenant needed them or not.
        self._pos += ticks
        self._rope += ticks
        self._last = tokens[-1].astype(np.int32).copy()
        for b in range(self.slots_n):
            if self._slots[b] is None:
                continue
            for j in range(ticks):
                if self._slots[b].done:
                    break
                self._emit(b, int(tokens[j, b]))
            self._retire(b)
        # Idle rows still ride every chunk; pinning them at 0 keeps their
        # scatter writes in-bounds forever (re-admission overwrites the row).
        for b in range(self.slots_n):
            if self._slots[b] is None:
                self._pos[b] = 0
                self._rope[b] = 0

    def _retire(self, b: int) -> None:
        slot = self._slots[b]
        if slot is not None and slot.done:
            self._done.append(Completion(id=slot.request.id, tokens=slot.out))
            self.telemetry.on_retire(slot.request, len(slot.out))
            metrics.SERVE_REQUESTS.inc()
            metrics.SERVE_TOKENS.inc(len(slot.out))
            self._slots[b] = None
            # stale sampling params must not keep the sampled program hot
            self._temp[b] = 0.0
            self._topk[b] = 0
            self._topp[b] = 1.0
            # retired rows must stop scatter-writing past max_len and stop
            # attending stale K/V: rewind and invalidate the cache row
            self._pos[b] = 0
            self._rope[b] = 0
            self._key_valid[b, :] = False
            self._adapter_rows[b] = 0
