from nos_tpu.serve.engine import Engine, GenRequest  # noqa: F401
