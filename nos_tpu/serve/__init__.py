from nos_tpu.serve.engine import Engine, GenRequest  # noqa: F401
from nos_tpu.serve.spec_engine import SpecEngine  # noqa: F401
from nos_tpu.serve.telemetry import (  # noqa: F401
    RequestRecord,
    ServeClock,
    ServeTelemetry,
    VirtualServeClock,
)
from nos_tpu.serve.sharded import (  # noqa: F401
    kv_cache_sharding,
    shard_for_serving,
)
