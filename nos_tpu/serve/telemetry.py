"""Per-request serving telemetry: journeys, latency stamps, goodput.

The control plane has had journey tracing since PR 2 — a pending Pod's
observe→bind trace decomposes into quota/plan/actuate stages — but the
data plane exported only raw counters: no way to say what a request's
TTFT was, where its queue wait went, or whether the replica is meeting
any latency target. This module is the serving mirror of that stack:

- **Request journeys.** Each submitted request registers a journey root
  span (``serve.request``, keyed by ``(serve, engine, request id)`` the
  same way pod journeys key by ``("pod", ns/name)``) and the engine's
  stages parent onto it: ``serve.submit`` → ``serve.queue`` (submit to
  admit) → ``serve.admit`` (with ``serve.prefill`` and
  ``serve.prefix_restore`` sub-spans) → ``serve.decode`` (admission to
  last token) → ``serve.retire``. The admit/prefill/decode spans are
  context-managed, so the sampling profiler's phase attribution
  (util/profiling.py) decomposes a serve thread's wall time for free.
- **Latency stamps.** ``submit_t`` / ``admit_t`` / ``first_token_t`` /
  ``retire_t`` per request. The first-token stamp is taken when the
  token is *emitted to the host* — under deferred admission resolution
  the prefill token only reaches the host at the end-of-chunk pull, so
  TTFT honestly includes that decode chunk; an eagerly resolved
  admission (budget 1, eos) stamps right after prefill.
- **Derived metrics.** At retire the request observes TTFT, TPOT
  (per-token decode latency), end-to-end latency, queue wait, and
  request tokens/sec into labeled histograms (model/adapter/bucket),
  plus goodput counters: a request is *good* when it met the configured
  per-request latency targets (``ttft_target_s`` / ``e2e_target_s``,
  typically derived from the SLO specs via
  ``slo.engine.SLOEngine.latency_targets``).
- **Clocks.** Stamps come from a pluggable ``ServeClock``. The default
  reads ``time.monotonic`` and its cost hooks are no-ops (real work
  takes real time). ``VirtualServeClock`` advances a virtual timeline
  from a deterministic cost model (seconds per decode tick, per prefill
  token) — the open-loop driver (slo/driver.py) uses it so
  ``BENCH_serve.json`` latencies are bit-stable at a fixed seed.
"""
from __future__ import annotations

import contextlib
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from nos_tpu.util import metrics
from nos_tpu.util.tracing import NOOP_SPAN, TRACER, Span


class ServeClock:
    """Wall-clock stamps; cost hooks are no-ops (time passes by itself)."""

    def now(self) -> float:
        return time.monotonic()

    def on_prefill(self, tokens: int) -> None:
        pass

    def on_decode(self, ticks: int) -> None:
        pass


class VirtualServeClock(ServeClock):
    """Deterministic virtual timeline driven by a cost model.

    ``now()`` only moves when the engine reports work (``on_prefill`` /
    ``on_decode``) or the driver advances it to an arrival time, so every
    latency derived from it is a pure function of the workload and the
    engine's scheduling decisions — the property that makes
    ``BENCH_serve.json`` bit-stable across runs at a fixed seed.

    The defaults approximate a small model on one v5e chip: 8 ms per
    batched decode tick and 0.2 ms per prefill token. They are a *model*,
    not a measurement — the point is determinism, and that relative
    effects (queue waits under load, chunked-prefill cost, prefix-cache
    savings) show up with realistic proportions.
    """

    def __init__(
        self,
        tick_cost_s: float = 0.008,
        prefill_token_cost_s: float = 0.0002,
        start: float = 0.0,
    ) -> None:
        self.tick_cost_s = tick_cost_s
        self.prefill_token_cost_s = prefill_token_cost_s
        self._now = float(start)

    def now(self) -> float:
        return self._now

    def advance(self, dt: float) -> None:
        self._now += max(0.0, dt)

    def advance_to(self, t: float) -> None:
        self._now = max(self._now, t)

    def on_prefill(self, tokens: int) -> None:
        self._now += tokens * self.prefill_token_cost_s

    def on_decode(self, ticks: int) -> None:
        self._now += ticks * self.tick_cost_s


@dataclass
class RequestRecord:
    """One request's journey stamps (None until the stage happens)."""

    id: int
    model: str
    adapter: int
    bucket: int
    prompt_tokens: int
    max_new_tokens: int
    submit_t: float
    trace_id: str = ""
    admit_t: Optional[float] = None
    first_token_t: Optional[float] = None
    retire_t: Optional[float] = None
    tokens: int = 0
    good: Optional[bool] = None

    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.admit_t is None:
            return None
        return self.admit_t - self.submit_t

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    @property
    def e2e_s(self) -> Optional[float]:
        if self.retire_t is None:
            return None
        return self.retire_t - self.submit_t

    @property
    def tpot_s(self) -> Optional[float]:
        """Per-token decode latency: last-token minus first-token wall
        time over the tokens after the first. None until retired; 0.0
        for single-token completions (no decode happened)."""
        if self.retire_t is None or self.first_token_t is None:
            return None
        if self.tokens <= 1:
            return 0.0
        return (self.retire_t - self.first_token_t) / (self.tokens - 1)

    @property
    def tokens_per_s(self) -> Optional[float]:
        e2e = self.e2e_s
        if e2e is None:
            return None
        return self.tokens / e2e if e2e > 0 else float(self.tokens)


class ServeTelemetry:
    """Per-engine request tracker: stamps, spans, histograms, goodput.

    One instance per engine (the engine constructs a default); the
    engine calls the hooks at its stage boundaries. Everything is
    bounded: live records are popped at retire and completed records
    land in a capped ring (``completed``, newest kept).
    """

    MAX_COMPLETED = 4096

    def __init__(
        self,
        model: str = "default",
        clock: Optional[ServeClock] = None,
        ttft_target_s: Optional[float] = None,
        e2e_target_s: Optional[float] = None,
        on_complete: Optional[Callable[[RequestRecord], None]] = None,
    ) -> None:
        self.model = model
        self.clock = clock or ServeClock()
        # Per-request goodput targets; None = that dimension never
        # disqualifies. Both None: every completed request is good.
        self.ttft_target_s = ttft_target_s
        self.e2e_target_s = e2e_target_s
        self.on_complete = on_complete
        self._live: Dict[int, RequestRecord] = {}
        self._queue_spans: Dict[int, Span] = {}
        self._decode_spans: Dict[int, Span] = {}
        self.completed: "OrderedDict[int, RequestRecord]" = OrderedDict()

    # ------------------------------------------------------------- keys

    def _journey_key(self, request_id: int) -> Any:
        return ("serve", id(self), request_id)

    def record(self, request_id: int) -> Optional[RequestRecord]:
        return self._live.get(request_id) or self.completed.get(request_id)

    # ------------------------------------------------------------ hooks

    def on_submit(
        self, request, bucket: int, submit_at: Optional[float] = None
    ) -> RequestRecord:
        """Stamp submission and open the journey. ``submit_at`` lets an
        open-loop driver stamp the request's *arrival* time even when it
        hands the request over later in virtual time."""
        now = self.clock.now() if submit_at is None else submit_at
        rec = RequestRecord(
            id=request.id,
            model=self.model,
            adapter=getattr(request, "adapter", 0),
            bucket=bucket,
            prompt_tokens=len(request.prompt),
            max_new_tokens=request.max_new_tokens,
            submit_t=now,
        )
        self._live[request.id] = rec
        root = TRACER.journey_root(
            self._journey_key(request.id),
            "serve.request",
            request=request.id,
            model=self.model,
            adapter=rec.adapter,
            prompt_tokens=rec.prompt_tokens,
            max_new_tokens=rec.max_new_tokens,
        )
        rec.trace_id = root.trace_id
        submit = TRACER.start_span(
            "serve.submit", parent=root, bucket=bucket
        )
        TRACER.end_span(submit)
        # Queue residency: ends when the admit span opens.
        self._queue_spans[request.id] = TRACER.start_span(
            "serve.queue", parent=root
        )
        return rec

    @contextlib.contextmanager
    def admit_span(self, request):
        """Wraps the engine's admission of one request: ends the queue
        span, stamps ``admit_t``, and makes ``serve.admit`` the current
        span so the prefill/prefix sub-spans (and profiler samples)
        attribute correctly."""
        rec = self._live.get(request.id)
        queue_span = self._queue_spans.pop(request.id, None)
        if queue_span is not None:
            TRACER.end_span(queue_span)
        if rec is not None:
            rec.admit_t = self.clock.now()
        root = TRACER.journey(self._journey_key(request.id))
        with TRACER.span(
            "serve.admit", parent=root or NOOP_SPAN, request=request.id
        ) as span:
            yield span
        # Decode residency: admission done -> last emitted token.
        if rec is not None and root is not None:
            self._decode_spans[request.id] = TRACER.start_span(
                "serve.decode", parent=root, request=request.id
            )

    @contextlib.contextmanager
    def prefill_span(self, request, tokens: int, path: str):
        """One prefill/ingest unit of ``tokens`` prompt tokens. Advances
        the clock's prefill cost on exit (even with tracing disabled —
        the cost model must not depend on the tracer)."""
        try:
            with TRACER.span(
                "serve.prefill", tokens=tokens, path=path
            ) as span:
                yield span
        finally:
            self.clock.on_prefill(tokens)

    @contextlib.contextmanager
    def prefix_restore_span(self, request, reused_tokens: int):
        """A prefix-cache hit restoring ``reused_tokens`` of cached K/V
        (the tokens whose prefill cost is being skipped)."""
        with TRACER.span(
            "serve.prefix_restore", reused_tokens=reused_tokens
        ) as span:
            yield span

    @contextlib.contextmanager
    def decode_span(self, chunks: int, active_slots: int):
        """The engine's batched decode dispatch for one scheduling round
        (all slots at once) — the profiler's 'decode' phase."""
        with TRACER.span(
            "serve.batch_decode", chunks=chunks, active_slots=active_slots
        ) as span:
            yield span

    def on_decode_ticks(self, ticks: int) -> None:
        """Decode progress for cost accounting; called after the round's
        device pull, *before* the host emits its tokens, so deferred
        first tokens carry the chunk's latency."""
        self.clock.on_decode(ticks)

    def on_first_token(self, request) -> None:
        rec = self._live.get(request.id)
        if rec is not None and rec.first_token_t is None:
            rec.first_token_t = self.clock.now()

    def on_retire(self, request, tokens: int) -> None:
        rec = self._live.pop(request.id, None)
        if rec is None:
            return
        now = self.clock.now()
        rec.retire_t = now
        rec.tokens = tokens
        rec.good = self._is_good(rec)
        decode_span = self._decode_spans.pop(request.id, None)
        if decode_span is not None:
            decode_span.set_attributes(tokens=tokens)
            TRACER.end_span(decode_span)
        root = TRACER.journey(self._journey_key(request.id))
        retire = TRACER.start_span(
            "serve.retire", parent=root or NOOP_SPAN, tokens=tokens
        )
        TRACER.end_span(retire)
        TRACER.end_journey(
            self._journey_key(request.id),
            tokens=tokens,
            ttft_s=round(rec.ttft_s or 0.0, 6),
            tpot_s=round(rec.tpot_s or 0.0, 6),
            e2e_s=round(rec.e2e_s or 0.0, 6),
            queue_wait_s=round(rec.queue_wait_s or 0.0, 6),
            good=bool(rec.good),
        )
        self._observe(rec)
        self.completed[rec.id] = rec
        while len(self.completed) > self.MAX_COMPLETED:
            self.completed.popitem(last=False)
        if self.on_complete is not None:
            self.on_complete(rec)

    # ---------------------------------------------------------- derived

    def _is_good(self, rec: RequestRecord) -> bool:
        if self.ttft_target_s is not None and (
            rec.ttft_s is None or rec.ttft_s > self.ttft_target_s
        ):
            return False
        if self.e2e_target_s is not None and (
            rec.e2e_s is None or rec.e2e_s > self.e2e_target_s
        ):
            return False
        return True

    def _observe(self, rec: RequestRecord) -> None:
        labels = dict(
            model=rec.model, adapter=str(rec.adapter), bucket=str(rec.bucket)
        )
        if rec.ttft_s is not None:
            metrics.SERVE_TTFT.labels(**labels).observe(rec.ttft_s)
        if rec.tpot_s is not None and rec.tokens > 1:
            metrics.SERVE_TPOT.labels(**labels).observe(rec.tpot_s)
        if rec.e2e_s is not None:
            metrics.SERVE_E2E.labels(**labels).observe(rec.e2e_s)
        if rec.queue_wait_s is not None:
            metrics.SERVE_QUEUE_WAIT.labels(**labels).observe(rec.queue_wait_s)
        if rec.tokens_per_s is not None:
            metrics.SERVE_REQUEST_TOKENS_PER_S.labels(**labels).observe(
                rec.tokens_per_s
            )
        verdict = "good" if rec.good else "late"
        metrics.SERVE_GOODPUT_REQUESTS.labels(
            model=rec.model, verdict=verdict
        ).inc()
        if rec.good:
            metrics.SERVE_GOODPUT_TOKENS.labels(model=rec.model).inc(
                rec.tokens
            )
