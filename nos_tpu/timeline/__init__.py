"""Longitudinal health timeline: in-process time-series sampling of the
metric registry, process vitals, and structure sizes into a bounded
delta-encoded ring, with pure leak/stall/regression detectors and a
wedge watchdog over registered controller loops on top."""
from nos_tpu.timeline import detectors
from nos_tpu.timeline.detectors import (
    LEAK,
    REGRESSION,
    STALL,
    detect_leak,
    detect_regression,
    detect_stall,
    run_detector,
)
from nos_tpu.timeline.sizes import SIZES, SizeRegistry
from nos_tpu.timeline.store import DetectorPolicy, TimelineStore
from nos_tpu.timeline.watchdog import WATCHDOG, WedgeWatchdog

__all__ = [
    "detectors",
    "LEAK",
    "REGRESSION",
    "STALL",
    "detect_leak",
    "detect_regression",
    "detect_stall",
    "run_detector",
    "SIZES",
    "SizeRegistry",
    "DetectorPolicy",
    "TimelineStore",
    "WATCHDOG",
    "WedgeWatchdog",
]
